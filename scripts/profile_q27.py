"""Diagnostic: where does q27's engine time go at 2M rows / 200K items?

Times each stage separately (forcing a device sync between stages via a
tiny readback) and an isolated 200K-key group-by through each aggregate
lane.  Not a recorded bench — a profiling aid for the round-5 udf_q27
work (VERDICT r4 #4).
"""
import time

import numpy as np


def sync(x):
    import jax
    jax.block_until_ready(x)
    return x


def t(label, fn, n=3):
    best = float("inf")
    out = None
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    print(f"{label:48s} {best*1e3:9.1f} ms")
    return out


def main():
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.models import tpcxbb
    from spark_rapids_tpu.models.data_util import make_sources
    from spark_rapids_tpu.plan import accelerate, collect

    rng = np.random.default_rng(21)
    n_reviews = 1 << 21
    rv = tpcxbb.gen_reviews(rng, n_reviews, n_reviews // 10,
                            n_reviews // 4)
    t0 = time.perf_counter()
    srcs = make_sources({"product_reviews": rv},
                        {"product_reviews": tpcxbb.REVIEWS_SCHEMA}, 2)
    print(f"make_sources (host->device upload): "
          f"{(time.perf_counter()-t0)*1e3:.0f} ms")
    conf = C.RapidsConf(
        {"spark.rapids.sql.variableFloatAgg.enabled": True})
    plan = accelerate(tpcxbb.QUERIES["q27"](srcs, lambda p: None), conf)
    assert isinstance(plan, TpuExec)
    collect(plan, conf)  # warm
    t("q27 end-to-end (engine collect)", lambda: collect(plan, conf))

    # per-exec metric breakdown from the last run
    def walk(p, depth=0):
        ms = p.metrics.as_dict() if hasattr(p, "metrics") else {}
        tot = ms.get("total time", 0)
        print(f"  {'  '*depth}{type(p).__name__:36s} "
              f"{tot*1e3 if tot else 0:8.1f} ms  {ms}")
        for c in getattr(p, "children", []) or []:
            walk(c, depth + 1)
    walk(plan)

    # ---- isolated 200K-key group-by at 2M rows, per lane ----
    from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
    from spark_rapids_tpu.exprs.base import col
    from spark_rapids_tpu.plan import CpuAggregate, CpuSource
    import pandas as pd

    rows, n_keys = 1 << 21, 200_000
    df = pd.DataFrame({
        "k": rng.integers(0, n_keys, rows).astype(np.int64),
        "v": rng.uniform(0, 100, rows),
    })
    src = CpuSource.from_pandas(df, num_partitions=1)
    cpu_plan = CpuAggregate(
        [col("k")],
        [Sum(col("v")).alias("sv"), Count(col("v")).alias("c"),
         Average(col("v")).alias("av")], src)
    for name, extra in (
            ("agg 200K keys: default lanes", {}),
            ("agg 200K keys: sort lane",
             {"spark.rapids.tpu.dictGroupby.enabled": False,
              "spark.rapids.tpu.bandedGroupby.enabled": False}),
            ("agg 200K keys: banded lane",
             {"spark.rapids.tpu.dictGroupby.enabled": False}),
    ):
        lconf = C.RapidsConf(
            {"spark.rapids.sql.variableFloatAgg.enabled": True, **extra})
        lplan = accelerate(cpu_plan, lconf)
        collect(lplan, lconf)  # warm + compile
        t(name, lambda p=lplan, c=lconf: collect(p, c))

    tp = t("pandas same groupby",
           lambda: df.groupby("k").agg(sv=("v", "sum"), c=("v", "size"),
                                       av=("v", "mean")))

    # ---- raw kernel costs at this shape ----
    from spark_rapids_tpu.ops.sort_encode import sort_with_bounds
    from spark_rapids_tpu.columnar.vector import ColumnVector
    from spark_rapids_tpu import types as T

    k64 = jnp.asarray(df["k"].to_numpy())
    k32 = k64.astype(jnp.int32)
    v32 = jnp.asarray(df["v"].to_numpy(), jnp.float32)
    mask = jnp.ones((rows,), bool)

    @jax.jit
    def just_sort(kk, m):
        kc = ColumnVector(T.INT64, kk.astype(jnp.int64), m,
                          narrow=kk.astype(jnp.int32))
        perm, sv, bounds, _ = sort_with_bounds([(kc, True, True)], m)
        return perm, sv, bounds

    sync(just_sort(k32, mask))
    t("sort_with_bounds 2M i64(narrow i32) keys",
      lambda: sync(just_sort(k32, mask)))

    from jax import lax

    @jax.jit
    def payload_sort(kk, v, m):
        return lax.sort([kk.astype(jnp.uint32), v, m], num_keys=1,
                        is_stable=True)

    sync(payload_sort(k32, v32, mask))
    t("bare u32 payload sort (1 f32 + mask payload)",
      lambda: sync(payload_sort(k32, v32, mask)))

    from spark_rapids_tpu.ops.grouped_window import window_group_sums

    @jax.jit
    def banded_window(kk, v, m):
        # pretend sorted: seg ids from adjacent-diff boundaries
        bounds = jnp.concatenate(
            [jnp.ones((1,), bool), kk[1:] != kk[:-1]])
        seg = jnp.cumsum(bounds.astype(jnp.int32)) - 1
        return window_group_sums(seg, (v, m.astype(jnp.float32)),
                                 out_cap=1 << 18, capacity=rows)

    ks = jnp.sort(k32)
    sync(banded_window(ks, v32, mask))
    t("window_group_sums (2 measures, 256K out cap)",
      lambda: sync(banded_window(ks, v32, mask)))

    print(f"\npandas reference: {tp if tp is not None else ''}")


if __name__ == "__main__":
    main()
