#!/usr/bin/env python
"""tpulint CLI: run the engine-invariant checker over the repo.

    python scripts/lint.py                     # lint spark_rapids_tpu
    python scripts/lint.py --format json       # CI lane output
    python scripts/lint.py --disable host-sync # prove a rule is load-bearing
    python scripts/lint.py --write-baseline    # grandfather current findings
                                               # (repo policy: keep it empty)

Exits 0 iff there are no active (unsuppressed, unbaselined) findings.
The linter is pure stdlib-ast — it never imports the engine it checks,
so it needs no JAX/device environment.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from spark_rapids_tpu.analysis import (  # noqa: E402
    ALL_RULES, format_json, format_text, run_lint, summary_line,
    write_baseline)
from spark_rapids_tpu.analysis.core import DEFAULT_BASELINE  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the "
                         "spark_rapids_tpu package)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE", help="disable a rule by id")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as active")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current active findings to the "
                         "baseline file and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.rule_id}: {r.doc}")
        print("bad-suppress: a tpulint disable comment must carry "
              "' -- <reason>'")
        return 0

    result = run_lint(
        paths=args.paths or None, disable=args.disable,
        baseline_path=None if args.no_baseline else args.baseline)

    if args.write_baseline:
        write_baseline(args.baseline, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    if args.format == "json":
        print(format_json(result))
        print(summary_line(result), file=sys.stderr)
    else:
        print(format_text(result,
                          verbose_suppressed=args.show_suppressed))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
