#!/usr/bin/env python
"""bench_diff: compare two BENCH_r*.json rounds and gate regressions.

ROADMAP item 3 demands every slow-lane fix land with an
instrument-validated before/after, but bench rounds were hand-diffed
JSON blobs.  This tool makes rounds DIFFABLE and regression-GATED:

  python scripts/bench_diff.py BENCH_r07.json BENCH_r08.json

parses both rounds (the driver's ``{"tail": "...jsonl..."}`` wrapper or
raw JSON-lines), matches bench lanes by metric name, and reports the
per-lane delta — direction-aware (rows/s and GB/s up = better, wall_ms
and overhead down = better).  A lane regressed past ``--threshold``
percent (default 10) exits non-zero, so CI can gate on it; lanes
present in only one round (a new bench, a phase the wall-clock cap
killed, an error-shaped line) are TOLERATED and listed, never failed —
partial rounds stay comparable.

Attribution: for every regressed (or improved) lane the report names
what moved underneath it, joining the per-lane and summary-line
instrument fields both rounds already carry — utilization cause shifts
(telemetry sampler), per-edge movement deltas (data-movement ledger),
kernel-catalog/cache counters (kernel_cache_size/evictions,
host_syncs, pipeline_wait), and per-kernel rows when a round embeds a
``kernels`` list (utils/kernelprof.py) — so a round-to-round slowdown
points at a kernel or an edge, not just a number.

``--selftest`` runs the synthetic-round checks (regression detected /
improvement passes / missing phase tolerated) and is wired into the
lint tier of scripts/run_suite.sh.
"""
from __future__ import annotations

import argparse
import json
import sys

#: default regression gate (percent)
DEFAULT_THRESHOLD = 10.0

#: name fragments marking a metric where LOWER is better
_LOWER_BETTER = ("_ms", "wall", "overhead", "latency", "host_syncs",
                 "p95", "p50", "hbm_high_water", "leaks",
                 "merge_passes", "spill_mb", "slowdown")


def lower_is_better(name: str) -> bool:
    return any(tok in name for tok in _LOWER_BETTER)


# ---------------------------------------------------------------------------
# parsing
def _iter_json_lines(text: str):
    for line in text.splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            yield json.loads(line)
        except ValueError:
            continue


def parse_round(text: str) -> dict:
    """Parse one bench round into {"meta", "metrics", "summary"}.
    Accepts the driver wrapper (a single JSON object whose "tail"
    holds the final stdout lines), raw JSON-lines, or a JSON list."""
    meta: dict = {}
    recs: list = []
    text = text.strip()
    obj = None
    if text.startswith("{") or text.startswith("["):
        try:
            obj = json.loads(text)
        except ValueError:
            obj = None
    if isinstance(obj, dict) and "metric" not in obj:
        meta = {k: obj.get(k) for k in ("n", "cmd", "rc", "note")
                if k in obj}
        recs = list(_iter_json_lines(str(obj.get("tail", ""))))
        # older rounds carry the driver-parsed final summary separately
        if isinstance(obj.get("parsed"), dict):
            recs.append(obj["parsed"])
    elif isinstance(obj, list):
        recs = [r for r in obj if isinstance(r, dict)]
    else:
        recs = list(_iter_json_lines(text))
    metrics: dict = {}
    summary = None
    for r in recs:
        if "metric" not in r:
            continue
        # the driver-facing rolling summary rides extra engine-wide
        # fields; keep the LAST occurrence of it AND of each lane
        if "submetrics" in r or ("hbm_probe_gbps" in r
                                 and "host_syncs" in r):
            summary = r
            continue
        metrics[r["metric"]] = r
    # a truncated round (the driver keeps a bounded stdout tail) may
    # have lost its per-lane lines: the rolling summary's compact
    # submetrics carry every lane measured so far — fold them in
    # without shadowing full lines
    for sub in (summary or {}).get("submetrics") or []:
        if isinstance(sub, dict) and sub.get("metric") not in metrics:
            metrics[sub["metric"]] = sub
    return {"meta": meta, "metrics": metrics, "summary": summary}


def load_round(path: str) -> dict:
    with open(path) as f:
        rnd = parse_round(f.read())
    rnd["path"] = path
    return rnd


# ---------------------------------------------------------------------------
# attribution: what moved underneath a lane
def _util_shift(a: dict, b: dict) -> list:
    ua, ub = a.get("util") or {}, b.get("util") or {}
    notes = []
    for cause in sorted(set(ua) | set(ub)):
        if cause == "samples":
            continue
        d = float(ub.get(cause, 0.0)) - float(ua.get(cause, 0.0))
        if abs(d) >= 5.0:
            notes.append(f"util.{cause} {d:+.1f}pp")
    return notes


def _kernel_shift(a: dict, b: dict) -> list:
    """Per-kernel rows (utils/kernelprof.py report embeds) keyed by
    label: the biggest device-time movers."""
    ka = {r.get("label"): r for r in a.get("kernels") or []}
    kb = {r.get("label"): r for r in b.get("kernels") or []}
    moves = []
    for label in set(ka) | set(kb):
        da = float((ka.get(label) or {}).get("device_ms", 0.0))
        db = float((kb.get(label) or {}).get("device_ms", 0.0))
        if da or db:
            moves.append((abs(db - da), label, da, db))
    moves.sort(reverse=True)
    return [f"kernel[{label}] {da:.1f}->{db:.1f}ms"
            for _, label, da, db in moves[:3] if abs(db - da) > 0.05]


def _edge_shift(a: dict, b: dict) -> list:
    """movement_edges ({edge: [MB, GB/s]}) deltas from summary lines."""
    ea, eb = a.get("movement_edges") or {}, b.get("movement_edges") or {}
    notes = []
    for edge in sorted(set(ea) | set(eb)):
        mba = float((ea.get(edge) or [0])[0])
        mbb = float((eb.get(edge) or [0])[0])
        if abs(mbb - mba) >= max(1.0, 0.25 * max(mba, mbb)) \
                and (mba or mbb):
            notes.append(f"edge.{edge} {mba:.1f}->{mbb:.1f}MB")
    return notes


def _residency_shift(a: dict, b: dict) -> list:
    """Per-lane observed HBM high-water deltas (`hbm_high_water`
    bytes, from the residency ledger).  Direction-aware: residency
    going DOWN is an improvement — a lane that got faster by holding
    more HBM (or slower while ballooning) should say so."""
    ha, hb = a.get("hbm_high_water"), b.get("hbm_high_water")
    if ha is None or hb is None:
        return []
    ha, hb = float(ha), float(hb)
    if not (ha or hb):
        return []
    if abs(hb - ha) < max(float(1 << 20), 0.25 * max(ha, hb)):
        return []
    tag = "down=good, shrank" if hb < ha else "down=good, GREW"
    return [f"hbm_high_water {ha / 1e6:.1f}->{hb / 1e6:.1f}MB ({tag})"]


def _summary_shift(a: dict, b: dict) -> list:
    notes = []
    for k in ("kernel_cache_size", "kernel_cache_evictions",
              "host_syncs", "prefetch_hits"):
        va, vb = a.get(k), b.get(k)
        if va is None or vb is None or va == vb:
            continue
        rel = abs(vb - va) / max(abs(va), 1)
        if rel >= 0.25:
            notes.append(f"{k} {va}->{vb}")
    pa, pb = a.get("pipeline_wait_ms"), b.get("pipeline_wait_ms")
    if pa is not None and pb is not None \
            and abs(pb - pa) >= max(1000.0, 0.25 * max(pa, pb)):
        notes.append(f"pipeline_wait_ms {pa:.0f}->{pb:.0f}")
    return notes


# ---------------------------------------------------------------------------
def compare_rounds(a: dict, b: dict,
                   threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The diff: per-lane deltas with direction-aware classification
    plus attribution notes.  `threshold` is the regression gate in
    percent."""
    ma, mb = a["metrics"], b["metrics"]
    lanes, regressions = [], []
    added = sorted(set(mb) - set(ma))
    removed = sorted(set(ma) - set(mb))
    for name in sorted(set(ma) & set(mb)):
        la, lb = ma[name], mb[name]
        failed_a = bool(la.get("error")) or not la.get("value")
        failed_b = bool(lb.get("error")) or not lb.get("value")
        if failed_a or failed_b:
            # a lane that errored or recorded 0 in either round is a
            # missing phase, not a measured regression
            lanes.append({"metric": name, "status": "incomparable",
                          "a": la.get("value"), "b": lb.get("value"),
                          "error": (la.get("error")
                                    or lb.get("error"))})
            continue
        va, vb = float(la["value"]), float(lb["value"])
        lower = lower_is_better(name)
        delta_pct = 100.0 * (vb - va) / abs(va) if va else 0.0
        worse = (delta_pct > 0) if lower else (delta_pct < 0)
        magnitude = abs(delta_pct)
        status = "flat"
        if magnitude >= threshold:
            status = "regressed" if worse else "improved"
        notes = (_util_shift(la, lb) + _kernel_shift(la, lb)
                 + _edge_shift(la, lb) + _residency_shift(la, lb))
        lane = {"metric": name, "status": status,
                "a": va, "b": vb,
                "delta_pct": round(delta_pct, 2),
                "lower_is_better": lower,
                "vs_baseline": [la.get("vs_baseline"),
                                lb.get("vs_baseline")],
                "attribution": notes}
        lanes.append(lane)
        if status == "regressed":
            regressions.append(lane)
    summary_notes = []
    if a.get("summary") and b.get("summary"):
        summary_notes = (_summary_shift(a["summary"], b["summary"])
                         + _util_shift(a["summary"], b["summary"])
                         + _edge_shift(a["summary"], b["summary"])
                         + _residency_shift(a["summary"], b["summary"]))
    return {"threshold_pct": threshold,
            "lanes": lanes,
            "regressions": [l["metric"] for l in regressions],
            "added": added, "removed": removed,
            "engine_wide": summary_notes}


def format_report(rep: dict, a_name: str, b_name: str) -> str:
    lines = [f"== bench diff: {a_name} -> {b_name} "
             f"(gate {rep['threshold_pct']:.0f}%) =="]
    order = {"regressed": 0, "improved": 1, "flat": 2,
             "incomparable": 3}
    for l in sorted(rep["lanes"],
                    key=lambda l: (order[l["status"]],
                                   -abs(l.get("delta_pct", 0)))):
        if l["status"] == "incomparable":
            lines.append(f"  ~ {l['metric']:34s} incomparable "
                         f"({l['a']!r} -> {l['b']!r})"
                         + (f"  [{str(l['error'])[:60]}]"
                            if l.get("error") else ""))
            continue
        mark = {"regressed": "-", "improved": "+", "flat": "="}[
            l["status"]]
        arrow = "(lower=better)" if l["lower_is_better"] else ""
        lines.append(
            f"  {mark} {l['metric']:34s} {l['a']:>14.3f} -> "
            f"{l['b']:>14.3f}  {l['delta_pct']:+7.2f}%  "
            f"{l['status']} {arrow}")
        for n in l["attribution"]:
            lines.append(f"        attributed: {n}")
    for name in rep["added"]:
        lines.append(f"  + {name:34s} new lane (no baseline)")
    for name in rep["removed"]:
        lines.append(f"  ~ {name:34s} missing in the newer round "
                     "(tolerated)")
    if rep["engine_wide"]:
        lines.append("  engine-wide: " + "; ".join(rep["engine_wide"]))
    n_reg = len(rep["regressions"])
    lines.append(f"  verdict: {n_reg} regression(s) past the gate"
                 + (f" -> {', '.join(rep['regressions'])}"
                    if n_reg else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
def _selftest() -> int:
    """Synthetic-round behavior checks: regression detected, improvement
    passes, missing phase tolerated, attribution surfaces."""
    base = "\n".join(json.dumps(r) for r in [
        {"metric": "tpch_q1_rows_per_sec", "value": 100.0,
         "vs_baseline": 2.0, "hbm_high_water": 100e6,
         "util": {"samples": 100, "busy": 60.0, "idle": 40.0}},
        {"metric": "groupby_sf1_wall_ms", "value": 50.0,
         "vs_baseline": 1.0},
        {"metric": "udf_q27_rows_per_sec", "value": 10.0},
    ])
    a = parse_round(base)
    assert set(a["metrics"]) == {"tpch_q1_rows_per_sec",
                                 "groupby_sf1_wall_ms",
                                 "udf_q27_rows_per_sec"}, a["metrics"]
    # wrapper form parses identically
    wrapped = parse_round(json.dumps({"n": 1, "rc": 0, "tail": base}))
    assert set(wrapped["metrics"]) == set(a["metrics"])

    # 1) injected regression on a higher-is-better lane is detected,
    #    with a utilization attribution note
    reg = parse_round("\n".join(json.dumps(r) for r in [
        {"metric": "tpch_q1_rows_per_sec", "value": 70.0,
         "vs_baseline": 1.4, "hbm_high_water": 300e6,
         "util": {"samples": 100, "busy": 30.0, "idle": 70.0},
         "kernels": [{"label": "agg-update", "device_ms": 900.0}]},
        {"metric": "groupby_sf1_wall_ms", "value": 50.0},
        {"metric": "udf_q27_rows_per_sec", "value": 10.0},
    ]))
    rep = compare_rounds(a, reg, threshold=10.0)
    assert rep["regressions"] == ["tpch_q1_rows_per_sec"], rep
    lane = next(l for l in rep["lanes"]
                if l["metric"] == "tpch_q1_rows_per_sec")
    assert any("util." in n for n in lane["attribution"]), lane
    assert any("kernel[" in n for n in lane["attribution"]), lane
    # residency attribution: the slowdown also names the HBM
    # high-water balloon (direction-aware: GREW)
    assert any("hbm_high_water" in n and "GREW" in n
               for n in lane["attribution"]), lane

    # 1b) a lane whose METRIC is an hbm_high_water reading gates
    #     lower-better: residency growing past the threshold regresses
    res_a = parse_round(json.dumps(
        {"metric": "q5_hbm_high_water_bytes", "value": 100e6}))
    res_b = parse_round(json.dumps(
        {"metric": "q5_hbm_high_water_bytes", "value": 150e6}))
    rep = compare_rounds(res_a, res_b, threshold=10.0)
    assert rep["regressions"] == ["q5_hbm_high_water_bytes"], rep
    rep = compare_rounds(res_b, res_a, threshold=10.0)  # shrank: good
    assert rep["regressions"] == [], rep

    # 2) improvement (and a lower-is-better improvement) passes
    imp = parse_round("\n".join(json.dumps(r) for r in [
        {"metric": "tpch_q1_rows_per_sec", "value": 130.0},
        {"metric": "groupby_sf1_wall_ms", "value": 40.0},
        {"metric": "udf_q27_rows_per_sec", "value": 10.5},
    ]))
    rep = compare_rounds(a, imp, threshold=10.0)
    assert rep["regressions"] == [], rep
    assert {l["status"] for l in rep["lanes"]} == {"improved", "flat"}

    # 3) a wall_ms lane getting SLOWER is a regression
    slow = parse_round(json.dumps(
        {"metric": "groupby_sf1_wall_ms", "value": 80.0}) + "\n"
        + json.dumps({"metric": "tpch_q1_rows_per_sec",
                      "value": 100.0}) + "\n"
        + json.dumps({"metric": "udf_q27_rows_per_sec", "value": 10.0}))
    rep = compare_rounds(a, slow, threshold=10.0)
    assert rep["regressions"] == ["groupby_sf1_wall_ms"], rep

    # 4) missing / errored phases are tolerated, never gated
    partial = parse_round("\n".join(json.dumps(r) for r in [
        {"metric": "tpch_q1_rows_per_sec", "value": 99.0},
        {"metric": "udf_q27_rows_per_sec", "value": 0,
         "error": "TimeoutError: wall cap"},
    ]))
    rep = compare_rounds(a, partial, threshold=10.0)
    assert rep["regressions"] == [], rep
    assert "groupby_sf1_wall_ms" in rep["removed"], rep
    assert any(l["status"] == "incomparable" for l in rep["lanes"])
    print("bench_diff selftest: ok (regression gated, improvement "
          "passed, missing phase tolerated, attribution surfaced)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("rounds", nargs="*",
                    help="two BENCH_r*.json rounds: old new")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="regression gate in percent (default 10)")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only; always exit 0")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="run the synthetic-round behavior checks")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if len(args.rounds) != 2:
        ap.error("expected exactly two rounds (old new)")
    a, b = load_round(args.rounds[0]), load_round(args.rounds[1])
    rep = compare_rounds(a, b, threshold=args.threshold)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print(format_report(rep, args.rounds[0], args.rounds[1]))
    if args.no_gate:
        return 0
    return 1 if rep["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
