"""Stage-level timing of the 200K-key group-by through the engine:
what fills the gap between raw kernel cost (~160ms) and engine collect
(~390ms)?  Times each jitted kernel invocation with a hard sync, then
the full collect, then collect with a patched no-op dense()/prefetch to
isolate host-exit costs."""
import time

import numpy as np


def sync(x):
    import jax
    jax.block_until_ready(x)
    return x


def t(label, fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    print(f"{label:52s} {best*1e3:9.1f} ms")


def main():
    import pandas as pd
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
    from spark_rapids_tpu.exprs.base import col
    from spark_rapids_tpu.plan import CpuAggregate, CpuSource, accelerate, collect

    rng = np.random.default_rng(7)
    rows, n_keys = 1 << 21, 200_000
    df = pd.DataFrame({
        "k": rng.integers(0, n_keys, rows).astype(np.int64),
        "v": rng.uniform(0, 100, rows),
    })
    src = CpuSource.from_pandas(df, num_partitions=1)
    cpu_plan = CpuAggregate(
        [col("k")],
        [Sum(col("v")).alias("sv"), Count(col("v")).alias("c"),
         Average(col("v")).alias("av")], src)
    conf = C.RapidsConf(
        {"spark.rapids.sql.variableFloatAgg.enabled": True})
    plan = accelerate(cpu_plan, conf)
    with C.session(conf):
        out = plan.collect()
    print("plan:", plan.describe() if hasattr(plan, "describe") else plan)

    def full():
        with C.session(conf):
            plan.collect()
    t("engine collect -> batch (no to_pandas)", full)

    def full_pd():
        with C.session(conf):
            plan.collect().to_pandas()
    t("engine collect + to_pandas", full_pd)

    # walk the plan: time each exec's process_partition output with sync
    execs = []
    p = plan
    while p is not None:
        execs.append(p)
        ch = getattr(p, "children", None) or []
        p = ch[0] if ch else None
    print("exec chain:", [type(e).__name__ for e in execs])

    # cumulative: sync after each stage boundary from the source up
    from spark_rapids_tpu.exec.base import TpuExec
    for i in range(len(execs) - 1, -1, -1):
        e = execs[i]
        if not isinstance(e, TpuExec):
            continue

        def run_to(e=e):
            with C.session(conf):
                outs = []
                for it in e.execute_partitions():
                    for b in it:
                        outs.append(b.columns[0].data)
                sync(outs)
        try:
            t(f"cumulative through {type(e).__name__}", run_to)
        except Exception as ex:
            print(f"  {type(e).__name__}: {type(ex).__name__}: {ex}")


if __name__ == "__main__":
    main()
