#!/usr/bin/env bash
# CI suite runner (reference jenkins/spark-tests.sh analog): runs the
# fast unit tier, the scale ("slow") tier, a shim version matrix over
# the version-sensitive suites, and a bench smoke. Usage:
#   scripts/run_suite.sh [fast|slow|shims|bench|all]
set -euo pipefail
cd "$(dirname "$0")/.."

TIER="${1:-fast}"
PYTEST=(python -m pytest -q -p no:randomly)

run_gate() {
  echo "== multichip gate (driver-shape invocation -> MULTICHIP_LOCAL.json) =="
  python scripts/multichip_check.py 8
}

run_lint() {
  # static-analysis lane (budget <30s, no device/JAX needed): tpulint
  # enforces the engine invariants (host-sync accounting, semaphore
  # blocking discipline, bounded waits, conf registration, compile-
  # outside-the-lock) over the whole package, then the configs drift
  # gate proves docs/configs.md matches the registry.  The JSON run
  # feeds tooling; the summary line matches the other lanes.
  echo "== lint lane (tpulint engine invariants + configs drift gate) =="
  # JSON on stdout for tooling; the summary line rides stderr
  python scripts/lint.py --format json > /dev/null
  python scripts/gen_configs_doc.py --check
  # bench-round drift gate: the differ's synthetic-round behavior
  # checks (regression detected -> non-zero exit, improvement passes,
  # missing phase tolerated), then a report-only diff of the two
  # newest committed rounds so round-to-round drift is visible in
  # every lint run without gating on environmental noise
  python scripts/bench_diff.py --selftest
  latest=$(ls BENCH_r*.json 2>/dev/null | sort | tail -2)
  if [ "$(echo "$latest" | wc -l)" -eq 2 ]; then
    # shellcheck disable=SC2086
    python scripts/bench_diff.py $latest --no-gate | tail -5
  fi
}

run_fast() {
  run_lint
  run_gate
  echo "== fast tier (unit + integration, virtual 8-device CPU mesh) =="
  "${PYTEST[@]}" tests/ -m "not slow" --ignore=tests/test_workloads.py
  echo "== workload parity (TPC-H / TPC-DS / TPCx-BB / Mortgage) =="
  "${PYTEST[@]}" tests/test_workloads.py
  run_oom_soak
  run_pipeline
  run_recovery
  run_watchdog
  run_profile
  run_movement
  run_concurrency
  run_fusion
  run_spmd
  run_speculation
  run_telemetry
  run_kernelprof
  run_residency
  run_oocore
}

run_residency() {
  # HBM residency lane: the ledger suite (provenance registration,
  # high-water reconciliation, leak detection, underflow guard, storm
  # isolation), then one profiled manager-lane q5 whose residency
  # report must show a NONZERO high-water mark with a peak composition
  # that sums to it and a clean leak verdict — the summary line
  # carries peak bytes, top site, and the verdict.
  echo "== residency lane (HBM provenance ledger, high-water marks, leak check) =="
  "${PYTEST[@]}" tests/test_residency.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import profile as P
from spark_rapids_tpu.utils import residency as RS

tables = gen_tables(np.random.default_rng(11), 1000)
run_query(5, tables, engine="tpu", conf=C.RapidsConf({
    **BENCH_CONF,
    "spark.rapids.sql.profile.enabled": True,
    "spark.rapids.shuffle.enabled": True,
    "spark.rapids.shuffle.localExecutors": 2}))
prof = P.last_profile()
res = prof.residency
assert res is not None and res["hbm_high_water"] > 0, res
comp = res["peak_composition"]
assert sum(comp.values()) == res["hbm_high_water"], comp
assert res["leaks"] == 0, res["leaked"]
assert res["live_end_bytes"] == 0, res
assert "-- residency --" in prof.explain()
assert RS.live_records_for_query(prof.query_id) == []
top = max(comp.items(), key=lambda kv: kv[1])
print("residency summary: q5 peak_mb=%.2f top_site=%s sites=%d "
      "allocs=%d leaks=%d verdict=clean" % (
          res["hbm_high_water"] / 1e6, top[0], len(comp),
          res["allocs"], res["leaks"]))
PYEOF
}

run_kernelprof() {
  # kernel-attribution lane: the kernelprof suite (disabled-path
  # parity, sampling, per-query isolation, catalog/cost capture,
  # roofline single-source) + bench_diff units, then one profiled q1
  # whose '-- kernels --' section must attribute the compute bucket —
  # the summary line carries coverage, top kernel, and roofline %.
  echo "== kernelprof lane (per-kernel device timing, cost/roofline attribution) =="
  "${PYTEST[@]}" tests/test_kernelprof.py tests/test_bench_diff.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from pandas.testing import assert_frame_equal
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import kernelprof as KP
from spark_rapids_tpu.utils import profile as P

tables = gen_tables(np.random.default_rng(11), 20000)
off = C.RapidsConf(dict(BENCH_CONF))
on = C.RapidsConf({**BENCH_CONF,
    "spark.rapids.sql.pipeline.enabled": False,
    "spark.rapids.sql.profile.enabled": True,
    "spark.rapids.sql.profile.kernels.enabled": True,
    "spark.rapids.sql.profile.kernels.sampleRate": 1})
ref = run_query(1, tables, conf=off)
run_query(1, tables, conf=on)      # warm: first dispatches = compile
got = run_query(1, tables, conf=on)
assert_frame_equal(got.reset_index(drop=True),
                   ref.reset_index(drop=True))
prof = P.last_profile()
rows = prof.kernels
assert rows, "no kernel attribution rows"
assert "-- kernels --" in prof.explain()
kernel_ms = sum(r["device_ms"] for r in rows)
compute_ms = prof.breakdown["compute_s"] * 1e3
cov = kernel_ms / compute_ms if compute_ms else 0.0
roofed = [r for r in rows if "roofline_pct" in r]
assert roofed, "no kernel carried a cost/roofline join"
assert 0.35 <= cov <= 1.5, f"kernel/compute coverage wildly off: {cov}"
top = rows[0]
print("kernelprof summary: kernels=%d dispatches=%d kernel_ms=%.1f "
      "compute_ms=%.1f coverage=%.2f top=%s@%.1fms roofline=%.3f%% "
      "(%s-bound) catalog=%d" % (
          len(rows), sum(r["dispatches"] for r in rows), kernel_ms,
          compute_ms, cov, top["label"], top["device_ms"],
          top.get("roofline_pct", 0.0), top.get("bound", "?"),
          KP.catalog_size()))
KP.reset()
PYEOF
}

run_spmd() {
  # SPMD whole-stage lane: the gang-execution suite (parity, ragged
  # partitions, deopt, ledger reconciliation), then a q1 parity smoke
  # over the 8-device mesh whose summary line carries the per-stage
  # dispatch counts — the O(partitions)->O(1) dispatch evidence.
  echo "== spmd lane (whole-mesh stage execution: parity + dispatch counts) =="
  "${PYTEST[@]}" tests/test_spmd.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from pandas.testing import assert_frame_equal
from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec import spmd as SP
from spark_rapids_tpu.exec.scheduler import mesh_gate_stats
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.parallel.mesh import active_mesh, make_mesh

tables = gen_tables(np.random.default_rng(11), 1000)
off = C.RapidsConf(dict(BENCH_CONF))
on = C.RapidsConf({**BENCH_CONF,
                   "spark.rapids.sql.spmd.enabled": True})
ref = run_query(1, tables, conf=off)
mesh = make_mesh(min(8, len(jax.devices())))
SP.reset_spmd_stats()
with active_mesh(mesh):
    for parts in (2, 8):
        got = run_query(1, tables, conf=on, num_partitions=parts)
        assert_frame_equal(got.reset_index(drop=True),
                           ref.reset_index(drop=True))
st = SP.spmd_stats()
assert st["gang_dispatches"] >= 2 and st["deopts"] == 0, st
gate = mesh_gate_stats()
print("spmd summary: q1 bit-exact spmd-vs-per-partition at 2 and 8 "
      "partitions; gang_dispatches=%d (one per stage) batches=%d "
      "slots=%d deopts=%d gate_dispatches=%d" % (
          st["gang_dispatches"], st["gang_batches"], st["gang_slots"],
          st["deopts"], gate["dispatches"]))
PYEOF
}

run_telemetry() {
  # engine-wide telemetry lane: the registry/exporter/sampler suite,
  # then one live smoke — a Prometheus scrape against the HTTP
  # endpoint WHILE a concurrent q1/q5 pair runs, asserting the
  # operator-facing gauges parse and the utilization timeline names
  # every sampled instant — with a busy-vs-idle summary line.
  echo "== telemetry lane (metrics registry, Prometheus export, utilization timeline) =="
  "${PYTEST[@]}" tests/test_telemetry.py
  python - <<'PYEOF'
import threading, time, urllib.request
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import telemetry as T

t = T.start(C.RapidsConf({
    "spark.rapids.sql.telemetry.enabled": True,
    "spark.rapids.sql.telemetry.samplePeriodMs": 10.0}), http_port=0)
tables = gen_tables(np.random.default_rng(11), 1000)
conf = C.RapidsConf({**BENCH_CONF,
                     "spark.rapids.sql.profile.enabled": True})
for q in (1, 5):  # warm compiles outside the scraped window
    run_query(q, tables, conf=C.RapidsConf(dict(BENCH_CONF)))
errors = []
def worker(q):
    try:
        run_query(q, tables, conf=conf)
    except BaseException as e:
        errors.append((q, repr(e)))
ts = [threading.Thread(target=worker, args=(q,)) for q in (1, 5, 1, 5)]
[x.start() for x in ts]
scrapes = 0
url = "http://127.0.0.1:%d/metrics" % t.http_port
text = ""
while any(x.is_alive() for x in ts):
    text = urllib.request.urlopen(url, timeout=10).read().decode()
    scrapes += 1
    time.sleep(0.05)
[x.join(300) for x in ts]
assert not errors, errors
assert scrapes > 0 and "tpu_rapids_hbm_budget_bytes" in text
assert "tpu_rapids_semaphore_max_concurrent" in text
assert "tpu_rapids_scheduler_queue_depth" in text
assert "tpu_rapids_kernel_cache_entries" in text
util = t.utilization_summary()
named = sum(v for k, v in util.items() if k != "samples")
assert util["samples"] > 10 and named >= 99.0, util
slow = t.slow_query_log()
print("telemetry summary: scrapes=%d samples=%d util=%s "
      "slow_query_fingerprints=%d" % (
          scrapes, util["samples"],
          {k: v for k, v in util.items() if k != "samples"}, len(slow)))
T.stop()
PYEOF
}

run_speculation() {
  # tail-tolerance lane: the speculation/hedging/replication suite
  # (first-wins races, loser cancellation, replica promotion, spill
  # corruption, wire:wasted honesty), then an injected straggler run
  # whose summary line carries the speculation/hedge/replication
  # counters — the p95 trajectory's round-to-round evidence.
  echo "== speculation lane (stragglers, hedged fetches, replication) =="
  "${PYTEST[@]}" tests/test_speculation.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, pandas as pd
from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec.basic import LocalBatchSource
from spark_rapids_tpu.exec.speculation import speculation_stats
from spark_rapids_tpu.exprs.base import col
from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
from spark_rapids_tpu.utils.watchdog import slow_injection_counts

conf = C.RapidsConf({
    "spark.rapids.shuffle.enabled": True,
    "spark.rapids.shuffle.localExecutors": 3,
    "spark.rapids.shuffle.replication.factor": 2,
    "spark.rapids.shuffle.hedge.enabled": True,
    "spark.rapids.shuffle.hedge.delayMs": 40.0,
    "spark.rapids.sql.speculation.enabled": True,
    "spark.rapids.sql.speculation.minTaskRuntimeMs": 50.0,
    "spark.rapids.sql.speculation.minCompletedTasks": 1,
    "spark.rapids.sql.watchdog.pollInterval": 0.05,
    "spark.rapids.memory.faultInjection.slowSite": "map-task",
    "spark.rapids.memory.faultInjection.slowFactor": 10.0,
    "spark.rapids.memory.faultInjection.slowUnitMs": 40.0,
    "spark.rapids.memory.faultInjection.slowVictim": "local-1",
    "spark.rapids.memory.faultInjection.slowSeed": 11,
})
rng = np.random.default_rng(7)
df = pd.DataFrame({"k": rng.integers(0, 50, 4000).astype(np.int64),
                   "v": rng.integers(0, 10**6, 4000).astype(np.int64)})
with C.session(conf):
    src = LocalBatchSource.from_pandas(df, num_partitions=4)
    ex = ShuffleExchangeExec(HashPartitioning([col("k")], 3), src)
    rows = sum(b.num_rows for it in ex.execute_partitions() for b in it)
assert rows == len(df), f"row loss under slow injection: {rows}"
m = ex.metrics.as_dict()
s = speculation_stats()
print("speculation summary: rows=%d spec_tasks=%d spec_wins=%d "
      "losers_cancelled=%d hedged=%d hedged_wins=%d replicated_mb=%.2f "
      "slow_units=%s" % (
          rows, m.get("numSpeculativeTasks", 0),
          m.get("numSpeculativeWins", 0), s["losers_cancelled"],
          m.get("numHedgedFetches", 0), m.get("numHedgedWins", 0),
          m.get("replicatedBytes", 0) / 1e6, slow_injection_counts()))
assert m.get("numSpeculativeWins", 0) > 0, m
PYEOF
}

run_movement() {
  # data-movement lane: the ledger suite (edge conservation, spill
  # reconciliation, disabled-path parity, per-query isolation), then
  # TPC-H q1/q5 movement-report validation — q5 through the manager
  # shuffle lane (2 in-process executors + seeded OOM injection) must
  # report upload/readback/spill/wire traffic with wire-conservation
  # (bytes served == bytes assembled) holding — and a per-edge summary
  # line with effective GB/s.
  echo "== movement lane (per-query data-movement ledger, roofline) =="
  "${PYTEST[@]}" tests/test_movement.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import json
import numpy as np
from spark_rapids_tpu import config as C
from spark_rapids_tpu.memory import retry as R
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import profile as P

tables = gen_tables(np.random.default_rng(11), 1000)
for q, extra in ((1, {}), (5, {
        "spark.rapids.shuffle.enabled": True,
        "spark.rapids.shuffle.localExecutors": 2,
        "spark.rapids.memory.faultInjection.oomRate": 0.5,
        "spark.rapids.memory.faultInjection.seed": 7,
        "spark.rapids.memory.faultInjection.maxInjections": 16})):
    R.reset_oom_injection()
    run_query(q, tables, engine="tpu", conf=C.RapidsConf({
        **BENCH_CONF, "spark.rapids.sql.profile.enabled": True, **extra}))
    R.reset_oom_injection()
    prof = P.last_profile()
    mv = prof.movement
    assert mv is not None and mv["total_bytes"] > 0, mv
    edges = mv["edges"]
    if q == 5:
        for e in ("upload", "readback", "wire"):
            assert edges[e]["bytes"] > 0, (e, edges[e])
        sites = edges["wire"]["sites"]
        sent = sum(v["bytes"] for s, v in sites.items()
                   if s.startswith("send"))
        recv = sum(v["bytes"] for s, v in sites.items()
                   if s.startswith("recv"))
        assert sent == recv > 0, (sent, recv)
    assert "-- data movement --" in prof.explain()
    counters = [e for e in prof.chrome_trace()["traceEvents"]
                if e["ph"] == "C"]
    assert counters, "no Perfetto counter tracks"
    print("movement summary: q%d total_mb=%.2f %s" % (
        q, mv["total_bytes"] / 1e6,
        " ".join("%s=%.2fMB@%.3fGB/s" % (
            e, d["bytes"] / 1e6, d["gbps_avg"])
            for e, d in edges.items() if d["bytes"])))
PYEOF
}

run_fusion() {
  # whole-stage fusion lane: the fusion suite (composition, CSE,
  # per-member metrics, KernelCache bound), then TPC-H q1/q5 parity
  # with fusion ON vs OFF (bit-exact), and a deopt check — a query
  # mixing supported + unsupported (ANSI-cast) expressions must run
  # with only the affected stage unfused, never error.
  echo "== fusion lane (whole-stage XLA fusion parity + deopt) =="
  "${PYTEST[@]}" tests/test_fusion.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from pandas.testing import assert_frame_equal
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables

tables = gen_tables(np.random.default_rng(11), 1000)
on = C.RapidsConf(dict(BENCH_CONF))
off = C.RapidsConf({**BENCH_CONF,
                    "spark.rapids.sql.fusion.enabled": False})
for q in (1, 5):
    a = run_query(q, tables, conf=on)
    b = run_query(q, tables, conf=off)
    assert_frame_equal(a.reset_index(drop=True),
                       b.reset_index(drop=True))
from spark_rapids_tpu.exec.base import (kernel_cache_evictions,
                                        kernel_cache_size)
print("fusion summary: q1/q5 bit-exact fused-vs-unfused "
      "kernel_cache_size=%d evictions=%d" % (
          kernel_cache_size(), kernel_cache_evictions()))
PYEOF
}

run_concurrency() {
  # multi-query serving lane: the scheduler suite (admission control,
  # fair-share semaphore, cross-query fault isolation, result cache),
  # then a 4-thread mixed q1/q5 storm with seeded OOM injection aimed
  # at ONE victim session — every result bit-exact vs serial, zero
  # leaked permits/admissions/producers — with a metrics summary line.
  echo "== concurrency lane (admission control, fair share, fault isolation) =="
  "${PYTEST[@]}" tests/test_scheduler.py
  python - <<'PYEOF'
import threading
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from pandas.testing import assert_frame_equal
from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec.scheduler import scheduler_stats
from spark_rapids_tpu.memory.device_manager import DeviceManager
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables

tables = gen_tables(np.random.default_rng(11), 1000)
clean = C.RapidsConf(dict(BENCH_CONF))
victim = C.RapidsConf({**BENCH_CONF,
    "spark.rapids.memory.faultInjection.oomRate": 1.0,
    "spark.rapids.memory.faultInjection.seed": 13,
    "spark.rapids.memory.faultInjection.maxInjections": 16})
ref = {q: run_query(q, tables, conf=clean) for q in (1, 5)}
results, errors = {}, []
def worker(i, q, conf):
    try:
        results[i] = (q, run_query(q, tables, conf=conf))
    except BaseException as e:
        errors.append((i, q, repr(e)))
mix = [(1, victim), (5, clean), (1, clean), (5, clean)]
ts = [threading.Thread(target=worker, args=(i, q, conf))
      for i, (q, conf) in enumerate(mix)]
[t.start() for t in ts]; [t.join(300) for t in ts]
assert not errors, errors
for i, (q, df) in results.items():
    assert_frame_equal(df.reset_index(drop=True),
                       ref[q].reset_index(drop=True))
snap = TpuSemaphore.get().snapshot()
assert snap["refs"] == {}, snap
dm = DeviceManager.get()
assert dm.admissions() == {} and dm.reserved_bytes == 0
st = scheduler_stats()
print("concurrency summary: queries=%d bit_exact=ok admitted=%d "
      "queued=%d rejected=%d longest_queue_wait_ms=%d "
      "sem_longest_wait_ms=%d sem_waits=%d" % (
          len(results), st["admitted"], st["queued"], st["rejected"],
          st["longest_queue_wait_ms"], snap["longestWaitMs"],
          snap["waitCount"]))
PYEOF
}

run_profile() {
  # observability lane: TPC-H q1/q5 with per-query profiling on must
  # yield a Perfetto-parseable Chrome trace with a deep multi-thread
  # span tree, an EXPLAIN-with-metrics report where every node carries
  # resolved counters, and a correlated JSONL event log — then print
  # the wall-clock breakdown as the lane's summary line.
  echo "== profile lane (span tracing, Chrome trace, EXPLAIN-with-metrics) =="
  "${PYTEST[@]}" tests/test_profile.py
  python - <<'PYEOF'
import json
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import profile as P

tables = gen_tables(np.random.default_rng(11), 1000)
conf = C.RapidsConf({**BENCH_CONF,
                     "spark.rapids.sql.profile.enabled": True})
for q in (1, 5):
    run_query(q, tables, engine="tpu", conf=conf)
    prof = P.last_profile()
    trace = json.loads(json.dumps(prof.chrome_trace()))  # must parse
    threads = {e["tid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert prof.span_depth() >= 4, prof.span_depth()
    assert len(threads) >= 3, threads
    assert all(ln.rstrip().endswith("]")
               for ln in prof.plan_report.splitlines()), "unannotated node"
    assert {e["query_id"] for e in prof.events} == {prof.query_id}
    print("profile summary: q%d wall_ms=%.1f spans=%d depth=%d "
          "threads=%d events=%d breakdown=%s" % (
              q, prof.wall_s * 1e3, len(prof.spans), prof.span_depth(),
              len(threads), len(prof.events),
              json.dumps(prof.breakdown)))
PYEOF
}

run_watchdog() {
  # liveness lane: every seeded hang site (producer, collective,
  # shuffle-server, pyudf, compile) must end in a descriptive
  # TpuQueryTimeout + diagnostic dump within ~2x its deadline — never
  # a hang, never leaked permits/threads — and the process must run a
  # clean bit-exact query afterwards.  The summary line reports the
  # timeout/cancel metrics of one injected query.
  echo "== watchdog lane (seeded hang injection, deadlines, cancellation) =="
  "${PYTEST[@]}" tests/test_watchdog.py
  python - <<'PYEOF'
import time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.plan.overrides import ExecutionPlanCapture
from spark_rapids_tpu.utils import watchdog as W

tables = gen_tables(np.random.default_rng(11), 500)
conf = C.RapidsConf({**BENCH_CONF,
    "spark.rapids.memory.faultInjection.hangSite": "producer",
    "spark.rapids.memory.faultInjection.hangAfterBatches": 1,
    "spark.rapids.sql.watchdog.taskTimeout": 2.0,
    "spark.rapids.sql.watchdog.pollInterval": 0.1})
t0 = time.monotonic()
try:
    run_query(1, tables, engine="tpu", conf=conf)
    raise SystemExit("hang injection did not cancel the query")
except W.TpuQueryTimeout:
    pass
el = time.monotonic() - t0
m = ExecutionPlanCapture.last_plan.metrics.as_dict()
print("watchdog summary: cancelled_in=%.1fs timeouts=%d cancels=%d "
      "dumps=%d slowest_heartbeat_ms=%d" % (
          el, m.get("numWatchdogTimeouts", 0), m.get("numCancels", 0),
          m.get("watchdogDumps", 0), m.get("slowestHeartbeatMs", 0)))
W.reset_hang_injection()
PYEOF
}

run_recovery() {
  # shuffle fault-recovery lane: seeded peer_kill injection (the victim
  # executor goes dark mid-stream on both transport lanes) must yield
  # bit-exact results via map recomputation + bounded stage retries —
  # plus epoch staleness, blacklist decay, and exhaustion (raise, never
  # hang) coverage.  The summary line reports the recovery metrics of
  # one injected exchange, like the oom/pipeline/bench summaries.
  echo "== shuffle recovery lane (seeded peer-kill injection, bounded stage retries) =="
  "${PYTEST[@]}" tests/test_shuffle_recovery.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, pandas as pd
from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec.basic import LocalBatchSource
from spark_rapids_tpu.exprs.base import col
from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
from spark_rapids_tpu.shuffle.partitioning import HashPartitioning

conf = C.RapidsConf({
    "spark.rapids.shuffle.enabled": True,
    "spark.rapids.shuffle.localExecutors": 2,
    "spark.rapids.shuffle.bounceBuffers.size": 2048,
    "spark.rapids.shuffle.fetch.maxRetries": 1,
    "spark.rapids.shuffle.fetch.backoff.baseMs": 1.0,
    "spark.rapids.shuffle.recovery.blacklist.failureThreshold": 1,
    "spark.rapids.shuffle.transport.faultInjection.peerKillAfterFrames": 3,
})
rng = np.random.default_rng(7)
df = pd.DataFrame({"k": rng.integers(0, 50, 4000).astype(np.int64),
                   "v": rng.integers(0, 10**6, 4000).astype(np.int64)})
with C.session(conf):
    src = LocalBatchSource.from_pandas(df, num_partitions=4)
    ex = ShuffleExchangeExec(HashPartitioning([col("k")], 3), src)
    rows = sum(b.num_rows for it in ex.execute_partitions() for b in it)
assert rows == len(df), f"row loss under injection: {rows}"
m = ex.metrics.as_dict()
print("recovery summary: rows=%d fetch_failures=%d map_recomputes=%d "
      "stage_retries=%d peers_blacklisted=%d recovery_ms=%.1f" % (
          rows, m.get("numFetchFailures", 0),
          m.get("numMapRecomputes", 0), m.get("numStageRetries", 0),
          m.get("numPeersBlacklisted", 0),
          m.get("recoveryTime", 0) / 1e6))
PYEOF
}

run_pipeline() {
  # async-pipeline lane: the parity suites must be bit-identical with
  # bounded prefetch ON (depth 2) and fully OFF — the overlap layer may
  # move work across threads but never change a result.  Env overrides
  # flip the conf defaults suite-wide (config.py PIPELINE_* entries).
  echo "== pipeline lane (prefetchDepth=2 vs pipelining disabled) =="
  SPARK_RAPIDS_TPU_PIPELINE=1 SPARK_RAPIDS_TPU_PIPELINE_DEPTH=2 \
    "${PYTEST[@]}" tests/test_pipeline.py tests/test_tpch.py
  SPARK_RAPIDS_TPU_PIPELINE=0 \
    "${PYTEST[@]}" tests/test_pipeline.py tests/test_tpch.py
}

run_oom_soak() {
  # the retry/split/fallback lattice must run on EVERY suite invocation,
  # not just when a real TPU OOMs: seeded reservation fault injection +
  # a tiny accounted HBM budget (conf overrides inside the suite) drive
  # spill, batch splitting, floor fallback, and the semaphore
  # release/reacquire path on the CPU mesh.  OOM_SOAK=1 widens the
  # seed sweep beyond the default single pass.
  echo "== OOM soak lane (seeded reservation fault injection, tiny HBM budget) =="
  SPARK_RAPIDS_TPU_OOM_SOAK="${SPARK_RAPIDS_TPU_OOM_SOAK:-1}" \
    "${PYTEST[@]}" tests/test_oom_retry.py -m "not slow"
}

run_oocore() {
  # out-of-core lane: the bounded-HBM degradation suite (external
  # sort / grace join / agg spill bit-exactness, ledger reconciliation,
  # corruption recovery, watchdog-covered merge passes, the chaos
  # composite soak including the slow q5 leg), then one TPC-H q5 run
  # under a budget a fraction of its working set with spill-corruption
  # injection lit — bit-exact vs the unconstrained lane, overflow bytes
  # proven onto the movement ledger's oocore spill edges, zero leaked
  # buffers/admissions/reservations — with a spill-traffic summary line.
  echo "== out-of-core lane (bounded-HBM external sort/join/agg, spill-tier streaming) =="
  "${PYTEST[@]}" tests/test_out_of_core.py
  python - <<'PYEOF'
import jax
jax.config.update("jax_platforms", "cpu")
import tempfile
import numpy as np
from pandas.testing import assert_frame_equal
from spark_rapids_tpu import config as C
from spark_rapids_tpu.memory import ResourceEnv
from spark_rapids_tpu.memory import oocore as OC
from spark_rapids_tpu.memory import retry as R
from spark_rapids_tpu.memory import stores as ST
from spark_rapids_tpu.models.tpch_bench import BENCH_CONF, run_query
from spark_rapids_tpu.models.tpch_data import gen_tables
from spark_rapids_tpu.utils import movement as MV
from spark_rapids_tpu.utils import profile as P

tables = gen_tables(np.random.default_rng(11), 3000)
ref = run_query(5, tables, conf=C.RapidsConf(dict(BENCH_CONF)))
conf = C.RapidsConf({**BENCH_CONF,
    "spark.rapids.sql.profile.enabled": True,
    "spark.rapids.memory.hbmBudgetBytes": 1 << 14,
    "spark.rapids.memory.host.spillStorageSize": 1 << 14,
    "spark.rapids.memory.faultInjection.spillCorruptRate": 0.005,
    "spark.rapids.memory.faultInjection.seed": 7,
    "spark.rapids.memory.oocore.runReplicas": 2,
    "spark.rapids.memory.gpu.allocFraction": 1.0,
    "spark.rapids.memory.gpu.reserve": 0})
C.set_active_conf(conf)
env = ResourceEnv.init(hbm_total=1 << 26,
                       spill_dir=tempfile.mkdtemp())
R.reset_oom_injection()
ST.reset_spill_corruption()
OC.reset_run_accounting()
got = run_query(5, tables, conf=conf)
assert_frame_equal(got.reset_index(drop=True),
                   ref.reset_index(drop=True), check_exact=True)
prof = P.last_profile()
sites = prof.movement["edges"][MV.EDGE_SPILL]["sites"]
oocore_mb = sum(v["bytes"] for s, v in sites.items()
                if s.startswith(OC.SITE_PREFIX)) / 1e6
assert abs(oocore_mb * 1e6 - OC.run_bytes_spilled()) < 1, \
    (oocore_mb, OC.run_bytes_spilled())
assert prof.oocore is not None, "profile lost the out-of-core section"
dm = env.device_manager
assert len(env.catalog) == 0, "leaked buffers"
assert dm.admissions() == {} and dm.reserved_bytes == 0
assert env.disk_store.orphaned_spill_files() == []
tot = prof.oocore["totals"]
print("oocore summary: q5 bit-exact under %dKB budget; runs=%d "
      "spill_mb=%.2f merge_passes=%d grace_partitions=%d "
      "corruptions_injected=%d recovered=%d leaks=0" % (
          (1 << 14) // 1024, OC.runs_spilled(), oocore_mb,
          tot["merge_passes"], tot["grace_partitions"],
          ST.injected_spill_corruptions(),
          tot["corrupt_recovered"]))
ResourceEnv.shutdown()
PYEOF
}

run_slow() {
  echo "== slow tier (multi-batch scale + asserted spill) =="
  "${PYTEST[@]}" tests/test_scale_workloads.py -m slow
}

run_shims() {
  # the shim suite internally parametrizes the full version matrix
  # (3.0.0 / 3.0.1 / 3.0.2 / 3.1.0 / databricks) via
  # spark.rapids.tpu.sparkVersion — the per-version premerge analog
  # (reference jenkins/Jenkinsfile.30*)
  echo "== shim version matrix =="
  "${PYTEST[@]}" tests/test_shims.py tests/test_plan_overrides.py
}

run_bench() {
  echo "== bench smoke (one JSON line per metric; real chip if present) =="
  python bench.py
}

case "$TIER" in
  lint)     run_lint ;;
  gate)     run_gate ;;
  fast)     run_fast ;;
  slow)     run_slow ;;
  shims)    run_shims ;;
  bench)    run_bench ;;
  oom)      run_oom_soak ;;
  pipeline) run_pipeline ;;
  recovery) run_recovery ;;
  watchdog) run_watchdog ;;
  profile)  run_profile ;;
  movement) run_movement ;;
  concurrency) run_concurrency ;;
  fusion)   run_fusion ;;
  spmd)     run_spmd ;;
  speculation) run_speculation ;;
  telemetry) run_telemetry ;;
  kernelprof) run_kernelprof ;;
  residency) run_residency ;;
  oocore)   run_oocore ;;
  all)      run_fast; run_slow; run_shims; run_bench ;;
  *) echo "usage: $0 [lint|gate|fast|slow|shims|bench|oom|pipeline|recovery|watchdog|profile|movement|concurrency|fusion|spmd|speculation|telemetry|kernelprof|residency|oocore|all]" >&2
     exit 2 ;;
esac
