#!/usr/bin/env python
"""Generate docs/configs.md from the typed conf registry — or, with
--check, verify the checked-in file matches what the registry would
generate (the drift gate the lint lane runs: a conf added/edited in
config.py without regenerating docs fails CI instead of silently
diverging, giving tpulint's conf-discipline rule a documentation
counterpart).

    python scripts/gen_configs_doc.py            # (re)write docs/configs.md
    python scripts/gen_configs_doc.py --check    # exit 1 on drift
"""
import argparse
import difflib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", nargs="?", default="docs/configs.md")
    ap.add_argument("--check", action="store_true",
                    help="diff regenerated output against the file "
                         "and fail on drift instead of writing")
    args = ap.parse_args(argv)

    from spark_rapids_tpu import config as C
    want = C.help_text()
    if not args.check:
        C.write_docs(args.path)
        print(f"wrote {args.path}")
        return 0
    try:
        with open(args.path) as f:
            have = f.read()
    except OSError as e:
        print(f"configs drift gate: cannot read {args.path}: {e}")
        return 1
    if have == want:
        n = sum(1 for ln in want.splitlines()
                if ln.startswith("| `"))
        print(f"configs drift gate: ok ({n} documented confs)")
        return 0
    diff = list(difflib.unified_diff(
        have.splitlines(), want.splitlines(),
        fromfile=args.path, tofile="<registry>", lineterm=""))
    print("\n".join(diff[:60]))
    print(f"configs drift gate: {args.path} is stale — run "
          "'python scripts/gen_configs_doc.py' and commit the result")
    return 1


if __name__ == "__main__":
    sys.exit(main())
