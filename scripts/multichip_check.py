#!/usr/bin/env python
"""Local multichip-gate artifact writer (VERDICT r4 next-round item #1).

Reproduces the driver's invocation shape — a FRESH interpreter, env
untouched (so a present-but-broken TPU plugin is discoverable, the exact
scenario MULTICHIP_r01..r04 recorded), importing `__graft_entry__` and
calling `dryrun_multichip(8)` — and writes the result to
`MULTICHIP_LOCAL.json` at the repo root, stamped with the gate
fingerprint (git SHA, UTC time, jax version, route taken).

A driver artifact that disagrees with this one is then immediately
diagnosable: compare `git_sha`/`utc` to see whether the driver record
predates HEAD or its environment diverges.

Usage: python scripts/multichip_check.py [n_devices]
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _diagnose_driver_artifact():
    """Compare the newest driver-written MULTICHIP_r*.json against HEAD
    so a failing driver record is attributable on its face: a record
    with no gate fingerprint was produced by a build that predates the
    stamped gate (r1-era code), not by HEAD."""
    import glob
    import re

    def _round_no(p):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1
    arts = sorted(glob.glob(os.path.join(ROOT, "MULTICHIP_r*.json")),
                  key=_round_no)
    if not arts:
        return None
    path = arts[-1]
    try:
        with open(path) as f:
            rec = json.load(f)
    except Exception as e:
        return {"path": os.path.basename(path), "ok": None,
                "has_gate_fingerprint": False,
                "verdict": f"unreadable driver record: {e}"}
    # a stamped run carries a parsed top-level fingerprint; the tail
    # substring is only a fallback (the 2000-char tail window can cut
    # the fingerprint line when a long traceback follows it)
    stamped = bool(rec.get("fingerprint")) or \
        "gate_fingerprint" in (rec.get("tail", "") or "")
    try:
        head = subprocess.run(["git", "-C", ROOT, "rev-parse", "HEAD"],
                              capture_output=True, text=True,
                              timeout=10).stdout.strip()
    except Exception:
        head = ""
    return {
        "path": os.path.basename(path),
        "ok": rec.get("ok"),
        "has_gate_fingerprint": stamped,
        # a missing fingerprint is AMBIGUOUS — do not assert one cause
        "verdict": ("driver record carries no gate fingerprint; one of: "
                    "(a) pre-stamp build — the record predates the "
                    f"stamped gate at HEAD {head[:12]}; (b) the run "
                    "crashed before reaching the mesh step that prints "
                    "the fingerprint; (c) the 2000-char tail window "
                    "truncated the fingerprint line behind a long "
                    "traceback.  Compare the record's git_sha/utc and "
                    "whether its tail ends mid-traceback to tell which."
                    if not stamped else
                    "driver record is fingerprint-stamped"),
    }


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    code = (
        "import __graft_entry__ as g\n"
        f"g.dryrun_multichip({n})\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    try:
        res = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                             env=env, capture_output=True, text=True,
                             timeout=900)
        rc, stdout, stderr = res.returncode, res.stdout, res.stderr
    except subprocess.TimeoutExpired as e:
        # a hung gate must still overwrite the artifact — leaving a
        # prior run's ok:true in place is the stale-record confusion
        # this script exists to eliminate
        def _s(x):
            return x.decode(errors="replace") if isinstance(x, bytes) \
                else (x or "")
        rc = -1
        stdout = _s(e.stdout)
        stderr = _s(e.stderr) + "\n[multichip_check: TIMEOUT after 900s]"
    out = (stdout or "") + (stderr or "")
    fingerprint = None
    for line in (stdout or "").splitlines():
        if line.startswith('{"gate_fingerprint"'):
            try:
                fingerprint = json.loads(line)["gate_fingerprint"]
            except Exception:
                pass
    record = {
        "n_devices": n,
        "rc": rc,
        "ok": rc == 0,
        "skipped": False,
        "tail": out[-2000:],
        "fingerprint": fingerprint,
        "driver_artifact": _diagnose_driver_artifact(),
    }
    path = os.path.join(ROOT, "MULTICHIP_LOCAL.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"multichip_check: ok={record['ok']} rc={rc} -> {path}")
    if fingerprint:
        print(f"multichip_check: fingerprint {fingerprint}")
    if record["driver_artifact"]:
        print(f"multichip_check: driver artifact "
              f"{record['driver_artifact']['path']}: "
              f"ok={record['driver_artifact']['ok']} — "
              f"{record['driver_artifact']['verdict']}")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
