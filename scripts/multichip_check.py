#!/usr/bin/env python
"""Local multichip-gate artifact writer (VERDICT r4 next-round item #1).

Reproduces the driver's invocation shape — a FRESH interpreter, env
untouched (so a present-but-broken TPU plugin is discoverable, the exact
scenario MULTICHIP_r01..r04 recorded), importing `__graft_entry__` and
calling `dryrun_multichip(8)` — and writes the result to
`MULTICHIP_LOCAL.json` at the repo root, stamped with the gate
fingerprint (git SHA, UTC time, jax version, route taken).

A driver artifact that disagrees with this one is then immediately
diagnosable: compare `git_sha`/`utc` to see whether the driver record
predates HEAD or its environment diverges.

Usage: python scripts/multichip_check.py [n_devices]
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    code = (
        "import __graft_entry__ as g\n"
        f"g.dryrun_multichip({n})\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    try:
        res = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                             env=env, capture_output=True, text=True,
                             timeout=900)
        rc, stdout, stderr = res.returncode, res.stdout, res.stderr
    except subprocess.TimeoutExpired as e:
        # a hung gate must still overwrite the artifact — leaving a
        # prior run's ok:true in place is the stale-record confusion
        # this script exists to eliminate
        def _s(x):
            return x.decode(errors="replace") if isinstance(x, bytes) \
                else (x or "")
        rc = -1
        stdout = _s(e.stdout)
        stderr = _s(e.stderr) + "\n[multichip_check: TIMEOUT after 900s]"
    out = (stdout or "") + (stderr or "")
    fingerprint = None
    for line in (stdout or "").splitlines():
        if line.startswith('{"gate_fingerprint"'):
            try:
                fingerprint = json.loads(line)["gate_fingerprint"]
            except Exception:
                pass
    record = {
        "n_devices": n,
        "rc": rc,
        "ok": rc == 0,
        "skipped": False,
        "tail": out[-2000:],
        "fingerprint": fingerprint,
    }
    path = os.path.join(ROOT, "MULTICHIP_LOCAL.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"multichip_check: ok={record['ok']} rc={rc} -> {path}")
    if fingerprint:
        print(f"multichip_check: fingerprint {fingerprint}")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
