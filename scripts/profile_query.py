"""Profile one query through the engine and print its QueryProfile.

Folds the old ad-hoc diagnostics (profile_q27.py's per-lane timing,
profile_agg_stages.py's stage walk) into the first-class observability
subsystem (utils/profile.py): run the query with
`spark.rapids.sql.profile.enabled`, then print the
EXPLAIN-with-metrics plan report, the wall-clock breakdown (compute vs
pipeline wait vs shuffle vs compile vs retry-block), and the slowest
spans — and write the Chrome trace-event JSON for Perfetto.

Usage:
    python scripts/profile_query.py                      # TPC-H q5
    python scripts/profile_query.py --query 1 --scale 100000
    python scripts/profile_query.py --suite tpcxbb --query q27
    python scripts/profile_query.py --chrome /tmp/q5.trace.json \
        --events /tmp/q5.events.jsonl --runs 2
"""
import argparse
import os
import sys
import time

# runnable as `python scripts/profile_query.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _run_tpch(query: int, scale: int, conf, runs: int):
    import numpy as np
    from spark_rapids_tpu.models.tpch_bench import run_query
    from spark_rapids_tpu.models.tpch_data import gen_tables
    tables = gen_tables(np.random.default_rng(11), scale)
    out = None
    for _ in range(max(1, runs)):
        t0 = time.perf_counter()
        out = run_query(query, tables, engine="tpu", conf=conf)
        print(f"collect: {(time.perf_counter() - t0) * 1e3:.1f} ms "
              f"({len(out)} rows)")
    return out


def _run_tpcxbb(query: str, scale: int, conf, runs: int):
    import numpy as np
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.models import tpcxbb
    from spark_rapids_tpu.models.data_util import make_sources
    from spark_rapids_tpu.plan import accelerate, collect
    rng = np.random.default_rng(21)
    n = scale
    rv = tpcxbb.gen_reviews(rng, n, n // 10, n // 4)
    srcs = make_sources({"product_reviews": rv},
                        {"product_reviews": tpcxbb.REVIEWS_SCHEMA}, 2)
    plan = accelerate(tpcxbb.QUERIES[query](srcs, lambda p: None), conf)
    out = None
    for _ in range(max(1, runs)):
        with C.session(conf):
            t0 = time.perf_counter()
            out = collect(plan, conf)
            print(f"collect: {(time.perf_counter() - t0) * 1e3:.1f} ms "
                  f"({len(out)} rows)")
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", choices=("tpch", "tpcxbb"),
                    default="tpch")
    ap.add_argument("--query", default="5",
                    help="TPC-H query number, or a TPCx-BB key like q27")
    ap.add_argument("--scale", type=int, default=0,
                    help="rows (default: 100000 tpch / 2**20 tpcxbb)")
    ap.add_argument("--runs", type=int, default=2,
                    help="collects per profile; the LAST run's profile "
                    "is reported (run 1 pays cold compiles)")
    ap.add_argument("--chrome", default="",
                    help="Chrome trace output path (Perfetto-loadable)")
    ap.add_argument("--events", default="",
                    help="JSONL event log output path")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest spans to print")
    ap.add_argument("--kernels", action="store_true",
                    help="per-kernel attribution: time every dispatch "
                    "(sampleRate=1, pipelining off so the kernel sum "
                    "is comparable to the compute bucket) and print "
                    "the '-- kernels --' roofline table")
    ap.add_argument("--memory", action="store_true",
                    help="HBM residency: print the residency report "
                    "(high-water mark, peak-instant composition by "
                    "provenance site, leak verdict) plus the "
                    "DeviceManager accounting snapshot")
    args = ap.parse_args()

    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.utils import profile as P
    kv = {
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        "spark.rapids.sql.incompatibleOps.enabled": True,
        "spark.rapids.sql.profile.enabled": True,
    }
    if args.kernels:
        kv.update({
            "spark.rapids.sql.profile.kernels.enabled": True,
            "spark.rapids.sql.profile.kernels.sampleRate": 1,
            "spark.rapids.sql.pipeline.enabled": False,
        })
    if args.memory:
        kv["spark.rapids.sql.profile.residency.enabled"] = True
    conf = C.RapidsConf(kv)
    if args.suite == "tpch":
        _run_tpch(int(args.query), args.scale or 100_000, conf,
                  args.runs)
    else:
        _run_tpcxbb(str(args.query), args.scale or (1 << 20), conf,
                    args.runs)

    prof = P.last_profile()
    if prof is None:
        raise SystemExit("no QueryProfile recorded — is "
                         "spark.rapids.sql.profile.enabled on?")
    print()
    print(prof.explain())
    if args.memory:
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        from spark_rapids_tpu.utils import residency as RS
        print("\n== HBM residency ==")
        print(RS.format_report(prof.residency))
        dm = DeviceManager.peek()
        if dm is not None:
            snap = dm.snapshot()
            print(f"accounting: store={snap['store_bytes']} "
                  f"reserved={snap['reserved_bytes']} "
                  f"in_use={snap['in_use_bytes']} "
                  f"budget={snap['budget']} "
                  f"headroom={snap['admission_headroom_bytes']} "
                  f"underflows={snap['store_bytes_underflow']}")
        if RS.enabled():
            print(f"live tracked now: {RS.by_tier() or '(none)'}")
    print(f"\nspan depth: {prof.span_depth()}  spans: "
          f"{len(prof.spans)}  events: {len(prof.events)}  threads: "
          f"{len({s.thread_id for s in prof.spans})}")
    if args.chrome:
        path = prof.write_chrome_trace(args.chrome)
        print(f"chrome trace written to {path} "
              f"(open in Perfetto / chrome://tracing)")
    if args.events:
        path = prof.write_event_log(args.events)
        print(f"event log written to {path}")


if __name__ == "__main__":
    main()
