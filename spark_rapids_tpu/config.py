"""Typed configuration registry.

Mirrors the reference's `RapidsConf.scala` (SURVEY.md §2.14): typed entries
with defaults, per-operator auto-derived enable keys, and self-documenting
`help()` output that generates docs/configs.md.  Keys keep the
`spark.rapids.*` naming so users of the reference find the same surface.
"""
from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from typing import Any, Callable, Optional

_REGISTRY: dict[str, "ConfEntry"] = {}


@dataclasses.dataclass
class ConfEntry:
    key: str
    default: Any
    doc: str
    converter: Callable[[str], Any]
    internal: bool = False

    def get(self, conf: "RapidsConf") -> Any:
        return conf.get(self.key, self.default)


def _register(entry: ConfEntry) -> ConfEntry:
    _REGISTRY[entry.key] = entry
    return entry


def _bool(s):
    return s if isinstance(s, bool) else str(s).lower() in ("true", "1", "yes")


def conf(key: str, default: Any, doc: str, internal: bool = False) -> ConfEntry:
    conv = {bool: _bool, int: int, float: float, str: str}[type(default)]
    return _register(ConfEntry(key, default, doc, conv, internal))


# --- core enables (reference RapidsConf.scala:271-690) ----------------------
SQL_ENABLED = conf("spark.rapids.sql.enabled", True,
                   "Enable or disable TPU SQL acceleration entirely.")
EXPLAIN = conf("spark.rapids.sql.explain", "NONE",
               "Explain why parts of a plan were not placed on the TPU: "
               "NONE, NOT_ON_GPU, ALL.")
INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled", False,
                        "Enable operators producing results that differ "
                        "slightly from Spark (e.g. float aggregation order).")
IMPROVED_FLOAT = conf("spark.rapids.sql.improvedFloatOps.enabled", False,
                      "Enable improved-precision float transcendental ops.")
HAS_NANS = conf("spark.rapids.sql.hasNans", True,
                "Assume floating point data may contain NaNs.")
VARIABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled", False,
                          "Allow float aggregations whose result can vary "
                          "with evaluation order.")
CASTS_FLOAT_TO_STRING = conf("spark.rapids.sql.castFloatToString.enabled",
                             False, "Enable float->string cast (formatting "
                             "differs slightly from Spark).")
CASTS_STRING_TO_FLOAT = conf("spark.rapids.sql.castStringToFloat.enabled",
                             False, "Enable string->float cast.")
CASTS_STRING_TO_TS = conf("spark.rapids.sql.castStringToTimestamp.enabled",
                          False, "Enable string->timestamp cast.")
REPLACE_SORT_MERGE_JOIN = conf(
    "spark.rapids.sql.replaceSortMergeJoin.enabled", True,
    "Replace SortMergeJoin with a TPU shuffled hash join.")
TEST_ENABLED = conf("spark.rapids.sql.test.enabled", False,
                    "Testing hook: fail if an op expected on TPU falls back.",
                    internal=True)
TEST_ALLOWED_NONGPU = conf("spark.rapids.sql.test.allowedNonGpu", "",
                           "Comma-separated ops allowed on CPU in test mode.",
                           internal=True)
EXPORT_COLUMNAR_RDD = conf("spark.rapids.sql.exportColumnarRdd", False,
                           "Expose the final columnar output for ML "
                           "integration (ColumnarRdd).")
SPARK_VERSION = conf("spark.rapids.tpu.sparkVersion", "3.0.1",
                     "Spark version the session emulates; selects the "
                     "shim set (reference ShimLoader.scala:26-61).")
ALLOW_UNKNOWN_SPARK_VERSION = conf(
    "spark.rapids.tpu.allowUnknownSparkVersion", False,
    "When no shim matches the Spark version exactly, fall back to the "
    "nearest same-minor shim with a warning instead of failing "
    "(default: fail, like the reference ShimLoader).")
MAX_BATCH_ROWS = conf("spark.rapids.tpu.batchMaxRows", 65536,
                      "Row cap per device batch at upload/scan/coalesce "
                      "boundaries.  Bounds the set of compiled kernel "
                      "shapes: every operator compiles at a few bucketed "
                      "capacities <= this and streams larger data as "
                      "multiple batches (XLA:TPU sort compile time grows "
                      "steeply with capacity).")
PRUNE_COLUMNS = conf("spark.rapids.tpu.columnPruning.enabled", True,
                     "Prune unreferenced columns at scan/source leaves "
                     "before plan rewrite (the role Catalyst's "
                     "ColumnPruning plays for the reference).")

# --- batch sizing / memory (reference :271-360) -----------------------------
BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes", 2147483136,
                        "Target device batch size in bytes for coalescing.")
MAX_READER_BATCH_ROWS = conf("spark.rapids.sql.reader.batchSizeRows",
                             2147483647, "Max rows per scan batch.")
MAX_READER_BATCH_BYTES = conf("spark.rapids.sql.reader.batchSizeBytes",
                              2147483136, "Soft max bytes per scan batch.")
CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentGpuTasks", 1,
                            "Number of tasks that may hold the accelerator "
                            "concurrently (GpuSemaphore analog).")
HBM_ALLOC_FRACTION = conf("spark.rapids.memory.gpu.allocFraction", 0.9,
                          "Fraction of HBM to dedicate to the arena pool.")
HBM_RESERVE = conf("spark.rapids.memory.gpu.reserve", 1073741824,
                   "HBM bytes kept free for XLA scratch/fusion temporaries.")
HBM_BUDGET_BYTES = conf(
    "spark.rapids.memory.hbmBudgetBytes", 0,
    "Hard cap (bytes) on the accounted HBM arena budget, applied AFTER "
    "the allocFraction/reserve arithmetic: the effective budget is "
    "min(total*allocFraction - reserve, this).  0 (default) disables "
    "the cap.  This is the out-of-core lever: capping the budget below "
    "an operator's working set makes DeviceManager.try_reserve report "
    "no headroom, which routes sort/join/aggregate through their "
    "external (spill-backed) algorithms instead of split-retrying to "
    "the row floor — bounded-HBM execution on data larger than device "
    "memory.")
HOST_SPILL_STORAGE = conf("spark.rapids.memory.host.spillStorageSize",
                          1073741824, "Host memory for spilled device data.")
PINNED_POOL_SIZE = conf("spark.rapids.memory.pinnedPool.size", 0,
                        "Pinned host staging pool bytes (0 = disabled).")
HBM_DEBUG = conf("spark.rapids.memory.gpu.debug", "NONE",
                 "Arena allocation debug logging: NONE, STDOUT, STDERR.")
RETRY_MIN_SPLIT_ROWS = conf(
    "spark.rapids.memory.retry.minSplitRows", 1024,
    "Floor for OOM split-and-retry: a batch at or below this many rows "
    "is not subdivided further; reservation failure there takes the "
    "retry.fallback path instead (memory/retry.py harness; the role of "
    "the reference's SplitAndRetryOOM minimum split size).")
RETRY_FALLBACK = conf(
    "spark.rapids.memory.retry.fallback", "bestEffort",
    "What happens when a batch at the minimum split size still cannot "
    "be reserved: bestEffort runs it unreserved (the accounted arena "
    "is advisory; a true device OOM surfaces as an XLA allocation "
    "error), error fails the query with an actionable message.  Never "
    "a silent wrong answer.")
OOM_INJECT_RATE = conf(
    "spark.rapids.memory.faultInjection.oomRate", 0.0,
    "TEST ONLY: probability that a device-memory reservation is forced "
    "to fail, exercising the spill -> retry -> split-and-retry -> "
    "floor-fallback lattice on CPU CI without a real HBM-sized "
    "workload (the memory-layer sibling of the shuffle transport "
    "fault injector).  0 disables injection.")
OOM_INJECT_SEED = conf(
    "spark.rapids.memory.faultInjection.seed", 0,
    "Deterministic seed for OOM fault injection.")
OOM_INJECT_MAX = conf(
    "spark.rapids.memory.faultInjection.maxInjections", 1024,
    "Hard cap on injected reservation failures per injector lifetime, "
    "guaranteeing forward progress in soak loops even at oomRate=1.0 "
    "(0 = unlimited).")

# --- query watchdog (utils/watchdog.py) --------------------------------------
WATCHDOG_ENABLED = conf(
    "spark.rapids.sql.watchdog.enabled", True,
    "Detect hung queries: every long-lived activity (prefetch "
    "producers, shuffle servers and fetch loops, collective-exchange "
    "dispatches, AQE stage fills, pyudf workers, XLA compiles) "
    "registers a progress heartbeat; a scanner thread that sees no "
    "progress past the activity's deadline class emits one diagnostic "
    "dump and cancels the query cooperatively, raising a descriptive "
    "TpuQueryTimeout instead of hanging forever.  The liveness analog "
    "of Spark's task-level speculation/kill machinery, which a "
    "standalone engine otherwise lacks.")
WATCHDOG_POLL_INTERVAL = conf(
    "spark.rapids.sql.watchdog.pollInterval", 1.0,
    "Seconds between watchdog scans of registered heartbeats.  Bounds "
    "detection latency at deadline + pollInterval; lower values only "
    "matter with sub-second deadlines (tests).")
WATCHDOG_TASK_TIMEOUT = conf(
    "spark.rapids.sql.watchdog.taskTimeout", 300.0,
    "Deadline (seconds) for task-class activities: prefetch producer "
    "loops, shuffle server/fetch handlers, AQE stage fills, pyudf "
    "workers.  An activity making no progress for this long is "
    "declared hung and the query is cancelled with a diagnostic dump.")
WATCHDOG_COLLECTIVE_TIMEOUT = conf(
    "spark.rapids.sql.watchdog.collectiveTimeout", 120.0,
    "Deadline (seconds) for collective-class activities (ICI "
    "all-to-all exchange dispatches).  Collectives block ALL mesh "
    "participants when one goes dark, so their deadline is tighter "
    "than the task class.")
WATCHDOG_COMPILE_TIMEOUT = conf(
    "spark.rapids.sql.watchdog.compileTimeout", 600.0,
    "Deadline (seconds) for XLA kernel compiles (and single-flight "
    "waiters parked on another thread's compile).  Sort-heavy shapes "
    "legitimately compile for minutes; raise this before blaming a "
    "pathological compile.")
WATCHDOG_DUMP_ON_TIMEOUT = conf(
    "spark.rapids.sql.watchdog.dumpOnTimeout", True,
    "Emit one diagnostic dump (all thread stacks, semaphore holders, "
    "prefetch queue stats, in-flight shuffle fetches, hang-injection "
    "state) when the watchdog declares a timeout; the dump rides on "
    "the raised TpuQueryTimeout and is logged at ERROR.")
HANG_INJECT_SITE = conf(
    "spark.rapids.memory.faultInjection.hangSite", "",
    "TEST ONLY: inject a hang at the named site so watchdog "
    "detection, cancellation, and resource release are testable "
    "without a real dead peer or wedged compile.  Sites: producer "
    "(prefetch producer loop), collective (mesh exchange dispatch), "
    "shuffle-server (chunk emit stall), pyudf (wedged UDF worker), "
    "compile (KernelCache builder).  The injected hang blocks until "
    "the query's CancelToken fires — like a Spark task kill, "
    "cancellation is cooperative.  Empty disables.", internal=True)
HANG_INJECT_AFTER = conf(
    "spark.rapids.memory.faultInjection.hangAfterBatches", 0,
    "TEST ONLY: the injected hang engages after this many units of "
    "progress (batches produced, chunks served, compiles started) at "
    "the configured hangSite.", internal=True)
SLOW_INJECT_SITE = conf(
    "spark.rapids.memory.faultInjection.slowSite", "",
    "TEST ONLY: inject a seeded delay at the named site so the "
    "tail-tolerance layer (speculation, hedged fetches) is testable "
    "without a real degraded peer — the *slow* sibling of the "
    "kill/hang/corrupt injectors.  Sites: map-task (per batch of a "
    "manager-lane map task), shuffle-server (per served buffer).  The "
    "delay is cancellable (a losing speculative/hedged attempt parked "
    "in it wakes immediately on cancellation).  Empty disables.",
    internal=True)
SLOW_INJECT_FACTOR = conf(
    "spark.rapids.memory.faultInjection.slowFactor", 0.0,
    "TEST ONLY: slowdown multiplier for slowSite — each unit of work "
    "at the site sleeps (slowFactor - 1) x slowUnitMs, so factor 10 "
    "models a peer running 10x slower than nominal.  <= 1 disables.",
    internal=True)
SLOW_INJECT_SEED = conf(
    "spark.rapids.memory.faultInjection.slowSeed", 0,
    "TEST ONLY: seed for the slow injector's +/-25% delay jitter — "
    "deterministic straggler schedules in soak tests.", internal=True)
SLOW_INJECT_VICTIM = conf(
    "spark.rapids.memory.faultInjection.slowVictim", "",
    "TEST ONLY: executor id the slow injector targets (e.g. "
    "'local-1'); empty slows every executor that reaches the site.",
    internal=True)
SLOW_INJECT_UNIT_MS = conf(
    "spark.rapids.memory.faultInjection.slowUnitMs", 20.0,
    "TEST ONLY: nominal per-unit work time (ms) the slowFactor "
    "multiplies — the injected delay per batch/buffer is "
    "(slowFactor - 1) x this.", internal=True)
SPILL_CORRUPT_RATE = conf(
    "spark.rapids.memory.faultInjection.spillCorruptRate", 0.0,
    "TEST ONLY: probability that a freshly written spill file has one "
    "payload byte flipped on disk (after the CRC frame was written), "
    "proving the disk re-read's integrity check surfaces "
    "SpillCorruptionError instead of deserializing garbage.  Seeded "
    "by faultInjection.seed.  0 disables.", internal=True)

# --- out-of-core execution (memory/oocore.py) --------------------------------
OOCORE_ENABLED = conf(
    "spark.rapids.memory.oocore.enabled", True,
    "Degrade gracefully to external algorithms when an operator's "
    "working set exceeds the HBM budget's headroom: sort spills sorted "
    "runs and k-way merges them back in budget-sized windows, hash "
    "join grace-partitions the build AND probe sides by key hash and "
    "joins partition pairs that fit, and hash aggregate spills partial "
    "group state and re-merges it.  Runs travel the existing "
    "device->host->disk spill tiers (every hop on the movement "
    "ledger's spill edges).  OOM split-and-retry remains the inner "
    "lattice; out-of-core is the outer ring engaged BEFORE the "
    "retry.fallback path.  Off: the pre-out-of-core behavior (split "
    "to minSplitRows, then bestEffort|error).")
OOCORE_WINDOW_FRACTION = conf(
    "spark.rapids.memory.oocore.windowFraction", 0.5,
    "Fraction of the HBM budget one operator may hold resident before "
    "degrading to its external algorithm — and the size of each merge "
    "window when it does.  The working-set estimate is real "
    "accounting (2x device batch bytes, the same estimate the OOM "
    "harness reserves with) judged against try_reserve headroom, not "
    "a guess.  Smaller values spill earlier and merge in more passes; "
    "larger values risk the inner retry lattice engaging first.")
OOCORE_GRACE_PARTITIONS = conf(
    "spark.rapids.memory.oocore.gracePartitions", 8,
    "Fan-out of one grace-hash partitioning pass: build and probe "
    "sides split into this many key-hash partitions, each joined "
    "independently (partition pairs are key-disjoint).  A partition "
    "whose build side still exceeds the window re-partitions "
    "recursively with a depth-salted hash, up to "
    "oocore.maxRecursionDepth.")
OOCORE_MAX_RECURSION = conf(
    "spark.rapids.memory.oocore.maxRecursionDepth", 4,
    "Bound on grace-hash re-partitioning recursion (and on external "
    "sort/aggregate re-spill rounds).  A partition that cannot be "
    "made to fit within this depth — pathological key skew, e.g. one "
    "key carrying the whole build side — fails with a descriptive "
    "error naming the skewed partition and the knobs, never a hang "
    "and never partial data.")
OOCORE_RUN_REPLICAS = conf(
    "spark.rapids.memory.oocore.runReplicas", 1,
    "Copies written per spilled run.  At 2+, a SpillCorruption on "
    "re-read (disk rot, faultInjection.spillCorruptRate) quarantines "
    "the corrupt buffer and recovers from a replica instead of "
    "failing the query (numSpillCorruptionsRecovered counts these); "
    "at 1 recovery needs a recompute closure or the corruption "
    "surfaces as the descriptive SpillCorruption error.  Replicas "
    "cost spill-tier capacity, not HBM.")

# --- query profiles (utils/profile.py) ---------------------------------------
PROFILE_ENABLED = conf(
    "spark.rapids.sql.profile.enabled", False,
    "Record a per-query observability profile: a span tree (query -> "
    "stage/exchange -> operator -> batch/compile/shuffle-fetch/retry) "
    "with thread-propagated parenting, dual-emitted to "
    "jax.profiler.TraceAnnotation (xprof captures still work) and to an "
    "in-process ring buffer, plus a structured event log (retries, "
    "fetch failures, blacklists, watchdog dumps, cancellations — all "
    "carrying the query id).  On collect() the spans, events, an "
    "EXPLAIN-with-metrics plan report, and a wall-clock breakdown "
    "(compute vs pipeline wait vs shuffle vs compile vs retry-block) "
    "assemble into a QueryProfile kept in a bounded history.  Disabled "
    "(default) the batch hot loop allocates no tracer objects.")
PROFILE_HISTORY_SIZE = conf(
    "spark.rapids.sql.profile.historySize", 16,
    "How many completed QueryProfiles to retain in the in-process "
    "history (utils.profile.profile_history), queryable from tests and "
    "bench harnesses.  Oldest profiles are dropped first.")
PROFILE_EVENT_LOG_PATH = conf(
    "spark.rapids.sql.profile.eventLog.path", "",
    "When set, every profiled query appends its structured event "
    "records (span open/close, retries, fetch failures, blacklists, "
    "watchdog dumps, cancellations) to this file as JSON lines, each "
    "carrying the query id.  Empty disables the file sink; the "
    "in-process QueryProfile.events view is always available.")
PROFILE_CHROME_TRACE_PATH = conf(
    "spark.rapids.sql.profile.chromeTrace.path", "",
    "When set, every profiled query writes its span tree to this path "
    "as Chrome trace-event JSON (loadable in Perfetto / "
    "chrome://tracing).  A '{query_id}' placeholder in the path is "
    "substituted so consecutive queries do not overwrite each other.  "
    "Empty disables the file sink; QueryProfile.chrome_trace() always "
    "serves the same payload in-process.")
MOVEMENT_ENABLED = conf(
    "spark.rapids.sql.profile.movement.enabled", True,
    "When profiling is on, additionally record the per-query "
    "data-movement ledger (utils/movement.py): bytes + duration on "
    "every edge where data crosses a boundary — host->device uploads, "
    "device->host readbacks, spill tier migrations, shuffle wire "
    "bytes (compressed AND uncompressed), and ICI collective "
    "payloads.  The QueryProfile then carries a movement report "
    "(per-edge totals, effective GB/s vs roofline, compression "
    "ratios), Chrome-trace counter tracks, and data_movement event "
    "records.  Off: the profiler records time only, as before.")
MOVEMENT_ROOFLINE_GBPS = conf(
    "spark.rapids.sql.profile.movement.rooflineGBps", 0.0,
    "Bandwidth ceiling (GB/s) the movement report computes "
    "utilization against, for every edge.  0 (default) resolves the "
    "per-edge ceilings through the shared roofline table "
    "(spark.rapids.sql.profile.roofline.*, utils/roofline.py — the "
    "same source kernelprof judges kernels against); a non-zero "
    "value overrides ALL edges at once, e.g. with a probed number "
    "(bench.py's probe_hbm_bandwidth) to judge every edge against "
    "measured hardware instead.")
MOVEMENT_MIN_EVENT_BYTES = conf(
    "spark.rapids.sql.profile.movement.minEventBytes", 65536,
    "Movement records at or above this many bytes also land in the "
    "structured event log as data_movement records (correlatable with "
    "retries, fetch failures, and watchdog dumps by query id); "
    "smaller records are aggregated into the ledger only, keeping the "
    "event ring for interesting transfers.  0 logs every record.")
RESIDENCY_ENABLED = conf(
    "spark.rapids.sql.profile.residency.enabled", True,
    "When profiling is on, additionally run the HBM residency ledger "
    "(utils/residency.py): every tracked device-resident allocation — "
    "tiered-store buffers (including shuffle catalog buffers), OOM-"
    "harness reservations, pinned SPMD gang inputs — registers "
    "per-buffer provenance (query id, operator site, size, tier) on "
    "creation and retires it on free/spill.  Profiled queries get a "
    "'-- residency --' section (HBM high-water mark, peak-instant "
    "composition by site/tier, leak verdict), Perfetto "
    "residency:<site> counter tracks, and an end-of-query leak check "
    "that dumps still-resident buffers with provenance; the "
    "slow-query log aggregates observed high-water marks per plan "
    "fingerprint (the feed learned admission budgets consume) and "
    "telemetry exports hbm_resident_bytes{tier} plus per-site "
    "gauges.  Tracking is process-sticky once the first residency-"
    "enabled query runs; off (default until then) every hook is one "
    "global read and allocates nothing.")
RESIDENCY_TIMELINE_SIZE = conf(
    "spark.rapids.sql.profile.residency.timelineSize", 4096,
    "Bound on per-query residency timeline samples (one per tracked "
    "alloc/free) backing the Perfetto residency:<site> counter "
    "tracks; oldest samples are dropped first.  The high-water mark "
    "and peak composition are exact regardless of this bound.")
RESIDENCY_LEAK_DUMP = conf(
    "spark.rapids.sql.profile.residency.leakDump", 8,
    "How many leaked buffers (still resident at query end) the "
    "residency report and event log render with full provenance "
    "(site, tier, kind, size, age); the leak COUNT is always exact.")
KERNELPROF_ENABLED = conf(
    "spark.rapids.sql.profile.kernels.enabled", False,
    "Per-kernel performance attribution (utils/kernelprof.py): every "
    "compiled executable in the KernelCache is wrapped so a sampled "
    "fraction of its dispatches is timed with a device sync "
    "(block_until_ready bracket, accounted via note_host_sync) and "
    "joined with XLA cost_analysis()/memory_analysis() — FLOPs, bytes "
    "accessed, temp allocation, captured once per kernel at its first "
    "dispatch (the actual compile point) — into achieved GFLOP/s and "
    "GB/s vs the conf-overridable roofline table "
    "(spark.rapids.sql.profile.roofline.*).  Profiled queries "
    "additionally get a '-- kernels --' section in their QueryProfile "
    "(top-N kernels by cumulative device time, roofline %, compile "
    "ms, dispatch counts, owning plan nodes) plus Perfetto kernel "
    "tracks, and the slow-query log names each fingerprint's hottest "
    "kernel.  Off (default): kernels dispatch raw — zero wrappers, "
    "zero syncs, bit-exact.")
KERNELPROF_SAMPLE_RATE = conf(
    "spark.rapids.sql.profile.kernels.sampleRate", 8,
    "Time every Nth dispatch of each kernel (1 = every dispatch).  "
    "Each timed dispatch pays one block_until_ready device sync, so "
    "the rate trades attribution accuracy (unsampled dispatches are "
    "estimated by scaling the sampled mean) against pipeline-overlap "
    "perturbation; 8 keeps measured overhead well inside the "
    "profiler's <2% budget while a rate of 1 makes the per-kernel "
    "device-time sum directly comparable to the wall-clock "
    "breakdown's compute category.")
KERNELPROF_COST_ANALYSIS = conf(
    "spark.rapids.sql.profile.kernels.costAnalysis", True,
    "Capture XLA cost_analysis()/memory_analysis() (FLOPs, bytes "
    "accessed, argument/output/temp sizes) once per kernel at its "
    "first dispatch, enabling the achieved-GFLOP/s / GB/s roofline "
    "join.  Capture re-lowers the jitted function once (a second "
    "trace+compile per kernel); disable to keep timing-only "
    "attribution on compile-dominated workloads.")
KERNELPROF_TOP_N = conf(
    "spark.rapids.sql.profile.kernels.topN", 12,
    "How many kernels (by cumulative attributed device time) the "
    "QueryProfile's '-- kernels --' section renders; the full "
    "per-fingerprint table stays queryable via "
    "QueryProfile.kernels and utils.kernelprof.catalog().")

# --- shared roofline table (utils/roofline.py) --------------------------------
# ONE conf-overridable source for every bandwidth/compute ceiling the
# instruments judge against: the movement ledger's per-edge GB/s
# utilization AND kernelprof's achieved-GFLOP/s / GB/s join both
# resolve through utils/roofline.py (two diverging nominal tables was
# the bug class this replaces).
ROOFLINE_UPLOAD_GBPS = conf(
    "spark.rapids.sql.profile.roofline.uploadGBps", 32.0,
    "Nominal host->device bandwidth ceiling (GB/s) for the movement "
    "report's upload edge (PCIe-gen4-x16-class / tunnel attachment).")
ROOFLINE_READBACK_GBPS = conf(
    "spark.rapids.sql.profile.roofline.readbackGBps", 32.0,
    "Nominal device->host bandwidth ceiling (GB/s) for the movement "
    "report's readback edge.")
ROOFLINE_SPILL_GBPS = conf(
    "spark.rapids.sql.profile.roofline.spillGBps", 32.0,
    "Nominal bandwidth ceiling (GB/s) for spill tier migrations "
    "(device->host->disk hops share the host-link ceiling).")
ROOFLINE_WIRE_GBPS = conf(
    "spark.rapids.sql.profile.roofline.wireGBps", 12.5,
    "Nominal shuffle-wire bandwidth ceiling (GB/s); the default "
    "models a 100 Gb/s DCN NIC.")
ROOFLINE_COLLECTIVE_GBPS = conf(
    "spark.rapids.sql.profile.roofline.collectiveGBps", 400.0,
    "Nominal ICI collective bandwidth ceiling (GB/s); the default is "
    "the v5e per-chip ICI nominal.")
ROOFLINE_HBM_GBPS = conf(
    "spark.rapids.sql.profile.roofline.hbmGBps", 819.0,
    "HBM bandwidth ceiling (GB/s) kernelprof judges per-kernel "
    "achieved GB/s (XLA bytes-accessed / device time) against; the "
    "default is the v5e nominal.  Set to a probed number (bench.py "
    "hbm_probe_gbps) to judge against measured hardware.")
ROOFLINE_PEAK_GFLOPS = conf(
    "spark.rapids.sql.profile.roofline.peakGflops", 197000.0,
    "Compute ceiling (GFLOP/s) kernelprof judges per-kernel achieved "
    "GFLOP/s against; the default is the v5e bf16 nominal (197 "
    "TFLOP/s).  A kernel's roofline utilization is the max of its "
    "compute fraction and its HBM-bandwidth fraction — whichever "
    "resource binds.")

PROFILE_EVENT_LOG_MAX_BYTES = conf(
    "spark.rapids.sql.profile.eventLog.maxBytes", 134217728,
    "Size-based rotation bound for the profile event-log JSONL sink "
    "(and the telemetry snapshot records riding it): when an append "
    "would push the file past this many bytes it is rotated to "
    "<path>.1 (older rotations shift to .2, .3, ...) so long-running "
    "serving never grows one unbounded file.  0 disables rotation "
    "(the pre-rotation behavior).")
PROFILE_EVENT_LOG_KEEP_FILES = conf(
    "spark.rapids.sql.profile.eventLog.keepFiles", 4,
    "How many rotated event-log files (<path>.1 .. <path>.N) to "
    "retain; the oldest is dropped at each rotation.  0 discards the "
    "full file at rotation instead of keeping any history.")

# --- engine-wide telemetry (utils/telemetry.py) -------------------------------
TELEMETRY_ENABLED = conf(
    "spark.rapids.sql.telemetry.enabled", False,
    "Run the process-wide telemetry layer: a live metrics registry "
    "(HBM budget/in-use and the admission ledger, TPU semaphore "
    "holds/waiters, scheduler queue depth and admission counters, "
    "kernel-cache size/evictions/compile time, prefetch hits/stalls, "
    "in-flight shuffle fetches, speculation/recovery counters, spill "
    "tier sizes, cumulative data-movement edge bytes) plus a low-rate "
    "background sampler that builds a device-utilization timeline — "
    "each sample attributed to busy-compute or a named idle cause "
    "(queue wait, semaphore wait, pipeline stall, host sync, compile, "
    "shuffle wait, truly idle).  Surfaced as a Prometheus text "
    "endpoint (telemetry.port), periodic JSONL snapshots on the "
    "profile event-log sink, and a slow-query log aggregated by plan "
    "fingerprint.  Disabled (default) every hook is a single "
    "module-global read and allocates nothing.")
TELEMETRY_PORT = conf(
    "spark.rapids.sql.telemetry.port", 0,
    "TCP port for the opt-in HTTP exporter (binds 127.0.0.1): GET "
    "/metrics serves Prometheus text exposition format, GET "
    "/telemetry a JSON snapshot (gauges + utilization summary + "
    "slow-query log).  0 (default) starts no server; the in-process "
    "views (utils.telemetry.prometheus_text / snapshot) are always "
    "available while telemetry is enabled.")
TELEMETRY_SAMPLE_PERIOD_MS = conf(
    "spark.rapids.sql.telemetry.samplePeriodMs", 100.0,
    "Period of the utilization sampler: each tick attributes the "
    "instant to busy-compute or a named idle cause using the "
    "already-instrumented heartbeats, semaphore, scheduler queue, "
    "prefetch queues, and in-flight fetches.  Low-rate by design — "
    "at the default 100ms a sample costs a handful of lock-free "
    "reads, far inside the telemetry overhead budget (<2%).")
TELEMETRY_TIMELINE_SIZE = conf(
    "spark.rapids.sql.telemetry.timelineSize", 4096,
    "Bound on retained utilization-timeline samples (a ring buffer; "
    "cause PERCENTAGES aggregate over the whole process lifetime "
    "regardless).  4096 samples at the default period is ~7 minutes "
    "of full-resolution timeline.")
TELEMETRY_SNAPSHOT_PERIOD_S = conf(
    "spark.rapids.sql.telemetry.snapshotPeriodS", 10.0,
    "Period of the JSONL telemetry snapshots (gauges + utilization "
    "summary) appended to the profile event-log sink "
    "(spark.rapids.sql.profile.eventLog.path) with kind="
    "'telemetry_snapshot'.  0 disables periodic snapshots; snapshots "
    "also require the event-log path to be set.")
TELEMETRY_SLOW_QUERY_LOG_SIZE = conf(
    "spark.rapids.sql.telemetry.slowQueryLog.size", 64,
    "How many distinct plan fingerprints the slow-query log retains "
    "(least-recently-updated dropped first).  Each entry aggregates "
    "the completed QueryProfiles of one plan shape: run count, "
    "p50/p95/max wall clock, and the top idle cause from the "
    "wall-clock breakdown.  Requires spark.rapids.sql.profile.enabled "
    "on the queries to be aggregated.")

# --- concurrent multi-query serving (exec/scheduler.py) ----------------------
SCHED_ENABLED = conf(
    "spark.rapids.sql.scheduler.enabled", True,
    "Admission-control concurrent queries against the accounted HBM "
    "budget: each top-level collect declares an HBM budget estimate "
    "(scheduler.queryBudgetBytes) and is admitted only while the sum "
    "of admitted budgets fits the device budget and fewer than "
    "scheduler.maxConcurrentQueries queries are in flight; otherwise "
    "it waits FIFO in a bounded queue and is shed with a descriptive "
    "TpuQueryRejected when the queue is full — queueing at the front "
    "door instead of thrashing the spill/retry lattice once the "
    "device is saturated.")
SCHED_MAX_CONCURRENT = conf(
    "spark.rapids.sql.scheduler.maxConcurrentQueries", 4,
    "Cap on concurrently ADMITTED queries per process (sessions, not "
    "tasks — spark.rapids.sql.concurrentGpuTasks still governs "
    "task-level device holds within each query).  Also the divisor "
    "for the default per-query budget when queryBudgetBytes is 0.")
SCHED_QUERY_BUDGET = conf(
    "spark.rapids.sql.scheduler.queryBudgetBytes", 0,
    "HBM bytes a query declares at admission (its working-set "
    "estimate, charged against the DeviceManager admission ledger "
    "for the query's lifetime).  0 derives an equal share: device "
    "budget / maxConcurrentQueries.  Declaring honestly matters in "
    "both directions: too low admits more queries than fit and "
    "pushes pressure into the OOM spill/retry lattice, too high "
    "queues queries the device could have served.")
SCHED_QUEUE_DEPTH = conf(
    "spark.rapids.sql.scheduler.queueDepth", 32,
    "Bound on queries waiting in the admission queue.  A query "
    "arriving at a full queue is rejected immediately with "
    "TpuQueryRejected (shed load early, keep latency bounded) rather "
    "than queued indefinitely.")
SCHED_QUEUE_TIMEOUT = conf(
    "spark.rapids.sql.scheduler.queueTimeout", 120.0,
    "Seconds a query may wait in the admission queue before being "
    "shed with TpuQueryRejected.  The queued wait is additionally "
    "registered as a task-class watchdog heartbeat that beats only "
    "as the queue drains, so a wedged queue produces a diagnostic "
    "dump naming every admitted query.")
RESULT_CACHE_ENABLED = conf(
    "spark.rapids.sql.scheduler.resultCache.enabled", False,
    "Cache collected query results keyed by (plan structural "
    "fingerprint, source-data identity, session-conf fingerprint) "
    "for repeated dashboard-style queries: a hit returns the cached "
    "result bit-exactly without touching the device.  Any conf "
    "change changes the key (stale-conf hits are impossible); plans "
    "with unrecognized leaves are simply not cached.  Off by "
    "default: in-memory sources are keyed by object identity, so "
    "callers that mutate source data in place must leave this off.")
RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.sql.scheduler.resultCache.maxBytes", 268435456,
    "Byte bound on the result cache (LRU eviction; host memory).  A "
    "single result larger than this is never cached.")

# --- speculative partition execution (exec/speculation.py) -------------------
SPECULATION_ENABLED = conf(
    "spark.rapids.sql.speculation.enabled", False,
    "Launch duplicate attempts of straggling manager-lane map tasks "
    "(spark.rapids.shuffle.enabled with localExecutors >= 2): a task "
    "running far past its stage's completed-task median (a *slow* "
    "watchdog classification, distinct from *hung*) is re-executed "
    "from the exchange's retained lineage on another in-process "
    "executor; whichever attempt commits its map output first wins "
    "and the loser is cancelled via its per-attempt CancelToken.  "
    "First-wins commit is epoch-guarded in the MapOutputRegistry, so "
    "a losing attempt can never publish — results stay bit-exact.  "
    "The p95/p99 lever for one degraded executor; speculation never "
    "fires on a healthy stage.")
SPECULATION_MULTIPLIER = conf(
    "spark.rapids.sql.speculation.multiplier", 3.0,
    "How many times slower than the stage's completed-task median a "
    "running task must be before a speculative duplicate launches "
    "(spark.speculation.multiplier analog).")
SPECULATION_MIN_RUNTIME_MS = conf(
    "spark.rapids.sql.speculation.minTaskRuntimeMs", 100.0,
    "A task is never speculated before running at least this long — "
    "guards against duplicating every task of a stage whose median is "
    "microseconds.")
SPECULATION_MIN_COMPLETED = conf(
    "spark.rapids.sql.speculation.minCompletedTasks", 2,
    "Completed tasks the stage needs before its median is trusted for "
    "slow classification (spark.speculation.quantile analog: no "
    "speculation while the baseline is unknown).")

# --- whole-stage fusion (plan/fusion.py) -------------------------------------
FUSION_ENABLED = conf(
    "spark.rapids.sql.fusion.enabled", True,
    "Collapse fusible operator chains between pipeline breaks "
    "(project->filter->project, and project/filter chains feeding a "
    "partial or complete aggregation's update lane) into ONE jitted "
    "XLA program per stage: the per-operator expression evaluators "
    "compose into a single kernel, so intermediate ColumnarBatch "
    "materialization and per-operator dispatch disappear from the hot "
    "path.  The composed expression DAG is simplified "
    "(cross-operator constant folding + common-subexpression dedup) "
    "before compiling, and compiled programs land in the shared "
    "KernelCache keyed by the fused-stage structural signature.  A "
    "stage containing an expression the fuser cannot compose (e.g. "
    "ANSI-checked casts) deopts to the unfused per-operator lane — "
    "only that stage, never the query.")
SPMD_ENABLED = conf(
    "spark.rapids.sql.spmd.enabled", False,
    "Execute fused stages as ONE sharded XLA program over the active "
    "device mesh (exec/spmd.py): the stage's partition batches are "
    "stacked along a leading axis laid out with NamedSharding(mesh, "
    "P('data')), padded per shard with explicit row-count masks so "
    "ragged partitions stay bit-exact, and the whole "
    "project->filter chain runs in one jit-with-shardings dispatch — "
    "one Python dispatch per stage instead of one per partition, with "
    "XLA owning the (few) cross-shard collectives.  Requires an "
    "active mesh (spark_rapids_tpu.parallel.mesh.set_active_mesh); "
    "without one, or on unsupported stages, uneven batch layouts, or "
    "trace failure, the stage deopts to the per-partition lane "
    "(numSpmdDeopts).  Also changes plan shape: fusible chains stay "
    "standalone FusedStageExec nodes (single-operator chains "
    "included) instead of folding into the aggregate update lane, so "
    "the SPMD program sees them.  Off (default): byte-identical to "
    "the per-partition engine.")
KERNEL_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.sql.kernelCache.maxEntries", 512,
    "Entry-count bound on the process-global compiled-kernel LRU "
    "(exec/base.py KernelCache).  Fused-stage keys multiply cache "
    "pressure (every stage shape x batch signature is an entry), so "
    "the cache evicts least-recently-used executables past this "
    "bound; the eviction count is surfaced in the bench summary "
    "(kernel_cache_evictions).  XLA CPU clients have been observed "
    "to segfault with thousands of live loaded executables — raise "
    "with care.")

# --- async pipelined execution (exec/pipeline.py) ----------------------------
# env-overridable defaults so CI lanes (scripts/run_suite.sh pipeline)
# can flip the whole suite without threading a conf through every test
import os as _os

PIPELINE_ENABLED = conf(
    "spark.rapids.sql.pipeline.enabled",
    _bool(_os.environ.get("SPARK_RAPIDS_TPU_PIPELINE", "true")),
    "Overlap pipeline stages with bounded background prefetch: at "
    "pipeline breaks (scan->compute, both sides of a shuffle exchange, "
    "coalesce boundaries, AQE stage materialization) a producer thread "
    "runs the upstream iterator prefetchDepth batches ahead while the "
    "consumer computes, so host orchestration, H2D transfer, and device "
    "kernels overlap instead of strictly alternating.  Producers obey "
    "the TPU semaphore discipline: one blocked on a full queue never "
    "holds the semaphore.")
PIPELINE_PREFETCH_DEPTH = conf(
    "spark.rapids.sql.pipeline.prefetchDepth",
    int(_os.environ.get("SPARK_RAPIDS_TPU_PIPELINE_DEPTH", "2")),
    "How many batches a pipeline producer may run ahead of its "
    "consumer at each pipeline break (the prefetch queue bound).  "
    "Bounds peak device memory at ~depth extra batches per break; 0 "
    "disables prefetch at that break like pipeline.enabled=false.")

# --- I/O formats (reference RapidsConf.scala format enables + Spark's
# spark.sql.files.* split planning keys) --------------------------------------
PARQUET_ENABLED = conf("spark.rapids.sql.format.parquet.enabled", True,
                       "Enable parquet scan/write acceleration.")
PARQUET_READ_ENABLED = conf("spark.rapids.sql.format.parquet.read.enabled",
                            True, "Enable accelerated parquet reads.")
PARQUET_WRITE_ENABLED = conf("spark.rapids.sql.format.parquet.write.enabled",
                             True, "Enable accelerated parquet writes.")
ORC_ENABLED = conf("spark.rapids.sql.format.orc.enabled", True,
                   "Enable ORC scan/write acceleration.")
ORC_READ_ENABLED = conf("spark.rapids.sql.format.orc.read.enabled", True,
                        "Enable accelerated ORC reads.")
ORC_WRITE_ENABLED = conf("spark.rapids.sql.format.orc.write.enabled", True,
                         "Enable accelerated ORC writes.")
CSV_ENABLED = conf("spark.rapids.sql.format.csv.enabled", True,
                   "Enable CSV scan acceleration (reads only).")
CSV_READ_ENABLED = conf("spark.rapids.sql.format.csv.read.enabled", True,
                        "Enable accelerated CSV reads.")
MULTITHREAD_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads", 20,
    "Host file-buffering threads per executor (small-file optimization).")
MAX_PARTITION_BYTES = conf("spark.sql.files.maxPartitionBytes", 134217728,
                           "Max bytes packed into one scan partition.")
FILE_OPEN_COST = conf("spark.sql.files.openCostInBytes", 4194304,
                      "Estimated cost in bytes of opening a file when "
                      "packing splits into scan partitions.")
MIN_PARTITION_NUM = conf("spark.sql.files.minPartitionNum", 8,
                         "Suggested minimum scan partition count (Spark "
                         "defaults this to the cluster parallelism).")

# --- shuffle (reference :592-631) -------------------------------------------
RAPIDS_SHUFFLE_ENABLED = conf(
    "spark.rapids.shuffle.enabled", False,
    "Route exchanges through the accelerated shuffle manager (spillable "
    "catalog + ICI/DCN transport) instead of the in-process exchange.")
SHUFFLE_TRANSPORT_CLASS = conf(
    "spark.rapids.shuffle.transport.class",
    "spark_rapids_tpu.shuffle.ici_transport.IciShuffleTransport",
    "Fully-qualified RapidsShuffleTransport implementation.")
SHUFFLE_MAX_RECV_INFLIGHT = conf(
    "spark.rapids.shuffle.maxMetadataFetchSize", 1073741824,
    "Max in-flight receive bytes per client (throttle).")
SHUFFLE_BOUNCE_BUFFER_SIZE = conf(
    "spark.rapids.shuffle.bounceBuffers.size", 4194304,
    "Bounce/staging buffer size for cross-slice (DCN) transfers.")
SHUFFLE_BOUNCE_BUFFER_COUNT = conf(
    "spark.rapids.shuffle.bounceBuffers.count", 32,
    "Number of staging buffers per transport direction.")
SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.shuffle.compression.codec", "none",
    "Codec for serialized shuffle payloads on the transport wire: "
    "none, copy (testing), lz4, zstd.")
SHUFFLE_FAULT_DROP_RATE = conf(
    "spark.rapids.shuffle.transport.faultInjection.dropRate", 0.0,
    "TEST ONLY: probability that the transport server aborts a "
    "transfer mid-stream (connection-loss injection; the reference "
    "builds UCX with --enable-fault-injection for the same class of "
    "soak testing). The client's bounded-retry path must recover.",
    internal=True)
SHUFFLE_FAULT_CORRUPT_RATE = conf(
    "spark.rapids.shuffle.transport.faultInjection.corruptRate", 0.0,
    "TEST ONLY: probability that a DATA chunk payload is corrupted on "
    "the wire; the receiver's deserialization/CRC checks must detect "
    "it and the fetch must retry.", internal=True)
SHUFFLE_FAULT_SEED = conf(
    "spark.rapids.shuffle.transport.faultInjection.seed", 0,
    "Deterministic seed for fault injection.", internal=True)
SHUFFLE_FAULT_PEER_KILL_FRAMES = conf(
    "spark.rapids.shuffle.transport.faultInjection.peerKillAfterFrames", 0,
    "TEST ONLY: after serving this many DATA frames (across both the "
    "TCP and loopback lanes) the transport kills its own peer: sockets "
    "close mid-stream, the accept loop stops, and the loopback "
    "registration disappears — a hard executor loss, not a polite "
    "error.  The shuffle fault-recovery subsystem must invalidate the "
    "peer's map outputs and recompute them.  0 disables.",
    internal=True)
SHUFFLE_FETCH_MAX_RETRIES = conf(
    "spark.rapids.shuffle.fetch.maxRetries", 3,
    "Transfer-level retry budget per peer fetch: a failed transaction "
    "(mid-stream abort, wire corruption, dead socket) is retried on a "
    "fresh connection up to this many times before the fetch surfaces "
    "a FetchFailedError to the stage-recovery layer (reference "
    "RapidsShuffleClient FetchRetry).")
SHUFFLE_FETCH_BACKOFF_BASE_MS = conf(
    "spark.rapids.shuffle.fetch.backoff.baseMs", 50.0,
    "Base delay for exponential backoff between fetch retries: attempt "
    "k sleeps min(capMs, baseMs * 2^(k-1)) with +/-50% deterministic "
    "jitter (seeded from faultInjection.seed when set), so a flapping "
    "peer is not hammered with immediate reconnects.")
SHUFFLE_FETCH_BACKOFF_CAP_MS = conf(
    "spark.rapids.shuffle.fetch.backoff.capMs", 2000.0,
    "Upper bound on a single fetch-retry backoff sleep.")
SHUFFLE_RECOVERY_ENABLED = conf(
    "spark.rapids.shuffle.recovery.enabled", True,
    "Recover from shuffle fetch failures instead of failing the query: "
    "a FetchFailedError at the reduce side invalidates the failed "
    "peer's map outputs (per-shuffle epoch bump), recomputes only the "
    "lost map tasks from the exchange's retained lineage, and retries "
    "the reduce — the role Spark's DAG scheduler plays for the "
    "reference's FetchFailedException.")
SHUFFLE_RECOVERY_MAX_STAGE_ATTEMPTS = conf(
    "spark.rapids.shuffle.recovery.maxStageAttempts", 4,
    "Bounded stage retries: how many times a reduce partition may be "
    "attempted (initial try + recoveries) before the query fails with "
    "a descriptive FetchFailedError — never a hang, never a partial "
    "result (Spark's spark.stage.maxConsecutiveAttempts analog).")
SHUFFLE_BLACKLIST_THRESHOLD = conf(
    "spark.rapids.shuffle.recovery.blacklist.failureThreshold", 3,
    "Consecutive recovery-attributed failures after which a peer "
    "address is blacklisted: readers route around it via the "
    "MapStatus's alternate address and map tasks stop being placed on "
    "it, instead of waiting out its full timeout every stage.")
SHUFFLE_BLACKLIST_DECAY_S = conf(
    "spark.rapids.shuffle.recovery.blacklist.decaySeconds", 30.0,
    "A blacklist entry expires after this long and the peer gets a "
    "fresh consecutive-failure budget — a recovered (flapping) "
    "executor rejoins service instead of being shunned forever.")
SHUFFLE_LOCAL_EXECUTORS = conf(
    "spark.rapids.shuffle.localExecutors", 1,
    "Number of in-process executor environments the manager-lane "
    "exchange spreads map tasks across (round-robin).  >1 makes map "
    "outputs genuinely remote to the reducing executor — loopback/TCP "
    "fetches, fault injection, and recovery all exercise multi-executor "
    "behavior in one process, like the reference's mocked-transport "
    "suites.  1 (default) keeps the single local manager.")
SHUFFLE_REPLICATION_FACTOR = conf(
    "spark.rapids.shuffle.replication.factor", 1,
    "Copies of each map output across in-process executors (1 = "
    "primary only, the default).  At 2+ the CachingShuffleWriter "
    "pushes each partition's serialized payload to factor-1 backup "
    "executors at write time: hedged fetches "
    "(spark.rapids.shuffle.hedge.enabled) can race a replica against "
    "a slow primary, and shuffle recovery promotes a live replica to "
    "primary on peer loss instead of recomputing from lineage "
    "(recompute remains the fallback when no replica survives).  "
    "Costs one extra serialization + host-store copy per replicated "
    "partition (replicatedBytes on the exchange's metrics and the "
    "movement ledger's wire:replicate site).")
SHUFFLE_HEDGE_ENABLED = conf(
    "spark.rapids.shuffle.hedge.enabled", False,
    "Hedge slow shuffle fetches: when a remote fetch has not "
    "completed after the hedge delay (hedge.delayMs floor, or the "
    "hedge.quantile of recently observed fetch durations once enough "
    "samples exist), issue the same block request to a replica peer "
    "(shuffle.replication.factor >= 2) and keep the first complete, "
    "uncorrupted response — the loser is cancelled and its buffers "
    "freed, its wire bytes charged to the ledger's wire:wasted site.  "
    "First-wins is bit-exact: both attempts serve identical "
    "serialized payloads.")
SHUFFLE_HEDGE_DELAY_MS = conf(
    "spark.rapids.shuffle.hedge.delayMs", 1000.0,
    "Floor (and cold-start fallback) for the hedge trigger delay: a "
    "fetch outstanding this long fires the hedge even before enough "
    "latency samples exist to compute the quantile.")
SHUFFLE_HEDGE_QUANTILE = conf(
    "spark.rapids.shuffle.hedge.quantile", 0.95,
    "Latency quantile of recently completed fetches above which an "
    "outstanding fetch is considered straggling and hedged (once >= 8 "
    "samples exist; the effective delay is max(quantile latency, "
    "hedge.delayMs)).")
MESH_EXCHANGE_ENABLED = conf(
    "spark.rapids.shuffle.meshExchange.enabled", True,
    "Route hash shuffle exchanges through the device-mesh ICI all-to-all "
    "collective when an active mesh is set "
    "(spark_rapids_tpu.parallel.mesh.set_active_mesh) and the exchange "
    "is mesh-routable (hash keys are plain columns, partition count == "
    "mesh size). The TCP/manager lane remains the DCN fallback — the "
    "reference's equivalent split is UCX-inside-the-shuffle-manager "
    "(RapidsShuffleInternalManager.scala:199, UCXShuffleTransport.scala:47).")

# --- python / udf -----------------------------------------------------------
PYTHON_CONCURRENT_WORKERS = conf(
    "spark.rapids.python.concurrentPythonWorkers", 0,
    "Cap on concurrent accelerated python UDF workers (0 = unlimited).")
PYTHON_DAEMON_ENABLED = conf(
    "spark.rapids.python.daemon.enabled", False,
    "Run vectorized python UDFs in out-of-process daemon workers "
    "(Arrow IPC over pipes) instead of in-process — process isolation "
    "at one host round-trip of cost (reference python/rapids/daemon.py).")
PYTHON_ON_TPU = conf(
    "spark.rapids.python.onTpu.enabled", False,
    "Allow daemon UDF workers to initialize the TPU platform; off by "
    "default because the chip is single-process and belongs to the "
    "executor (reference RAPIDS_PYTHON_ENABLED gate, "
    "python/rapids/worker.py:22-30).")
PYTHON_MEM_LIMIT = conf(
    "spark.rapids.python.memory.limitBytes", 0,
    "Address-space rlimit per daemon UDF worker, 0 = unlimited (the "
    "role of the reference's per-worker RMM pool size, "
    "python/rapids/worker.py:34-50).")
UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled", True,
                            "Compile Python UDF bytecode to expressions.")

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level", "MODERATE",
                     "Operator metric detail: ESSENTIAL, MODERATE, DEBUG.")

PALLAS_Q1_ENABLED = conf(
    "spark.rapids.tpu.pallas.q1.enabled", False,
    "Use the Pallas kernel for SINGLE-batch TPC-H Q1 dispatches. In "
    "this dispatch-overhead-bound mode the lighter XLA einsum kernel "
    "measures faster (9.6 vs 13.0 ms/dispatch on a tunnel-attached "
    "v5e), so it stays the single-batch default; see q1Fused for the "
    "mode where Pallas wins 3x.")
DICT_GROUPBY_ENABLED = conf(
    "spark.rapids.tpu.dictGroupby.enabled", True,
    "Planner-automatic sort-free grouped aggregation via the fused "
    "Pallas one-hot kernel when a single integral group key's runtime "
    "range fits dictGroupby.maxGroups (Sum/Count/Average over floats, "
    "Count over anything). The whole batch runs as ONE dispatch (window "
    "slots + grouped sum + finalize); a first-batch probe sizes the "
    "dictionary and per-batch overflow counts trigger fallback to the "
    "sort path. Float Sum/Average additionally require "
    "variableFloatAgg.enabled: sums accumulate in f32, a "
    "variableFloatAgg-class tolerance. Count-only plans are exact.")
DICT_GROUPBY_MAX_GROUPS = conf(
    "spark.rapids.tpu.dictGroupby.maxGroups", 32768,
    "Max runtime key range for the dictionary group-by fast path. The "
    "one-hot kernel tiles its VMEM block by group count, so cost grows "
    "mildly with range (measured: 4K groups 100ms, 16K 118ms, 64K "
    "332ms at 2M rows); 32K covers e.g. TPCx-BB q27's ~26K items "
    "while staying ~2x the 4K floor.")
BANDED_GROUPBY_ENABLED = conf(
    "spark.rapids.tpu.bandedGroupby.enabled", True,
    "Sum/Count/Average group-bys aggregate through the banded windowed "
    "MXU kernel (ops/grouped_window.py) after the grouping sort: "
    "per-block one-hot local tables merged by one small matmul, no "
    "serialized scatters, no positions/segmented-scan machinery — and "
    "group count is UNBOUNDED (no dictGroupby range budget). "
    "Accumulation is f32: integral measures are exact-or-deopt via the "
    "sum(|v|) certificate, float measures additionally require "
    "variableFloatAgg.enabled. Group keys of any sortable type are "
    "recovered through first-row-index limb measures + one gather.")
HASH_GROUPING_ENABLED = conf(
    "spark.rapids.tpu.hashGrouping.enabled", True,
    "Wide grouping key sets (aggregate GROUP BY, window PARTITION BY) "
    "sort by two murmur3-derived words instead of the lexicographic "
    "key encode, whose width scales with key content (string keys "
    "emit one 9-bit sort word slice PER CHARACTER; a 15-column string "
    "grouper is ~100 packed words and its XLA compile alone runs "
    "minutes). Exact: segment boundaries come from the actual "
    "adjacent key values, and a detected 64-bit hash collision deopts "
    "the query to the lexicographic lane via the deferred-check "
    "retry.")
DENSE_JOIN_ENABLED = conf(
    "spark.rapids.tpu.denseJoin.enabled", True,
    "Direct-address equi-join fast path: when a single integral build "
    "key's runtime span fits denseJoin.maxSpan and the keys are unique "
    "(PK-FK joins on dense surrogate keys), the build side becomes a "
    "dense slot table and each probe batch is ONE dispatch of two fused "
    "gathers — no concat, no sort.  Falls back to the sort-merge kernel "
    "otherwise.")
DENSE_JOIN_MAX_SPAN = conf(
    "spark.rapids.tpu.denseJoin.maxSpan", 1 << 22,
    "Max build-key span for the direct-address join table (table memory "
    "is 8 bytes per slot).")
PALLAS_Q1_FUSED_ENABLED = conf(
    "spark.rapids.tpu.pallas.q1Fused.enabled", True,
    "Use the Pallas single-HBM-pass kernel for STACKED multi-batch Q1 "
    "dispatches (the device-side batch loop). Measured 3.0x the XLA "
    "einsum formulation on v5e (~2060 vs 689 Mrows/s over 8x16.8M "
    "rows): XLA materializes the one-hot einsum operands in HBM (~19GB "
    "traffic for 3.8GB of input) where the Pallas kernel touches each "
    "input byte once (ops/pallas_kernels.py).")

# --- adaptive query execution ----------------------------------------------
# Spark-owned keys the plugin reads (reference: AQE is driven by Spark's
# spark.sql.adaptive.* confs; the plugin supplies GpuCustomShuffleReaderExec
# and the query-stage prep rule, GpuOverrides.scala:1807-1881).
ADAPTIVE_ENABLED = conf(
    "spark.sql.adaptive.enabled", False,
    "Re-plan at query-stage boundaries from runtime shuffle statistics.")
COALESCE_PARTITIONS_ENABLED = conf(
    "spark.sql.adaptive.coalescePartitions.enabled", True,
    "Merge adjacent small reduce partitions after a shuffle stage.")
ADVISORY_PARTITION_SIZE = conf(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes", 64 * 1024 * 1024,
    "Target post-shuffle partition size for AQE partition coalescing.")
AUTO_BROADCAST_THRESHOLD = conf(
    "spark.sql.autoBroadcastJoinThreshold", 10 * 1024 * 1024,
    "Max build-side bytes for the AQE shuffled-hash-join to "
    "broadcast-join demotion (-1 disables).")
BROADCAST_TIMEOUT = conf(
    "spark.sql.broadcastTimeout", 300,
    "Seconds allowed for materializing a broadcast build side before "
    "the exchange fails (reference GpuBroadcastExchangeExec timeout "
    "on the build-side collect future).")
MAX_BROADCAST_TABLE_BYTES = conf(
    "spark.rapids.tpu.maxBroadcastTableBytes", 8 << 30,
    "Hard cap on a broadcast build side's device bytes; exceeding it "
    "fails the query with a clear error instead of exhausting HBM "
    "(Spark's 8GB broadcast-table limit).")


def op_enable_key(kind: str, name: str) -> str:
    """Auto-derived per-operator enable key
    (reference GpuOverrides.scala:129-137)."""
    return f"spark.rapids.sql.{kind}.{name}"


class RapidsConf:
    """Immutable snapshot of config values, read once at plan time
    (reference reads per-query: GpuOverrides.scala:1885)."""

    def __init__(self, settings: Optional[dict[str, Any]] = None):
        self._settings = dict(settings or {})

    def is_set(self, key: str) -> bool:
        """True when `key` was EXPLICITLY set on this conf (as opposed
        to resolving through the registry default) — lets layered
        defaults (e.g. the test harness's conservative global watchdog
        deadlines) yield to per-session settings without shadowing
        them."""
        return key in self._settings

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._settings:
            val = self._settings[key]
            entry = _REGISTRY.get(key)
            if entry is not None and isinstance(val, str):
                return entry.converter(val)
            return val
        entry = _REGISTRY.get(key)
        if entry is not None:
            return entry.default
        return default

    def __getitem__(self, entry: ConfEntry) -> Any:
        return self.get(entry.key, entry.default)

    def is_op_enabled(self, kind: str, name: str, default: bool = True) -> bool:
        return _bool(self.get(op_enable_key(kind, name), default))

    def with_overrides(self, **kv) -> "RapidsConf":
        s = dict(self._settings)
        s.update({k.replace("__", "."): v for k, v in kv.items()})
        return RapidsConf(s)

    def set(self, key: str, value: Any) -> "RapidsConf":
        s = dict(self._settings)
        s[key] = value
        return RapidsConf(s)

    def fingerprint(self) -> tuple:
        """Stable hashable identity of every EXPLICIT setting — the
        result cache's conf component, so two sessions differing in any
        setting can never serve each other's cached results."""
        return tuple(sorted((k, repr(v))
                            for k, v in self._settings.items()))

    @property
    def sql_enabled(self) -> bool:
        return self[SQL_ENABLED]


_active = threading.local()


def get_active_conf() -> RapidsConf:
    c = getattr(_active, "conf", None)
    if c is None:
        # execution-time fallback: a helper thread carrying a query
        # context (TaskContext.query_ctx / scheduler-scoped) reads ITS
        # query's conf snapshot, never another session's thread-local
        # or the registry defaults — the PR 2 captured-default-conf
        # bug class, closed at the resolver
        try:
            from spark_rapids_tpu.exec import scheduler as _S
            qc = _S.current()
            if qc is not None:
                return qc.conf
        except ImportError:
            pass
        c = RapidsConf()
        _active.conf = c
    return c


def set_active_conf(conf_: RapidsConf) -> None:
    _active.conf = conf_


@contextmanager
def session(conf_: Optional[RapidsConf]):
    """Install `conf_` as the active conf for the duration (the
    driver-side analog of Spark's session-scoped SQLConf: plan-time conf
    decisions and run-time conf reads see the same values —
    GpuOverrides.scala:1885 reads conf at plan time; our collect()
    installs the plan's conf for execution)."""
    if conf_ is None:
        yield
        return
    prev = getattr(_active, "conf", None)
    _active.conf = conf_
    try:
        yield
    finally:
        _active.conf = prev


def help_text() -> str:
    """Generate docs/configs.md content (reference ConfHelper.makeConfAnchor,
    RapidsConf.scala help())."""
    lines = ["# Configuration", "",
             "| Key | Default | Description |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"| `{e.key}` | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"


def write_docs(path: str = "docs/configs.md") -> None:
    import os
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(help_text())
