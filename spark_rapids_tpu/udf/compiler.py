"""Python-bytecode -> Expression UDF compiler.

Reference: the `udf-compiler/` module (SURVEY.md §2.11) — JVM lambda
bytecode is reflected (`LambdaReflection.scala`), split into a basic-block
CFG (`CFG.scala`), abstractly interpreted opcode-by-opcode
(`Instruction.scala`: symbolic stack/locals producing Catalyst
expressions), and branch states merge into `If`/`CaseWhen`
(`CatalystExpressionBuilder.scala`), with silent fallback on any
unsupported construct (`udf-compiler/.../Plugin.scala:48-52`).

TPU-native analog: user UDFs are *Python* functions, so the bytecode is
CPython's (`dis`).  Same architecture: CFG over `dis` instructions,
symbolic stack/locals holding `Expression` nodes, recursive block
evaluation that turns conditional jumps into `If` expressions (the CFG of
loop-free Python is a DAG), and `None` return on anything unsupported —
the caller keeps the original UDF (CPU fallback), exactly the reference's
contract.  A compiled UDF fuses into the surrounding XLA kernel instead of
breaking the plan at a host Python boundary.
"""
from __future__ import annotations

import dataclasses
import dis
import math
from typing import Any, Callable, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import conditional as CO
from spark_rapids_tpu.exprs import math_exprs as MX
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import string_fns as S
from spark_rapids_tpu.exprs.base import Expression, Literal, col
from spark_rapids_tpu.exprs.cast import Cast


class UdfCompileError(Exception):
    """Internal control flow; never escapes compile_udf."""


# -- supported call targets ---------------------------------------------------
def _fn_substring(s, start, end=None):
    # python slicing start is 0-based; Substring is 1-based
    if end is None:
        return S.Substring(s, _plus1(start), Literal.of(2 ** 31 - 1))
    return S.Substring(s, _plus1(start), _len_of(start, end))


def _plus1(e):
    if isinstance(e, Literal):
        return Literal.of(e.value + 1)
    return A.Add(e, Literal.of(1))


def _len_of(start, end):
    if isinstance(start, Literal) and isinstance(end, Literal):
        return Literal.of(max(0, end.value - start.value))
    return A.Subtract(end, start)


def _variadic_minmax(le_builder):
    def build(*args):
        if len(args) < 2:
            raise UdfCompileError("min/max need >= 2 args")
        acc = args[0]
        for nxt in args[1:]:
            acc = CO.If(le_builder(acc, nxt), acc, nxt)
        return acc
    return build


_GLOBAL_CALLS: dict[str, Callable[..., Expression]] = {
    "abs": lambda x: A.Abs(x),
    "len": lambda x: S.Length(x),
    "min": _variadic_minmax(P.LessThanOrEqual),
    "max": _variadic_minmax(P.GreaterThanOrEqual),
    "round": lambda x, nd=None: MX.Round(
        x, nd if nd is not None else Literal.of(0)),
    "float": lambda x: Cast(x, T.FLOAT64),
    "int": lambda x: Cast(x, T.INT64),
    "bool": lambda x: Cast(x, T.BOOL),
    "str": lambda x: Cast(x, T.STRING),
    # math module functions arrive as "math.<name>"
    "math.sqrt": lambda x: MX.Sqrt(x),
    "math.exp": lambda x: MX.Exp(x),
    "math.expm1": lambda x: MX.Expm1(x),
    "math.log": lambda x: MX.Log(x),
    "math.log1p": lambda x: MX.Log1p(x),
    "math.log2": lambda x: MX.Log2(x),
    "math.log10": lambda x: MX.Log10(x),
    "math.sin": lambda x: MX.Sin(x),
    "math.cos": lambda x: MX.Cos(x),
    "math.tan": lambda x: MX.Tan(x),
    "math.asin": lambda x: MX.Asin(x),
    "math.acos": lambda x: MX.Acos(x),
    "math.atan": lambda x: MX.Atan(x),
    "math.atan2": lambda y, x: MX.Atan2(y, x),
    "math.sinh": lambda x: MX.Sinh(x),
    "math.cosh": lambda x: MX.Cosh(x),
    "math.tanh": lambda x: MX.Tanh(x),
    "math.degrees": lambda x: MX.ToDegrees(x),
    "math.radians": lambda x: MX.ToRadians(x),
    "math.pow": lambda x, y: MX.Pow(x, y),
    "math.floor": lambda x: Cast(MX.Floor(x), T.INT64),
    "math.ceil": lambda x: Cast(MX.Ceil(x), T.INT64),
    "math.fabs": lambda x: A.Abs(Cast(x, T.FLOAT64)),
}

_METHOD_CALLS: dict[str, Callable[..., Expression]] = {
    "upper": lambda s: S.Upper(s),
    "lower": lambda s: S.Lower(s),
    "strip": lambda s: S.StringTrim(s),
    "lstrip": lambda s: S.StringTrimLeft(s),
    "rstrip": lambda s: S.StringTrimRight(s),
    "title": lambda s: S.InitCap(s),
    "startswith": lambda s, p: S.StartsWith(s, p),
    "endswith": lambda s, p: S.EndsWith(s, p),
    "replace": lambda s, a, b: S.StringReplace(s, a, b),
    "find": lambda s, sub: A.Subtract(
        S.StringLocate(sub, s, Literal.of(1)), Literal.of(1)),
    # python ljust/rjust never truncate; Spark's pads do — guard on
    # length so per-row results match python exactly
    "ljust": lambda s, n, pad=None: CO.If(
        P.GreaterThanOrEqual(S.Length(s), n), s,
        S.RPad(s, n, pad if pad is not None else Literal.of(" "))),
    "rjust": lambda s, n, pad=None: CO.If(
        P.GreaterThanOrEqual(S.Length(s), n), s,
        S.LPad(s, n, pad if pad is not None else Literal.of(" "))),
}

# Python `%` is sign-follows-divisor: exactly Spark's Pmod, NOT
# Remainder (Java %).  Python `//` (floor division) has no direct
# equivalent (IntegralDivide truncates toward zero) and is left
# unsupported so such UDFs fall back rather than change results.
_BINARY_OPS = {
    0: lambda l, r: A.Add(l, r),            # +
    10: lambda l, r: A.Subtract(l, r),      # -
    5: lambda l, r: A.Multiply(l, r),       # *
    11: lambda l, r: A.Divide(l, r),        # /
    6: lambda l, r: A.Pmod(l, r),           # %
    8: lambda l, r: MX.Pow(l, r),           # **
    1: lambda l, r: P.And(l, r),            # & (on bools)
    7: lambda l, r: P.Or(l, r),             # | (on bools)
    # +=, -=, ... (inplace variants)
    13: lambda l, r: A.Add(l, r),
    23: lambda l, r: A.Subtract(l, r),
    18: lambda l, r: A.Multiply(l, r),
    24: lambda l, r: A.Divide(l, r),
    19: lambda l, r: A.Pmod(l, r),          # %=
}

_COMPARE_OPS = {
    "<": P.LessThan, "<=": P.LessThanOrEqual, ">": P.GreaterThan,
    ">=": P.GreaterThanOrEqual, "==": P.EqualTo,
}


@dataclasses.dataclass
class _Block:
    start: int
    instructions: list
    # (opname, target_offset | None) terminator


class _CFG:
    """Basic blocks keyed by bytecode offset (reference CFG.scala)."""

    def __init__(self, code):
        instructions = [i for i in dis.get_instructions(code)
                        if i.opname not in ("RESUME", "CACHE", "PRECALL",
                                            "NOP", "COPY_FREE_VARS",
                                            "MAKE_CELL")]
        targets = set()
        for ins in instructions:
            if ins.opname.startswith(("POP_JUMP", "JUMP")):
                targets.add(ins.argval)
        starts = {instructions[0].offset} | targets
        self.blocks: dict[int, _Block] = {}
        cur: list = []
        cur_start: Optional[int] = None
        for ins in instructions:
            if cur and ins.offset in starts:
                # a jump target begins a new block mid-stream
                self.blocks[cur_start] = _Block(cur_start, cur)
                cur = []
            if not cur:
                cur_start = ins.offset
            cur.append(ins)
            if ins.opname.startswith(("POP_JUMP", "JUMP")) or \
                    ins.opname in ("RETURN_VALUE", "RETURN_CONST"):
                self.blocks[cur_start] = _Block(cur_start, cur)
                cur = []
        if cur:
            self.blocks[cur_start] = _Block(cur_start, cur)
        self.entry = instructions[0].offset


def compile_udf(fn: Callable, arg_exprs: Sequence[Expression]
                ) -> Optional[Expression]:
    """Compile `fn(args...)` into an Expression over `arg_exprs`.
    Returns None when any construct is unsupported (caller falls back)."""
    try:
        code = fn.__code__
        if code.co_argcount != len(arg_exprs):
            return None
        if fn.__closure__:  # only closed-over constants are handled
            freevars = {}
            for name, cell in zip(code.co_freevars, fn.__closure__):
                v = cell.cell_contents
                if not isinstance(v, (int, float, str, bool)):
                    return None
                freevars[name] = v
        else:
            freevars = {}
        cfg = _CFG(code)
        locals_ = {code.co_varnames[i]: e
                   for i, e in enumerate(arg_exprs)}
        interp = _Interpreter(cfg, fn.__globals__, freevars)
        return interp.eval_block(cfg.entry, locals_, [], depth=0)
    except (UdfCompileError, KeyError, IndexError, AttributeError,
            TypeError, ValueError):
        # TypeError/ValueError cover arity or operand-kind mismatches
        # inside expression builders — fall back like any other
        # unsupported construct
        return None


class _Marker:
    """Non-expression stack values: global refs, method refs, modules."""

    def __init__(self, kind: str, payload):
        self.kind = kind
        self.payload = payload


def _as_expr(v) -> Expression:
    if isinstance(v, Expression):
        return v
    if isinstance(v, (bool, int, float, str)):
        return Literal.of(v)
    if v is None:
        raise UdfCompileError("untyped None on stack")
    raise UdfCompileError(f"non-expression value {v!r}")


class _Interpreter:
    """Symbolic executor (reference Instruction.scala + State.scala):
    stack/locals hold Expressions; conditional jumps evaluate both
    successor blocks and merge into If."""

    MAX_DEPTH = 64

    def __init__(self, cfg: _CFG, globals_: dict, freevars: dict):
        self.cfg = cfg
        self.globals = globals_
        self.freevars = freevars

    def eval_block(self, offset: int, locals_: dict, stack: list,
                   depth: int) -> Expression:
        if depth > self.MAX_DEPTH:
            raise UdfCompileError("CFG too deep")
        block = self.cfg.blocks[offset]
        locals_ = dict(locals_)
        stack = list(stack)
        for ins in block.instructions:
            op = ins.opname
            if op == "LOAD_FAST":
                if ins.argval not in locals_:
                    raise UdfCompileError(f"unbound local {ins.argval}")
                stack.append(locals_[ins.argval])
            elif op == "STORE_FAST":
                locals_[ins.argval] = stack.pop()
            elif op == "LOAD_CONST":
                stack.append(ins.argval)
            elif op == "LOAD_DEREF":
                if ins.argval not in self.freevars:
                    raise UdfCompileError(f"free var {ins.argval}")
                stack.append(self.freevars[ins.argval])
            elif op == "LOAD_GLOBAL":
                stack.append(_Marker("global", ins.argval))
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                recv = stack.pop()
                if isinstance(recv, _Marker) and recv.kind == "global":
                    stack.append(_Marker("global",
                                         f"{recv.payload}.{ins.argval}"))
                else:
                    stack.append(_Marker("method", (ins.argval, recv)))
            elif op == "PUSH_NULL":
                pass
            elif op == "CALL":
                argc = ins.argval
                args = [stack.pop() for _ in range(argc)][::-1]
                target = stack.pop()
                stack.append(self._call(target, args))
            elif op == "BINARY_OP":
                r, l = stack.pop(), stack.pop()
                builder = _BINARY_OPS.get(ins.arg)
                if builder is None:
                    raise UdfCompileError(f"binary op {ins.argrepr}")
                stack.append(builder(_as_expr(l), _as_expr(r)))
            elif op == "COMPARE_OP":
                r, l = stack.pop(), stack.pop()
                sym = ins.argrepr.strip()
                if sym == "!=":
                    stack.append(P.Not(P.EqualTo(_as_expr(l), _as_expr(r))))
                elif sym in _COMPARE_OPS:
                    stack.append(_COMPARE_OPS[sym](_as_expr(l),
                                                   _as_expr(r)))
                else:
                    raise UdfCompileError(f"compare {sym}")
            elif op == "IS_OP":
                r, l = stack.pop(), stack.pop()
                if r is not None:
                    raise UdfCompileError("is only supported vs None")
                e = P.IsNull(_as_expr(l))
                stack.append(P.Not(e) if ins.arg == 1 else e)
            elif op == "BINARY_SLICE":
                stop = stack.pop()
                start = stack.pop()
                seq = _as_expr(stack.pop())
                for bound in (start, stop):
                    if isinstance(bound, int) and bound < 0:
                        raise UdfCompileError("negative slice index")
                start_e = _as_expr(start if start is not None else 0)
                stack.append(_fn_substring(
                    seq, start_e,
                    None if stop is None else _as_expr(stop)))
            elif op == "CONTAINS_OP":
                container = stack.pop()
                item = stack.pop()
                if isinstance(container, (tuple, list, set, frozenset)):
                    # `x in (a, b, c)` over literal constants -> InSet
                    vals = tuple(container)
                    if not all(isinstance(v, (bool, int, float, str))
                               for v in vals):
                        raise UdfCompileError("non-literal IN set")
                    e = P.InSet(_as_expr(item), vals)
                elif isinstance(item, str):
                    # `"lit" in s` -> Contains (literal pattern only,
                    # like the reference's regexp-as-literal handling)
                    e = S.Contains(_as_expr(container), Literal.of(item))
                else:
                    raise UdfCompileError("unsupported `in` operands")
                stack.append(P.Not(e) if ins.arg == 1 else e)
            elif op == "UNARY_NEGATIVE":
                stack.append(A.UnaryMinus(_as_expr(stack.pop())))
            elif op == "UNARY_POSITIVE":
                stack.append(_as_expr(stack.pop()))
            elif op == "CALL_INTRINSIC_1":
                if ins.argrepr == "INTRINSIC_UNARY_POSITIVE":
                    stack.append(_as_expr(stack.pop()))
                else:
                    raise UdfCompileError(f"intrinsic {ins.argrepr}")
            elif op == "UNARY_NOT":
                stack.append(P.Not(_as_expr(stack.pop())))
            elif op == "TO_BOOL":
                pass  # 3.13+; COMPARE_OP results are already bool
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                v = _as_expr(stack.pop())
                if op == "POP_JUMP_IF_FALSE":
                    cond = v
                elif op == "POP_JUMP_IF_TRUE":
                    cond = P.Not(v)
                elif op == "POP_JUMP_IF_NONE":
                    cond = P.Not(P.IsNull(v))
                else:
                    cond = P.IsNull(v)
                # blocks split exactly at the branch, so the fall-through
                # successor is the next block in offset order
                then_off = self._fallthrough(block.start)
                then_e = self.eval_block(then_off, locals_, stack,
                                         depth + 1)
                else_e = self.eval_block(ins.argval, locals_, stack,
                                         depth + 1)
                return CO.If(cond, then_e, else_e)
            elif op in ("JUMP_FORWARD", "JUMP_ABSOLUTE"):
                return self.eval_block(ins.argval, locals_, stack,
                                       depth + 1)
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops are not supported")
            elif op == "RETURN_VALUE":
                return _as_expr(stack.pop())
            elif op == "RETURN_CONST":
                return _as_expr(ins.argval)
            elif op == "POP_TOP":
                stack.pop()
            elif op == "COPY":
                stack.append(stack[-ins.arg])
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
            else:
                raise UdfCompileError(f"unsupported opcode {op}")
        # fell off the block: continue to the next block in offset order
        return self.eval_block(self._fallthrough(block.start), locals_,
                               stack, depth + 1)

    def _fallthrough(self, block_start: int) -> int:
        nxt = min((o for o in self.cfg.blocks if o > block_start),
                  default=None)
        if nxt is None:
            raise UdfCompileError("no fall-through block")
        return nxt

    def _check_not_shadowed(self, name: str) -> None:
        """Global-call dispatch is by name; if the UDF's module rebinds
        that name (`def round(x): ...`, `math = something`), compiling it
        as the builtin would silently change results — fall back
        instead."""
        import builtins
        base = name.split(".", 1)[0]
        if base not in self.globals:
            return
        bound = self.globals[base]
        expected = math if base == "math" else getattr(builtins, base, None)
        if bound is not expected:
            raise UdfCompileError(f"global {base} is shadowed in the "
                                  "UDF's module")

    def _call(self, target, args) -> Expression:
        if not isinstance(target, _Marker):
            raise UdfCompileError(f"call of {target!r}")
        if target.kind == "global":
            name = target.payload
            self._check_not_shadowed(name)
            builder = _GLOBAL_CALLS.get(name)
            if builder is None:
                raise UdfCompileError(f"unsupported function {name}")
            return builder(*[_as_expr(a) for a in args])
        if target.kind == "method":
            name, recv = target.payload
            builder = _METHOD_CALLS.get(name)
            if builder is None:
                raise UdfCompileError(f"unsupported method {name}")
            return builder(_as_expr(recv), *[_as_expr(a) for a in args])
        raise UdfCompileError(f"call of {target.kind}")


