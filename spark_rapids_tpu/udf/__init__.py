"""User-defined functions: the `tpu_udf` decorator, the `PythonUDF`
expression, and the plan-rewrite pass that compiles UDF bytecode into
native expressions.

Reference: `udf-compiler/` (SURVEY.md §2.11) — a logical-plan resolution
rule finds `ScalaUDF`, attempts bytecode->Catalyst compilation, and falls
back silently to the original UDF on any unsupported construct
(`udf-compiler/.../Plugin.scala:28-94`).  Identical contract here:
`rewrite_udfs` runs at the head of `accelerate()` (gated by
`spark.rapids.sql.udfCompiler.enabled`); a `PythonUDF` that does not
compile stays in the plan, has no TPU rule, and therefore falls back to
the CPU engine, which row-applies the original function.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Callable, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import Expression, _lit
from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.udf.compiler import compile_udf


@dataclasses.dataclass(eq=False)
class PythonUDF(Expression):
    """Uncompiled user function over child expressions.  No TPU rule is
    registered for it, so an uncompiled UDF forces CPU fallback (the
    reference keeps the original ScalaUDF the same way)."""
    fn: Callable
    return_type: T.DataType
    args: tuple

    def data_type(self, schema) -> T.DataType:
        return self.return_type

    def children(self) -> Sequence[Expression]:
        return self.args

    def with_children(self, kids):
        return PythonUDF(self.fn, self.return_type, tuple(kids))

    def eval(self, ctx):
        raise RuntimeError(
            "PythonUDF must be compiled or run on the CPU engine")

    def __repr__(self):
        name = getattr(self.fn, "__name__", "udf")
        return f"{name}({', '.join(map(repr, self.args))})"


def tpu_udf(return_type: T.DataType):
    """Decorator: `@tpu_udf(T.INT64)` makes `fn(col("a"), ...)` build a
    PythonUDF expression (Spark's `udf(...)` analog)."""

    def wrap(fn: Callable):
        def build(*args) -> PythonUDF:
            return PythonUDF(fn, return_type,
                             tuple(_lit(a) for a in args))
        build.fn = fn
        build.return_type = return_type
        build.__name__ = getattr(fn, "__name__", "udf")
        return build
    return wrap


def compile_expression(e: Expression) -> Expression:
    """Recursively replace compilable PythonUDFs.  The compiled body is
    cast to the declared return type so plan schemas match the fallback
    path exactly."""
    e = e.map_children(compile_expression)
    if isinstance(e, PythonUDF):
        compiled = compile_udf(e.fn, list(e.args))
        if compiled is not None:
            # peephole the compiled body: bytecode `find(x) >= 0`
            # shapes collapse to Contains/StartsWith (presence tests
            # don't pay the locate position machinery)
            from spark_rapids_tpu.exprs.simplify import simplify
            return Cast(simplify(compiled), e.return_type)
    return e


def rewrite_udfs(node):
    """Plan-wide UDF compilation pass (reference LogicalPlanRules.apply).
    Returns a new tree; the input is never mutated."""
    from spark_rapids_tpu.plan import nodes as N
    new_children = [rewrite_udfs(c) for c in node.children]
    changed = any(nc is not oc for nc, oc in zip(new_children,
                                                 node.children))
    rewrites = {}
    if isinstance(node, N.CpuProject):
        new = [compile_expression(x) for x in node.exprs]
        if any(a is not b for a, b in zip(new, node.exprs)):
            rewrites["exprs"] = new
    elif isinstance(node, N.CpuFilter):
        ne = compile_expression(node.condition)
        if ne is not node.condition:
            rewrites["condition"] = ne
    elif isinstance(node, N.CpuAggregate):
        ng = [compile_expression(x) for x in node.group_exprs]
        if any(a is not b for a, b in zip(ng, node.group_exprs)):
            rewrites["group_exprs"] = ng
        from spark_rapids_tpu.exprs.aggregates import AggAlias
        na = []
        agg_changed = False
        for a in node.aggregates:
            if a.func.child is not None:
                nc = compile_expression(a.func.child)
                if nc is not a.func.child:
                    f = copy.copy(a.func)
                    f.child = nc
                    a = AggAlias(f, a.name)
                    agg_changed = True
            na.append(a)
        if agg_changed:
            rewrites["aggregates"] = na
    elif isinstance(node, N.CpuHashJoin):
        nl = [compile_expression(x) for x in node.left_keys]
        nr = [compile_expression(x) for x in node.right_keys]
        if any(a is not b for a, b in zip(
                nl + nr, node.left_keys + node.right_keys)):
            rewrites["left_keys"] = nl
            rewrites["right_keys"] = nr
        if node.condition is not None:
            ncond = compile_expression(node.condition)
            if ncond is not node.condition:
                rewrites["condition"] = ncond
    if not changed and not rewrites:
        return node
    out = copy.copy(node)
    out.children = new_children
    for k, v in rewrites.items():
        setattr(out, k, v)
    return out
