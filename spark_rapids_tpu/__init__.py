"""spark_rapids_tpu: a TPU-native Spark-SQL columnar accelerator framework.

Re-creation of the capability surface of NVIDIA's RAPIDS Accelerator for
Apache Spark (reference: andygrove/spark-rapids v0.2.0-SNAPSHOT), designed
TPU-first: columnar batches are static-shape JAX arrays in HBM, operators
compile to fused XLA executables cached per batch bucket, shuffle rides
ICI collectives under shard_map, and spill management is an explicit
host-driven tier chain (HBM -> host -> disk).

Spark parity requires 64-bit longs/doubles, so x64 is enabled at import
(the reference's cuDF kernels are 64-bit native; on TPU f64 is emulated --
performance-sensitive pipelines should prefer f32/bf16 columns).
"""
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA executable cache: sort-heavy kernels take 10-100s to
# compile on TPU, but compiled artifacts round-trip the disk cache across
# processes (verified through the axon tunnel), so cold starts are paid
# once per machine.  Opt out with SPARK_RAPIDS_TPU_NO_COMPILE_CACHE=1 or
# override the standard JAX_COMPILATION_CACHE_DIR.
if not _os.environ.get("SPARK_RAPIDS_TPU_NO_COMPILE_CACHE"):
    _cache_dir = _os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        _os.path.expanduser("~/.cache/spark_rapids_tpu/xla"))
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           2.0)
    except Exception:  # older jax without the knobs: in-memory only
        pass

__version__ = "0.2.0"

from spark_rapids_tpu import types  # noqa: E402,F401
from spark_rapids_tpu.config import RapidsConf  # noqa: E402,F401
