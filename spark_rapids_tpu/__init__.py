"""spark_rapids_tpu: a TPU-native Spark-SQL columnar accelerator framework.

Re-creation of the capability surface of NVIDIA's RAPIDS Accelerator for
Apache Spark (reference: andygrove/spark-rapids v0.2.0-SNAPSHOT), designed
TPU-first: columnar batches are static-shape JAX arrays in HBM, operators
compile to fused XLA executables cached per batch bucket, shuffle rides
ICI collectives under shard_map, and spill management is an explicit
host-driven tier chain (HBM -> host -> disk).

Spark parity requires 64-bit longs/doubles, so x64 is enabled at import
(the reference's cuDF kernels are 64-bit native; on TPU f64 is emulated --
performance-sensitive pipelines should prefer f32/bf16 columns).
"""
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA executable cache: sort-heavy kernels take 10-100s to
# compile on TPU, but compiled artifacts round-trip the disk cache across
# processes (verified through the axon tunnel), so cold starts are paid
# once per machine.  Opt out with SPARK_RAPIDS_TPU_NO_COMPILE_CACHE=1 or
# override the standard JAX_COMPILATION_CACHE_DIR.
def _host_cache_key() -> str:
    """Fingerprint the host's CPU feature set: XLA:CPU AOT artifacts
    compiled on one machine type SIGILL on another (observed when a
    cache dir written under avx512 'prefer-no-gather' hosts was loaded
    on a different host), so each machine type gets its own cache dir."""
    import hashlib
    import platform
    feat = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feat += line
                    break
    except OSError:
        pass
    return hashlib.sha1(feat.encode()).hexdigest()[:12]


if not _os.environ.get("SPARK_RAPIDS_TPU_NO_COMPILE_CACHE"):
    _cache_dir = _os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        _os.path.expanduser("~/.cache/spark_rapids_tpu/xla-"
                            + _host_cache_key()))
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # the workload suites compile hundreds of small kernels per
        # query (~70ms each on XLA:CPU, 68 for TPC-DS q1 alone); at the
        # default threshold NONE of them persist and every suite run
        # re-pays the full compile bill — persist everything
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           0.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                           0)
    except Exception:  # older jax without the knobs: in-memory only
        pass

__version__ = "0.2.0"

from spark_rapids_tpu import types  # noqa: E402,F401
from spark_rapids_tpu.config import RapidsConf  # noqa: E402,F401
