"""spark_rapids_tpu: a TPU-native Spark-SQL columnar accelerator framework.

Re-creation of the capability surface of NVIDIA's RAPIDS Accelerator for
Apache Spark (reference: andygrove/spark-rapids v0.2.0-SNAPSHOT), designed
TPU-first: columnar batches are static-shape JAX arrays in HBM, operators
compile to fused XLA executables cached per batch bucket, shuffle rides
ICI collectives under shard_map, and spill management is an explicit
host-driven tier chain (HBM -> host -> disk).

Spark parity requires 64-bit longs/doubles, so x64 is enabled at import
(the reference's cuDF kernels are 64-bit native; on TPU f64 is emulated --
performance-sensitive pipelines should prefer f32/bf16 columns).
"""
import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.2.0"

from spark_rapids_tpu import types  # noqa: E402,F401
from spark_rapids_tpu.config import RapidsConf  # noqa: E402,F401
