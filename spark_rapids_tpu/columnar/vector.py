"""TPU column vectors: static-shape, validity-masked JAX arrays.

Role parallel to the reference's `GpuColumnVector.java:39` (a Spark
ColumnVector wrapping a cuDF device column).  The TPU twist: XLA compiles
per shape, so every vector is padded to a *bucketed capacity* (powers of two)
and carries an explicit validity mask.  A batch's logical row count lives on
the host (`ColumnarBatch.num_rows`); inside jitted kernels the row mask is
derived from an iota < num_rows operand so the same executable serves every
batch in the bucket.

Strings (reference: cuDF string columns) are a uint8[capacity, char_cap]
byte tensor plus int32 lengths — fixed-width so string kernels vectorize on
the VPU (see exprs/strings.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T

# ---------------------------------------------------------------------------
# capacity bucketing — the compile-cache key discipline (SURVEY.md §7 hard
# part (a)): batches are padded to the next bucket so XLA executables are
# reused across batches.
MIN_CAPACITY = 32
MIN_CHAR_CAP = 8


def bucket_capacity(n: int, minimum: int = MIN_CAPACITY) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


def bucket_char_cap(n: int) -> int:
    return bucket_capacity(max(n, 1), MIN_CHAR_CAP)


def _f32_shadow(x_f64: np.ndarray) -> np.ndarray:
    """FLOAT64 -> f32 narrow shadow with EXPLICIT overflow semantics
    (VERDICT r4: the bare astype overflowed finite values to ±inf with
    a silent RuntimeWarning — exactly where a parity bug would hide).
    Invariants consumers rely on:
      - monotone: x <= y  =>  shadow(x) <= shadow(y)  (top-k pruning)
      - finiteness preserved: finite f64 -> finite f32 (clamped to
        ±f32max past the f32 range), ±inf -> ±inf, NaN -> NaN
      - sign preserved (incl. -0.0)."""
    with np.errstate(over="ignore"):
        n32 = x_f64.astype(np.float32)
    over = np.isinf(n32) & np.isfinite(x_f64)
    if over.any():
        fmax = np.finfo(np.float32).max
        n32 = np.where(over, np.copysign(fmax, x_f64).astype(np.float32),
                       n32)
    return n32


def _pad_to(arr: np.ndarray, capacity: int, axis: int = 0) -> np.ndarray:
    n = arr.shape[axis]
    if n == capacity:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, capacity - n)
    return np.pad(arr, pad)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ColumnVector:
    """One column: `data` padded to capacity, `validity` True where non-null.

    For STRING columns `data` is uint8[capacity, char_cap] and `lengths`
    int32[capacity]; otherwise `lengths` is None.

    `narrow` is an optional 32-BIT SHADOW of `data`: 64-bit elementwise
    ops are ~50-100x slower than 32-bit on TPU (no native 64-bit; XLA
    emulates), so sources upload an i32 copy of INT64 columns whose
    values fit int32 (EXACT — verified host-side) and an f32 copy of
    FLOAT64 columns (LOSSY — only used by paths that already carry
    variableFloatAgg-class tolerance).  Kernels check for it at trace
    time (it is part of the batch signature).
    """
    dtype: T.DataType
    data: jnp.ndarray
    validity: jnp.ndarray
    lengths: Optional[jnp.ndarray] = None
    narrow: Optional[jnp.ndarray] = None

    # -- pytree protocol so vectors flow through jit/shard_map --------------
    def tree_flatten(self):
        children = (self.data, self.validity, self.lengths, self.narrow)
        return children, self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity, lengths, narrow = children
        return cls(aux, data, validity, lengths, narrow)

    # -----------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def char_cap(self) -> int:
        assert self.dtype.is_string
        return self.data.shape[1]

    def has_nulls_upto(self, num_rows: int) -> bool:
        v = np.asarray(self.validity[:num_rows])
        return not bool(v.all())

    # -- host <-> device ----------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, dtype: Optional[T.DataType] = None,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "ColumnVector":
        if dtype is None:
            dtype = T.from_numpy_dtype(values.dtype)
        n = len(values)
        cap = capacity or bucket_capacity(n)
        if validity is None:
            if values.dtype == object:
                validity = np.array([v is not None for v in values], bool)
            elif np.issubdtype(values.dtype, np.floating):
                validity = np.ones(n, bool)  # NaN is a value, not null (Spark)
            else:
                validity = np.ones(n, bool)
        validity = _pad_to(np.asarray(validity, bool), cap)

        if dtype.is_string:
            return _strings_from_host(values, validity, cap)

        storage = dtype.storage_dtype
        if values.dtype == object:
            safe = np.array([v if v is not None else 0 for v in values],
                            dtype=storage)
        elif values.dtype.kind == "M":
            safe = values.astype("datetime64[us]").astype(np.int64)
        else:
            safe = np.asarray(values).astype(storage, copy=False)
        safe = _pad_to(safe, cap)
        narrow = None
        if dtype.id == T.TypeId.INT64 and len(safe):
            lo, hi = safe.min(), safe.max()
            if np.iinfo(np.int32).min <= lo and hi <= np.iinfo(np.int32).max:
                narrow = jnp.asarray(safe.astype(np.int32))
        elif dtype.id == T.TypeId.FLOAT64:
            narrow = jnp.asarray(_f32_shadow(safe))
        return ColumnVector(dtype, jnp.asarray(safe), jnp.asarray(validity),
                            None, narrow)

    @staticmethod
    def from_scalar(value: Any, dtype: T.DataType, capacity: int,
                    num_rows: int) -> "ColumnVector":
        """Broadcast a scalar to a column (partition values, literals)."""
        if value is None:
            validity = jnp.zeros(capacity, bool)
            if dtype.is_string:
                data = jnp.zeros((capacity, MIN_CHAR_CAP), jnp.uint8)
                return ColumnVector(dtype, data, validity,
                                    jnp.zeros(capacity, jnp.int32))
            return ColumnVector(
                dtype, jnp.zeros(capacity, dtype.storage_dtype), validity)
        validity = jnp.arange(capacity) < num_rows
        if dtype.is_string:
            raw = np.frombuffer(str(value).encode("utf-8"), np.uint8)
            cc = bucket_char_cap(len(raw))
            data = np.zeros((capacity, cc), np.uint8)
            data[:, : len(raw)] = raw
            lengths = jnp.where(validity, len(raw), 0).astype(jnp.int32)
            return ColumnVector(dtype, jnp.asarray(data), validity, lengths)
        data = jnp.full(capacity, value, dtype.storage_dtype)
        return ColumnVector(dtype, data, validity)

    def to_numpy(self, num_rows: int) -> tuple[np.ndarray, np.ndarray]:
        """Return (values, validity) trimmed to num_rows; strings decode to
        an object array of python str (None for nulls)."""
        validity = np.asarray(self.validity)[:num_rows]
        if self.dtype.is_string:
            raw = np.asarray(self.data)[:num_rows]
            lens = np.asarray(self.lengths)[:num_rows]
            out = np.empty(num_rows, object)
            for i in range(num_rows):
                out[i] = (raw[i, : lens[i]].tobytes().decode("utf-8", "replace")
                          if validity[i] else None)
            return out, validity
        vals = np.asarray(self.data)[:num_rows]
        if self.dtype.id == T.TypeId.TIMESTAMP_US:
            pass  # keep int64 micros; callers convert for display
        return vals, validity

    def to_pylist(self, num_rows: int) -> list:
        vals, validity = self.to_numpy(num_rows)
        if self.dtype.is_string:
            return list(vals)
        return [vals[i].item() if validity[i] else None
                for i in range(num_rows)]

    # -- structural ops (host orchestration; device work stays in kernels) --
    def with_capacity(self, capacity: int) -> "ColumnVector":
        if capacity == self.capacity:
            return self
        if capacity < self.capacity:
            data = self.data[:capacity]
            validity = self.validity[:capacity]
            lengths = None if self.lengths is None else self.lengths[:capacity]
            narrow = (None if self.narrow is None
                      else self.narrow[:capacity])
        else:
            extra = capacity - self.capacity
            data = jnp.concatenate(
                [self.data, jnp.zeros((extra,) + self.data.shape[1:],
                                      self.data.dtype)])
            validity = jnp.concatenate([self.validity,
                                        jnp.zeros(extra, bool)])
            lengths = (None if self.lengths is None else
                       jnp.concatenate([self.lengths,
                                        jnp.zeros(extra, jnp.int32)]))
            narrow = (None if self.narrow is None else jnp.concatenate(
                [self.narrow, jnp.zeros(extra, self.narrow.dtype)]))
        return ColumnVector(self.dtype, data, validity, lengths, narrow)

    def gather(self, indices: jnp.ndarray,
               index_valid: Optional[jnp.ndarray] = None) -> "ColumnVector":
        """Take rows by index (cuDF gather analog). indices beyond num_rows
        must point at padded/zero rows; index_valid marks rows kept."""
        data = jnp.take(self.data, indices, axis=0, mode="clip")
        validity = jnp.take(self.validity, indices, mode="clip")
        if index_valid is not None:
            validity = validity & index_valid
        lengths = (None if self.lengths is None
                   else jnp.take(self.lengths, indices, mode="clip"))
        narrow = (None if self.narrow is None
                  else jnp.take(self.narrow, indices, mode="clip"))
        return ColumnVector(self.dtype, data, validity, lengths, narrow)


#: how many column validities fit one packed-i32 bitmask (callers batch
#: all columns' validity resolution into ONE random-access stream)
VMASK_BITS = 30


def validity_bit_assignment(columns) -> dict:
    """{ordinal: bit} for the first VMASK_BITS NON-STRING columns
    (strings resolve validity inside their own gather, so giving them a
    bit would waste mask capacity).  Pure dtype metadata — safe to call
    from either side of a producer/consumer kernel pair; both sides get
    the SAME assignment by construction."""
    bits: dict = {}
    for ci, c in enumerate(columns):
        if c.dtype.is_string:
            continue
        if len(bits) >= VMASK_BITS:
            break
        bits[ci] = len(bits)
    return bits


def pack_validity_bits(columns):
    """`validity_bit_assignment` plus the packed i32 mask itself, one
    bit per column per row.  Returns ({ordinal: bit}, mask-or-None)."""
    bits = validity_bit_assignment(columns)
    if not bits:
        return bits, None
    packed = jnp.zeros(columns[0].validity.shape[0], jnp.int32)
    for ci, bit in bits.items():
        packed = packed | (columns[ci].validity.astype(jnp.int32) << bit)
    return bits, packed


def gather_columns_grouped(columns, order, valid, packed_bits=None):
    """Reorder EVERY column by `order` with the fewest random-access
    streams.  A gather's cost on this chip is per random ROW ACCESS
    (~70ns), not per byte, so all 4-byte value streams (i32 data,
    narrow shadows, bitcast f32, upcast i8/i16/bool, the packed
    validity word) stack into ONE [cap, k] gather, and all f64 streams
    into another — a wide numeric batch reorders in ~2 random streams
    instead of one per column.  Strings keep their own char-tensor
    gathers.  Returns the reordered column list; `valid` marks live
    output rows."""
    from jax import lax
    bits, packed = (pack_validity_bits(columns) if packed_bits is None
                    else packed_bits)
    g32, g64f, g64i, plans = [], [], [], []
    if packed is not None:
        vm_slot = len(g32)
        g32.append(packed)
    for ci, c in enumerate(columns):
        if c.dtype.is_string:
            plans.append(("string", None, None, None))
            continue
        dt = c.data.dtype
        if c.narrow is not None and c.dtype.id in (T.TypeId.INT64,
                                                   T.TypeId.TIMESTAMP_US):
            plans.append(("narrow64", len(g32), ci, None))
            g32.append(c.narrow)
        elif dt == jnp.int32:
            plans.append(("i32", len(g32), ci, None))
            g32.append(c.data)
        elif dt == jnp.float32:
            plans.append(("f32", len(g32), ci, None))
            g32.append(lax.bitcast_convert_type(c.data, jnp.int32))
        elif dt in (jnp.dtype(jnp.bool_), jnp.dtype(jnp.int8),
                    jnp.dtype(jnp.int16)):
            plans.append((str(dt), len(g32), ci, None))
            g32.append(c.data.astype(jnp.int32))
        elif dt == jnp.float64:
            nslot = None
            if c.narrow is not None:  # lossy f32 shadow rides the i32 bus
                nslot = len(g32)
                g32.append(lax.bitcast_convert_type(
                    c.narrow.astype(jnp.float32), jnp.int32))
            plans.append(("f64", len(g64f), ci, nslot))
            g64f.append(c.data)
        else:  # int64/timestamp without a narrow shadow
            plans.append(("i64", len(g64i), ci, None))
            g64i.append(c.data)

    def taker(group):
        if not group:
            return lambda i: None
        if len(group) == 1:
            g = jnp.take(group[0], order, mode="clip")
            return lambda i: g
        stacked = jnp.take(jnp.stack(group, axis=1), order, axis=0,
                           mode="clip")
        return lambda i: stacked[:, i]

    t32, t64f, t64i = taker(g32), taker(g64f), taker(g64i)
    vm = t32(vm_slot) if packed is not None else None
    out = []
    for (kind, slot, ci, nslot), c in zip(plans, columns):
        if kind == "string":
            out.append(c.gather(order, valid))
            continue
        if ci in bits:
            v = valid & (((vm >> bits[ci]) & 1) != 0)
        else:  # beyond the 32-bit mask: own validity stream
            v = valid & jnp.take(c.validity, order, mode="clip")
        if kind == "narrow64":
            nd = t32(slot)
            out.append(ColumnVector(c.dtype, nd.astype(c.data.dtype),
                                    v, None, nd))
        elif kind == "i32":
            out.append(ColumnVector(c.dtype, t32(slot), v))
        elif kind == "f32":
            out.append(ColumnVector(
                c.dtype, lax.bitcast_convert_type(t32(slot), jnp.float32),
                v))
        elif kind == "f64":
            narrow = (None if nslot is None else
                      lax.bitcast_convert_type(t32(nslot), jnp.float32))
            out.append(ColumnVector(c.dtype, t64f(slot), v, None, narrow))
        elif kind == "i64":
            out.append(ColumnVector(c.dtype, t64i(slot), v))
        else:  # bool/int8/int16 round-trip through the i32 bus exactly
            out.append(ColumnVector(c.dtype,
                                    t32(slot).astype(c.data.dtype), v))
    return out


def gather_narrowest(c: ColumnVector, indices: jnp.ndarray,
                     valid: jnp.ndarray) -> ColumnVector:
    """Gather a non-string column's value streams with a PRE-RESOLVED
    validity (the caller batched validity into one packed-bitmask
    gather).  Random-access streams cost ~70ns/row on this chip, so:
    int64-with-narrow gathers ONLY the i32 shadow and widens exactly;
    everything else gathers data plus the narrow shadow if present."""
    from spark_rapids_tpu import types as T
    if c.narrow is not None and c.dtype.id in (T.TypeId.INT64,
                                               T.TypeId.TIMESTAMP_US):
        nd = jnp.take(c.narrow, indices, mode="clip")
        return ColumnVector(c.dtype, nd.astype(c.data.dtype), valid,
                            None, nd)
    data = jnp.take(c.data, indices, axis=0, mode="clip")
    narrow = (None if c.narrow is None
              else jnp.take(c.narrow, indices, mode="clip"))
    return ColumnVector(c.dtype, data, valid, None, narrow)


def _strings_from_host(values: np.ndarray, validity_padded: np.ndarray,
                       cap: int) -> ColumnVector:
    enc = [(v.encode("utf-8") if isinstance(v, str)
            else (v if isinstance(v, (bytes, bytearray)) else
                  (str(v).encode("utf-8") if v is not None else b"")))
           for v in values]
    n = len(enc)
    lens = np.fromiter((len(e) for e in enc), np.int32, count=n)
    max_len = int(lens.max()) if n else 0
    cc = bucket_char_cap(max_len)
    data = np.zeros((cap, cc), np.uint8)
    if n and lens.any():
        # one pass: scatter the concatenated bytes into the padded
        # matrix at vectorized flat offsets (the per-row copy loop was
        # the hot spot of every host->device string upload)
        flat = np.frombuffer(b"".join(enc), np.uint8)
        starts = np.zeros(n, np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        row = np.repeat(np.arange(n, dtype=np.int64), lens)
        off = np.arange(len(flat), dtype=np.int64) - np.repeat(starts,
                                                               lens)
        data.reshape(-1)[row * cc + off] = flat
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lens
    lengths = np.where(validity_padded, lengths, 0).astype(np.int32)
    return ColumnVector(T.STRING, jnp.asarray(data),
                        jnp.asarray(validity_padded), jnp.asarray(lengths))


def align_char_caps(a: ColumnVector, b: ColumnVector
                    ) -> tuple[ColumnVector, ColumnVector]:
    """Pad two string vectors to a shared char capacity (for concat etc.)."""
    assert a.dtype.is_string and b.dtype.is_string
    cc = max(a.char_cap, b.char_cap)
    return _pad_chars(a, cc), _pad_chars(b, cc)


def _pad_chars(v: ColumnVector, cc: int) -> ColumnVector:
    if v.char_cap == cc:
        return v
    pad = jnp.zeros((v.capacity, cc - v.char_cap), jnp.uint8)
    return ColumnVector(v.dtype, jnp.concatenate([v.data, pad], axis=1),
                        v.validity, v.lengths)
