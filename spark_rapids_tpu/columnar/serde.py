"""Contiguous host serialization of ColumnarBatch.

Role parallel of the reference's `JCudfSerialization` host stream format
(`GpuColumnarBatchSerializer.scala:37-123`) and `MetaUtils.scala` TableMeta:
one contiguous byte payload per batch plus a small metadata header, so a
batch can (a) spill device->host->disk as a single blob and (b) travel the
shuffle wire.  Rows are trimmed to `num_rows` on serialize and re-padded to
the capacity bucket on deserialize — padding never hits the wire or disk.

Layout: MAGIC | header_len:u32 | header(json utf8) | col payloads…
Header: {num_rows, fields: [{name, dtype, char_cap?}], sizes: [...]}.
Each column payload = data bytes (row-trimmed) + validity (packed bits).
"""
from __future__ import annotations

import json
from typing import Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import (
    ColumnVector, _pad_to, bucket_capacity, bucket_char_cap)

MAGIC = b"TPUB"


def _pack_bits(mask: np.ndarray) -> bytes:
    return np.packbits(mask.astype(np.uint8), bitorder="little").tobytes()


def _unpack_bits(data: bytes, n: int) -> np.ndarray:
    return np.unpackbits(np.frombuffer(data, np.uint8),
                         bitorder="little")[:n].astype(bool)


def serialize_batch(batch: ColumnarBatch) -> bytes:
    batch = batch.dense()
    # movement ledger: serialization pulls the full padded device
    # arrays host-side (spill / shuffle-serve readback)
    from spark_rapids_tpu.utils import movement as MV
    if MV.ledger() is not None:
        MV.record(MV.EDGE_READBACK, batch.device_size_bytes(),
                  site="serde.serialize")
    batch.prefetch()
    batch.verify_checks()
    n = batch.num_rows
    fields_meta = []
    payloads = []
    for f, c in zip(batch.schema.fields, batch.columns):
        data = np.asarray(c.data)[:n]
        validity = np.asarray(c.validity)[:n]
        meta = {"name": f.name, "dtype": f.dtype.id.value}
        if f.dtype.is_string:
            lens = np.asarray(c.lengths)[:n]
            # trim char dimension to what the rows actually use
            used = int(lens.max()) if n else 0
            data = np.ascontiguousarray(data[:, :used])
            meta["char_cap"] = used
            payload = (data.tobytes() + lens.astype(np.int32).tobytes()
                       + _pack_bits(validity))
        else:
            payload = (np.ascontiguousarray(data).tobytes()
                       + _pack_bits(validity))
        meta["size"] = len(payload)
        fields_meta.append(meta)
        payloads.append(payload)
    header = json.dumps({"num_rows": n, "fields": fields_meta},
                        separators=(",", ":")).encode()
    out = bytearray()
    out += MAGIC
    out += len(header).to_bytes(4, "little")
    out += header
    for p in payloads:
        out += p
    return bytes(out)


def peek_meta(blob: bytes) -> dict:
    """Read just the header (the TableMeta analog) without materializing."""
    assert blob[:4] == MAGIC, "bad magic"
    hlen = int.from_bytes(blob[4:8], "little")
    return json.loads(blob[8:8 + hlen].decode())


def deserialize_batch(blob: bytes,
                      capacity: Optional[int] = None) -> ColumnarBatch:
    meta = peek_meta(blob)
    hlen = int.from_bytes(blob[4:8], "little")
    off = 8 + hlen
    n = meta["num_rows"]
    cap = capacity or bucket_capacity(n)
    cols, fields = [], []
    for fm in meta["fields"]:
        dt = T.DataType(T.TypeId(fm["dtype"]))
        payload = blob[off:off + fm["size"]]
        off += fm["size"]
        if dt.is_string:
            used = fm["char_cap"]
            dsz = n * used
            raw = np.frombuffer(payload[:dsz], np.uint8).reshape(n, used)
            lens = np.frombuffer(payload[dsz:dsz + 4 * n], np.int32)
            validity = _unpack_bits(payload[dsz + 4 * n:], n)
            cc = bucket_char_cap(used)
            data = np.zeros((cap, cc), np.uint8)
            data[:n, :used] = raw
            col = ColumnVector(
                dt, _dev(data), _dev(_pad_to(validity, cap)),
                _dev(_pad_to(lens, cap)))
        else:
            storage = dt.storage_dtype
            dsz = n * storage.itemsize
            vals = np.frombuffer(payload[:dsz], storage)
            validity = _unpack_bits(payload[dsz:], n)
            col = ColumnVector(dt, _dev(_pad_to(vals, cap)),
                               _dev(_pad_to(validity, cap)))
        cols.append(col)
        fields.append(T.Field(fm["name"], dt))
    out = ColumnarBatch(T.Schema(tuple(fields)), cols, n)
    # movement ledger: deserialization re-uploads the padded arrays
    # (spill re-read / shuffle-receive materialization)
    from spark_rapids_tpu.utils import movement as MV
    if MV.ledger() is not None:
        MV.record(MV.EDGE_UPLOAD, out.device_size_bytes(),
                  site="serde.deserialize")
    return out


def _dev(arr: np.ndarray):
    import jax.numpy as jnp
    return jnp.asarray(arr)
