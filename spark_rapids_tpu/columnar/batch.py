"""ColumnarBatch: the unit of execution, mirroring Spark's ColumnarBatch of
`GpuColumnVector`s (reference `GpuColumnVector.java:252-261` converters and
`GpuCoalesceBatches.scala` concat).

A batch is host-orchestrated: `num_rows` is a Python int (the driver of
bucketed compilation); the device payload is a pytree of padded arrays, so a
whole batch can be passed into one jitted kernel.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import (
    ColumnVector, align_char_caps, bucket_capacity)


@dataclasses.dataclass
class ColumnarBatch:
    schema: T.Schema
    columns: list[ColumnVector]
    num_rows: int

    def __post_init__(self):
        assert len(self.columns) == len(self.schema.fields)
        caps = {c.capacity for c in self.columns}
        assert len(caps) <= 1, f"ragged capacities {caps}"

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket_capacity(
            self.num_rows)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name_or_idx) -> ColumnVector:
        if isinstance(name_or_idx, str):
            return self.columns[self.schema.index(name_or_idx)]
        return self.columns[name_or_idx]

    def row_mask(self) -> jnp.ndarray:
        return jnp.arange(self.capacity) < self.num_rows

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(data: dict[str, np.ndarray],
                   schema: Optional[T.Schema] = None,
                   validity: Optional[dict[str, np.ndarray]] = None,
                   capacity: Optional[int] = None) -> "ColumnarBatch":
        names = list(data)
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket_capacity(n)
        cols, fields = [], []
        for name in names:
            dt = schema.field(name).dtype if schema else None
            v = validity.get(name) if validity else None
            col = ColumnVector.from_numpy(np.asarray(data[name]), dt, v, cap)
            cols.append(col)
            fields.append(T.Field(name, col.dtype))
        return ColumnarBatch(schema or T.Schema(tuple(fields)), cols, n)

    @staticmethod
    def from_pandas(df) -> "ColumnarBatch":
        data, validity = {}, {}
        for name in df.columns:
            s = df[name]
            if s.dtype == object or str(s.dtype) in ("string", "str"):
                vals = np.array(
                    [None if v is None or (isinstance(v, float) and np.isnan(v))
                     else v for v in s.tolist()], dtype=object)
                data[name] = vals
            else:
                mask = s.isna().to_numpy()
                arr = s.to_numpy()
                if mask.any() and arr.dtype.kind == "f":
                    arr = np.where(mask, 0.0, arr)
                data[name] = arr
                validity[name] = ~mask
        return ColumnarBatch.from_numpy(data, validity=validity or None)

    @staticmethod
    def from_arrow(table) -> "ColumnarBatch":
        """Arrow table/record-batch → device batch (the scan upload path,
        reference `Table.readParquet` + `GpuColumnVector.from`)."""
        data, validity, fields = {}, {}, []
        for i, name in enumerate(table.schema.names):
            col = table.column(i)
            if hasattr(col, "combine_chunks"):
                col = col.combine_chunks()
            dt = T.from_arrow(col.type)
            fields.append(T.Field(name, dt))
            np_valid = ~np.asarray(col.is_null())
            if dt.is_string:
                data[name] = np.array(
                    [v.as_py() for v in col], dtype=object)
            elif dt.id == T.TypeId.TIMESTAMP_US:
                import pyarrow.compute as pc
                import pyarrow as pa
                c = col.cast(pa.timestamp("us"))
                arr = c.to_numpy(zero_copy_only=False)
                arr = arr.astype("datetime64[us]").astype(np.int64)
                arr = np.where(np_valid, arr, 0)
                data[name] = arr
            else:
                arr = col.to_numpy(zero_copy_only=False)
                if arr.dtype.kind == "f" and (~np_valid).any():
                    arr = np.where(np_valid, arr, 0.0)
                arr = np.asarray(arr, dt.storage_dtype)
                data[name] = arr
            validity[name] = np_valid
        return ColumnarBatch.from_numpy(
            data, T.Schema(tuple(fields)), validity)

    # -- host conversion ----------------------------------------------------
    def to_pandas(self):
        import pandas as pd
        out = {}
        for f, c in zip(self.schema.fields, self.columns):
            vals, validity = c.to_numpy(self.num_rows)
            if f.dtype.is_string:
                out[f.name] = pd.Series(list(vals), dtype=object)
            elif f.dtype.id == T.TypeId.TIMESTAMP_US:
                s = pd.Series(vals.astype("datetime64[us]"))
                s[~validity] = pd.NaT
                out[f.name] = s
            elif validity.all():
                out[f.name] = pd.Series(vals)
            else:
                s = pd.Series(vals).astype(object)
                s[~validity] = None
                out[f.name] = s
        return pd.DataFrame(out)

    def to_pylist(self) -> list[dict]:
        cols = {f.name: c.to_pylist(self.num_rows)
                for f, c in zip(self.schema.fields, self.columns)}
        return [{k: v[i] for k, v in cols.items()}
                for i in range(self.num_rows)]

    def to_arrow(self):
        import pyarrow as pa
        arrays = []
        for f, c in zip(self.schema.fields, self.columns):
            vals, validity = c.to_numpy(self.num_rows)
            if f.dtype.is_string:
                arrays.append(pa.array(list(vals), T.to_arrow(f.dtype)))
            else:
                mask = None if validity.all() else ~validity
                if f.dtype.id == T.TypeId.TIMESTAMP_US:
                    arrays.append(pa.array(vals, pa.int64(), mask=mask).cast(
                        T.to_arrow(f.dtype)))
                else:
                    arrays.append(
                        pa.array(vals, T.to_arrow(f.dtype), mask=mask))
        return pa.table(arrays, names=list(self.schema.names))

    # -- structural ---------------------------------------------------------
    def select(self, names: Iterable[str]) -> "ColumnarBatch":
        names = list(names)
        cols = [self.column(n) for n in names]
        fields = tuple(self.schema.field(n) for n in names)
        return ColumnarBatch(T.Schema(fields), cols, self.num_rows)

    def with_capacity(self, capacity: int) -> "ColumnarBatch":
        if capacity == self.capacity:
            return self
        return ColumnarBatch(
            self.schema, [c.with_capacity(capacity) for c in self.columns],
            min(self.num_rows, capacity))

    def gather(self, indices: jnp.ndarray, index_valid: jnp.ndarray,
               new_num_rows: int) -> "ColumnarBatch":
        cols = [c.gather(indices, index_valid) for c in self.columns]
        return ColumnarBatch(self.schema, cols, new_num_rows)

    def slice(self, start: int, length: int) -> "ColumnarBatch":
        """Host-side row slice (reference SlicedGpuColumnVector)."""
        length = max(0, min(length, self.num_rows - start))
        cap = bucket_capacity(length)
        idx = jnp.arange(cap) + start
        valid = jnp.arange(cap) < length
        cols = [c.gather(jnp.where(valid, idx, 0), valid)
                for c in self.columns]
        return ColumnarBatch(self.schema, cols, length)

    def device_size_bytes(self) -> int:
        total = 0
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size
            if c.lengths is not None:
                total += c.lengths.size * 4
        return total


def empty_batch(schema: T.Schema) -> ColumnarBatch:
    """Zero-row batch with properly-typed zero-filled columns."""
    from spark_rapids_tpu.columnar.vector import MIN_CAPACITY, MIN_CHAR_CAP
    cols = []
    for f in schema.fields:
        validity = jnp.zeros(MIN_CAPACITY, bool)
        if f.dtype.is_string:
            cols.append(ColumnVector(
                f.dtype, jnp.zeros((MIN_CAPACITY, MIN_CHAR_CAP), jnp.uint8),
                validity, jnp.zeros(MIN_CAPACITY, jnp.int32)))
        else:
            cols.append(ColumnVector(
                f.dtype, jnp.zeros(MIN_CAPACITY, f.dtype.storage_dtype),
                validity))
    return ColumnarBatch(schema, cols, 0)


def concat_batches(batches: list[ColumnarBatch]) -> ColumnarBatch:
    """Device-side concat (reference `Table.concatenate`,
    `GpuCoalesceBatches.scala:53`): stack padded columns then gather the
    valid rows of each input into a fresh bucketed batch."""
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    total = sum(b.num_rows for b in batches)
    cap = bucket_capacity(total)
    out_cols = []
    for ci, f in enumerate(schema.fields):
        vecs = [b.columns[ci] for b in batches]
        if f.dtype.is_string:
            cc = max(v.char_cap for v in vecs)
            from spark_rapids_tpu.columnar.vector import _pad_chars
            vecs = [_pad_chars(v, cc) for v in vecs]
        data = jnp.concatenate([v.data for v in vecs])
        validity = jnp.concatenate([v.validity for v in vecs])
        lengths = (jnp.concatenate([v.lengths for v in vecs])
                   if vecs[0].lengths is not None else None)
        # build gather indices mapping output row -> stacked row
        out_cols.append((data, validity, lengths))
    # gather indices: for each batch, rows [0, num_rows) at its offset
    idx_parts, off = [], 0
    for b in batches:
        idx_parts.append(np.arange(b.num_rows) + off)
        off += b.capacity
    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    idx = np.pad(idx, (0, cap - len(idx)))
    jidx = jnp.asarray(idx)
    valid = jnp.arange(cap) < total
    cols = []
    for (data, validity, lengths), f in zip(out_cols, schema.fields):
        cols.append(ColumnVector(
            f.dtype,
            jnp.take(data, jidx, axis=0, mode="clip"),
            jnp.take(validity, jidx, mode="clip") & valid,
            None if lengths is None else jnp.take(lengths, jidx, mode="clip")))
    return ColumnarBatch(schema, cols, total)
