"""ColumnarBatch: the unit of execution, mirroring Spark's ColumnarBatch of
`GpuColumnVector`s (reference `GpuColumnVector.java:252-261` converters and
`GpuCoalesceBatches.scala` concat).

A batch is host-orchestrated, but LAZILY so: `num_rows` may be either a
Python int or a device scalar still being computed.  Reading `.num_rows`
materializes (a ~150ms round trip on a tunnel-attached chip — the single
most expensive primitive in this engine), while `.num_rows_i32` /
`.row_mask()` / `.maybe_nonempty()` keep the pipeline asynchronous.  This
is the TPU analog of the reference keeping everything on the CUDA stream
until a deliberate sync (`GpuColumnVector`/stream discipline): dispatches
are ~0.25ms, syncs are ~150ms, so the engine syncs only at host exits.

Batches can also carry deferred validity `checks` (device bool scalars)
registered by optimistic fast paths — see utils/checks.py.  Host-exit
conversions verify them before results are trusted.
"""
from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import (
    ColumnVector, align_char_caps, bucket_capacity)


def _async_copy(arr) -> None:
    try:
        arr.copy_to_host_async()
    except Exception:
        pass


class ColumnarBatch:
    """schema + padded device columns + (possibly lazy) row count.

    A batch may be SPARSE: `sparse` is a device bool mask selecting the
    live rows (a Velox-style selection vector).  Compaction (nonzero +
    gather) costs ~130ms per 2M rows on TPU, so filters and joins defer
    it: sparse-aware consumers (sort, aggregate, filter, project, join
    probe) fold the mask into their own row masking for free; everyone
    else calls `.dense()` to compact on demand.  For a sparse batch,
    rows [0, num_rows) are NOT contiguous — `num_rows` is the mask
    popcount."""

    __slots__ = ("schema", "columns", "_rows", "checks", "sparse")

    def __init__(self, schema: T.Schema, columns: list[ColumnVector],
                 num_rows, checks: tuple = (), sparse=None):
        self.schema = schema
        self.columns = columns
        self.sparse = sparse
        if num_rows is None:
            assert sparse is not None
            num_rows = jnp.sum(sparse).astype(jnp.int32)
        self._rows = num_rows
        self.checks = tuple(checks)
        assert len(self.columns) == len(self.schema.fields)
        caps = {c.capacity for c in self.columns}
        assert len(caps) <= 1, f"ragged capacities {caps}"

    def dense(self) -> "ColumnarBatch":
        """Compact a sparse batch to the dense rows-first layout (the
        expensive step deferred selection exists to avoid — only host
        exits and position-addressed ops should need it)."""
        if self.sparse is None:
            return self
        cap = self.capacity
        n = self.num_rows_i32
        (idx,) = jnp.nonzero(self.sparse, size=cap, fill_value=cap - 1)
        valid = jnp.arange(cap) < n
        cols = [c.gather(idx, valid) for c in self.columns]
        rows = self._rows if isinstance(self._rows, int) else n
        return ColumnarBatch(self.schema, cols, rows, self.checks)

    # -- row count (lazy) ---------------------------------------------------
    @property
    def num_rows(self) -> int:
        """Host row count — SYNCS if the count is still a device scalar."""
        if not isinstance(self._rows, int):
            from spark_rapids_tpu.utils import checks as CK
            CK.note_host_sync("batch.num_rows", nbytes=4)
            self._rows = int(np.asarray(self._rows))
        return self._rows

    @num_rows.setter
    def num_rows(self, value):
        self._rows = value

    @property
    def num_rows_known(self) -> bool:
        return isinstance(self._rows, int)

    @property
    def num_rows_i32(self):
        """Row count as an int32 operand for kernels — never syncs."""
        return jnp.asarray(self._rows, jnp.int32)

    def maybe_nonempty(self) -> bool:
        """True unless the batch is KNOWN to be empty (no sync)."""
        return not isinstance(self._rows, int) or self._rows > 0

    def prefetch(self) -> None:
        """Start async D2H copies of the row count and all buffers so a
        following host conversion pays ~one round trip, not one per
        array."""
        if not isinstance(self._rows, int):
            _async_copy(self._rows)
        for c in self.columns:
            _async_copy(c.data)
            _async_copy(c.validity)
            if c.lengths is not None:
                _async_copy(c.lengths)

    def verify_checks(self) -> None:
        from spark_rapids_tpu.utils import checks as CK
        CK.verify(self.checks)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket_capacity(
            self.num_rows)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name_or_idx) -> ColumnVector:
        if isinstance(name_or_idx, str):
            return self.columns[self.schema.index(name_or_idx)]
        return self.columns[name_or_idx]

    def row_mask(self) -> jnp.ndarray:
        if self.sparse is not None:
            return self.sparse
        return jnp.arange(self.capacity) < self.num_rows_i32

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(data: dict[str, np.ndarray],
                   schema: Optional[T.Schema] = None,
                   validity: Optional[dict[str, np.ndarray]] = None,
                   capacity: Optional[int] = None) -> "ColumnarBatch":
        names = list(data)
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket_capacity(n)
        cols, fields = [], []
        for name in names:
            dt = schema.field(name).dtype if schema else None
            v = validity.get(name) if validity else None
            col = ColumnVector.from_numpy(np.asarray(data[name]), dt, v, cap)
            cols.append(col)
            fields.append(T.Field(name, col.dtype))
        # movement ledger: this is THE host->device construction point
        # (from_arrow / from_pandas funnel through here) — one upload
        # record per batch, padded device footprint incl. narrow shadows
        from spark_rapids_tpu.utils import movement as MV
        if cols and MV.ledger() is not None:
            MV.record(MV.EDGE_UPLOAD,
                      sum(MV.vector_device_bytes(c) for c in cols),
                      site="batch.from_numpy", rows=n)
        return ColumnarBatch(schema or T.Schema(tuple(fields)), cols, n)

    @staticmethod
    def from_pandas(df) -> "ColumnarBatch":
        data, validity = {}, {}
        for name in df.columns:
            s = df[name]
            if s.dtype == object or str(s.dtype) in ("string", "str"):
                vals = np.array(
                    [None if v is None or (isinstance(v, float) and np.isnan(v))
                     else v for v in s.tolist()], dtype=object)
                data[name] = vals
            else:
                mask = s.isna().to_numpy()
                arr = s.to_numpy()
                if mask.any() and arr.dtype.kind == "f":
                    arr = np.where(mask, 0.0, arr)
                data[name] = arr
                validity[name] = ~mask
        return ColumnarBatch.from_numpy(data, validity=validity or None)

    @staticmethod
    def from_arrow(table) -> "ColumnarBatch":
        """Arrow table/record-batch → device batch (the scan upload path,
        reference `Table.readParquet` + `GpuColumnVector.from`)."""
        data, validity, fields = {}, {}, []
        for i, name in enumerate(table.schema.names):
            col = table.column(i)
            if hasattr(col, "combine_chunks"):
                col = col.combine_chunks()
            dt = T.from_arrow(col.type)
            fields.append(T.Field(name, dt))
            np_valid = ~np.asarray(col.is_null())
            if dt.is_string:
                data[name] = np.array(
                    [v.as_py() for v in col], dtype=object)
            elif dt.id == T.TypeId.TIMESTAMP_US:
                import pyarrow.compute as pc
                import pyarrow as pa
                c = col.cast(pa.timestamp("us"))
                arr = c.to_numpy(zero_copy_only=False)
                arr = arr.astype("datetime64[us]").astype(np.int64)
                arr = np.where(np_valid, arr, 0)
                data[name] = arr
            else:
                arr = col.to_numpy(zero_copy_only=False)
                if arr.dtype.kind == "f" and (~np_valid).any():
                    arr = np.where(np_valid, arr, 0.0)
                arr = np.asarray(arr, dt.storage_dtype)
                data[name] = arr
            validity[name] = np_valid
        return ColumnarBatch.from_numpy(
            data, T.Schema(tuple(fields)), validity)

    def _note_readback(self, site: str) -> None:
        """Ledger hook for the host-conversion sinks: the full padded
        device arrays are pulled to the host (to_numpy trims after the
        transfer), so the moved bytes are the device footprint."""
        from spark_rapids_tpu.utils import movement as MV
        if MV.ledger() is not None:
            MV.record(MV.EDGE_READBACK, self.device_size_bytes(),
                      site=site)

    # -- host conversion ----------------------------------------------------
    def to_pandas(self):
        import pandas as pd
        if self.sparse is not None:
            return self.dense().to_pandas()
        self._note_readback("collect.to_pandas")
        self.prefetch()
        self.verify_checks()
        out = {}
        for f, c in zip(self.schema.fields, self.columns):
            vals, validity = c.to_numpy(self.num_rows)
            if f.dtype.is_string:
                out[f.name] = pd.Series(list(vals), dtype=object)
            elif f.dtype.id == T.TypeId.TIMESTAMP_US:
                s = pd.Series(vals.astype("datetime64[us]"))
                s[~validity] = pd.NaT
                out[f.name] = s
            elif validity.all():
                out[f.name] = pd.Series(vals)
            else:
                s = pd.Series(vals).astype(object)
                s[~validity] = None
                out[f.name] = s
        return pd.DataFrame(out)

    def to_pylist(self) -> list[dict]:
        if self.sparse is not None:
            return self.dense().to_pylist()
        self._note_readback("collect.to_pylist")
        self.prefetch()
        self.verify_checks()
        cols = {f.name: c.to_pylist(self.num_rows)
                for f, c in zip(self.schema.fields, self.columns)}
        return [{k: v[i] for k, v in cols.items()}
                for i in range(self.num_rows)]

    def to_arrow(self):
        import pyarrow as pa
        if self.sparse is not None:
            return self.dense().to_arrow()
        self._note_readback("collect.to_arrow")
        self.prefetch()
        self.verify_checks()
        arrays = []
        for f, c in zip(self.schema.fields, self.columns):
            vals, validity = c.to_numpy(self.num_rows)
            if f.dtype.is_string:
                arrays.append(pa.array(list(vals), T.to_arrow(f.dtype)))
            else:
                mask = None if validity.all() else ~validity
                if f.dtype.id == T.TypeId.TIMESTAMP_US:
                    arrays.append(pa.array(vals, pa.int64(), mask=mask).cast(
                        T.to_arrow(f.dtype)))
                else:
                    arrays.append(
                        pa.array(vals, T.to_arrow(f.dtype), mask=mask))
        return pa.table(arrays, names=list(self.schema.names))

    # -- structural ---------------------------------------------------------
    def select(self, names: Iterable[str]) -> "ColumnarBatch":
        names = list(names)
        cols = [self.column(n) for n in names]
        fields = tuple(self.schema.field(n) for n in names)
        return ColumnarBatch(T.Schema(fields), cols, self._rows,
                             self.checks, self.sparse)

    def with_capacity(self, capacity: int) -> "ColumnarBatch":
        if capacity == self.capacity:
            return self
        if self.sparse is not None:
            return self.dense().with_capacity(capacity)
        rows = (min(self._rows, capacity) if self.num_rows_known
                else jnp.minimum(self._rows, capacity))
        return ColumnarBatch(
            self.schema, [c.with_capacity(capacity) for c in self.columns],
            rows, self.checks)

    def gather(self, indices: jnp.ndarray, index_valid: jnp.ndarray,
               new_num_rows) -> "ColumnarBatch":
        assert self.sparse is None, "gather() addresses dense rows"
        cols = [c.gather(indices, index_valid) for c in self.columns]
        return ColumnarBatch(self.schema, cols, new_num_rows, self.checks)

    def slice(self, start: int, length: int) -> "ColumnarBatch":
        """Host-side row slice (reference SlicedGpuColumnVector)."""
        if self.sparse is not None:
            return self.dense().slice(start, length)
        length = max(0, min(length, self.num_rows - start))
        cap = bucket_capacity(length)
        idx = jnp.arange(cap) + start
        valid = jnp.arange(cap) < length
        cols = [c.gather(jnp.where(valid, idx, 0), valid)
                for c in self.columns]
        return ColumnarBatch(self.schema, cols, length, self.checks)

    def take_head(self, n: int) -> "ColumnarBatch":
        """First min(n, num_rows) rows at a STATIC bucket(n) capacity,
        without syncing on the row count (limit/top-N building block)."""
        if self.sparse is not None:
            return self.dense().take_head(n)
        cap = bucket_capacity(n)
        if cap >= self.capacity:
            rows = (min(self._rows, n) if self.num_rows_known
                    else jnp.minimum(self.num_rows_i32, n))
            return ColumnarBatch(self.schema, self.columns, rows,
                                 self.checks)
        idx = jnp.arange(cap)
        count = jnp.minimum(self.num_rows_i32, n)
        valid = idx < count
        cols = [c.gather(idx, valid) for c in self.columns]
        rows = min(self._rows, n) if self.num_rows_known else count
        return ColumnarBatch(self.schema, cols, rows, self.checks)

    def device_size_bytes(self) -> int:
        total = 0
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size
            if c.lengths is not None:
                total += c.lengths.size * 4
        return total


def empty_batch(schema: T.Schema) -> ColumnarBatch:
    """Zero-row batch with properly-typed zero-filled columns."""
    from spark_rapids_tpu.columnar.vector import MIN_CAPACITY, MIN_CHAR_CAP
    cols = []
    for f in schema.fields:
        validity = jnp.zeros(MIN_CAPACITY, bool)
        if f.dtype.is_string:
            cols.append(ColumnVector(
                f.dtype, jnp.zeros((MIN_CAPACITY, MIN_CHAR_CAP), jnp.uint8),
                validity, jnp.zeros(MIN_CAPACITY, jnp.int32)))
        else:
            cols.append(ColumnVector(
                f.dtype, jnp.zeros(MIN_CAPACITY, f.dtype.storage_dtype),
                validity))
    return ColumnarBatch(schema, cols, 0)


def concat_batches(batches: list[ColumnarBatch],
                   sparse_ok: bool = False) -> ColumnarBatch:
    """Device-side concat (reference `Table.concatenate`,
    `GpuCoalesceBatches.scala:53`): stack padded columns then gather the
    valid rows of each input into a fresh bucketed batch.

    When any input's row count is still a device scalar, the gather
    indices are computed DEVICE-SIDE (no sync): output capacity is then
    the bucketed sum of input CAPACITIES (the static worst case) and the
    output row count stays lazy.

    `sparse_ok=True` (callers whose consumer takes deferred-selection
    batches — the aggregate merge kernel, collect's final dense):
    sparse inputs skip their per-input dense() gathers entirely — padded
    columns and selection masks are stacked as-is and the result stays
    sparse, so the whole concat is sequential copies (bandwidth-bound)
    instead of two random-access gather rounds (~70ns/row each on this
    chip)."""
    assert batches
    if len(batches) == 1:
        return batches[0]
    if sparse_ok and any(b.sparse is not None for b in batches):
        return _concat_sparse(batches)
    batches = [b.dense() for b in batches]
    schema = batches[0].schema
    checks = tuple(c for b in batches for c in b.checks)
    lazy = not all(b.num_rows_known for b in batches)
    if lazy:
        return _concat_lazy(batches, schema, checks)
    total = sum(b.num_rows for b in batches)
    cap = bucket_capacity(total)
    out_cols = _stack_columns(batches, schema)
    # gather indices: for each batch, rows [0, num_rows) at its offset
    idx_parts, off = [], 0
    for b in batches:
        idx_parts.append(np.arange(b.num_rows) + off)
        off += b.capacity
    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    idx = np.pad(idx, (0, cap - len(idx)))
    jidx = jnp.asarray(idx)
    valid = jnp.arange(cap) < total
    cols = []
    for (data, validity, lengths, narrow), f in zip(out_cols, schema.fields):
        cols.append(ColumnVector(
            f.dtype,
            jnp.take(data, jidx, axis=0, mode="clip"),
            jnp.take(validity, jidx, mode="clip") & valid,
            None if lengths is None else jnp.take(lengths, jidx, mode="clip"),
            None if narrow is None else jnp.take(narrow, jidx, mode="clip")))
    return ColumnarBatch(schema, cols, total, checks)


def _stack_columns(batches, schema):
    out_cols = []
    for ci, f in enumerate(schema.fields):
        vecs = [b.columns[ci] for b in batches]
        if f.dtype.is_string:
            cc = max(v.char_cap for v in vecs)
            from spark_rapids_tpu.columnar.vector import _pad_chars
            vecs = [_pad_chars(v, cc) for v in vecs]
        data = jnp.concatenate([v.data for v in vecs])
        validity = jnp.concatenate([v.validity for v in vecs])
        lengths = (jnp.concatenate([v.lengths for v in vecs])
                   if vecs[0].lengths is not None else None)
        narrow = (jnp.concatenate([v.narrow for v in vecs])
                  if all(v.narrow is not None for v in vecs) else None)
        out_cols.append((data, validity, lengths, narrow))
    return out_cols


def _concat_sparse(batches) -> ColumnarBatch:
    """Gather-free concat: stack each input's padded columns and its
    selection mask; the output batch keeps capacity = bucketed sum of
    input capacities with selection still deferred.  Compaction, if a
    consumer needs it, costs the same single gather round dense() always
    costs — so this path strictly saves the per-input dense gathers."""
    schema = batches[0].schema
    checks = tuple(c for b in batches for c in b.checks)
    scap = sum(b.capacity for b in batches)
    cap = bucket_capacity(scap)
    pad = cap - scap
    masks = [b.sparse if b.sparse is not None else b.row_mask()
             for b in batches]
    if pad:
        masks.append(jnp.zeros((pad,), bool))
    mask = jnp.concatenate(masks)
    total = sum(b.num_rows for b in batches) \
        if all(b.num_rows_known for b in batches) else \
        jnp.sum(jnp.stack([b.num_rows_i32 for b in batches]))

    def pad_tail(arr, fill=0):
        if not pad or arr is None:
            return arr
        tail_shape = (pad,) + arr.shape[1:]
        return jnp.concatenate(
            [arr, jnp.full(tail_shape, fill, arr.dtype)])

    out_cols = []
    for (data, validity, lengths, narrow), f in zip(
            _stack_columns(batches, schema), schema.fields):
        out_cols.append(ColumnVector(
            f.dtype, pad_tail(data), pad_tail(validity, False),
            pad_tail(lengths), pad_tail(narrow)))
    return ColumnarBatch(schema, out_cols, total, checks, sparse=mask)


def _concat_lazy(batches, schema, checks):
    """Sync-free concat: output row i maps to input batch
    j = #(cumulative counts <= i) at local row i - start_j; all index
    math runs on device against the (small) per-batch count vector.

    Tree-chunked past 64 inputs: the bucket-id search materializes a
    [out_cap, B] compare matrix, which at B=400 inputs of a 26M-row
    reduce partition reached a 12.8GB intermediate and OOMed HBM at
    compile time — chunks bound the matrix and recurse on the (few)
    chunk results."""
    if len(batches) > 64:
        chunks = [concat_batches(batches[i:i + 64])
                  for i in range(0, len(batches), 64)]
        return concat_batches(chunks)
    ns = jnp.stack([b.num_rows_i32 for b in batches])
    cum = jnp.cumsum(ns)
    starts = cum - ns
    total = cum[-1]
    cap_offsets = np.concatenate(
        [[0], np.cumsum([b.capacity for b in batches])[:-1]])
    cap = bucket_capacity(int(sum(b.capacity for b in batches)))
    out_cols = _stack_columns(batches, schema)
    i = jnp.arange(cap, dtype=jnp.int32)
    bid = (i[:, None] >= cum[None, :]).sum(axis=1)  # cap x B compares
    bid_c = jnp.minimum(bid, len(batches) - 1)
    local = i - jnp.take(starts, bid_c)
    jidx = jnp.take(jnp.asarray(cap_offsets, jnp.int32), bid_c) + local
    valid = i < total
    jidx = jnp.where(valid, jidx, 0)
    cols = []
    for (data, validity, lengths, narrow), f in zip(out_cols, schema.fields):
        cols.append(ColumnVector(
            f.dtype,
            jnp.take(data, jidx, axis=0, mode="clip"),
            jnp.take(validity, jidx, mode="clip") & valid,
            None if lengths is None else jnp.take(lengths, jidx, mode="clip"),
            None if narrow is None else jnp.take(narrow, jidx, mode="clip")))
    return ColumnarBatch(schema, cols, total, checks)
