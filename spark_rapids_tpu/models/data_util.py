"""Shared workload-data plumbing: DataFrame partition splitting and
CpuSource construction, used by every benchmark suite's `sources()`."""
from __future__ import annotations

import numpy as np
import pandas as pd


def split_partitions(df: pd.DataFrame, num_partitions: int
                     ) -> list[pd.DataFrame]:
    if num_partitions <= 1 or len(df) < num_partitions:
        return [df]
    bounds = np.linspace(0, len(df), num_partitions + 1).astype(int)
    return [df.iloc[bounds[i]:bounds[i + 1]].reset_index(drop=True)
            for i in range(num_partitions)]


def make_sources(tables: dict, schemas: dict, num_partitions: int = 1):
    """Wrap generated tables as CpuSource plan leaves with declared
    schemas."""
    from spark_rapids_tpu.plan.nodes import CpuSource
    return {name: CpuSource(split_partitions(df, num_partitions),
                            schemas[name])
            for name, df in tables.items()}
