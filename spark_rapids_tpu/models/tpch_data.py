"""TPC-H table schemas + dbgen-shaped synthetic data (reference
`integration_tests/.../tpch/TpchLikeSpark.scala:30-120` table readers; the
reference reads dbgen output from disk — we generate value-compatible
tables in-memory so the suite is self-contained).

Dates are stored as int32 days-since-epoch (the engine's DATE32 storage
model).  Key relationships (orderkey/custkey/partkey/suppkey/nationkey/
regionkey) are referentially consistent so every join has matches.
"""
from __future__ import annotations

import datetime as pydt

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T

EPOCH = pydt.date(1970, 1, 1)


def days(s: str) -> int:
    """'1994-01-01' -> int32 days since epoch (DATE32 literal helper)."""
    return (pydt.date.fromisoformat(s) - EPOCH).days


REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [  # (name, regionkey) — the 25 dbgen nations
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
            "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
              "5-LOW"]
SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE",
                "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAIN_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAIN_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "burnished", "chartreuse", "chiffon", "chocolate", "coral",
          "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
          "dim", "dodger", "drab", "firebrick", "floral", "forest",
          "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
          "honeydew", "hot", "hotpink", "indian", "ivory", "khaki"]

SCHEMAS = {
    "region": T.Schema.of(
        ("r_regionkey", T.INT64), ("r_name", T.STRING),
        ("r_comment", T.STRING)),
    "nation": T.Schema.of(
        ("n_nationkey", T.INT64), ("n_name", T.STRING),
        ("n_regionkey", T.INT64), ("n_comment", T.STRING)),
    "supplier": T.Schema.of(
        ("s_suppkey", T.INT64), ("s_name", T.STRING),
        ("s_address", T.STRING), ("s_nationkey", T.INT64),
        ("s_phone", T.STRING), ("s_acctbal", T.FLOAT64),
        ("s_comment", T.STRING)),
    "customer": T.Schema.of(
        ("c_custkey", T.INT64), ("c_name", T.STRING),
        ("c_address", T.STRING), ("c_nationkey", T.INT64),
        ("c_phone", T.STRING), ("c_acctbal", T.FLOAT64),
        ("c_mktsegment", T.STRING), ("c_comment", T.STRING)),
    "part": T.Schema.of(
        ("p_partkey", T.INT64), ("p_name", T.STRING),
        ("p_mfgr", T.STRING), ("p_brand", T.STRING),
        ("p_type", T.STRING), ("p_size", T.INT32),
        ("p_container", T.STRING), ("p_retailprice", T.FLOAT64),
        ("p_comment", T.STRING)),
    "partsupp": T.Schema.of(
        ("ps_partkey", T.INT64), ("ps_suppkey", T.INT64),
        ("ps_availqty", T.INT32), ("ps_supplycost", T.FLOAT64),
        ("ps_comment", T.STRING)),
    "orders": T.Schema.of(
        ("o_orderkey", T.INT64), ("o_custkey", T.INT64),
        ("o_orderstatus", T.STRING), ("o_totalprice", T.FLOAT64),
        ("o_orderdate", T.DATE32), ("o_orderpriority", T.STRING),
        ("o_clerk", T.STRING), ("o_shippriority", T.INT32),
        ("o_comment", T.STRING)),
    "lineitem": T.Schema.of(
        ("l_orderkey", T.INT64), ("l_partkey", T.INT64),
        ("l_suppkey", T.INT64), ("l_linenumber", T.INT32),
        ("l_quantity", T.FLOAT64), ("l_extendedprice", T.FLOAT64),
        ("l_discount", T.FLOAT64), ("l_tax", T.FLOAT64),
        ("l_returnflag", T.STRING), ("l_linestatus", T.STRING),
        ("l_shipdate", T.DATE32), ("l_commitdate", T.DATE32),
        ("l_receiptdate", T.DATE32), ("l_shipinstruct", T.STRING),
        ("l_shipmode", T.STRING), ("l_comment", T.STRING)),
}


def _pick(rng, options, n):
    return np.array(options, dtype=object)[
        rng.integers(0, len(options), n)]


#: nations the query suite predicates on (FRANCE/GERMANY q7, BRAZIL q8,
#: CANADA q20, SAUDI ARABIA q21, GERMANY q11, ASIA-region INDIA/CHINA for
#: q5) get elevated draw weight so tiny test scales still produce
#: qualifying rows — dbgen at SF>=1 gets density from volume instead
_HOT_NATIONS = (2, 3, 6, 7, 8, 18, 20)


def _nation_keys(rng, n):
    w = np.ones(len(NATIONS))
    w[list(_HOT_NATIONS)] = 8.0
    return rng.choice(len(NATIONS), size=n, p=w / w.sum()).astype(
        np.int64)


def _money(rng, lo, hi, n):
    return np.round(rng.uniform(lo, hi, n), 2)


def _comment(rng, n, specials=()):
    """Random word-ish comments; `specials` phrases are planted in ~8% of
    rows so LIKE-predicate queries (Q13/Q16/Q19) select non-empty sets."""
    base = _pick(rng, COLORS, n)
    mid = _pick(rng, COLORS, n)
    out = np.array([f"{a} {b} requests" for a, b in zip(base, mid)],
                   dtype=object)
    for phrase in specials:
        hit = rng.random(n) < 0.08
        out[hit] = np.array([f"{a} {phrase} {b} requests"
                             for a, b in zip(base[hit], mid[hit])],
                            dtype=object)
    return out


def gen_tables(rng: np.random.Generator, scale: int = 1000
               ) -> dict[str, pd.DataFrame]:
    """Generate all 8 tables; `scale` ~ lineitem row count.  Row ratios
    follow dbgen (orders = scale/4, part = scale/5, etc., floored small)."""
    n_orders = max(scale // 4, 20)
    n_part = max(scale // 5, 20)
    n_supp = max(scale // 100, 5)
    n_cust = max(scale // 10, 15)
    n_ps = n_part * 2

    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": np.array(REGIONS, dtype=object),
        "r_comment": _comment(rng, 5),
    })
    nation = pd.DataFrame({
        "n_nationkey": np.arange(len(NATIONS), dtype=np.int64),
        "n_name": np.array([n for n, _ in NATIONS], dtype=object),
        "n_regionkey": np.array([r for _, r in NATIONS], np.int64),
        "n_comment": _comment(rng, len(NATIONS)),
    })
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(n_supp, dtype=np.int64),
        "s_name": np.array([f"Supplier#{i:09d}" for i in range(n_supp)],
                           dtype=object),
        "s_address": _comment(rng, n_supp),
        "s_nationkey": _nation_keys(rng, n_supp),
        "s_phone": np.array(
            [f"{rng.integers(10, 35)}-{rng.integers(100, 999)}-"
             f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
             for _ in range(n_supp)], dtype=object),
        "s_acctbal": _money(rng, -999.99, 9999.99, n_supp),
        "s_comment": _comment(rng, n_supp,
                              specials=["Customer", "Complaints"]),
    })
    # plant the Q16 phrase as one token so both engines match it
    hit = rng.random(n_supp) < 0.1
    supplier.loc[hit, "s_comment"] = "Customer Complaints " + \
        supplier.loc[hit, "s_comment"]
    customer = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(n_cust)],
                           dtype=object),
        "c_address": _comment(rng, n_cust),
        "c_nationkey": _nation_keys(rng, n_cust),
        "c_phone": np.array(
            [f"{rng.integers(10, 35)}-{rng.integers(100, 999)}-"
             f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
             for _ in range(n_cust)], dtype=object),
        "c_acctbal": _money(rng, -999.99, 9999.99, n_cust),
        "c_mktsegment": _pick(rng, SEGMENTS, n_cust),
        "c_comment": _comment(rng, n_cust, specials=["special"]),
    })
    part = pd.DataFrame({
        "p_partkey": np.arange(n_part, dtype=np.int64),
        "p_name": np.array(
            [("forest " if rng.random() < 0.05 else "") +
             " ".join(rng.choice(COLORS, 3, replace=False))
             for _ in range(n_part)], dtype=object),
        "p_mfgr": np.array(
            [f"Manufacturer#{rng.integers(1, 6)}"
             for _ in range(n_part)], dtype=object),
        "p_brand": np.array(
            [f"Brand#{rng.integers(1, 6)}{rng.integers(1, 6)}"
             for _ in range(n_part)], dtype=object),
        "p_type": np.array(
            ["ECONOMY ANODIZED STEEL" if rng.random() < 0.05 else
             "LARGE POLISHED BRASS" if rng.random() < 0.08 else
             f"{rng.choice(TYPE_S1)} {rng.choice(TYPE_S2)} "
             f"{rng.choice(TYPE_S3)}" for _ in range(n_part)],
            dtype=object),
        "p_size": np.where(rng.random(n_part) < 0.04, 15,
                           rng.integers(1, 51, n_part)).astype(np.int32),
        "p_container": np.array(
            [f"{rng.choice(CONTAIN_S1)} {rng.choice(CONTAIN_S2)}"
             for _ in range(n_part)], dtype=object),
        "p_retailprice": _money(rng, 900.0, 2000.0, n_part),
        "p_comment": _comment(rng, n_part),
    })
    partsupp = pd.DataFrame({
        "ps_partkey": np.repeat(np.arange(n_part, dtype=np.int64), 2),
        "ps_suppkey": rng.integers(0, n_supp, n_ps).astype(np.int64),
        "ps_availqty": rng.integers(1, 10000, n_ps).astype(np.int32),
        "ps_supplycost": _money(rng, 1.0, 1000.0, n_ps),
        "ps_comment": _comment(rng, n_ps),
    }).drop_duplicates(["ps_partkey", "ps_suppkey"],
                       ignore_index=True)
    odate = rng.integers(days("1992-01-01"), days("1998-08-02"),
                         n_orders).astype(np.int32)
    orders = pd.DataFrame({
        "o_orderkey": np.arange(n_orders, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_orders).astype(np.int64),
        "o_orderstatus": _pick(rng, ["F", "O", "P"], n_orders),
        "o_totalprice": _money(rng, 1000.0, 400000.0, n_orders),
        "o_orderdate": odate,
        "o_orderpriority": _pick(rng, PRIORITIES, n_orders),
        "o_clerk": np.array(
            [f"Clerk#{rng.integers(1, 1000):09d}"
             for _ in range(n_orders)], dtype=object),
        "o_shippriority": np.zeros(n_orders, np.int32),
        "o_comment": _comment(rng, n_orders,
                              specials=["special", "pending", "deposits",
                                        "accounts"]),
    })
    ps_pairs = partsupp["ps_suppkey"].to_numpy().reshape(-1)
    part_first = np.searchsorted(
        partsupp["ps_partkey"].to_numpy(),
        np.arange(n_part))
    part_count = np.diff(np.append(part_first, len(partsupp)))
    l_order = rng.integers(0, n_orders, scale).astype(np.int64)
    ship_delay = rng.integers(1, 122, scale).astype(np.int32)
    l_ship = odate[l_order] + ship_delay
    # commit windows sized so ~25% of lines are late (receipt > commit):
    # q21's "sole late supplier in a multi-supplier order" pattern needs
    # late lines to be the exception, not the rule
    l_commit = odate[l_order] + rng.integers(60, 151, scale).astype(
        np.int32)
    l_receipt = l_ship + rng.integers(1, 31, scale).astype(np.int32)
    l_part = rng.integers(0, n_part, scale).astype(np.int64)
    pick = rng.integers(0, 1 << 30, scale) % np.maximum(
        part_count[l_part], 1)
    l_supp = ps_pairs[part_first[l_part] + pick]
    qty = rng.integers(1, 51, scale).astype(np.float64)
    price = np.round(qty * rng.uniform(900.0, 2000.0, scale), 2)
    lineitem = pd.DataFrame({
        "l_orderkey": l_order,
        "l_partkey": l_part,
        "l_suppkey": l_supp,
        "l_linenumber": rng.integers(1, 8, scale).astype(np.int32),
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": np.round(rng.uniform(0.0, 0.11, scale), 2),
        "l_tax": np.round(rng.uniform(0.0, 0.09, scale), 2),
        "l_returnflag": _pick(rng, ["A", "N", "R"], scale),
        "l_linestatus": _pick(rng, ["F", "O"], scale),
        "l_shipdate": l_ship,
        "l_commitdate": l_commit,
        "l_receiptdate": l_receipt,
        "l_shipinstruct": _pick(rng, INSTRUCTIONS, scale),
        "l_shipmode": _pick(rng, SHIP_MODES, scale),
        "l_comment": _comment(rng, scale),
    })
    return {"region": region, "nation": nation, "supplier": supplier,
            "customer": customer, "part": part, "partsupp": partsupp,
            "orders": orders, "lineitem": lineitem}


def sources(tables: dict[str, pd.DataFrame], num_partitions: int = 1):
    """Wrap generated tables as CpuSource plan leaves with the declared
    schemas (DATE32 columns stay int32 storage)."""
    from spark_rapids_tpu.models.data_util import make_sources
    return make_sources(tables, SCHEMAS, num_partitions)
