"""TPC-DS-like query set: all 103 queries of the reference suite
(`integration_tests/.../tpcds/TpcdsLikeSpark.scala:708+`), each built
faithfully from the reference query text as a plan tree in the same
DSL style as tpch_queries, over the engine's v0 type matrix (no
decimals; money as float64).  Where a reference literal has no support
in the synthetic data domain (state lists, month-seq anchors,
price/cov thresholds), a stand-in literal is used and commented at the
site."""
from __future__ import annotations

from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import Coalesce, If
from spark_rapids_tpu.exprs.predicates import InSet, IsNotNull, IsNull
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort, CpuUnion)

J = JoinType


def _join(left, right, lk, rk, jt=J.INNER, condition=None):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right, condition=condition)


def q3(t, run):
    """Brand revenue by year for one manufacturer in December."""
    dd = CpuFilter(col("d_moy") == lit(12), t["date_dim"])
    it = CpuFilter(col("i_manufact_id") == lit(5), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("sum_agg")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("sum_agg")),
         asc(col("i_brand_id"))], agg))


def q19(t, run):
    """Brand revenue for one month/year by manager."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(8), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand"), col("i_manufact_id")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id")),
         asc(col("i_manufact_id"))], agg))


def q42(t, run):
    """Category revenue for one month/year."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_category_id"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("total")), asc(col("d_year")),
         asc(col("i_category_id"))], agg))


def q52(t, run):
    """Brand revenue, one month/year (q42 by brand)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("ext_price")),
         asc(col("i_brand_id"))], agg))


def q55(t, run):
    """Brand revenue for one manager, month, year."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    # reference manager 28 stands in as 4 (a zipf-hot head slice)
    it = CpuFilter(col("i_manager_id") == lit(4), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id"))], agg))


def q68(t, run):
    """Per-ticket totals for high-dependency households in two cities."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   InSet(col("d_dom"), tuple(range(1, 3))),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          st, ["ss_store_sk"], ["s_store_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_ext_sales_price")).alias("extended_price"),
         Sum(col("ss_ext_list_price")).alias("list_price"),
         Sum(col("ss_ext_wholesale_cost")).alias("extended_tax")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("ss_ticket_number"), col("extended_price"),
         col("extended_tax"), col("list_price")], j2)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))], out))


def q73(t, run):
    """Ticket counts per customer for mid-size baskets."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    hd = CpuFilter(col("hd_buy_potential") == lit(">10000"),
                   t["household_demographics"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    big = CpuFilter((col("cnt") >= lit(2)) & (col("cnt") <= lit(50)),
                    per_ticket)
    j2 = _join(big, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         col("ss_ticket_number"), col("cnt")], j2)
    return CpuSort([desc(col("cnt")), asc(col("c_last_name")),
                    asc(col("ss_ticket_number"))], out)


def q96(t, run):
    """Count of sales in a demographic/time slice."""
    hd = CpuFilter(col("hd_dep_count") == lit(7),
                   t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Count(None).alias("cnt")], j)


# ---------------------------------------------------------------------------
# returns / correlated-average shapes
def q1(t, run):
    """Customers whose store-return total exceeds 1.2x their store's
    average (reference q1's correlated subquery, decorrelated into an
    aggregate-join)."""
    ctr = CpuAggregate(
        [col("sr_customer_sk"), col("sr_store_sk")],
        [Sum(col("sr_return_amt")).alias("ctr_total")],
        t["store_returns"])
    avg_ctr = CpuAggregate(
        [col("sr_store_sk")],
        [Average(col("ctr_total")).alias("avg_ret")],
        CpuProject([col("sr_store_sk"), col("ctr_total")], ctr))
    big = CpuFilter(
        col("ctr_total") > col("avg_ret") * lit(1.2),
        _join(ctr, CpuProject(
            [col("sr_store_sk").alias("st2"), col("avg_ret")], avg_ctr),
            ["sr_store_sk"], ["st2"]))
    st = CpuFilter(col("s_state") == lit("TX"), t["store"])
    j = _join(_join(big, st, ["sr_store_sk"], ["s_store_sk"]),
              t["customer"], ["sr_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], j)))


def q65(t, run):
    """Store items whose revenue is at most 10% of the store's average
    item revenue."""
    sa = CpuAggregate(
        [col("ss_store_sk"), col("ss_item_sk")],
        [Sum(col("ss_sales_price")).alias("revenue")], t["store_sales"])
    sb = CpuAggregate(
        [col("ss_store_sk")],
        [Average(col("revenue")).alias("ave")],
        CpuProject([col("ss_store_sk"), col("revenue")], sa))
    low = CpuFilter(
        col("revenue") <= col("ave") * lit(0.1),
        _join(sa, CpuProject([col("ss_store_sk").alias("sk2"),
                              col("ave")], sb),
              ["ss_store_sk"], ["sk2"]))
    j = _join(_join(low, t["store"], ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("i_item_id"))],
        CpuProject([col("s_store_name"), col("i_item_id"),
                    col("revenue")], j)))


# ---------------------------------------------------------------------------
# catalog / web channel star joins
def q26(t, run):
    """Catalog item averages for one demographic slice (q7's catalog
    twin)."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"]),
              t["item"], ["cs_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_sales_price")).alias("agg3")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


# ---------------------------------------------------------------------------
# multi-channel unions
# ---------------------------------------------------------------------------
# existence tests (semi/anti joins)
def q97(t, run):
    """Customer-item overlap between store and catalog channels
    (reference q97: FULL OUTER join of deduplicated channel pairs)."""
    ssci = CpuAggregate(
        [col("ss_customer_sk"), col("ss_item_sk")],
        [Count(None).alias("_s")], t["store_sales"])
    csci = CpuAggregate(
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        [Count(None).alias("_c")], t["catalog_sales"])
    j = CpuHashJoin(
        J.FULL_OUTER,
        [col("ss_customer_sk"), col("ss_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")], ssci, csci)
    return CpuAggregate(
        [],
        [Sum(If(IsNotNull(col("_s")) & IsNull(col("_c")),
                lit(1), lit(0))).alias("store_only"),
         Sum(If(IsNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("catalog_only"),
         Sum(If(IsNotNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("store_and_catalog")], j)


# ---------------------------------------------------------------------------
# returns netting / outer joins
# ---------------------------------------------------------------------------
# shipping-lag bucketing
def _lag_buckets(lag, prefix):
    b = lambda c: Sum(If(c, lit(1), lit(0)))
    return [
        b(lag <= lit(30)).alias(f"{prefix}30_days"),
        b((lag > lit(30)) & (lag <= lit(60))).alias(f"{prefix}60_days"),
        b((lag > lit(60)) & (lag <= lit(90))).alias(f"{prefix}90_days"),
        b(lag > lit(90)).alias(f"{prefix}more_days"),
    ]


# ---------------------------------------------------------------------------
# pivots, time slots, ratios
QUERIES = {
    "q1": q1, "q3": q3, "q19": q19,
    "q26": q26, "q42": q42, "q52": q52, "q55": q55, "q65": q65, "q68": q68, "q73": q73, "q96": q96, "q97": q97,
}


# ---------------------------------------------------------------------------
# round-2 growth toward the reference's 103 (TpcdsLikeSpark.scala:709+):
# year-over-year ratio family (q4/q11/q74), ROLLUP grouping-sets through
# CpuExpand (q5/q22/q86), channel unions (q56/q76), windowed deviation
# reports (q53/q57/q89), returns chains (q17/q24/q29/q49/q78/q81/q83/q85),
# inventory (q39/q72), existence/self-join shapes (q14/q35/q95).
from spark_rapids_tpu import types as _T
from spark_rapids_tpu.exprs.base import Literal as _Lit
from spark_rapids_tpu.plan.nodes import CpuExpand as _CpuExpand


def _rollup_expand(child, keys, passthrough):
    """Spark ROLLUP(keys...) lowering: CpuExpand with one projection per
    key prefix plus the grand total, carrying a grouping id — the exact
    shape Spark's planner feeds ExpandExec (reference GpuExpandExec)."""
    cs = child.output_schema()
    n = len(keys)
    projs = []
    for level in range(n, -1, -1):
        proj = [col(k) if i < level else _Lit(None, cs.field(k).dtype)
                for i, k in enumerate(keys)]
        proj.append(_Lit((1 << (n - level)) - 1, _T.INT32))
        proj.extend(col(p) for p in passthrough)
        projs.append(proj)
    names = list(keys) + ["gid"] + list(passthrough)
    return _CpuExpand(projs, names, child)


def _yoy_growth(t, sales, date_key, cust_key, val, year1=1999):
    """Per-customer totals for two consecutive years, joined: the
    q4/q11/q74 year-over-year scaffold."""
    def year_total(y, alias):
        dd = CpuFilter(col("d_year") == lit(y), t["date_dim"])
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  t["customer"], [cust_key], ["c_customer_sk"])
        return CpuAggregate([col("c_customer_id")],
                            [Sum(col(val)).alias(alias)], j)
    y1 = year_total(year1, "total1")
    y2 = CpuProject([col("c_customer_id").alias("cid2"),
                     col("total2")],
                    year_total(year1 + 1, "total2"))
    j = _join(CpuFilter(col("total1") > lit(0.0), y1), y2,
              ["c_customer_id"], ["cid2"])
    return CpuProject([col("c_customer_id"),
                       (col("total2") / col("total1")).alias("growth")], j)


def q22_rollup(t, run):
    """Inventory average quantity on hand, ROLLUP(category, brand) — a
    true grouping-sets plan through CpuExpand (reference q22)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
              t["item"], ["inv_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"],
                        ["inv_quantity_on_hand"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Average(col("inv_quantity_on_hand")).alias("qoh")], ex)
    return CpuLimit(100, CpuSort(
        [asc(col("qoh")), asc(col("i_category")), asc(col("i_brand")),
         asc(col("gid"))], agg))


def q86_rollup(t, run):
    """Web revenue ROLLUP(category, brand) report (reference q86 uses
    category/class; the v0 item schema carries brand)."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(dd, t["web_sales"], ["d_date_sk"],
                    ["ws_sold_date_sk"]),
              t["item"], ["ws_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"], ["ws_net_paid"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Sum(col("ws_net_paid")).alias("total_sum")], ex)
    return CpuLimit(100, CpuSort(
        [desc(col("total_sum")), asc(col("i_category")),
         asc(col("i_brand")), asc(col("gid"))], agg))


def _cat_ratio(t, sales, date_key, item_key, price, year, moy):
    """q12/q20/q98 scaffold: item revenue + windowed share of its
    category's revenue."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinSum)
    dd = CpuFilter((col("d_year") == lit(year)) &
                   (col("d_moy") == lit(moy)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Books", "Music", "Home")), t["item"])
    j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
              it, [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category")],
        [Sum(col(price)).alias("itemrevenue")], j)
    w = CpuWindow(
        [WinSum(col("itemrevenue")).alias("cat_rev")],
        WindowSpec([col("i_category")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    share = CpuProject(
        [col("i_item_id"), col("i_category"), col("itemrevenue"),
         (col("itemrevenue") * lit(100.0) / col("cat_rev"))
         .alias("revenueratio")], w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_category")), asc(col("i_item_id")),
         asc(col("revenueratio"))], share))


def _cast_i64(e):
    from spark_rapids_tpu.exprs.cast import Cast
    return Cast(e, _T.INT64)


QUERIES.update({ "q22": q22_rollup, "q86": q86_rollup,
})


# a/b variants (the reference counts q14a/b, q23a/b, q24a/b, q39a/b as
# separate queries — TpcdsLikeSpark.scala) + q91.


# ---------------------------------------------------------------------------
# round-3 faithful upgrades: full reference query text
# (TpcdsLikeSpark.scala:709+) over the extended generator schemas —
# (every earlier reduced variant is replaced query-for-query).
from spark_rapids_tpu.exprs.string_fns import Like, Substring as _Substring


def _date(y, m, d):
    """DATE32 literal: days since unix epoch."""
    import datetime
    days = (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days
    return _Lit(days, _T.DATE32)


def _between(c, lo, hi):
    return (c >= lo) & (c <= hi)


def q7(t, run):
    """Reference q7: item averages for one demographic slice + promo."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    promo = CpuFilter((col("p_channel_email") == lit("N")) |
                      (col("p_channel_event") == lit("N")),
                      t["promotion"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
                    promo, ["ss_promo_sk"], ["p_promo_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q13(t, run):
    """Reference q13: averages under OR-of-AND demographic/address
    bands (join keys inner, band predicates as a post-join filter)."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"]),
        t["household_demographics"], ["ss_hdemo_sk"], ["hd_demo_sk"]),
        t["customer_demographics"], ["ss_cdemo_sk"], ["cd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    demo = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Advanced Degree")) &
         _between(col("ss_sales_price"), lit(100.0), lit(150.0)) &
         (col("hd_dep_count") == lit(3))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         _between(col("ss_sales_price"), lit(50.0), lit(100.0)) &
         (col("hd_dep_count") == lit(1))) |
        ((col("cd_marital_status") == lit("W")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         _between(col("ss_sales_price"), lit(150.0), lit(200.0)) &
         (col("hd_dep_count") == lit(1))))
    addr = (
        (col("ca_country") == lit("United States")) &
        (InSet(col("ca_state"), ("TX", "NY")) &
         _between(col("ss_net_profit"), lit(100), lit(200)) |
         InSet(col("ca_state"), ("CA", "IL")) &
         _between(col("ss_net_profit"), lit(150), lit(300)) |
         InSet(col("ca_state"), ("WA", "GA")) &
         _between(col("ss_net_profit"), lit(50), lit(250))))
    f = CpuFilter(demo & addr, j)
    return CpuAggregate(
        [], [Average(col("ss_quantity")).alias("avg_qty"),
             Average(col("ss_ext_sales_price")).alias("avg_esp"),
             Average(col("ss_ext_wholesale_cost")).alias("avg_ewc"),
             Sum(col("ss_ext_wholesale_cost")).alias("sum_ewc")], f)


def q15(t, run):
    """Reference q15: catalog revenue by zip (zip/state/price OR)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    t["customer"],
                    ["cs_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    zips = ("85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792")
    f = CpuFilter(
        InSet(_Substring(col("ca_zip"), lit(1), lit(5)), zips) |
        InSet(col("ca_state"), ("CA", "WA", "GA")) |
        (col("cs_sales_price") > lit(500.0)), j)
    agg = CpuAggregate([col("ca_zip")],
                       [Sum(col("cs_sales_price")).alias("total")], f)
    return CpuLimit(100, CpuSort([asc(col("ca_zip"))], agg))


def q25(t, run):
    """Reference q25: store profit / returns loss / catalog profit per
    item+store across the d1/d2/d3 date windows."""
    d1 = CpuFilter(_between(col("d_moy"), lit(1), lit(6)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    d2 = CpuFilter(_between(col("d_moy"), lit(1), lit(12)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    d3 = CpuFilter(_between(col("d_moy"), lit(1), lit(12)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    ss = _join(d1, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
    sr = _join(CpuProject([col("d_date_sk").alias("d2_sk")], d2),
               t["store_returns"], ["d2_sk"], ["sr_returned_date_sk"])
    cs = _join(CpuProject([col("d_date_sk").alias("d3_sk")], d3),
               t["catalog_sales"], ["d3_sk"], ["cs_sold_date_sk"])
    j = _join(ss, sr, ["ss_customer_sk", "ss_item_sk",
                       "ss_ticket_number"],
              ["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = _join(j, cs, ["sr_customer_sk", "sr_item_sk"],
              ["cs_bill_customer_sk", "cs_item_sk"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    j = _join(j, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("s_store_id"),
         col("s_store_name")],
        [Sum(col("ss_net_profit")).alias("store_sales_profit"),
         Sum(col("sr_net_loss")).alias("store_returns_loss"),
         Sum(col("cs_net_profit")).alias("catalog_sales_profit")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("s_store_id")), asc(col("s_store_name"))], agg))


def q27(t, run):
    """Reference q27: state-level item averages over ROLLUP
    (i_item_id, s_state) with the grouping flag."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2002), t["date_dim"])
    # reference lists TN; the generator's state domain stands in
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA")),
                   t["store"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        t["item"], ["ss_item_sk"], ["i_item_sk"])
    pre = CpuProject(
        [col("i_item_id"), col("s_state"), col("ss_quantity"),
         col("ss_list_price"), col("ss_coupon_amt"),
         col("ss_sales_price")], j)
    ex = _rollup_expand(pre, ["i_item_id", "s_state"],
                        ["ss_quantity", "ss_list_price",
                         "ss_coupon_amt", "ss_sales_price"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_state"), col("gid")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], ex)
    out = CpuProject(
        [col("i_item_id"), col("s_state"),
         If(col("gid") >= lit(1), lit(1), lit(0)).alias("g_state"),
         col("agg1"), col("agg2"), col("agg3"), col("agg4")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_state"))], out))


def _q28_block(t, qlo, qhi, lp, ca, wc, tag):
    base = CpuFilter(
        _between(col("ss_quantity"), lit(qlo), lit(qhi)) &
        (_between(col("ss_list_price"), lit(float(lp)),
                  lit(float(lp + 10))) |
         _between(col("ss_coupon_amt"), lit(float(ca)),
                  lit(float(ca + 1000))) |
         _between(col("ss_wholesale_cost"), lit(float(wc)),
                  lit(float(wc + 20)))), t["store_sales"])
    main = CpuProject(
        [lit(1).alias(f"_k{tag}"),
         col(f"{tag}_LP"), col(f"{tag}_CNT")],
        CpuAggregate(
            [], [Average(col("ss_list_price")).alias(f"{tag}_LP"),
                 Count(col("ss_list_price")).alias(f"{tag}_CNT")],
            base))
    dist = CpuProject(
        [lit(1).alias(f"_kd{tag}"), col(f"{tag}_CNTD")],
        CpuAggregate(
            [], [Count(col("ss_list_price")).alias(f"{tag}_CNTD")],
            CpuAggregate([col("ss_list_price")],
                         [Count(None).alias("_d")], base)))
    return _join(main, dist, [f"_k{tag}"], [f"_kd{tag}"])


def q28(t, run):
    """Reference q28: six quantity-band stats blocks cross-joined
    (count distinct via two-level aggregate)."""
    blocks = [
        _q28_block(t, 0, 5, 8, 459, 57, "B1"),
        _q28_block(t, 6, 10, 90, 2323, 31, "B2"),
        _q28_block(t, 11, 15, 142, 12214, 79, "B3"),
        _q28_block(t, 16, 20, 135, 6071, 38, "B4"),
        _q28_block(t, 21, 25, 122, 836, 17, "B5"),
        _q28_block(t, 26, 30, 154, 7326, 7, "B6"),
    ]
    out = blocks[0]
    for i, b in enumerate(blocks[1:], start=2):
        out = _join(out, b, [f"_kB{i - 1}"], [f"_kB{i}"])
    names = [c for tag in ("B1", "B2", "B3", "B4", "B5", "B6")
             for c in (f"{tag}_LP", f"{tag}_CNT", f"{tag}_CNTD")]
    return CpuLimit(100, CpuProject([col(c) for c in names], out))


def _q33_channel(t, sales, date_key, addr_key, item_key, val):
    manuf = CpuAggregate(
        [col("i_manufact_id")], [Count(None).alias("_c")],
        CpuFilter(InSet(col("i_category"), ("Electronics",)),
                  t["item"]))
    it = _join(t["item"], manuf, ["i_manufact_id"], ["i_manufact_id"],
               jt=J.LEFT_SEMI)
    dd = CpuFilter((col("d_year") == lit(1998)) &
                   (col("d_moy") == lit(5)), t["date_dim"])
    ca = CpuFilter(col("ca_gmt_offset") == lit(-5.0),
                   t["customer_address"])
    j = _join(_join(_join(dd, sales, ["d_date_sk"], [date_key]),
                    ca, [addr_key], ["ca_address_sk"]),
              it, [item_key], ["i_item_sk"])
    return CpuAggregate([col("i_manufact_id")],
                        [Sum(col(val)).alias("total_sales")], j)


def q33(t, run):
    """Reference q33: Electronics manufacturer revenue across the three
    channels, unioned and re-aggregated."""
    ss = _q33_channel(t, t["store_sales"], "ss_sold_date_sk",
                      "ss_addr_sk", "ss_item_sk", "ss_ext_sales_price")
    cs = _q33_channel(t, t["catalog_sales"], "cs_sold_date_sk",
                      "cs_bill_addr_sk", "cs_item_sk",
                      "cs_ext_sales_price")
    ws = _q33_channel(t, t["web_sales"], "ws_sold_date_sk",
                      "ws_bill_addr_sk", "ws_item_sk",
                      "ws_ext_sales_price")
    u = CpuUnion(ss, cs, ws)
    agg = CpuAggregate([col("i_manufact_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort([desc(col("total_sales"))], agg))


def q37(t, run):
    """Reference q37: in-stock catalog items in a price band."""
    it = CpuFilter(
        _between(col("i_current_price"), lit(20.0), lit(90.0)) &
        InSet(col("i_manufact_id"),
              tuple(range(1, 41))), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 1),
                            _date(2000, 12, 31)), t["date_dim"])
    inv = CpuFilter(_between(col("inv_quantity_on_hand"),
                             lit(100), lit(500)), t["inventory"])
    j = _join(_join(_join(it, inv, ["i_item_sk"], ["inv_item_sk"]),
                    dd, ["inv_date_sk"], ["d_date_sk"]),
              t["catalog_sales"], ["i_item_sk"], ["cs_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_current_price")],
        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_item_id"), col("i_item_desc"),
                      col("i_current_price")], agg)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], out))


def q40(t, run):
    """Reference q40: warehouse sales before/after one date, catalog
    left-outer returns netting."""
    j = _join(t["catalog_sales"], t["catalog_returns"],
              ["cs_order_number", "cs_item_sk"],
              ["cr_order_number", "cr_item_sk"], jt=J.LEFT_OUTER)
    # reference band is 0.99..1.49 over 2000-02-10..04-10; a wider
    # price band keeps the sparse synthetic item table populated
    it = CpuFilter(_between(col("i_current_price"),
                            lit(0.99), lit(3.49)), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 2, 10),
                            _date(2000, 4, 10)), t["date_dim"])
    j = _join(_join(_join(j, it, ["cs_item_sk"], ["i_item_sk"]),
                    t["warehouse"], ["cs_warehouse_sk"],
                    ["w_warehouse_sk"]),
              dd, ["cs_sold_date_sk"], ["d_date_sk"])
    net = col("cs_sales_price") - Coalesce((col("cr_refunded_cash"),
                                            lit(0.0)))
    agg = CpuAggregate(
        [col("w_state"), col("i_item_id")],
        [Sum(If(col("d_date") < _date(2000, 3, 11), net,
                lit(0.0))).alias("sales_before"),
         Sum(If(col("d_date") >= _date(2000, 3, 11), net,
                lit(0.0))).alias("sales_after")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("w_state")), asc(col("i_item_id"))], agg))


def q43(t, run):
    """Reference q43: store weekday sales pivot for one year/offset."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    st = CpuFilter(col("s_gmt_offset") == lit(-5.0), t["store"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])

    def day_sum(name, alias):
        return Sum(If(col("d_day_name") == lit(name),
                      col("ss_sales_price"), lit(0.0))).alias(alias)
    agg = CpuAggregate(
        [col("s_store_name"), col("s_store_id")],
        [day_sum("Sunday", "sun_sales"), day_sum("Monday", "mon_sales"),
         day_sum("Tuesday", "tue_sales"),
         day_sum("Wednesday", "wed_sales"),
         day_sum("Thursday", "thu_sales"),
         day_sum("Friday", "fri_sales"),
         day_sum("Saturday", "sat_sales")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("s_store_id")),
         asc(col("sun_sales")), asc(col("mon_sales"))], agg))


def q45(t, run):
    """Reference q45: web revenue by zip/city; zip prefix OR item-id
    semi-join on the primes item list."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"]),
        t["customer"], ["ws_bill_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"]),
        t["item"], ["ws_item_sk"], ["i_item_sk"])
    prime_ids = CpuAggregate(
        [col("prime_id")], [Count(None).alias("_c")],
        CpuProject(
            [col("i_item_id").alias("prime_id")],
            CpuFilter(InSet(col("i_item_sk"),
                            (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)),
                      t["item"])))
    prime_ids = CpuProject([col("prime_id")], prime_ids)
    j = _join(j, prime_ids, ["i_item_id"], ["prime_id"],
              jt=J.LEFT_OUTER)
    zips = ("85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792")
    f = CpuFilter(
        InSet(_Substring(col("ca_zip"), lit(1), lit(5)), zips) |
        IsNotNull(col("prime_id")), j)
    agg = CpuAggregate([col("ca_zip"), col("ca_city")],
                       [Sum(col("ws_sales_price")).alias("total")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_zip")), asc(col("ca_city"))], agg))


def q48(t, run):
    """Reference q48: quantity total across demographic price bands and
    address profit bands."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"]),
        t["customer_demographics"], ["ss_cdemo_sk"], ["cd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    demo = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree")) &
         _between(col("ss_sales_price"), lit(100.0), lit(150.0))) |
        ((col("cd_marital_status") == lit("D")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         _between(col("ss_sales_price"), lit(50.0), lit(100.0))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         _between(col("ss_sales_price"), lit(150.0), lit(200.0))))
    addr = (
        (col("ca_country") == lit("United States")) &
        (InSet(col("ca_state"), ("NY", "IL", "TX")) &
         _between(col("ss_net_profit"), lit(0), lit(2000)) |
         InSet(col("ca_state"), ("CA", "GA")) &
         _between(col("ss_net_profit"), lit(150), lit(3000)) |
         InSet(col("ca_state"), ("WA",)) &
         _between(col("ss_net_profit"), lit(50), lit(25000))))
    f = CpuFilter(demo & addr, j)
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("total")], f)


QUERIES.update({
    "q7": q7, "q13": q13, "q15": q15, "q25": q25, "q27": q27,
    "q28": q28, "q33": q33, "q37": q37, "q40": q40, "q43": q43,
    "q45": q45, "q48": q48,
})


def q34(t, run):
    """Reference q34: 15-20-item tickets for high-buy-potential
    households on month boundaries."""
    dd = CpuFilter(
        (_between(col("d_dom"), lit(1), lit(3)) |
         _between(col("d_dom"), lit(25), lit(28))) &
        InSet(col("d_year"), (1999, 2000, 2001)), t["date_dim"])
    hd = CpuFilter(
        ((col("hd_buy_potential") == lit(">10000")) |
         (col("hd_buy_potential") == lit("Unknown"))) &
        (col("hd_vehicle_count") > lit(0)) &
        (If(col("hd_vehicle_count") > lit(0),
            col("hd_dep_count") / col("hd_vehicle_count"),
            _Lit(None, _T.FLOAT64)) > lit(1.2)),
        t["household_demographics"])
    st = CpuFilter(InSet(col("s_county"), ("Williamson County",)),
                   t["store"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    dn = CpuAggregate([col("ss_ticket_number"), col("ss_customer_sk")],
                      [Count(None).alias("cnt")], j)
    # reference band is 15-20; the generator's post-filter per-ticket
    # counts are 1-3, so the band scales down
    dn = CpuFilter(_between(col("cnt"), lit(1), lit(20)), dn)
    out = _join(dn, t["customer"], ["ss_customer_sk"],
                ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("c_salutation"),
         col("c_preferred_cust_flag"), col("ss_ticket_number"),
         col("cnt")], out)
    return CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("c_salutation")), desc(col("c_preferred_cust_flag")),
         asc(col("ss_ticket_number"))], out)


def q46(t, run):
    """Reference q46: weekend coupon/profit per ticket where the bought
    city differs from the customer's current city."""
    dd = CpuFilter(InSet(col("d_dow"), (6, 0)) &
                   InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Fairview", "Midway")),
                   t["store"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    dn = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ss_addr_sk"), col("ca_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    dn = CpuProject(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city").alias("bought_city"), col("amt"),
         col("profit")], dn)
    out = _join(_join(dn, t["customer"], ["ss_customer_sk"],
                      ["c_customer_sk"]),
                t["customer_address"], ["c_current_addr_sk"],
                ["ca_address_sk"])
    out = CpuFilter(col("ca_city") != col("bought_city"), out)
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("bought_city"), col("ss_ticket_number"), col("amt"),
         col("profit")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("ca_city")), asc(col("bought_city")),
         asc(col("ss_ticket_number"))], out))


def _lag_buckets(diff, prefix=""):
    return [
        Sum(If(diff <= lit(30), lit(1), lit(0))).alias(
            f"{prefix}d30"),
        Sum(If((diff > lit(30)) & (diff <= lit(60)), lit(1),
               lit(0))).alias(f"{prefix}d31_60"),
        Sum(If((diff > lit(60)) & (diff <= lit(90)), lit(1),
               lit(0))).alias(f"{prefix}d61_90"),
        Sum(If((diff > lit(90)) & (diff <= lit(120)), lit(1),
               lit(0))).alias(f"{prefix}d91_120"),
        Sum(If(diff > lit(120), lit(1), lit(0))).alias(
            f"{prefix}d120plus"),
    ]


def q50(t, run):
    """Reference q50: return-lag buckets per store (full store column
    list) for one return month."""
    d2 = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(8)), t["date_dim"])
    j = _join(t["store_sales"], t["store_returns"],
              ["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
              ["sr_ticket_number", "sr_item_sk", "sr_customer_sk"])
    j = _join(j, CpuProject([col("d_date_sk").alias("d2_sk")], d2),
              ["sr_returned_date_sk"], ["d2_sk"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    diff = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    keys = ["s_store_name", "s_company_id", "s_street_number",
            "s_street_name", "s_street_type", "s_suite_number",
            "s_city", "s_county", "s_state", "s_zip"]
    agg = CpuAggregate([col(k) for k in keys], _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort([asc(col(k)) for k in keys], agg))


def q61(t, run):
    """Reference q61: promotional vs total revenue (two scalar branches
    joined on a constant key)."""
    def branch(with_promo, tag):
        dd = CpuFilter((col("d_year") == lit(1998)) &
                       (col("d_moy") == lit(11)), t["date_dim"])
        st = CpuFilter(col("s_gmt_offset") == lit(-5.0), t["store"])
        it = CpuFilter(col("i_category") == lit("Jewelry"), t["item"])
        ca = CpuFilter(col("ca_gmt_offset") == lit(-5.0),
                       t["customer_address"])
        j = _join(_join(dd, t["store_sales"],
                        ["d_date_sk"], ["ss_sold_date_sk"]),
                  st, ["ss_store_sk"], ["s_store_sk"])
        if with_promo:
            pr = CpuFilter((col("p_channel_dmail") == lit("Y")) |
                           (col("p_channel_email") == lit("Y")) |
                           (col("p_channel_tv") == lit("Y")),
                           t["promotion"])
            j = _join(j, pr, ["ss_promo_sk"], ["p_promo_sk"])
        j = _join(_join(_join(j, t["customer"], ["ss_customer_sk"],
                              ["c_customer_sk"]),
                        ca, ["c_current_addr_sk"], ["ca_address_sk"]),
                  it, ["ss_item_sk"], ["i_item_sk"])
        return CpuProject(
            [lit(1).alias(f"_k{tag}"), col(tag)],
            CpuAggregate(
                [], [Sum(col("ss_ext_sales_price")).alias(tag)], j))
    promo = branch(True, "promotions")
    total = branch(False, "total")
    j = _join(promo, total, ["_kpromotions"], ["_ktotal"])
    out = CpuProject(
        [col("promotions"), col("total"),
         (col("promotions") / col("total") * lit(100.0)).alias("ratio")],
        j)
    return CpuLimit(100, CpuSort(
        [asc(col("promotions")), asc(col("total"))], out))


def q62(t, run):
    """Reference q62: web shipping-lag buckets by warehouse prefix /
    ship mode / site."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_ship_date_sk"]),
        t["warehouse"], ["ws_warehouse_sk"], ["w_warehouse_sk"]),
        t["ship_mode"], ["ws_ship_mode_sk"], ["sm_ship_mode_sk"]),
        t["web_site"], ["ws_web_site_sk"], ["web_site_sk"])
    j = CpuProject(
        [_Substring(col("w_warehouse_name"), lit(1),
                    lit(20)).alias("wh_prefix"),
         col("sm_type"), col("web_name"), col("ws_ship_date_sk"),
         col("ws_sold_date_sk")], j)
    diff = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    agg = CpuAggregate(
        [col("wh_prefix"), col("sm_type"), col("web_name")],
        _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort(
        [asc(col("wh_prefix")), asc(col("sm_type")),
         asc(col("web_name"))], agg))


def q63(t, run):
    """Reference q63: manager monthly sales vs their cross-month
    average (window avg expressed as an aggregate re-join — identical
    semantics)."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    it = CpuFilter(
        (InSet(col("i_category"), ("Books", "Electronics", "Home")) &
         InSet(col("i_class"), tuple(f"class{i:02d}" for i in
                                     range(8)))) |
        (InSet(col("i_category"), ("Women", "Music", "Shoes")) &
         InSet(col("i_class"), tuple(f"class{i:02d}" for i in
                                     range(8, 16)))), t["item"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        it, ["ss_item_sk"], ["i_item_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"])
    monthly = CpuAggregate(
        [col("i_manager_id"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    avg = CpuProject(
        [col("i_manager_id").alias("_mgr"),
         col("avg_monthly_sales")],
        CpuAggregate(
            [col("i_manager_id")],
            [Average(col("sum_sales")).alias("avg_monthly_sales")],
            monthly))
    out = _join(monthly, avg, ["i_manager_id"], ["_mgr"])
    dev = (col("sum_sales") - col("avg_monthly_sales"))
    absdev = If(dev < lit(0.0), lit(0.0) - dev, dev)
    out = CpuFilter(
        If(col("avg_monthly_sales") > lit(0.0),
           absdev / col("avg_monthly_sales"),
           _Lit(None, _T.FLOAT64)) > lit(0.1), out)
    out = CpuProject([col("i_manager_id"), col("sum_sales"),
                      col("avg_monthly_sales")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manager_id")), asc(col("avg_monthly_sales")),
         asc(col("sum_sales"))], out))


def q69(t, run):
    """Reference q69: demographics of store-only shoppers in a quarter
    (EXISTS store AND NOT EXISTS web/catalog as semi/anti joins)."""
    ca = CpuFilter(InSet(col("ca_state"), ("GA", "NY", "TX")),
                   t["customer_address"])
    c = _join(t["customer"], ca, ["c_current_addr_sk"],
              ["ca_address_sk"])
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   _between(col("d_moy"), lit(4), lit(6)),
                   t["date_dim"])
    ss = _join(dd, t["store_sales"], ["d_date_sk"],
               ["ss_sold_date_sk"])
    ws = _join(CpuProject([col("d_date_sk").alias("dw_sk")], dd),
               t["web_sales"], ["dw_sk"], ["ws_sold_date_sk"])
    cs = _join(CpuProject([col("d_date_sk").alias("dc_sk")], dd),
               t["catalog_sales"], ["dc_sk"], ["cs_sold_date_sk"])
    c = _join(c, ss, ["c_customer_sk"], ["ss_customer_sk"],
              jt=J.LEFT_SEMI)
    c = _join(c, ws, ["c_customer_sk"], ["ws_bill_customer_sk"],
              jt=J.LEFT_ANTI)
    c = _join(c, cs, ["c_customer_sk"], ["cs_ship_customer_sk"],
              jt=J.LEFT_ANTI)
    j = _join(c, t["customer_demographics"], ["c_current_cdemo_sk"],
              ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cd_purchase_estimate"),
         col("cd_credit_rating")],
        [Count(None).alias("cnt1")], j)
    out = CpuProject(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cnt1"),
         col("cd_purchase_estimate"), col("cnt1").alias("cnt2"),
         col("cd_credit_rating"), col("cnt1").alias("cnt3")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("cd_gender")), asc(col("cd_marital_status")),
         asc(col("cd_education_status")),
         asc(col("cd_purchase_estimate")),
         asc(col("cd_credit_rating"))], out))


def q79(t, run):
    """Reference q79: Monday coupon/profit per ticket for large
    stores."""
    dd = CpuFilter((col("d_dow") == lit(1)) &
                   InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(6)) |
                   (col("hd_vehicle_count") > lit(2)),
                   t["household_demographics"])
    st = CpuFilter(_between(col("s_number_employees"),
                            lit(200), lit(295)), t["store"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    ms = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ss_addr_sk"), col("s_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    out = _join(ms, t["customer"], ["ss_customer_sk"],
                ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         _Substring(col("s_city"), lit(1), lit(30)).alias("city30"),
         col("ss_ticket_number"), col("amt"), col("profit")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("city30")), asc(col("profit"))], out))


def _q88_slot(t, h, half, tag):
    """one time-slot count(*) block (reference q88 s1..s8)."""
    td = CpuFilter((col("t_hour") == lit(h)) &
                   ((col("t_minute") < lit(30)) if half == 0 else
                    (col("t_minute") >= lit(30))), t["time_dim"])
    hd = CpuFilter(
        ((col("hd_dep_count") == lit(4)) &
         (col("hd_vehicle_count") <= lit(6))) |
        ((col("hd_dep_count") == lit(2)) &
         (col("hd_vehicle_count") <= lit(4))) |
        ((col("hd_dep_count") == lit(0)) &
         (col("hd_vehicle_count") <= lit(2))),
        t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(_join(
        td, t["store_sales"], ["t_time_sk"], ["ss_sold_time_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"])
    return CpuProject(
        [lit(1).alias(f"_k{tag}"), col(tag)],
        CpuAggregate([], [Count(None).alias(tag)], j))


def q88(t, run):
    """Reference q88: eight half-hour slot counts cross-joined."""
    slots = [("h8_30", 8, 1), ("h9", 9, 0), ("h9_30", 9, 1),
             ("h10", 10, 0), ("h10_30", 10, 1), ("h11", 11, 0),
             ("h11_30", 11, 1), ("h12", 12, 0)]
    blocks = [_q88_slot(t, h, half, tag) for tag, h, half in slots]
    out = blocks[0]
    prev_tag = slots[0][0]
    for b, (tag, _, _) in zip(blocks[1:], slots[1:]):
        out = _join(out, b, [f"_k{prev_tag}"], [f"_k{tag}"])
        prev_tag = tag
    return CpuProject([col(tag) for tag, _, _ in slots], out)


def q90(t, run):
    """Reference q90: am/pm web sales ratio for a dependent-count
    band."""
    def half(h_lo, h_hi, tag):
        td = CpuFilter(_between(col("t_hour"), lit(h_lo), lit(h_hi)),
                       t["time_dim"])
        hd = CpuFilter(col("hd_dep_count") == lit(6),
                       t["household_demographics"])
        wp = CpuFilter(_between(col("wp_char_count"),
                                lit(5000), lit(5200)), t["web_page"])
        j = _join(_join(_join(
            td, t["web_sales"], ["t_time_sk"], ["ws_sold_time_sk"]),
            hd, ["ws_ship_hdemo_sk"], ["hd_demo_sk"]),
            wp, ["ws_web_page_sk"], ["wp_web_page_sk"])
        return CpuProject(
            [lit(1).alias(f"_k{tag}"), col(tag)],
            CpuAggregate([], [Count(None).alias(tag)], j))
    am = half(8, 9, "amc")
    pm = half(19, 20, "pmc")
    j = _join(am, pm, ["_kamc"], ["_kpmc"])
    out = CpuProject(
        [(col("amc") / col("pmc")).alias("am_pm_ratio")], j)
    return CpuLimit(100, CpuSort([asc(col("am_pm_ratio"))], out))


def q93(t, run):
    """Reference q93: actual sales net of returns for one reason."""
    r = CpuFilter(col("r_reason_desc") == lit("reason 1"), t["reason"])
    j = _join(t["store_sales"], _join(
        t["store_returns"], r, ["sr_reason_sk"], ["r_reason_sk"]),
        ["ss_item_sk", "ss_ticket_number"],
        ["sr_item_sk", "sr_ticket_number"], jt=J.LEFT_OUTER)
    act = If(IsNotNull(col("sr_ticket_number")),
             (col("ss_quantity") - col("sr_return_quantity")) *
             col("ss_sales_price"),
             col("ss_quantity") * col("ss_sales_price"))
    pre = CpuProject([col("ss_customer_sk"), act.alias("act_sales")], j)
    agg = CpuAggregate([col("ss_customer_sk")],
                       [Sum(col("act_sales")).alias("sumsales")], pre)
    return CpuLimit(100, CpuSort(
        [asc(col("sumsales")), asc(col("ss_customer_sk"))], agg))


def q98(t, run):
    """Reference q98: store item/class revenue ratio (no limit)."""
    return _item_class_revenue(t, t["store_sales"], "ss_sold_date_sk",
                               "ss_item_sk", "ss_ext_sales_price",
                               limit=None)


def q99(t, run):
    """Reference q99: catalog shipping-lag buckets by warehouse prefix /
    ship mode / call center."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["catalog_sales"], ["d_date_sk"], ["cs_ship_date_sk"]),
        t["warehouse"], ["cs_warehouse_sk"], ["w_warehouse_sk"]),
        t["ship_mode"], ["cs_ship_mode_sk"], ["sm_ship_mode_sk"]),
        t["call_center"], ["cs_call_center_sk"], ["cc_call_center_sk"])
    j = CpuProject(
        [_Substring(col("w_warehouse_name"), lit(1),
                    lit(20)).alias("wh_prefix"),
         col("sm_type"), col("cc_name"), col("cs_ship_date_sk"),
         col("cs_sold_date_sk")], j)
    diff = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    agg = CpuAggregate(
        [col("wh_prefix"), col("sm_type"), col("cc_name")],
        _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort(
        [asc(col("wh_prefix")), asc(col("sm_type")),
         asc(col("cc_name"))], agg))


QUERIES.update({
    "q34": q34, "q46": q46, "q50": q50, "q61": q61, "q62": q62,
    "q63": q63, "q69": q69, "q79": q79, "q88": q88, "q90": q90,
    "q93": q93, "q98": q98, "q99": q99,
})


def _item_class_revenue(t, sales, date_key, item_key, val,
                        limit=100):
    """q12/q20/q98 family: item revenue + class revenue ratio over one
    30-day window (window sum as aggregate re-join)."""
    dd = CpuFilter(_between(col("d_date"), _date(1999, 2, 22),
                            _date(1999, 3, 24)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Sports", "Books", "Home")), t["item"])
    j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
              it, [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_category"),
         col("i_class"), col("i_current_price")],
        [Sum(col(val)).alias("itemrevenue")], j)
    cls = CpuProject(
        [col("i_class").alias("_cls"), col("classrev")],
        CpuAggregate([col("i_class")],
                     [Sum(col("itemrevenue")).alias("classrev")], agg))
    out = _join(agg, cls, ["i_class"], ["_cls"])
    out = CpuProject(
        [col("i_item_id"), col("i_item_desc"), col("i_category"),
         col("i_class"), col("i_current_price"), col("itemrevenue"),
         (col("itemrevenue") * lit(100.0) /
          col("classrev")).alias("revenueratio")], out)
    srt = CpuSort(
        [asc(col("i_category")), asc(col("i_class")),
         asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("revenueratio"))], out)
    return srt if limit is None else CpuLimit(limit, srt)


def q12(t, run):
    """Reference q12: web item/class revenue ratio."""
    return _item_class_revenue(t, t["web_sales"], "ws_sold_date_sk",
                               "ws_item_sk", "ws_ext_sales_price")


def q20(t, run):
    """Reference q20: catalog item/class revenue ratio."""
    return _item_class_revenue(t, t["catalog_sales"],
                               "cs_sold_date_sk", "cs_item_sk",
                               "cs_ext_sales_price")


def q82(t, run):
    """Reference q82: in-stock store items in a price band."""
    it = CpuFilter(
        _between(col("i_current_price"), lit(30.0), lit(95.0)) &
        InSet(col("i_manufact_id"), tuple(range(20, 61))), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 5, 25),
                            _date(2000, 11, 25)), t["date_dim"])
    inv = CpuFilter(_between(col("inv_quantity_on_hand"),
                             lit(100), lit(500)), t["inventory"])
    j = _join(_join(_join(it, inv, ["i_item_sk"], ["inv_item_sk"]),
                    dd, ["inv_date_sk"], ["d_date_sk"]),
              t["store_sales"], ["i_item_sk"], ["ss_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_current_price")],
        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_item_id"), col("i_item_desc"),
                      col("i_current_price")], agg)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], out))


def q92(t, run):
    """Reference q92: web discounts exceeding 1.3x the per-item window
    average (correlated subquery as aggregate re-join)."""
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 27),
                            _date(2000, 4, 26)), t["date_dim"])
    ws = _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"])
    it = CpuFilter(InSet(col("i_manufact_id"),
                         tuple(range(30, 40))), t["item"])
    j = _join(ws, it, ["ws_item_sk"], ["i_item_sk"])
    avg = CpuProject(
        [col("ws_item_sk").alias("_isk"),
         (col("a") * lit(1.3)).alias("threshold")],
        CpuAggregate(
            [col("ws_item_sk")],
            [Average(col("ws_ext_discount_amt")).alias("a")], ws))
    out = _join(j, avg, ["ws_item_sk"], ["_isk"])
    out = CpuFilter(col("ws_ext_discount_amt") > col("threshold"), out)
    agg = CpuAggregate(
        [], [Sum(col("ws_ext_discount_amt")).alias("excess")], out)
    return CpuLimit(100, agg)


def q94(t, run):
    """Reference q94: multi-warehouse never-returned web orders (EXISTS
    as a >1-warehouse-order semi join, NOT EXISTS as anti join)."""
    dd = CpuFilter(_between(col("d_date"), _date(1999, 2, 1),
                            _date(1999, 4, 2)), t["date_dim"])
    ca = CpuFilter(col("ca_state") == lit("IL"),
                   t["customer_address"])
    site = CpuFilter(col("web_company_name") == lit("pri"),
                     t["web_site"])
    ws1 = _join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_ship_date_sk"]),
        ca, ["ws_ship_addr_sk"], ["ca_address_sk"]),
        site, ["ws_web_site_sk"], ["web_site_sk"])
    multi_wh = CpuFilter(
        col("nwh") > lit(1),
        CpuAggregate(
            [col("morder")], [Count(None).alias("nwh")],
            CpuAggregate(
                [col("ws_order_number").alias("morder"),
                 col("ws_warehouse_sk")],
                [Count(None).alias("_c")], t["web_sales"])))
    ws1 = _join(ws1, multi_wh, ["ws_order_number"], ["morder"],
                jt=J.LEFT_SEMI)
    ws1 = _join(ws1, t["web_returns"], ["ws_order_number"],
                ["wr_order_number"], jt=J.LEFT_ANTI)
    dist = CpuAggregate(
        [], [Count(col("dorder")).alias("order_count")],
        CpuAggregate([col("ws_order_number").alias("dorder")],
                     [Count(None).alias("_d")], ws1))
    sums = CpuAggregate(
        [], [Sum(col("ws_ext_ship_cost")).alias("total_ship_cost"),
             Sum(col("ws_net_profit")).alias("total_net_profit")], ws1)
    j = _join(CpuProject([lit(1).alias("_ka"), col("order_count")],
                         dist),
              CpuProject([lit(1).alias("_kb"), col("total_ship_cost"),
                          col("total_net_profit")], sums),
              ["_ka"], ["_kb"])
    return CpuLimit(100, CpuProject(
        [col("order_count"), col("total_ship_cost"),
         col("total_net_profit")], j))


def _distinct_channel_triples(t, sales, date_key, cust_key):
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
              t["customer"], [cust_key], ["c_customer_sk"])
    return CpuAggregate(
        [col("c_last_name"), col("c_first_name"), col("d_date")],
        [Count(None).alias("_n")], j)


def q38(t, run):
    """Reference q38: customers active in ALL three channels
    (INTERSECT as successive semi joins on the distinct triples)."""
    ss = _distinct_channel_triples(t, t["store_sales"],
                                   "ss_sold_date_sk", "ss_customer_sk")
    cs = CpuProject(
        [col("c_last_name").alias("cl"), col("c_first_name").alias("cf"),
         col("d_date").alias("cd")],
        _distinct_channel_triples(t, t["catalog_sales"],
                                  "cs_sold_date_sk",
                                  "cs_bill_customer_sk"))
    ws = CpuProject(
        [col("c_last_name").alias("wl"), col("c_first_name").alias("wf"),
         col("d_date").alias("wd")],
        _distinct_channel_triples(t, t["web_sales"],
                                  "ws_sold_date_sk",
                                  "ws_bill_customer_sk"))
    both = _join(ss, cs, ["c_last_name", "c_first_name", "d_date"],
                 ["cl", "cf", "cd"], jt=J.LEFT_SEMI)
    allc = _join(both, ws, ["c_last_name", "c_first_name", "d_date"],
                 ["wl", "wf", "wd"], jt=J.LEFT_SEMI)
    return CpuLimit(100, CpuAggregate(
        [], [Count(None).alias("cnt")], allc))


def q87(t, run):
    """Reference q87: store-only customer/date triples (EXCEPT as
    successive anti joins)."""
    ss = _distinct_channel_triples(t, t["store_sales"],
                                   "ss_sold_date_sk", "ss_customer_sk")
    cs = CpuProject(
        [col("c_last_name").alias("cl"), col("c_first_name").alias("cf"),
         col("d_date").alias("cd")],
        _distinct_channel_triples(t, t["catalog_sales"],
                                  "cs_sold_date_sk",
                                  "cs_bill_customer_sk"))
    ws = CpuProject(
        [col("c_last_name").alias("wl"), col("c_first_name").alias("wf"),
         col("d_date").alias("wd")],
        _distinct_channel_triples(t, t["web_sales"],
                                  "ws_sold_date_sk",
                                  "ws_bill_customer_sk"))
    no_cs = _join(ss, cs, ["c_last_name", "c_first_name", "d_date"],
                  ["cl", "cf", "cd"], jt=J.LEFT_ANTI)
    only_ss = _join(no_cs, ws, ["c_last_name", "c_first_name",
                                "d_date"],
                    ["wl", "wf", "wd"], jt=J.LEFT_ANTI)
    return CpuAggregate([], [Count(None).alias("cnt")], only_ss)


QUERIES.update({
    "q12": q12, "q20": q20, "q82": q82, "q92": q92,
    "q94": q94, "q38": q38, "q87": q87,
})


def q9(t, run):
    """Reference q9: five quantity-band CASE buckets from scalar
    subqueries (run() materializes each, the CASE picks avg discount vs
    avg net_paid by count threshold; thresholds scaled to the
    generator's volumes)."""
    bands = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    exprs = []
    for i, (lo, hi) in enumerate(bands, start=1):
        stats = run(CpuAggregate(
            [], [Count(None).alias("c"),
                 Average(col("ss_ext_discount_amt")).alias("ad"),
                 Average(col("ss_net_paid")).alias("ap")],
            CpuFilter(_between(col("ss_quantity"), lit(lo), lit(hi)),
                      t["store_sales"])))
        cnt = int(stats["c"].iloc[0])
        val = float(stats["ad"].iloc[0] if cnt > 1200
                    else stats["ap"].iloc[0])
        exprs.append(lit(val).alias(f"bucket{i}"))
    one = CpuFilter(col("r_reason_sk") == lit(1), t["reason"])
    return CpuProject(exprs, one)


def q41(t, run):
    """Reference q41: distinct product names whose manufacturer also
    makes items in the listed color/unit/size combinations."""
    arms = (
        (InSet(col("i_category"), ("Women",)) &
         InSet(col("i_color"), ("powder", "khaki")) &
         InSet(col("i_units"), ("Ounce", "Oz")) &
         InSet(col("i_size"), ("medium", "extra large"))) |
        (InSet(col("i_category"), ("Music",)) &
         InSet(col("i_color"), ("floral", "deep")) &
         InSet(col("i_units"), ("N/A", "Dozen")) &
         InSet(col("i_size"), ("petite", "large"))) |
        (InSet(col("i_category"), ("Shoes",)) &
         InSet(col("i_color"), ("light", "cornflower")) &
         InSet(col("i_units"), ("Box", "Pound")) &
         InSet(col("i_size"), ("medium", "extra large"))) |
        (InSet(col("i_category"), ("Books",)) &
         InSet(col("i_color"), ("midnight", "snow")) &
         InSet(col("i_units"), ("Ounce", "Oz")) &
         InSet(col("i_size"), ("petite", "large"))))
    match_manufact = CpuProject(
        [col("i_manufact").alias("_mf")],
        CpuAggregate([col("i_manufact")], [Count(None).alias("_c")],
                     CpuFilter(arms, t["item"])))
    i1 = CpuFilter(_between(col("i_manufact_id"), lit(1), lit(40)),
                   t["item"])
    j = _join(i1, match_manufact, ["i_manufact"], ["_mf"],
              jt=J.LEFT_SEMI)
    dist = CpuAggregate([col("i_product_name")],
                        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_product_name")], dist)
    return CpuLimit(100, CpuSort([asc(col("i_product_name"))], out))


def q16(t, run):
    """Reference q16: multi-warehouse never-returned catalog orders for
    one county/state window (EXISTS/NOT EXISTS as semi/anti joins)."""
    dd = CpuFilter(_between(col("d_date"), _date(2002, 2, 1),
                            _date(2002, 4, 2)), t["date_dim"])
    ca = CpuFilter(col("ca_state") == lit("GA"),
                   t["customer_address"])
    cc = CpuFilter(InSet(col("cc_county"), ("Williamson County",)),
                   t["call_center"])
    cs1 = _join(_join(_join(
        dd, t["catalog_sales"], ["d_date_sk"], ["cs_ship_date_sk"]),
        ca, ["cs_ship_addr_sk"], ["ca_address_sk"]),
        cc, ["cs_call_center_sk"], ["cc_call_center_sk"])
    multi_wh = CpuFilter(
        col("nwh") > lit(1),
        CpuAggregate(
            [col("morder")], [Count(None).alias("nwh")],
            CpuAggregate(
                [col("cs_order_number").alias("morder"),
                 col("cs_warehouse_sk")],
                [Count(None).alias("_c")], t["catalog_sales"])))
    cs1 = _join(cs1, multi_wh, ["cs_order_number"], ["morder"],
                jt=J.LEFT_SEMI)
    cs1 = _join(cs1, t["catalog_returns"], ["cs_order_number"],
                ["cr_order_number"], jt=J.LEFT_ANTI)
    dist = CpuAggregate(
        [], [Count(col("dorder")).alias("order_count")],
        CpuAggregate([col("cs_order_number").alias("dorder")],
                     [Count(None).alias("_d")], cs1))
    sums = CpuAggregate(
        [], [Sum(col("cs_ext_ship_cost")).alias("total_ship_cost"),
             Sum(col("cs_net_profit")).alias("total_net_profit")], cs1)
    j = _join(CpuProject([lit(1).alias("_ka"), col("order_count")],
                         dist),
              CpuProject([lit(1).alias("_kb"), col("total_ship_cost"),
                          col("total_net_profit")], sums),
              ["_ka"], ["_kb"])
    return CpuLimit(100, CpuProject(
        [col("order_count"), col("total_ship_cost"),
         col("total_net_profit")], j))


QUERIES.update({"q9": q9, "q41": q41, "q16": q16})


def q21(t, run):
    """Reference q21: warehouse inventory before/after one cutover date
    for a price band, keeping ratio-bounded rows."""
    it = CpuFilter(_between(col("i_current_price"),
                            lit(10.0), lit(60.0)), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 1),
                            _date(2000, 6, 30)), t["date_dim"])
    j = _join(_join(_join(
        dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
        t["warehouse"], ["inv_warehouse_sk"], ["w_warehouse_sk"]),
        it, ["inv_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("i_item_id")],
        [Sum(If(col("d_date") < _date(2000, 3, 11),
                col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_date") >= _date(2000, 3, 11),
                col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    ratio = If(col("inv_before") > lit(0),
               col("inv_after") / col("inv_before"),
               _Lit(None, _T.FLOAT64))
    # reference band is 2/3..3/2; the sparse synthetic inventory
    # needs a wider one to keep rows
    out = CpuFilter((ratio >= lit(0.1)) & (ratio <= lit(10.0)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_name")), asc(col("i_item_id"))], out))


QUERIES.update({"q21": q21})


# ---------------------------------------------------------------------------
# round-3 continued: faithful upgrades over the extended generator
# (d_week_seq, t_meal_time, income_band, hd_income_band_sk, nullable
# channel fks).  Reference text: TpcdsLikeSpark.scala:708+.
from spark_rapids_tpu.exprs.math_exprs import Round
from spark_rapids_tpu.exprs.string_fns import ConcatStrings

def _day_sum(val: str, name: str, alias: str):
    """sum(case when d_day_name='<name>' then <val> else null end)"""
    return Sum(If(col("d_day_name") == lit(name), col(val),
                  _Lit(None, _T.FLOAT64))).alias(alias)


_DAYS = [("Sunday", "sun"), ("Monday", "mon"), ("Tuesday", "tue"),
         ("Wednesday", "wed"), ("Thursday", "thu"), ("Friday", "fri"),
         ("Saturday", "sat")]


def q2(t, run):
    """Reference q2: web+catalog weekly day-of-week sales, year-over-
    year ratio on week_seq1 = week_seq2 - 53."""
    wscs = CpuUnion(
        CpuProject([col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_ext_sales_price").alias("sales_price")],
                   t["web_sales"]),
        CpuProject([col("cs_sold_date_sk").alias("sold_date_sk"),
                    col("cs_ext_sales_price").alias("sales_price")],
                   t["catalog_sales"]))
    j = _join(t["date_dim"], wscs, ["d_date_sk"], ["sold_date_sk"])
    wswscs = CpuAggregate(
        [col("d_week_seq")],
        [_day_sum("sales_price", d, f"{p}_sales") for d, p in _DAYS], j)

    def year_slice(year, tag):
        wk = CpuProject([col("d_week_seq").alias(f"{tag}_wk")],
                        CpuFilter(col("d_year") == lit(year),
                                  t["date_dim"]))
        named = CpuProject(
            [col("d_week_seq").alias(f"d_week_seq{tag}")] +
            [col(f"{p}_sales").alias(f"{p}_sales{tag}")
             for _, p in _DAYS], wswscs)
        return _join(named, wk, [f"d_week_seq{tag}"], [f"{tag}_wk"])

    y = year_slice(2001, "1")
    z = CpuProject(
        [(col("d_week_seq2") - lit(53)).alias("z_key")] +
        [col(f"{p}_sales2") for _, p in _DAYS], year_slice(2002, "2"))
    j2 = _join(y, z, ["d_week_seq1"], ["z_key"])
    out = CpuProject(
        [col("d_week_seq1")] +
        [Round(col(f"{p}_sales1") / col(f"{p}_sales2"), 2
               ).alias(f"r_{p}") for _, p in _DAYS], j2)
    return CpuSort([asc(col("d_week_seq1"))], out)


def q59(t, run):
    """Reference q59: store weekly day-of-week sales, consecutive
    12-month windows ratio (week_seq1 = week_seq2 - 52)."""
    j = _join(t["date_dim"], t["store_sales"],
              ["d_date_sk"], ["ss_sold_date_sk"])
    wss = CpuAggregate(
        [col("d_week_seq"), col("ss_store_sk")],
        [_day_sum("ss_sales_price", d, f"{p}_sales")
         for d, p in _DAYS], j)

    def window(mlo, mhi, tag, days):
        # reference month_seq window 1212..1212+11 stands in as the
        # generator's month_seq domain [0, 59]
        wk = CpuProject(
            [col("d_week_seq").alias(f"{tag}_wk")],
            CpuFilter(_between(col("d_month_seq"), lit(mlo), lit(mhi)),
                      t["date_dim"]))
        named = CpuProject(
            [col("d_week_seq").alias(f"d_week_seq{tag}"),
             col("ss_store_sk").alias(f"store_sk{tag}")] +
            [col(f"{p}_sales").alias(f"{p}_sales{tag}") for p in days],
            wss)
        st = _join(named, t["store"], [f"store_sk{tag}"], ["s_store_sk"])
        keep = ([col(f"d_week_seq{tag}"),
                 col("s_store_name").alias(f"s_store_name{tag}"),
                 col("s_store_id").alias(f"s_store_id{tag}")] +
                [col(f"{p}_sales{tag}") for p in days])
        return _join(CpuProject(keep, st), wk,
                     [f"d_week_seq{tag}"], [f"{tag}_wk"])

    days1 = [p for _, p in _DAYS]
    y = window(12, 23, "1", days1)
    x = CpuProject(
        [(col("d_week_seq2") - lit(52)).alias("x_key"),
         col("s_store_id2")] +
        [col(f"{p}_sales2") for p in days1],
        window(24, 35, "2", days1))
    j2 = _join(y, x, ["d_week_seq1", "s_store_id1"],
               ["x_key", "s_store_id2"])
    out = CpuProject(
        [col("s_store_name1"), col("s_store_id1"), col("d_week_seq1")] +
        [(col(f"{p}_sales1") / col(f"{p}_sales2")).alias(f"r_{p}")
         for p in days1], j2)
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name1")), asc(col("s_store_id1")),
         asc(col("d_week_seq1"))], out))


def _q76_channel(t, sales, null_col, date_key, item_key, price, chan):
    f = CpuFilter(IsNull(col(null_col)), sales)
    j = _join(_join(f, t["item"], [item_key], ["i_item_sk"]),
              t["date_dim"], [date_key], ["d_date_sk"])
    return CpuProject(
        [lit(chan).alias("channel"), col(null_col).alias("col_name"),
         col("d_year"), col("d_qoy"), col("i_category"),
         col(price).alias("ext_sales_price")], j)


def q76(t, run):
    """Reference q76: sales rows with a NULL channel fk, per channel."""
    u = CpuUnion(
        _q76_channel(t, t["store_sales"], "ss_store_sk",
                     "ss_sold_date_sk", "ss_item_sk",
                     "ss_ext_sales_price", "store"),
        _q76_channel(t, t["web_sales"], "ws_ship_customer_sk",
                     "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price", "web"),
        _q76_channel(t, t["catalog_sales"], "cs_ship_addr_sk",
                     "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price", "catalog"))
    agg = CpuAggregate(
        [col("channel"), col("col_name"), col("d_year"), col("d_qoy"),
         col("i_category")],
        [Count(None).alias("sales_cnt"),
         Sum(col("ext_sales_price")).alias("sales_amt")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("col_name")), asc(col("d_year")),
         asc(col("d_qoy")), asc(col("i_category"))], agg))


def q84(t, run):
    """Reference q84: customers in one city within an income band who
    returned something."""
    ca = CpuFilter(col("ca_city") == lit("Edgewood"),
                   t["customer_address"])
    ib = CpuFilter((col("ib_lower_bound") >= lit(38128)) &
                   (col("ib_upper_bound") <= lit(38128 + 50000)),
                   t["income_band"])
    j = _join(t["customer"], ca, ["c_current_addr_sk"],
              ["ca_address_sk"])
    j = _join(j, t["customer_demographics"],
              ["c_current_cdemo_sk"], ["cd_demo_sk"])
    j = _join(j, t["household_demographics"],
              ["c_current_hdemo_sk"], ["hd_demo_sk"])
    j = _join(j, ib, ["hd_income_band_sk"], ["ib_income_band_sk"])
    j = _join(j, t["store_returns"], ["cd_demo_sk"], ["sr_cdemo_sk"])
    out = CpuProject(
        [col("c_customer_id").alias("customer_id"),
         ConcatStrings((Coalesce((col("c_last_name"), lit(""))),
                        lit(", "),
                        Coalesce((col("c_first_name"), lit(""))))
         ).alias("customername")], j)
    return CpuLimit(100, CpuSort([asc(col("customer_id"))], out))


def q91(t, run):
    """Reference q91: call-center catalog returns loss for one month by
    demographic slice."""
    # reference window is 1998-11; the full year stands in for the
    # sparse synthetic returns table
    dd = CpuFilter(col("d_year") == lit(1998), t["date_dim"])
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Unknown"))) |
        ((col("cd_marital_status") == lit("W")) &
         (col("cd_education_status") == lit("Advanced Degree"))),
        t["customer_demographics"])
    hd = CpuFilter(Like(col("hd_buy_potential"), lit("Unknown%")),
                   t["household_demographics"])
    ca = CpuFilter(col("ca_gmt_offset") == lit(-7.0),
                   t["customer_address"])
    j = _join(t["call_center"], t["catalog_returns"],
              ["cc_call_center_sk"], ["cr_call_center_sk"])
    j = _join(j, dd, ["cr_returned_date_sk"], ["d_date_sk"])
    j = _join(j, t["customer"], ["cr_returning_customer_sk"],
              ["c_customer_sk"])
    j = _join(j, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    j = _join(j, hd, ["c_current_hdemo_sk"], ["hd_demo_sk"])
    j = _join(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate(
        [col("cc_call_center_id"), col("cc_name"), col("cc_manager"),
         col("cd_marital_status"), col("cd_education_status")],
        [Sum(col("cr_net_loss")).alias("returns_loss")], j)
    out = CpuProject(
        [col("cc_call_center_id").alias("call_center"),
         col("cc_name").alias("call_center_name"),
         col("cc_manager").alias("manager"),
         col("returns_loss")], agg)
    return CpuSort([desc(col("returns_loss"))], out)


def q71(t, run):
    """Reference q71: one manager's brand revenue by hour/minute over
    all three channels, breakfast + dinner meal times."""
    def chan(sales, price, date_key, item_key, time_key):
        dd = CpuFilter((col("d_moy") == lit(11)) &
                       (col("d_year") == lit(1999)), t["date_dim"])
        j = _join(dd, sales, ["d_date_sk"], [date_key])
        return CpuProject(
            [col(price).alias("ext_price"),
             col(item_key).alias("sold_item_sk"),
             col(time_key).alias("time_sk")], j)

    u = CpuUnion(
        chan(t["web_sales"], "ws_ext_sales_price", "ws_sold_date_sk",
             "ws_item_sk", "ws_sold_time_sk"),
        chan(t["catalog_sales"], "cs_ext_sales_price",
             "cs_sold_date_sk", "cs_item_sk", "cs_sold_time_sk"),
        chan(t["store_sales"], "ss_ext_sales_price", "ss_sold_date_sk",
             "ss_item_sk", "ss_sold_time_sk"))
    it = CpuFilter(col("i_manager_id") == lit(1), t["item"])
    td = CpuFilter((col("t_meal_time") == lit("breakfast")) |
                   (col("t_meal_time") == lit("dinner")), t["time_dim"])
    j = _join(_join(u, it, ["sold_item_sk"], ["i_item_sk"]),
              td, ["time_sk"], ["t_time_sk"])
    agg = CpuAggregate(
        [col("i_brand"), col("i_brand_id"), col("t_hour"),
         col("t_minute")],
        [Sum(col("ext_price")).alias("ext_price")], j)
    out = CpuProject(
        [col("i_brand_id").alias("brand_id"),
         col("i_brand").alias("brand"), col("t_hour"), col("t_minute"),
         col("ext_price")], agg)
    return CpuSort([desc(col("ext_price")), asc(col("brand_id"))], out)


def q8(t, run):
    """Reference q8: store profit for stores whose zip prefix matches
    zips that are both on the campaign list and dense in preferred
    customers (INTERSECT via inner join of the two distinct sets)."""
    # reference's 400-zip campaign list stands in as its intersection
    # with the generator zip pool + three pool zips
    zips = ("10144", "10336", "10390", "10445", "10516", "10567",
            "85669", "86197", "88274")
    listed = CpuAggregate(
        [col("zip5")], [Count(None).alias("_a")],
        CpuProject([_Substring(col("ca_zip"), lit(1),
                               lit(5)).alias("zip5")],
                   CpuFilter(InSet(_Substring(col("ca_zip"), lit(1),
                                              lit(5)), zips),
                             t["customer_address"])))
    pref = _join(t["customer_address"], 
                 CpuFilter(col("c_preferred_cust_flag") == lit("Y"),
                           t["customer"]),
                 ["ca_address_sk"], ["c_current_addr_sk"])
    dense = CpuFilter(
        col("cnt") > lit(10),
        CpuAggregate([col("pzip")], [Count(None).alias("cnt")],
                     CpuProject([_Substring(col("ca_zip"), lit(1),
                                            lit(5)).alias("pzip")],
                                pref)))
    v1 = CpuProject(
        [_Substring(col("zip5"), lit(1), lit(2)).alias("zip2")],
        _join(listed, dense, ["zip5"], ["pzip"]))
    dd = CpuFilter((col("d_qoy") == lit(2)) &
                   (col("d_year") == lit(1998)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    j = CpuProject(
        [col("s_store_name"), col("ss_net_profit"),
         _Substring(col("s_zip"), lit(1), lit(2)).alias("szip2")], j)
    j = _join(j, CpuAggregate([col("zip2")],
                              [Count(None).alias("_b")], v1),
              ["szip2"], ["zip2"], jt=J.LEFT_SEMI)
    agg = CpuAggregate([col("s_store_name")],
                       [Sum(col("ss_net_profit")).alias("profit")], j)
    return CpuLimit(100, CpuSort([asc(col("s_store_name"))], agg))


QUERIES.update({
    "q2": q2, "q59": q59, "q76": q76, "q84": q84, "q91": q91,
    "q71": q71, "q8": q8,
})


def _year_total_slice(t, chan, year, amount, group_extra=()):
    """One year_total CTE slice (q4/q11/q74): per-customer yearly sum
    for one channel.  `amount(prefix)` builds the summed expression."""
    sales, cust_key, date_key = {
        "s": ("store_sales", "ss_customer_sk", "ss_sold_date_sk"),
        "c": ("catalog_sales", "cs_bill_customer_sk",
              "cs_sold_date_sk"),
        "w": ("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk"),
    }[chan]
    dd = CpuFilter(col("d_year") == lit(year), t["date_dim"])
    j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
              t["customer"], [cust_key], ["c_customer_sk"])
    groups = ["c_customer_id", "c_first_name", "c_last_name",
              "c_preferred_cust_flag", "c_birth_country", "c_login",
              "c_email_address"] + list(group_extra)
    return CpuAggregate([col(g) for g in groups],
                        [Sum(amount).alias("year_total")], j)


def _yt_named(slice_plan, tag, keep):
    """Project a year_total slice to tag-prefixed columns."""
    return CpuProject(
        [col(c).alias(f"{tag}_{c}") for c in keep] +
        [col("year_total").alias(f"{tag}_total")], slice_plan)


def q4(t, run):
    """Reference q4: customers whose catalog growth beats both store
    and web growth, across all three channels."""
    y1, y2 = 2001, 2002
    amt = {
        "s": ((col("ss_ext_list_price") - col("ss_ext_wholesale_cost")
               - col("ss_ext_discount_amt")) +
              col("ss_ext_sales_price")) / lit(2.0),
        "c": ((col("cs_ext_list_price") - col("cs_wholesale_cost")
               - col("cs_ext_discount_amt")) +
              col("cs_ext_sales_price")) / lit(2.0),
        "w": ((col("ws_ext_list_price") - col("ws_ext_discount_amt"))
              + col("ws_ext_sales_price")) / lit(2.0),
    }
    named = {}
    for ch in ("s", "c", "w"):
        keep = (["c_customer_id", "c_first_name", "c_last_name",
                 "c_preferred_cust_flag"] if ch == "s" else
                ["c_customer_id"])
        named[(ch, 1)] = _yt_named(
            _year_total_slice(t, ch, y1, amt[ch]), f"{ch}1",
            ["c_customer_id"])
        named[(ch, 2)] = _yt_named(
            _year_total_slice(t, ch, y2, amt[ch]), f"{ch}2",
            keep if ch == "s" else ["c_customer_id"])
    j = _join(named[("s", 1)], named[("s", 2)],
              ["s1_c_customer_id"], ["s2_c_customer_id"])
    for ch in ("c", "w"):
        j = _join(j, named[(ch, 1)], ["s1_c_customer_id"],
                  [f"{ch}1_c_customer_id"])
        j = _join(j, named[(ch, 2)], ["s1_c_customer_id"],
                  [f"{ch}2_c_customer_id"])

    def growth(ch):
        return If(col(f"{ch}1_total") > lit(0.0),
                  col(f"{ch}2_total") / col(f"{ch}1_total"),
                  _Lit(None, _T.FLOAT64))
    f = CpuFilter(
        (col("s1_total") > lit(0.0)) &
        (col("c1_total") > lit(0.0)) &
        (col("w1_total") > lit(0.0)) &
        (growth("c") > growth("s")) & (growth("c") > growth("w")), j)
    out = CpuProject(
        [col("s2_c_customer_id").alias("customer_id"),
         col("s2_c_first_name").alias("customer_first_name"),
         col("s2_c_last_name").alias("customer_last_name"),
         col("s2_c_preferred_cust_flag"
             ).alias("customer_preferred_cust_flag")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("customer_id")), asc(col("customer_first_name")),
         asc(col("customer_last_name")),
         asc(col("customer_preferred_cust_flag"))], out))


def q11(t, run):
    """Reference q11: customers whose web growth beats store growth
    (else-0.0 ratio semantics)."""
    amt_s = col("ss_ext_list_price") - col("ss_ext_discount_amt")
    amt_w = col("ws_ext_list_price") - col("ws_ext_discount_amt")
    y1, y2 = 2001, 2002
    named = {
        ("s", 1): _yt_named(_year_total_slice(t, "s", y1, amt_s), "s1",
                            ["c_customer_id"]),
        ("s", 2): _yt_named(_year_total_slice(t, "s", y2, amt_s), "s2",
                            ["c_customer_id", "c_first_name",
                             "c_last_name", "c_preferred_cust_flag"]),
        ("w", 1): _yt_named(_year_total_slice(t, "w", y1, amt_w), "w1",
                            ["c_customer_id"]),
        ("w", 2): _yt_named(_year_total_slice(t, "w", y2, amt_w), "w2",
                            ["c_customer_id"]),
    }
    j = _join(named[("s", 1)], named[("s", 2)],
              ["s1_c_customer_id"], ["s2_c_customer_id"])
    j = _join(j, named[("w", 1)], ["s1_c_customer_id"],
              ["w1_c_customer_id"])
    j = _join(j, named[("w", 2)], ["s1_c_customer_id"],
              ["w2_c_customer_id"])

    def growth(ch):
        return If(col(f"{ch}1_total") > lit(0.0),
                  col(f"{ch}2_total") / col(f"{ch}1_total"), lit(0.0))
    f = CpuFilter(
        (col("s1_total") > lit(0.0)) & (col("w1_total") > lit(0.0)) &
        (growth("w") > growth("s")), j)
    out = CpuProject(
        [col("s2_c_customer_id").alias("customer_id"),
         col("s2_c_first_name").alias("customer_first_name"),
         col("s2_c_last_name").alias("customer_last_name"),
         col("s2_c_preferred_cust_flag"
             ).alias("customer_preferred_cust_flag")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("customer_id")), asc(col("customer_first_name")),
         asc(col("customer_last_name")),
         asc(col("customer_preferred_cust_flag"))], out))


def q74(t, run):
    """Reference q74: q11's shape on ss/ws_net_paid, null-else ratio,
    ordered by customer_id."""
    y1, y2 = 2001, 2002
    named = {
        ("s", 1): _yt_named(
            _year_total_slice(t, "s", y1, col("ss_net_paid")), "s1",
            ["c_customer_id"]),
        ("s", 2): _yt_named(
            _year_total_slice(t, "s", y2, col("ss_net_paid")), "s2",
            ["c_customer_id", "c_first_name", "c_last_name"]),
        ("w", 1): _yt_named(
            _year_total_slice(t, "w", y1, col("ws_net_paid")), "w1",
            ["c_customer_id"]),
        ("w", 2): _yt_named(
            _year_total_slice(t, "w", y2, col("ws_net_paid")), "w2",
            ["c_customer_id"]),
    }
    j = _join(named[("s", 1)], named[("s", 2)],
              ["s1_c_customer_id"], ["s2_c_customer_id"])
    j = _join(j, named[("w", 1)], ["s1_c_customer_id"],
              ["w1_c_customer_id"])
    j = _join(j, named[("w", 2)], ["s1_c_customer_id"],
              ["w2_c_customer_id"])

    def growth(ch):
        return If(col(f"{ch}1_total") > lit(0.0),
                  col(f"{ch}2_total") / col(f"{ch}1_total"),
                  _Lit(None, _T.FLOAT64))
    f = CpuFilter(
        (col("s1_total") > lit(0.0)) & (col("w1_total") > lit(0.0)) &
        (growth("w") > growth("s")), j)
    out = CpuProject(
        [col("s2_c_customer_id").alias("customer_id"),
         col("s2_c_first_name").alias("customer_first_name"),
         col("s2_c_last_name").alias("customer_last_name")], f)
    return CpuLimit(100, CpuSort([asc(col("customer_id"))], out))


QUERIES.update({"q4": q4, "q11": q11, "q74": q74})


def _ctr_plan(t, returns, date_key, cust_key, addr_key, amt, year):
    """customer_total_return CTE (q30/q81): per-customer, per-state
    return totals for one year."""
    dd = CpuFilter(col("d_year") == lit(year), t["date_dim"])
    j = _join(_join(dd, returns, ["d_date_sk"], [date_key]),
              t["customer_address"], [addr_key], ["ca_address_sk"])
    return CpuAggregate(
        [col(cust_key), col("ca_state")],
        [Sum(col(amt)).alias("ctr_total_return")], j)


def _ctr_above_state_avg(t, ctr, cust_key):
    """ctr rows above 1.2x their state's average total return
    (correlated scalar subquery as aggregate re-join)."""
    avg = CpuProject(
        [col("ca_state").alias("avg_state"),
         (col("_a") * lit(1.2)).alias("threshold")],
        CpuAggregate([col("ca_state")],
                     [Average(col("ctr_total_return")).alias("_a")],
                     ctr))
    j = _join(ctr, avg, ["ca_state"], ["avg_state"])
    return CpuFilter(col("ctr_total_return") > col("threshold"), j)


def q30(t, run):
    """Reference q30: GA customers whose web-return total beats 1.2x
    their state average, with the full customer attribute list."""
    ctr = _ctr_plan(t, t["web_returns"], "wr_returned_date_sk",
                    "wr_returning_customer_sk", "wr_returning_addr_sk",
                    "wr_return_amt", 2002)
    top = _ctr_above_state_avg(t, ctr, "wr_returning_customer_sk")
    ga = CpuFilter(col("ca_state") == lit("GA"),
                   t["customer_address"])
    ga = CpuProject([col("ca_address_sk").alias("ga_addr")], ga)
    j = _join(top, t["customer"], ["wr_returning_customer_sk"],
              ["c_customer_sk"])
    j = _join(j, ga, ["c_current_addr_sk"], ["ga_addr"])
    cols = ["c_customer_id", "c_salutation", "c_first_name",
            "c_last_name", "c_preferred_cust_flag", "c_birth_day",
            "c_birth_month", "c_birth_year", "c_birth_country",
            "c_login", "c_email_address", "c_last_review_date"]
    out = CpuProject([col(c) for c in cols] +
                     [col("ctr_total_return")], j)
    return CpuLimit(100, CpuSort(
        [asc(col(c)) for c in cols] +
        [asc(col("ctr_total_return"))], out))


def q81(t, run):
    """Reference q81: q30's shape on catalog returns (amt_inc_tax),
    returning the full address attribute list."""
    ctr = _ctr_plan(t, t["catalog_returns"], "cr_returned_date_sk",
                    "cr_returning_customer_sk", "cr_returning_addr_sk",
                    "cr_return_amt_inc_tax", 2000)
    top = _ctr_above_state_avg(t, ctr, "cr_returning_customer_sk")
    j = _join(top, t["customer"], ["cr_returning_customer_sk"],
              ["c_customer_sk"])
    ga = CpuFilter(col("ca_state") == lit("GA"),
                   t["customer_address"])
    ga = CpuProject(
        [col("ca_address_sk").alias("ga_addr"),
         col("ca_street_number"), col("ca_street_name"),
         col("ca_street_type"), col("ca_suite_number"),
         col("ca_city").alias("cust_city"),
         col("ca_county").alias("cust_county"),
         col("ca_state").alias("cust_state"),
         col("ca_zip").alias("cust_zip"),
         col("ca_country").alias("cust_country"),
         col("ca_gmt_offset").alias("cust_gmt"),
         col("ca_location_type")], ga)
    j = _join(j, ga, ["c_current_addr_sk"], ["ga_addr"])
    cols = ["c_customer_id", "c_salutation", "c_first_name",
            "c_last_name", "ca_street_number", "ca_street_name",
            "ca_street_type", "ca_suite_number", "cust_city",
            "cust_county", "cust_state", "cust_zip", "cust_country",
            "cust_gmt", "ca_location_type"]
    out = CpuProject([col(c) for c in cols] +
                     [col("ctr_total_return")], j)
    return CpuLimit(100, CpuSort(
        [asc(col(c)) for c in cols] +
        [asc(col("ctr_total_return"))], out))


def q31(t, run):
    """Reference q31: counties where web sales grew faster than store
    sales across 2000 Q1->Q2->Q3."""
    def chan(sales, date_key, addr_key, val, alias):
        j = _join(_join(t["date_dim"], sales,
                        ["d_date_sk"], [date_key]),
                  t["customer_address"], [addr_key], ["ca_address_sk"])
        return CpuAggregate(
            [col("ca_county"), col("d_qoy"), col("d_year")],
            [Sum(col(val)).alias(alias)], j)

    ss = chan(t["store_sales"], "ss_sold_date_sk", "ss_addr_sk",
              "ss_ext_sales_price", "store_sales")
    ws = chan(t["web_sales"], "ws_sold_date_sk", "ws_bill_addr_sk",
              "ws_ext_sales_price", "web_sales")

    def slice_q(plan, qoy, tag, val):
        return CpuProject(
            [col("ca_county").alias(f"{tag}_county"),
             col(val).alias(f"{tag}_total")],
            CpuFilter((col("d_qoy") == lit(qoy)) &
                      (col("d_year") == lit(2000)), plan))

    j = _join(slice_q(ss, 1, "ss1", "store_sales"),
              slice_q(ss, 2, "ss2", "store_sales"),
              ["ss1_county"], ["ss2_county"])
    j = _join(j, slice_q(ss, 3, "ss3", "store_sales"),
              ["ss1_county"], ["ss3_county"])
    j = _join(j, slice_q(ws, 1, "ws1", "web_sales"),
              ["ss1_county"], ["ws1_county"])
    j = _join(j, slice_q(ws, 2, "ws2", "web_sales"),
              ["ss1_county"], ["ws2_county"])
    j = _join(j, slice_q(ws, 3, "ws3", "web_sales"),
              ["ss1_county"], ["ws3_county"])

    def ratio(hi, lo):
        return If(col(f"{lo}_total") > lit(0.0),
                  col(f"{hi}_total") / col(f"{lo}_total"),
                  _Lit(None, _T.FLOAT64))
    f = CpuFilter(
        (ratio("ws2", "ws1") > ratio("ss2", "ss1")) &
        (ratio("ws3", "ws2") > ratio("ss3", "ss2")), j)
    out = CpuProject(
        [col("ss1_county").alias("ca_county"),
         lit(2000).alias("d_year"),
         (col("ws2_total") / col("ws1_total")
          ).alias("web_q1_q2_increase"),
         (col("ss2_total") / col("ss1_total")
          ).alias("store_q1_q2_increase"),
         (col("ws3_total") / col("ws2_total")
          ).alias("web_q2_q3_increase"),
         (col("ss3_total") / col("ss2_total")
          ).alias("store_q2_q3_increase")], f)
    return CpuSort([asc(col("ca_county"))], out)


def q6(t, run):
    """Reference q6: states with >=10 customers who bought items priced
    over 1.2x their category average in one month."""
    month = CpuProject(
        [col("d_month_seq").alias("target_seq"), lit(1).alias("_mk")],
        CpuAggregate(
            [col("d_month_seq")], [Count(None).alias("_c")],
            CpuFilter((col("d_year") == lit(2001)) &
                      (col("d_moy") == lit(1)), t["date_dim"])))
    dd = CpuProject(
        [col("d_date_sk")],
        CpuFilter(col("d_month_seq") == col("target_seq"),
                  _join(CpuProject([col("d_date_sk"),
                                    col("d_month_seq"),
                                    lit(1).alias("_dk")],
                                   t["date_dim"]),
                        month, ["_dk"], ["_mk"])))
    cat_avg = CpuProject(
        [col("i_category").alias("avg_cat"),
         (col("_a") * lit(1.2)).alias("cat_threshold")],
        CpuAggregate([col("i_category")],
                     [Average(col("i_current_price")).alias("_a")],
                     t["item"]))
    it = CpuFilter(
        col("i_current_price") > col("cat_threshold"),
        _join(t["item"], cat_avg, ["i_category"], ["avg_cat"]))
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["customer"], ["ss_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"],
        ["ca_address_sk"]),
        it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], j)
    # reference keeps states with >= 10 such customers; >= 5 stands
    # in at synthetic scale
    f = CpuFilter(col("cnt") >= lit(5), agg)
    out = CpuProject([col("ca_state").alias("state"), col("cnt")], f)
    return CpuLimit(100, CpuSort([asc(col("cnt"))], out))


def q32(t, run):
    """Reference q32: catalog discounts above 1.3x the per-item window
    average for one manufacturer."""
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 27),
                            _date(2000, 4, 26)), t["date_dim"])
    cs = _join(dd, t["catalog_sales"],
               ["d_date_sk"], ["cs_sold_date_sk"])
    # reference manufacturer 977 stands in as 7 (a zipf-hot slice)
    it = CpuFilter(col("i_manufact_id") == lit(7), t["item"])
    j = _join(cs, it, ["cs_item_sk"], ["i_item_sk"])
    avg = CpuProject(
        [col("cs_item_sk").alias("_isk"),
         (col("_a") * lit(1.3)).alias("threshold")],
        CpuAggregate(
            [col("cs_item_sk")],
            [Average(col("cs_ext_discount_amt")).alias("_a")], cs))
    out = _join(j, avg, ["cs_item_sk"], ["_isk"])
    f = CpuFilter(col("cs_ext_discount_amt") > col("threshold"), out)
    return CpuLimit(100, CpuAggregate(
        [], [Sum(col("cs_ext_discount_amt")
                 ).alias("excess_discount_amount")], f))


def q83(t, run):
    """Reference q83: per-item return quantities across the three
    channels for three chosen weeks, with channel shares."""
    wk = CpuProject(
        [col("d_week_seq").alias("sel_wk")],
        CpuAggregate(
            [col("d_week_seq")], [Count(None).alias("_c")],
            CpuFilter(InSet(col("d_date"),
                            (_date(2000, 6, 30).value,
                             _date(2000, 9, 27).value,
                             _date(2000, 11, 17).value)),
                      t["date_dim"])))
    dates = CpuProject(
        [col("d_date_sk")],
        _join(t["date_dim"], wk, ["d_week_seq"], ["sel_wk"],
              jt=J.LEFT_SEMI))

    def items(returns, date_key, item_key, qty, alias):
        j = _join(_join(dates, returns, ["d_date_sk"], [date_key]),
                  t["item"], [item_key], ["i_item_sk"])
        return CpuAggregate([col("i_item_id")],
                            [Sum(col(qty)).alias(alias)], j)

    sr = items(t["store_returns"], "sr_returned_date_sk",
               "sr_item_sk", "sr_return_quantity", "sr_item_qty")
    cr = CpuProject(
        [col("i_item_id").alias("cr_id"), col("cr_item_qty")],
        items(t["catalog_returns"], "cr_returned_date_sk",
              "cr_item_sk", "cr_return_quantity", "cr_item_qty"))
    wr = CpuProject(
        [col("i_item_id").alias("wr_id"), col("wr_item_qty")],
        items(t["web_returns"], "wr_returned_date_sk",
              "wr_item_sk", "wr_return_quantity", "wr_item_qty"))
    j = _join(_join(sr, cr, ["i_item_id"], ["cr_id"]),
              wr, ["i_item_id"], ["wr_id"])
    total = (col("sr_item_qty") + col("cr_item_qty") +
             col("wr_item_qty"))

    def dev(q):
        return (col(q) / total / lit(3.0) * lit(100.0)
                ).alias(q.replace("item_qty", "dev"))
    out = CpuProject(
        [col("i_item_id").alias("item_id"), col("sr_item_qty"),
         dev("sr_item_qty"), col("cr_item_qty"), dev("cr_item_qty"),
         col("wr_item_qty"), dev("wr_item_qty"),
         (total / lit(3.0)).alias("average")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("item_id")), asc(col("sr_item_qty"))], out))


QUERIES.update({
    "q30": q30, "q81": q81, "q31": q31, "q6": q6, "q32": q32,
    "q83": q83,
})


from spark_rapids_tpu.exprs.arithmetic import Abs as _Abs


def q36(t, run):
    """Reference q36: gross margin over ROLLUP(i_category, i_class)
    with grouping()-derived hierarchy level and rank within parent."""
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    # reference pins TN; the generator state domain stands in
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA")),
                   t["store"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["item"], ["ss_item_sk"], ["i_item_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"])
    pre = CpuProject(
        [col("i_category"), col("i_class"), col("ss_net_profit"),
         col("ss_ext_sales_price")], j)
    ex = _rollup_expand(pre, ["i_category", "i_class"],
                        ["ss_net_profit", "ss_ext_sales_price"])
    agg = CpuAggregate(
        [col("i_category"), col("i_class"), col("gid")],
        [Sum(col("ss_net_profit")).alias("_np"),
         Sum(col("ss_ext_sales_price")).alias("_esp")], ex)
    pre_w = CpuProject(
        [(col("_np") / col("_esp")).alias("gross_margin"),
         col("i_category"), col("i_class"),
         If(col("gid") == lit(3), lit(2),
            If(col("gid") == lit(1), lit(1), lit(0))
            ).alias("lochierarchy"),
         If(col("gid") == lit(0), col("i_category"),
            _Lit(None, _T.STRING)).alias("_parent_cat")], agg)
    w = CpuWindow(
        [Rank().alias("rank_within_parent")],
        WindowSpec([col("lochierarchy"), col("_parent_cat")],
                   [asc(col("gross_margin"))]), pre_w)
    keyed = CpuProject(
        [col("gross_margin"), col("i_category"), col("i_class"),
         col("lochierarchy"), col("rank_within_parent"),
         If(col("lochierarchy") == lit(0), col("i_category"),
            _Lit(None, _T.STRING)).alias("_ocat")], w)
    out = CpuLimit(100, CpuSort(
        [desc(col("lochierarchy")), asc(col("_ocat")),
         asc(col("rank_within_parent"))], keyed))
    return CpuProject(
        [col("gross_margin"), col("i_category"), col("i_class"),
         col("lochierarchy"), col("rank_within_parent")], out)


def q44(t, run):
    """Reference q44: best vs worst performing items by average net
    profit at one store, rank-aligned."""
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    # reference store 4 stands in as the generator's store 2
    base = CpuFilter(col("ss_store_sk") == lit(2), t["store_sales"])
    per_item = CpuAggregate(
        [col("ss_item_sk")],
        [Average(col("ss_net_profit")).alias("rank_col")], base)
    null_addr = CpuProject(
        [(col("_a") * lit(0.9)).alias("threshold"),
         lit(1).alias("_tk")],
        CpuAggregate(
            [col("ss_store_sk")],
            [Average(col("ss_net_profit")).alias("_a")],
            CpuFilter(IsNull(col("ss_addr_sk")), base)))
    keyed = CpuProject(
        [col("ss_item_sk").alias("item_sk"), col("rank_col"),
         lit(1).alias("_pk")], per_item)
    v1 = CpuFilter(col("rank_col") > col("threshold"),
                   _join(keyed, null_addr, ["_pk"], ["_tk"]))

    def ranked(direction, tag):
        order = [asc(col("rank_col"))] if direction == "asc" else \
            [desc(col("rank_col"))]
        w = CpuWindow([Rank().alias("rnk")],
                      WindowSpec([], order), v1)
        return CpuProject(
            [col("item_sk").alias(f"{tag}_sk"),
             col("rnk").alias(f"{tag}_rnk")],
            CpuFilter(col("rnk") < lit(11), w))

    j = _join(ranked("asc", "up"), ranked("desc", "down"),
              ["up_rnk"], ["down_rnk"])
    i1 = CpuProject([col("i_item_sk").alias("i1_sk"),
                     col("i_product_name").alias("best_performing")],
                    t["item"])
    i2 = CpuProject([col("i_item_sk").alias("i2_sk"),
                     col("i_product_name").alias("worst_performing")],
                    t["item"])
    j = _join(_join(j, i1, ["up_sk"], ["i1_sk"]),
              i2, ["down_sk"], ["i2_sk"])
    out = CpuProject(
        [col("up_rnk").alias("rnk"), col("best_performing"),
         col("worst_performing")], j)
    return CpuLimit(100, CpuSort([asc(col("rnk"))], out))


def _v1_monthly(t, sales, date_key, item_key, entity_joins, groups,
                val):
    """q47/q57 v1 CTE: monthly sums + whole-partition average + rank
    over the month sequence."""
    from spark_rapids_tpu.exec.window import (CpuWindow, Rank, WinAvg,
                                              WindowFrame, WindowSpec)
    dd = CpuFilter(
        (col("d_year") == lit(1999)) |
        ((col("d_year") == lit(1998)) & (col("d_moy") == lit(12))) |
        ((col("d_year") == lit(2000)) & (col("d_moy") == lit(1))),
        t["date_dim"])
    j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
              t["item"], [item_key], ["i_item_sk"])
    for right, lk, rk in entity_joins:
        j = _join(j, right, lk, rk)
    agg = CpuAggregate(
        [col(g) for g in groups + ["d_year", "d_moy"]],
        [Sum(col(val)).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col(g) for g in groups] + [col("d_year")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    return CpuWindow(
        [Rank().alias("rn")],
        WindowSpec([col(g) for g in groups],
                   [asc(col("d_year")), asc(col("d_moy"))]), w)


def _lag_lead_tail(v1, groups, out_extra):
    """q47/q57 v2 + final filter: self-join v1 against rn+-1."""
    lag = CpuProject(
        [col(g).alias(f"lag_{g}") for g in groups] +
        [(col("rn") + lit(1)).alias("lag_rn"),
         col("sum_sales").alias("psum")], v1)
    lead = CpuProject(
        [col(g).alias(f"lead_{g}") for g in groups] +
        [(col("rn") - lit(1)).alias("lead_rn"),
         col("sum_sales").alias("nsum")], v1)
    j = _join(v1, lag, groups + ["rn"],
              [f"lag_{g}" for g in groups] + ["lag_rn"])
    j = _join(j, lead, groups + ["rn"],
              [f"lead_{g}" for g in groups] + ["lead_rn"])
    ratio = If(col("avg_monthly_sales") > lit(0.0),
               _Abs(col("sum_sales") - col("avg_monthly_sales")) /
               col("avg_monthly_sales"), _Lit(None, _T.FLOAT64))
    f = CpuFilter((col("d_year") == lit(1999)) &
                  (col("avg_monthly_sales") > lit(0.0)) &
                  (ratio > lit(0.1)), j)
    out = CpuProject(
        [col(g) for g in groups] +
        [col("d_year"), col("d_moy"), col("avg_monthly_sales"),
         col("sum_sales"), col("psum"), col("nsum"),
         (col("sum_sales") - col("avg_monthly_sales")).alias("_dev")] +
        out_extra, f)
    return CpuLimit(100, CpuSort(
        [asc(col("_dev")), asc(col(groups[0]))], out))


def q47(t, run):
    """Reference q47: store monthly sales deviations with neighboring
    months via rank self-joins."""
    groups = ["i_category", "i_brand", "s_store_name",
              "s_company_name"]
    v1 = _v1_monthly(
        t, t["store_sales"], "ss_sold_date_sk", "ss_item_sk",
        [(t["store"], ["ss_store_sk"], ["s_store_sk"])], groups,
        "ss_sales_price")
    return _lag_lead_tail(v1, groups, [])


def q57(t, run):
    """Reference q57: q47's shape on catalog sales by call center."""
    groups = ["i_category", "i_brand", "cc_name"]
    v1 = _v1_monthly(
        t, t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk",
        [(t["call_center"], ["cs_call_center_sk"],
          ["cc_call_center_sk"])], groups, "cs_sales_price")
    return _lag_lead_tail(v1, groups, [])


def q53(t, run):
    """Reference q53: manufacturer quarterly sales vs their windowed
    average, banded item slices."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WinAvg,
                                              WindowFrame, WindowSpec)
    # reference month_seq window 1200..1211 stands in as 12..23
    dd = CpuFilter(_between(col("d_month_seq"), lit(12), lit(23)),
                   t["date_dim"])
    # reference brand/class lists stand in as generator domains
    it = CpuFilter(
        (InSet(col("i_category"), ("Books", "Electronics", "Home")) &
         InSet(col("i_class"), ("class00", "class01", "class02",
                                "class03")) &
         InSet(col("i_brand"), ("brand#1", "brand#2"))) |
        (InSet(col("i_category"), ("Women", "Music", "Shoes")) &
         InSet(col("i_class"), ("class04", "class05", "class06",
                                "class07")) &
         InSet(col("i_brand"), ("brand#3", "brand#4"))), t["item"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        it, ["ss_item_sk"], ["i_item_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"])
    agg = CpuAggregate(
        [col("i_manufact_id"), col("d_qoy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_quarterly_sales")],
        WindowSpec([col("i_manufact_id")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    ratio = If(col("avg_quarterly_sales") > lit(0.0),
               _Abs(col("sum_sales") - col("avg_quarterly_sales")) /
               col("avg_quarterly_sales"), _Lit(None, _T.FLOAT64))
    f = CpuFilter(ratio > lit(0.1), w)
    out = CpuProject([col("i_manufact_id"), col("sum_sales"),
                      col("avg_quarterly_sales")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("avg_quarterly_sales")), asc(col("sum_sales")),
         asc(col("i_manufact_id"))], out))


def q89(t, run):
    """Reference q89: monthly class sales vs the brand/store windowed
    average."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WinAvg,
                                              WindowFrame, WindowSpec)
    dd = CpuFilter(col("d_year") == lit(1999), t["date_dim"])
    it = CpuFilter(
        (InSet(col("i_category"), ("Books", "Electronics", "Sports")) &
         InSet(col("i_class"), ("class00", "class01", "class02"))) |
        (InSet(col("i_category"), ("Men", "Jewelry", "Women")) &
         InSet(col("i_class"), ("class03", "class04", "class05"))),
        t["item"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        it, ["ss_item_sk"], ["i_item_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"])
    agg = CpuAggregate(
        [col("i_category"), col("i_class"), col("i_brand"),
         col("s_store_name"), col("s_company_name"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col("i_category"), col("i_brand"),
                    col("s_store_name"), col("s_company_name")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    ratio = If(col("avg_monthly_sales") != lit(0.0),
               _Abs(col("sum_sales") - col("avg_monthly_sales")) /
               col("avg_monthly_sales"), _Lit(None, _T.FLOAT64))
    f = CpuFilter(ratio > lit(0.1), w)
    out = CpuProject(
        [col("i_category"), col("i_class"), col("i_brand"),
         col("s_store_name"), col("s_company_name"), col("d_moy"),
         col("sum_sales"), col("avg_monthly_sales"),
         (col("sum_sales") - col("avg_monthly_sales")).alias("_dev")],
        f)
    return CpuLimit(100, CpuSort(
        [asc(col("_dev")), asc(col("s_store_name"))], out))


QUERIES.update({
    "q36": q36, "q44": q44, "q47": q47, "q57": q57, "q53": q53,
    "q89": q89,
})


def _q49_channel(t, sales, returns, order_keys, ret_keys, qty, rqty,
                 amt, ramt, paid, profit, date_key, item_key, chan):
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    j = _join(t[sales], t[returns], order_keys, ret_keys,
              jt=J.LEFT_OUTER)
    # reference thresholds (return_amt > 10000, d_moy = 12) stand in
    # as amounts/periods the synthetic scale can populate
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(j, dd, [date_key], ["d_date_sk"])
    f = CpuFilter((col(ramt) > lit(500.0)) &
                  (col(profit) > lit(1.0)) &
                  (col(paid) > lit(0.0)) &
                  (col(qty) > lit(0)), j)
    agg = CpuAggregate(
        [col(item_key)],
        [Sum(Coalesce((col(rqty), lit(0)))).alias("_rq"),
         Sum(Coalesce((col(qty), lit(0)))).alias("_q"),
         Sum(Coalesce((col(ramt), lit(0.0)))).alias("_ra"),
         Sum(Coalesce((col(paid), lit(0.0)))).alias("_p")], f)
    ratios = CpuProject(
        [col(item_key).alias("item"),
         (col("_rq") / col("_q")).alias("return_ratio"),
         (col("_ra") / col("_p")).alias("currency_ratio")], agg)
    w = CpuWindow(
        [Rank().alias("return_rank")],
        WindowSpec([], [asc(col("return_ratio"))]), ratios)
    w = CpuWindow(
        [Rank().alias("currency_rank")],
        WindowSpec([], [asc(col("currency_ratio"))]), w)
    f2 = CpuFilter((col("return_rank") <= lit(10)) |
                   (col("currency_rank") <= lit(10)), w)
    return CpuProject(
        [lit(chan).alias("channel"), col("item"), col("return_ratio"),
         col("return_rank"), col("currency_rank")], f2)


def q49(t, run):
    """Reference q49: worst return ratios by channel, rank-unioned
    (UNION distinct via grouped dedup)."""
    u = CpuUnion(
        _q49_channel(t, "web_sales", "web_returns",
                     ["ws_order_number", "ws_item_sk"],
                     ["wr_order_number", "wr_item_sk"],
                     "ws_quantity", "wr_return_quantity",
                     "ws_ext_sales_price", "wr_return_amt",
                     "ws_net_paid", "ws_net_profit",
                     "ws_sold_date_sk", "ws_item_sk", "web"),
        _q49_channel(t, "catalog_sales", "catalog_returns",
                     ["cs_order_number", "cs_item_sk"],
                     ["cr_order_number", "cr_item_sk"],
                     "cs_quantity", "cr_return_quantity",
                     "cs_ext_sales_price", "cr_return_amount",
                     "cs_net_paid", "cs_net_profit",
                     "cs_sold_date_sk", "cs_item_sk", "catalog"),
        _q49_channel(t, "store_sales", "store_returns",
                     ["ss_ticket_number", "ss_item_sk"],
                     ["sr_ticket_number", "sr_item_sk"],
                     "ss_quantity", "sr_return_quantity",
                     "ss_ext_sales_price", "sr_return_amt",
                     "ss_net_paid", "ss_net_profit",
                     "ss_sold_date_sk", "ss_item_sk", "store"))
    dedup = CpuProject(
        [col("channel"), col("item"), col("return_ratio"),
         col("return_rank"), col("currency_rank")],
        CpuAggregate(
            [col("channel"), col("item"), col("return_ratio"),
             col("return_rank"), col("currency_rank")],
            [Count(None).alias("_n")], u))
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("return_rank")),
         asc(col("currency_rank"))], dedup))


def q51(t, run):
    """Reference q51: cumulative web vs store sales per item (windowed
    running sums, full outer join, running max comparison)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WinMax, WinSum,
                                              WindowFrame, WindowSpec)
    cume_frame = WindowFrame(is_rows=True, lower=None, upper=0)

    def v1(sales, date_key, item_key, price, tag):
        # reference month_seq window 1200..1211 stands in as 12..23
        dd = CpuFilter(_between(col("d_month_seq"), lit(12), lit(23)),
                       t["date_dim"])
        j = _join(dd, t[sales], ["d_date_sk"], [date_key])
        agg = CpuAggregate(
            [col(item_key), col("d_date")],
            [Sum(col(price)).alias("_s")], j)
        w = CpuWindow(
            [WinSum(col("_s")).alias("cume_sales")],
            WindowSpec([col(item_key)], [asc(col("d_date"))],
                       cume_frame), agg)
        return CpuProject(
            [col(item_key).alias(f"{tag}_item"),
             col("d_date").alias(f"{tag}_date"),
             col("cume_sales").alias(f"{tag}_cume")], w)

    web = v1("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_sales_price", "web")
    store = v1("store_sales", "ss_sold_date_sk", "ss_item_sk",
               "ss_sales_price", "store")
    fo = _join(web, store, ["web_item", "web_date"],
               ["store_item", "store_date"], jt=J.FULL_OUTER)
    x = CpuProject(
        [If(IsNotNull(col("web_item")), col("web_item"),
            col("store_item")).alias("item_sk"),
         If(IsNotNull(col("web_date")), col("web_date"),
            col("store_date")).alias("d_date"),
         col("web_cume").alias("web_sales"),
         col("store_cume").alias("store_sales")], fo)
    y = CpuWindow(
        [WinMax(col("web_sales")).alias("web_cumulative"),
         WinMax(col("store_sales")).alias("store_cumulative")],
        WindowSpec([col("item_sk")], [asc(col("d_date"))], cume_frame),
        x)
    f = CpuFilter(col("web_cumulative") > col("store_cumulative"), y)
    out = CpuProject(
        [col("item_sk"), col("d_date"), col("web_sales"),
         col("store_sales"), col("web_cumulative"),
         col("store_cumulative")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("item_sk")), asc(col("d_date"))], out))


def q67(t, run):
    """Reference q67: top items by sales over an 8-level ROLLUP, ranked
    within category."""
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    dd = CpuFilter(_between(col("d_month_seq"), lit(12), lit(23)),
                   t["date_dim"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"]),
        t["item"], ["ss_item_sk"], ["i_item_sk"])
    pre = CpuProject(
        [col("i_category"), col("i_class"), col("i_brand"),
         col("i_product_name"), col("d_year"), col("d_qoy"),
         col("d_moy"), col("s_store_id"),
         Coalesce((col("ss_sales_price") * col("ss_quantity"),
                   lit(0.0))).alias("_amt")], j)
    keys = ["i_category", "i_class", "i_brand", "i_product_name",
            "d_year", "d_qoy", "d_moy", "s_store_id"]
    ex = _rollup_expand(pre, keys, ["_amt"])
    agg = CpuAggregate(
        [col(k) for k in keys] + [col("gid")],
        [Sum(col("_amt")).alias("sumsales")], ex)
    w = CpuWindow(
        [Rank().alias("rk")],
        WindowSpec([col("i_category")], [desc(col("sumsales"))]), agg)
    f = CpuFilter(col("rk") <= lit(100), w)
    out = CpuProject([col(k) for k in keys] +
                     [col("sumsales"), col("rk")], f)
    return CpuLimit(100, CpuSort(
        [asc(col(k)) for k in keys] +
        [asc(col("sumsales")), asc(col("rk"))], out))


def q70(t, run):
    """Reference q70: profit over ROLLUP(s_state, s_county) limited to
    top-ranked states, with hierarchy ranks."""
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    dd = CpuFilter(_between(col("d_month_seq"), lit(12), lit(23)),
                   t["date_dim"])
    base = _join(_join(dd, t["store_sales"],
                       ["d_date_sk"], ["ss_sold_date_sk"]),
                 t["store"], ["ss_store_sk"], ["s_store_sk"])
    by_state = CpuAggregate(
        [col("s_state")], [Sum(col("ss_net_profit")).alias("_p")],
        base)
    ranked = CpuWindow(
        [Rank().alias("ranking")],
        WindowSpec([col("s_state")], [desc(col("_p"))]), by_state)
    top_states = CpuProject(
        [col("s_state").alias("sel_state")],
        CpuFilter(col("ranking") <= lit(5), ranked))
    j = _join(base, top_states, ["s_state"], ["sel_state"],
              jt=J.LEFT_SEMI)
    pre = CpuProject(
        [col("s_state"), col("s_county"), col("ss_net_profit")], j)
    ex = _rollup_expand(pre, ["s_state", "s_county"],
                        ["ss_net_profit"])
    agg = CpuAggregate(
        [col("s_state"), col("s_county"), col("gid")],
        [Sum(col("ss_net_profit")).alias("total_sum")], ex)
    pre_w = CpuProject(
        [col("total_sum"), col("s_state"), col("s_county"),
         If(col("gid") == lit(3), lit(2),
            If(col("gid") == lit(1), lit(1), lit(0))
            ).alias("lochierarchy"),
         If(col("gid") == lit(0), col("s_state"),
            _Lit(None, _T.STRING)).alias("_parent_state")], agg)
    w = CpuWindow(
        [Rank().alias("rank_within_parent")],
        WindowSpec([col("lochierarchy"), col("_parent_state")],
                   [desc(col("total_sum"))]), pre_w)
    keyed = CpuProject(
        [col("total_sum"), col("s_state"), col("s_county"),
         col("lochierarchy"), col("rank_within_parent"),
         If(col("lochierarchy") == lit(0), col("s_state"),
            _Lit(None, _T.STRING)).alias("_ostate")], w)
    out = CpuLimit(100, CpuSort(
        [desc(col("lochierarchy")), asc(col("_ostate")),
         asc(col("rank_within_parent"))], keyed))
    return CpuProject(
        [col("total_sum"), col("s_state"), col("s_county"),
         col("lochierarchy"), col("rank_within_parent")], out)


QUERIES.update({"q49": q49, "q51": q51, "q67": q67, "q70": q70})


def _active_customers(t, year):
    """q10/q35 EXISTS machinery: distinct active-customer key sets per
    channel; exists A and (exists B or exists C) = semi join on A, then
    semi join on (B union C)."""
    def chan(sales, date_key, cust_key, extra):
        dd = CpuFilter((col("d_year") == lit(year)) & extra,
                       t["date_dim"])
        j = _join(dd, sales, ["d_date_sk"], [date_key])
        return CpuProject(
            [col("_k")],
            CpuAggregate([col(cust_key).alias("_k")],
                         [Count(None).alias("_n")],
                         CpuProject([col(cust_key)], j)))
    return chan


def _q10_35(t, year, extra, group_cols, aggs, order_cols):
    chan = _active_customers(t, year)
    ss = chan(t["store_sales"], "ss_sold_date_sk", "ss_customer_sk",
              extra)
    ws = chan(t["web_sales"], "ws_sold_date_sk",
              "ws_bill_customer_sk", extra)
    cs = chan(t["catalog_sales"], "cs_sold_date_sk",
              "cs_ship_customer_sk", extra)
    wc = CpuProject(
        [col("_k")],
        CpuAggregate([col("_k")], [Count(None).alias("_n")],
                     CpuUnion(ws, cs)))
    j = _join(t["customer"], t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    j = _join(j, t["customer_demographics"],
              ["c_current_cdemo_sk"], ["cd_demo_sk"])
    j = _join(j, ss, ["c_customer_sk"], ["_k"], jt=J.LEFT_SEMI)
    j = _join(j, wc, ["c_customer_sk"], ["_k"], jt=J.LEFT_SEMI)
    return j


def q10(t, run):
    """Reference q10: demographic profile of county customers active
    in store AND (web OR catalog) channels in one quarter."""
    from spark_rapids_tpu.exec.joins import JoinType as _JT
    extra = _between(col("d_moy"), lit(1), lit(4))
    j = _q10_35(t, 2002, extra, None, None, None)
    # reference county list stands in as the generator county domain
    j = CpuFilter(InSet(col("ca_county"),
                        ("Williamson County", "Ziebach County",
                         "Walker County")), j)
    groups = ["cd_gender", "cd_marital_status", "cd_education_status",
              "cd_purchase_estimate", "cd_credit_rating",
              "cd_dep_count", "cd_dep_employed_count",
              "cd_dep_college_count"]
    agg = CpuAggregate([col(g) for g in groups],
                       [Count(None).alias("cnt")], j)
    out = CpuProject(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cnt").alias("cnt1"),
         col("cd_purchase_estimate"), col("cnt").alias("cnt2"),
         col("cd_credit_rating"), col("cnt").alias("cnt3"),
         col("cd_dep_count"), col("cnt").alias("cnt4"),
         col("cd_dep_employed_count"), col("cnt").alias("cnt5"),
         col("cd_dep_college_count"), col("cnt").alias("cnt6")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col(g)) for g in ("cd_gender", "cd_marital_status",
                               "cd_education_status",
                               "cd_purchase_estimate",
                               "cd_credit_rating", "cd_dep_count",
                               "cd_dep_employed_count",
                               "cd_dep_college_count")], out))


def q35(t, run):
    """Reference q35: per-state dependent-count stats for multi-channel
    active customers."""
    from spark_rapids_tpu.exprs.aggregates import Max, Min
    extra = col("d_qoy") < lit(4)
    j = _q10_35(t, 2002, extra, None, None, None)
    groups = ["ca_state", "cd_gender", "cd_marital_status",
              "cd_dep_count", "cd_dep_employed_count",
              "cd_dep_college_count"]
    agg = CpuAggregate(
        [col(g) for g in groups],
        [Count(None).alias("cnt1"),
         Min(col("cd_dep_count")).alias("min_dep"),
         Max(col("cd_dep_count")).alias("max_dep"),
         Average(col("cd_dep_count")).alias("avg_dep"),
         Min(col("cd_dep_employed_count")).alias("min_emp"),
         Max(col("cd_dep_employed_count")).alias("max_emp"),
         Average(col("cd_dep_employed_count")).alias("avg_emp"),
         Min(col("cd_dep_college_count")).alias("min_col"),
         Max(col("cd_dep_college_count")).alias("max_col"),
         Average(col("cd_dep_college_count")).alias("avg_col")], j)
    return CpuLimit(100, CpuSort(
        [asc(col(g)) for g in groups], agg))


def q85(t, run):
    """Reference q85: web-return reasons under matched refunding/
    returning demographics and address/profit bands."""
    j = _join(t["web_sales"], t["web_returns"],
              ["ws_item_sk", "ws_order_number"],
              ["wr_item_sk", "wr_order_number"])
    j = _join(j, t["web_page"], ["ws_web_page_sk"],
              ["wp_web_page_sk"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(j, dd, ["ws_sold_date_sk"], ["d_date_sk"])
    cd1 = CpuProject(
        [col("cd_demo_sk").alias("cd1_sk"),
         col("cd_marital_status").alias("cd1_ms"),
         col("cd_education_status").alias("cd1_es")],
        t["customer_demographics"])
    cd2 = CpuProject(
        [col("cd_demo_sk").alias("cd2_sk"),
         col("cd_marital_status").alias("cd2_ms"),
         col("cd_education_status").alias("cd2_es")],
        t["customer_demographics"])
    j = _join(j, cd1, ["wr_refunded_cdemo_sk"], ["cd1_sk"])
    j = _join(j, cd2, ["wr_returning_cdemo_sk"], ["cd2_sk"])
    j = _join(j, t["customer_address"], ["wr_refunded_addr_sk"],
              ["ca_address_sk"])
    j = _join(j, t["reason"], ["wr_reason_sk"], ["r_reason_sk"])

    def band(ms, es, lo, hi):
        return ((col("cd1_ms") == lit(ms)) &
                (col("cd1_ms") == col("cd2_ms")) &
                (col("cd1_es") == lit(es)) &
                (col("cd1_es") == col("cd2_es")) &
                _between(col("ws_sales_price"), lit(lo), lit(hi)))
    # reference price bands (100-150 etc.) stand in as wider bands
    # the synthetic price range populates
    demo = (band("M", "Advanced Degree", 50.0, 250.0) |
            band("S", "College", 25.0, 250.0) |
            band("W", "2 yr Degree", 50.0, 250.0))
    # reference profit bands (100..200 etc.) stand in as the wider
    # q48-style bands the synthetic profit range supports
    addr = (
        (col("ca_country") == lit("United States")) &
        (InSet(col("ca_state"), ("TX", "NY")) &
         _between(col("ws_net_profit"), lit(-500), lit(2000)) |
         InSet(col("ca_state"), ("CA", "IL")) &
         _between(col("ws_net_profit"), lit(-250), lit(3000)) |
         InSet(col("ca_state"), ("WA", "GA")) &
         _between(col("ws_net_profit"), lit(0), lit(25000))))
    f = CpuFilter(demo & addr, j)
    agg = CpuAggregate(
        [col("r_reason_desc")],
        [Average(col("ws_quantity")).alias("avg_qty"),
         Average(col("wr_refunded_cash")).alias("avg_cash"),
         Average(col("wr_fee")).alias("avg_fee")], f)
    out = CpuProject(
        [_Substring(col("r_reason_desc"), lit(1),
                    lit(20)).alias("reason"),
         col("avg_qty"), col("avg_cash"), col("avg_fee")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("reason")), asc(col("avg_qty")), asc(col("avg_cash")),
         asc(col("avg_fee"))], out))


def q18(t, run):
    """Reference q18: catalog averages over ROLLUP(i_item_id,
    ca_country, ca_state, ca_county) for one demographic slice."""
    cd1 = CpuProject(
        [col("cd_demo_sk").alias("cd1_sk"),
         col("cd_dep_count").alias("cd1_dep")],
        CpuFilter((col("cd_gender") == lit("F")) &
                  (col("cd_education_status") == lit("Unknown")),
                  t["customer_demographics"]))
    cd2 = CpuProject([col("cd_demo_sk").alias("cd2_sk")],
                     t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(1998), t["date_dim"])
    cust = CpuFilter(InSet(col("c_birth_month"), (1, 6, 8, 9, 12, 2)),
                     t["customer"])
    j = _join(dd, t["catalog_sales"], ["d_date_sk"],
              ["cs_sold_date_sk"])
    j = _join(j, cd1, ["cs_bill_cdemo_sk"], ["cd1_sk"])
    j = _join(j, cust, ["cs_bill_customer_sk"], ["c_customer_sk"])
    j = _join(j, cd2, ["c_current_cdemo_sk"], ["cd2_sk"])
    # reference state list stands in as the generator state domain
    ca = CpuFilter(InSet(col("ca_state"), ("TX", "NY", "CA")),
                   t["customer_address"])
    j = _join(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    j = _join(j, t["item"], ["cs_item_sk"], ["i_item_sk"])
    pre = CpuProject(
        [col("i_item_id"), col("ca_country"), col("ca_state"),
         col("ca_county"), col("cs_quantity"), col("cs_list_price"),
         col("cs_coupon_amt"), col("cs_sales_price"),
         col("cs_net_profit"), col("c_birth_year"), col("cd1_dep")], j)
    keys = ["i_item_id", "ca_country", "ca_state", "ca_county"]
    ex = _rollup_expand(pre, keys,
                        ["cs_quantity", "cs_list_price",
                         "cs_coupon_amt", "cs_sales_price",
                         "cs_net_profit", "c_birth_year", "cd1_dep"])
    agg = CpuAggregate(
        [col(k) for k in keys] + [col("gid")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_coupon_amt")).alias("agg3"),
         Average(col("cs_sales_price")).alias("agg4"),
         Average(col("cs_net_profit")).alias("agg5"),
         Average(col("c_birth_year")).alias("agg6"),
         Average(col("cd1_dep")).alias("agg7")], ex)
    out = CpuProject([col(k) for k in keys] +
                     [col(f"agg{i}") for i in range(1, 8)], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_country")), asc(col("ca_state")),
         asc(col("ca_county")), asc(col("i_item_id"))], out))


QUERIES.update({"q10": q10, "q35": q35, "q85": q85, "q18": q18})


def _rollup_channel_tail(u):
    """q5/q77/q80 shared tail: re-aggregate the unioned channel rows
    over ROLLUP(channel, id)."""
    ex = _rollup_expand(u, ["channel", "id"],
                        ["sales", "returns", "profit"])
    agg = CpuAggregate(
        [col("channel"), col("id"), col("gid")],
        [Sum(col("sales")).alias("sales"),
         Sum(col("returns")).alias("returns"),
         Sum(col("profit")).alias("profit")], ex)
    out = CpuProject(
        [col("channel"), col("id"), col("sales"), col("returns"),
         col("profit")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("id"))], out))


def q5(t, run):
    """Reference q5: 14-day sales/returns/profit per channel entity
    over ROLLUP(channel, id)."""
    d_lo, d_hi = _date(2000, 8, 23), _date(2000, 9, 6)
    dd = CpuFilter(_between(col("d_date"), d_lo, d_hi), t["date_dim"])

    def union_sr(sales_proj, returns_proj):
        return CpuUnion(sales_proj, returns_proj)

    # store channel
    ss = CpuProject(
        [col("ss_store_sk").alias("entity_sk"),
         col("ss_sold_date_sk").alias("date_sk"),
         col("ss_ext_sales_price").alias("sales_price"),
         col("ss_net_profit").alias("profit"),
         lit(0.0).alias("return_amt"), lit(0.0).alias("net_loss")],
        t["store_sales"])
    sr = CpuProject(
        [col("sr_store_sk").alias("entity_sk"),
         col("sr_returned_date_sk").alias("date_sk"),
         lit(0.0).alias("sales_price"), lit(0.0).alias("profit"),
         col("sr_return_amt").alias("return_amt"),
         col("sr_net_loss").alias("net_loss")], t["store_returns"])
    ssr = _join(_join(union_sr(ss, sr), dd, ["date_sk"],
                      ["d_date_sk"]),
                t["store"], ["entity_sk"], ["s_store_sk"])
    ssr = CpuAggregate(
        [col("s_store_id")],
        [Sum(col("sales_price")).alias("sales"),
         Sum(col("profit")).alias("profit"),
         Sum(col("return_amt")).alias("returns"),
         Sum(col("net_loss")).alias("profit_loss")], ssr)
    store_rows = CpuProject(
        [lit("store channel").alias("channel"),
         ConcatStrings((lit("store"), col("s_store_id"))).alias("id"),
         col("sales"), col("returns"),
         (col("profit") - col("profit_loss")).alias("profit")], ssr)

    # catalog channel
    cs = CpuProject(
        [col("cs_catalog_page_sk").alias("page_sk"),
         col("cs_sold_date_sk").alias("date_sk"),
         col("cs_ext_sales_price").alias("sales_price"),
         col("cs_net_profit").alias("profit"),
         lit(0.0).alias("return_amt"), lit(0.0).alias("net_loss")],
        t["catalog_sales"])
    cr = CpuProject(
        [col("cr_catalog_page_sk").alias("page_sk"),
         col("cr_returned_date_sk").alias("date_sk"),
         lit(0.0).alias("sales_price"), lit(0.0).alias("profit"),
         col("cr_return_amount").alias("return_amt"),
         col("cr_net_loss").alias("net_loss")], t["catalog_returns"])
    csr = _join(_join(union_sr(cs, cr), dd, ["date_sk"],
                      ["d_date_sk"]),
                t["catalog_page"], ["page_sk"],
                ["cp_catalog_page_sk"])
    csr = CpuAggregate(
        [col("cp_catalog_page_id")],
        [Sum(col("sales_price")).alias("sales"),
         Sum(col("profit")).alias("profit"),
         Sum(col("return_amt")).alias("returns"),
         Sum(col("net_loss")).alias("profit_loss")], csr)
    catalog_rows = CpuProject(
        [lit("catalog channel").alias("channel"),
         ConcatStrings((lit("catalog_page"),
                        col("cp_catalog_page_id"))).alias("id"),
         col("sales"), col("returns"),
         (col("profit") - col("profit_loss")).alias("profit")], csr)

    # web channel (returns joined back to sales for the site key)
    ws = CpuProject(
        [col("ws_web_site_sk").alias("site_sk"),
         col("ws_sold_date_sk").alias("date_sk"),
         col("ws_ext_sales_price").alias("sales_price"),
         col("ws_net_profit").alias("profit"),
         lit(0.0).alias("return_amt"), lit(0.0).alias("net_loss")],
        t["web_sales"])
    wr_join = _join(t["web_returns"],
                    CpuProject([col("ws_item_sk").alias("wi"),
                                col("ws_order_number").alias("wo"),
                                col("ws_web_site_sk")],
                               t["web_sales"]),
                    ["wr_item_sk", "wr_order_number"], ["wi", "wo"],
                    jt=J.LEFT_OUTER)
    wr = CpuProject(
        [col("ws_web_site_sk").alias("site_sk"),
         col("wr_returned_date_sk").alias("date_sk"),
         lit(0.0).alias("sales_price"), lit(0.0).alias("profit"),
         col("wr_return_amt").alias("return_amt"),
         col("wr_net_loss").alias("net_loss")], wr_join)
    wsr = _join(_join(union_sr(ws, wr), dd, ["date_sk"],
                      ["d_date_sk"]),
                t["web_site"], ["site_sk"], ["web_site_sk"])
    wsr = CpuAggregate(
        [col("web_site_id")],
        [Sum(col("sales_price")).alias("sales"),
         Sum(col("profit")).alias("profit"),
         Sum(col("return_amt")).alias("returns"),
         Sum(col("net_loss")).alias("profit_loss")], wsr)
    web_rows = CpuProject(
        [lit("web channel").alias("channel"),
         ConcatStrings((lit("web_site"),
                        col("web_site_id"))).alias("id"),
         col("sales"), col("returns"),
         (col("profit") - col("profit_loss")).alias("profit")], wsr)

    return _rollup_channel_tail(
        CpuUnion(store_rows, catalog_rows, web_rows))


def q77(t, run):
    """Reference q77: 30-day per-entity sales vs returns per channel,
    rollup-totaled (catalog side cross-joined)."""
    d_lo, d_hi = _date(2000, 8, 23), _date(2000, 9, 22)
    dd = CpuFilter(_between(col("d_date"), d_lo, d_hi), t["date_dim"])

    def agg_side(child, date_key, ent, sums):
        j = _join(dd, child, ["d_date_sk"], [date_key])
        return CpuAggregate(
            [col(ent)],
            [Sum(col(c)).alias(a) for c, a in sums], j)

    ss = agg_side(t["store_sales"], "ss_sold_date_sk", "ss_store_sk",
                  [("ss_ext_sales_price", "sales"),
                   ("ss_net_profit", "profit")])
    sr = CpuProject(
        [col("sr_store_sk").alias("r_sk"), col("returns"),
         col("profit_loss")],
        agg_side(t["store_returns"], "sr_returned_date_sk",
                 "sr_store_sk",
                 [("sr_return_amt", "returns"),
                  ("sr_net_loss", "profit_loss")]))
    store_rows = CpuProject(
        [lit("store channel").alias("channel"),
         col("ss_store_sk").alias("id"), col("sales"),
         Coalesce((col("returns"), lit(0.0))).alias("returns"),
         (col("profit") - Coalesce((col("profit_loss"), lit(0.0)))
          ).alias("profit")],
        _join(ss, sr, ["ss_store_sk"], ["r_sk"], jt=J.LEFT_OUTER))

    cs = CpuProject(
        [col("cs_call_center_sk"), col("sales"), col("profit"),
         lit(1).alias("_ck")],
        agg_side(t["catalog_sales"], "cs_sold_date_sk",
                 "cs_call_center_sk",
                 [("cs_ext_sales_price", "sales"),
                  ("cs_net_profit", "profit")]))
    cr = CpuProject(
        [col("returns"), col("profit_loss"), lit(1).alias("_rk")],
        CpuAggregate(
            [], [Sum(col("cr_return_amount")).alias("returns"),
                 Sum(col("cr_net_loss")).alias("profit_loss")],
            _join(dd, t["catalog_returns"], ["d_date_sk"],
                  ["cr_returned_date_sk"])))
    catalog_rows = CpuProject(
        [lit("catalog channel").alias("channel"),
         col("cs_call_center_sk").alias("id"), col("sales"),
         col("returns"),
         (col("profit") - col("profit_loss")).alias("profit")],
        _join(cs, cr, ["_ck"], ["_rk"]))

    ws = agg_side(
        _join(t["web_sales"], t["web_page"], ["ws_web_page_sk"],
              ["wp_web_page_sk"]),
        "ws_sold_date_sk", "wp_web_page_sk",
        [("ws_ext_sales_price", "sales"), ("ws_net_profit", "profit")])
    wr = CpuProject(
        [col("wp_web_page_sk").alias("r_sk"), col("returns"),
         col("profit_loss")],
        agg_side(
            _join(t["web_returns"], t["web_page"], ["wr_web_page_sk"],
                  ["wp_web_page_sk"]),
            "wr_returned_date_sk", "wp_web_page_sk",
            [("wr_return_amt", "returns"),
             ("wr_net_loss", "profit_loss")]))
    web_rows = CpuProject(
        [lit("web channel").alias("channel"),
         col("wp_web_page_sk").alias("id"), col("sales"),
         Coalesce((col("returns"), lit(0.0))).alias("returns"),
         (col("profit") - Coalesce((col("profit_loss"), lit(0.0)))
          ).alias("profit")],
        _join(ws, wr, ["wp_web_page_sk"], ["r_sk"], jt=J.LEFT_OUTER))

    # ids are entity sks (ints): normalize to strings for the shared
    # rollup tail like the reference's concat'd ids
    def str_id(rows):
        from spark_rapids_tpu.exprs.cast import Cast
        return CpuProject(
            [col("channel"),
             Cast(col("id"), _T.INT64).alias("id_int"), col("sales"),
             col("returns"), col("profit")], rows)
    u = CpuUnion(str_id(store_rows), str_id(catalog_rows),
                 str_id(web_rows))
    ex = _rollup_expand(u, ["channel", "id_int"],
                        ["sales", "returns", "profit"])
    agg = CpuAggregate(
        [col("channel"), col("id_int"), col("gid")],
        [Sum(col("sales")).alias("sales"),
         Sum(col("returns")).alias("returns"),
         Sum(col("profit")).alias("profit")], ex)
    out = CpuProject(
        [col("channel"), col("id_int").alias("id"), col("sales"),
         col("returns"), col("profit")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("id"))], out))


def q80(t, run):
    """Reference q80: 30-day promo'd high-price item sales net of
    returns per channel entity, rollup-totaled."""
    d_lo, d_hi = _date(2000, 8, 23), _date(2000, 9, 22)
    dd = CpuFilter(_between(col("d_date"), d_lo, d_hi), t["date_dim"])
    it = CpuFilter(col("i_current_price") > lit(50.0), t["item"])
    pr = CpuFilter(col("p_channel_tv") == lit("N"), t["promotion"])

    def channel(sales, returns, skeys, rkeys, date_key, item_key,
                promo_key, ent_join, ent_id, price, profit, ramt,
                rloss, chan, prefix):
        j = _join(t[sales], t[returns], skeys, rkeys, jt=J.LEFT_OUTER)
        j = _join(j, dd, [date_key], ["d_date_sk"])
        j = _join(j, it, [item_key], ["i_item_sk"])
        j = _join(j, pr, [promo_key], ["p_promo_sk"])
        right, lk, rk = ent_join
        j = _join(j, right, lk, rk)
        agg = CpuAggregate(
            [col(ent_id)],
            [Sum(col(price)).alias("sales"),
             Sum(Coalesce((col(ramt), lit(0.0)))).alias("returns"),
             Sum(col(profit) - Coalesce((col(rloss), lit(0.0)))
                 ).alias("profit")], j)
        return CpuProject(
            [lit(chan).alias("channel"),
             ConcatStrings((lit(prefix), col(ent_id))).alias("id"),
             col("sales"), col("returns"), col("profit")], agg)

    store_rows = channel(
        "store_sales", "store_returns",
        ["ss_item_sk", "ss_ticket_number"],
        ["sr_item_sk", "sr_ticket_number"],
        "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
        (t["store"], ["ss_store_sk"], ["s_store_sk"]), "s_store_id",
        "ss_ext_sales_price", "ss_net_profit", "sr_return_amt",
        "sr_net_loss", "store channel", "store")
    catalog_rows = channel(
        "catalog_sales", "catalog_returns",
        ["cs_item_sk", "cs_order_number"],
        ["cr_item_sk", "cr_order_number"],
        "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
        (t["catalog_page"], ["cs_catalog_page_sk"],
         ["cp_catalog_page_sk"]), "cp_catalog_page_id",
        "cs_ext_sales_price", "cs_net_profit", "cr_return_amount",
        "cr_net_loss", "catalog channel", "catalog_page")
    web_rows = channel(
        "web_sales", "web_returns",
        ["ws_item_sk", "ws_order_number"],
        ["wr_item_sk", "wr_order_number"],
        "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
        (t["web_site"], ["ws_web_site_sk"], ["web_site_sk"]),
        "web_site_id",
        "ws_ext_sales_price", "ws_net_profit", "wr_return_amt",
        "wr_net_loss", "web channel", "web_site")
    return _rollup_channel_tail(
        CpuUnion(store_rows, catalog_rows, web_rows))


QUERIES.update({"q5": q5, "q77": q77, "q80": q80})


def _q56_60_channel(t, sales, date_key, addr_key, item_key, val,
                    item_pred, year, moy):
    """q56/q60 channel CTE: per-item-id revenue for one month/offset,
    item-id semi-joined against a predicate item set."""
    ids = CpuProject(
        [col("sel_id")],
        CpuAggregate([col("i_item_id").alias("sel_id")],
                     [Count(None).alias("_c")],
                     CpuFilter(item_pred, t["item"])))
    it = _join(t["item"], ids, ["i_item_id"], ["sel_id"],
               jt=J.LEFT_SEMI)
    dd = CpuFilter((col("d_year") == lit(year)) &
                   (col("d_moy") == lit(moy)), t["date_dim"])
    ca = CpuFilter(col("ca_gmt_offset") == lit(-5.0),
                   t["customer_address"])
    j = _join(_join(_join(dd, sales, ["d_date_sk"], [date_key]),
                    ca, [addr_key], ["ca_address_sk"]),
              it, [item_key], ["i_item_sk"])
    return CpuAggregate([col("i_item_id")],
                        [Sum(col(val)).alias("total_sales")], j)


def q56(t, run):
    """Reference q56: revenue of chosen-color items by channel for one
    month, union re-aggregated."""
    pred = InSet(col("i_color"), ("slate", "powder", "khaki"))
    ss = _q56_60_channel(t, t["store_sales"], "ss_sold_date_sk",
                         "ss_addr_sk", "ss_item_sk",
                         "ss_ext_sales_price", pred, 2001, 2)
    cs = _q56_60_channel(t, t["catalog_sales"], "cs_sold_date_sk",
                         "cs_bill_addr_sk", "cs_item_sk",
                         "cs_ext_sales_price", pred, 2001, 2)
    ws = _q56_60_channel(t, t["web_sales"], "ws_sold_date_sk",
                         "ws_bill_addr_sk", "ws_item_sk",
                         "ws_ext_sales_price", pred, 2001, 2)
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")],
                       CpuUnion(ss, cs, ws))
    return CpuLimit(100, CpuSort([asc(col("total_sales"))], agg))


def q60(t, run):
    """Reference q60: q56's shape for one category in 1998-09."""
    pred = InSet(col("i_category"), ("Music",))
    ss = _q56_60_channel(t, t["store_sales"], "ss_sold_date_sk",
                         "ss_addr_sk", "ss_item_sk",
                         "ss_ext_sales_price", pred, 1998, 9)
    cs = _q56_60_channel(t, t["catalog_sales"], "cs_sold_date_sk",
                         "cs_bill_addr_sk", "cs_item_sk",
                         "cs_ext_sales_price", pred, 1998, 9)
    ws = _q56_60_channel(t, t["web_sales"], "ws_sold_date_sk",
                         "ws_bill_addr_sk", "ws_item_sk",
                         "ws_ext_sales_price", pred, 1998, 9)
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")],
                       CpuUnion(ss, cs, ws))
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("total_sales"))], agg))


def q58(t, run):
    """Reference q58: items with near-equal revenue in all three
    channels for one chosen week."""
    wk = CpuProject(
        [col("d_week_seq").alias("sel_wk")],
        CpuAggregate(
            [col("d_week_seq")], [Count(None).alias("_c")],
            CpuFilter(col("d_date") == _date(2000, 6, 30),
                      t["date_dim"])))
    dates = CpuProject(
        [col("d_date_sk")],
        _join(t["date_dim"], wk, ["d_week_seq"], ["sel_wk"],
              jt=J.LEFT_SEMI))

    def rev(sales, date_key, item_key, val, alias):
        j = _join(_join(dates, sales, ["d_date_sk"], [date_key]),
                  t["item"], [item_key], ["i_item_sk"])
        return CpuAggregate([col("i_item_id")],
                            [Sum(col(val)).alias(alias)], j)

    ss = rev(t["store_sales"], "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_item_rev")
    cs = CpuProject(
        [col("i_item_id").alias("cs_id"), col("cs_item_rev")],
        rev(t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk",
            "cs_ext_sales_price", "cs_item_rev"))
    ws = CpuProject(
        [col("i_item_id").alias("ws_id"), col("ws_item_rev")],
        rev(t["web_sales"], "ws_sold_date_sk", "ws_item_sk",
            "ws_ext_sales_price", "ws_item_rev"))
    j = _join(_join(ss, cs, ["i_item_id"], ["cs_id"]),
              ws, ["i_item_id"], ["ws_id"])
    # reference band is 0.9..1.1; the synthetic weekly sums (and the
    # 2:1:0.5 channel volume ratio) need a wider stand-in band
    lo, hi = lit(0.02), lit(50.0)

    def near(a, b):
        return _between(col(a), lo * col(b), hi * col(b))
    f = CpuFilter(
        near("ss_item_rev", "cs_item_rev") &
        near("ss_item_rev", "ws_item_rev") &
        near("cs_item_rev", "ss_item_rev") &
        near("cs_item_rev", "ws_item_rev") &
        near("ws_item_rev", "ss_item_rev") &
        near("ws_item_rev", "cs_item_rev"), j)
    total = (col("ss_item_rev") + col("cs_item_rev") +
             col("ws_item_rev"))
    out = CpuProject(
        [col("i_item_id").alias("item_id"), col("ss_item_rev"),
         (col("ss_item_rev") / total / lit(3.0) *
          lit(100.0)).alias("ss_dev"),
         col("cs_item_rev"),
         (col("cs_item_rev") / total / lit(3.0) *
          lit(100.0)).alias("cs_dev"),
         col("ws_item_rev"),
         (col("ws_item_rev") / total / lit(3.0) *
          lit(100.0)).alias("ws_dev"),
         (total / lit(3.0)).alias("average")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("item_id")), asc(col("ss_item_rev"))], out))


def q54(t, run):
    """Reference q54: revenue segments of customers who bought the
    target class cross-channel, then shopped locally next quarter."""
    from spark_rapids_tpu.exprs.cast import Cast
    u = CpuUnion(
        CpuProject([col("cs_sold_date_sk").alias("sold_date_sk"),
                    col("cs_bill_customer_sk").alias("customer_sk"),
                    col("cs_item_sk").alias("item_sk")],
                   t["catalog_sales"]),
        CpuProject([col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_bill_customer_sk").alias("customer_sk"),
                    col("ws_item_sk").alias("item_sk")],
                   t["web_sales"]))
    # reference Women/maternity stands in as the generator class pool
    it = CpuFilter((col("i_category") == lit("Women")) &
                   InSet(col("i_class"), ("class00", "class01",
                                          "class02", "class03")),
                   t["item"])
    dd = CpuFilter((col("d_moy") == lit(12)) &
                   (col("d_year") == lit(1998)), t["date_dim"])
    j = _join(_join(_join(u, dd, ["sold_date_sk"], ["d_date_sk"]),
                    it, ["item_sk"], ["i_item_sk"]),
              t["customer"], ["customer_sk"], ["c_customer_sk"])
    my_customers = CpuProject(
        [col("c_customer_sk"), col("c_current_addr_sk")],
        CpuAggregate([col("c_customer_sk"),
                      col("c_current_addr_sk")],
                     [Count(None).alias("_n")], j))
    # month-seq window (1998-12 month_seq = 11): +1 .. +3
    seq = CpuProject(
        [(col("d_month_seq") + lit(1)).alias("lo_seq"),
         (col("d_month_seq") + lit(3)).alias("hi_seq"),
         lit(1).alias("_sk")],
        CpuAggregate(
            [col("d_month_seq")], [Count(None).alias("_c")],
            CpuFilter((col("d_year") == lit(1998)) &
                      (col("d_moy") == lit(12)), t["date_dim"])))
    dd2 = CpuFilter(
        _between(col("d_month_seq"), col("lo_seq"), col("hi_seq")),
        _join(CpuProject([col("d_date_sk"), col("d_month_seq"),
                          lit(1).alias("_dk")], t["date_dim"]),
              seq, ["_dk"], ["_sk"]))
    dd2 = CpuProject([col("d_date_sk")], dd2)
    j2 = _join(my_customers, t["store_sales"],
               ["c_customer_sk"], ["ss_customer_sk"])
    j2 = _join(j2, t["customer_address"], ["c_current_addr_sk"],
               ["ca_address_sk"])
    j2 = _join(j2, t["store"], ["ca_county", "ca_state"],
               ["s_county", "s_state"])
    j2 = _join(j2, dd2, ["ss_sold_date_sk"], ["d_date_sk"],
               jt=J.LEFT_SEMI)
    my_revenue = CpuAggregate(
        [col("c_customer_sk")],
        [Sum(col("ss_ext_sales_price")).alias("revenue")], j2)
    segments = CpuProject(
        [Cast(col("revenue") / lit(50.0), _T.INT64).alias("segment")],
        my_revenue)
    agg = CpuAggregate([col("segment")],
                       [Count(None).alias("num_customers")], segments)
    out = CpuProject(
        [col("segment"), col("num_customers"),
         (col("segment") * lit(50)).alias("segment_base")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("segment")), asc(col("num_customers"))], out))


QUERIES.update({"q56": q56, "q60": q60, "q58": q58, "q54": q54})


from spark_rapids_tpu.exprs.aggregates import StddevSamp as _Stddev


def _q17_29_chain(t, d1_pred, d2_pred, d3_pred):
    """q17/q29 shared join chain: store sale -> its return -> the same
    customer's catalog purchase, each against its own date window."""
    d1 = CpuFilter(d1_pred, t["date_dim"])
    d2 = CpuProject([col("d_date_sk").alias("d2_sk")],
                    CpuFilter(d2_pred, t["date_dim"]))
    d3 = CpuProject([col("d_date_sk").alias("d3_sk")],
                    CpuFilter(d3_pred, t["date_dim"]))
    ss = _join(d1, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
    sr = _join(d2, t["store_returns"], ["d2_sk"],
               ["sr_returned_date_sk"])
    cs = _join(d3, t["catalog_sales"], ["d3_sk"], ["cs_sold_date_sk"])
    j = _join(ss, sr,
              ["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
              ["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = _join(j, cs, ["sr_customer_sk", "sr_item_sk"],
              ["cs_bill_customer_sk", "cs_item_sk"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    return _join(j, t["item"], ["ss_item_sk"], ["i_item_sk"])


def q17(t, run):
    """Reference q17: quantity count/mean/stddev/cov per item+state for
    sale -> return -> repurchase chains inside one quarter window."""
    # reference sale window is 2001Q1; the full year stands in for
    # the sparse synthetic sale->return->repurchase chains
    j = _q17_29_chain(
        t,
        col("d_year") == lit(2001),
        col("d_year") == lit(2001),
        col("d_year") == lit(2001))
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("s_state")],
        [Count(col("ss_quantity")).alias("store_sales_quantitycount"),
         Average(col("ss_quantity")).alias("store_sales_quantityave"),
         _Stddev(col("ss_quantity")).alias("store_sales_quantitystdev"),
         Count(col("sr_return_quantity")
               ).alias("store_returns_quantitycount"),
         Average(col("sr_return_quantity")
                 ).alias("store_returns_quantityave"),
         _Stddev(col("sr_return_quantity")
                 ).alias("store_returns_quantitystdev"),
         Count(col("cs_quantity")).alias("catalog_sales_quantitycount"),
         Average(col("cs_quantity")).alias("catalog_sales_quantityave"),
         _Stddev(col("cs_quantity")
                 ).alias("catalog_sales_quantitystdev")], j)
    out = CpuProject(
        [col("i_item_id"), col("i_item_desc"), col("s_state"),
         col("store_sales_quantitycount"),
         col("store_sales_quantityave"),
         col("store_sales_quantitystdev"),
         (col("store_sales_quantitystdev") /
          col("store_sales_quantityave")).alias("store_sales_cov"),
         col("store_returns_quantitycount"),
         col("store_returns_quantityave"),
         col("store_returns_quantitystdev"),
         (col("store_returns_quantitystdev") /
          col("store_returns_quantityave")).alias("store_returns_cov"),
         col("catalog_sales_quantitycount"),
         col("catalog_sales_quantityave"),
         (col("catalog_sales_quantitystdev") /
          col("catalog_sales_quantityave")).alias("catalog_sales_cov")],
        agg)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("s_state"))], out))


def q29(t, run):
    """Reference q29: quantity totals for sale -> return -> repurchase
    chains across widening date windows."""
    # reference sale window is 1999-09; the full year stands in for
    # the sparse synthetic sale->return->repurchase chains
    j = _q17_29_chain(
        t,
        col("d_year") == lit(1999),
        _between(col("d_moy"), lit(1), lit(12)) &
        (col("d_year") == lit(1999)),
        InSet(col("d_year"), (1999, 2000, 2001)))
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("s_store_id"),
         col("s_store_name")],
        [Sum(col("ss_quantity")).alias("store_sales_quantity"),
         Sum(col("sr_return_quantity")).alias("store_returns_quantity"),
         Sum(col("cs_quantity")).alias("catalog_sales_quantity")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("s_store_id")), asc(col("s_store_name"))], agg))


def _q39_inv(t):
    """q39 inv CTE: per warehouse/item/month inventory stdev & mean,
    kept when cov exceeds the (stand-in) threshold."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(_join(
        dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
        t["item"], ["inv_item_sk"], ["i_item_sk"]),
        t["warehouse"], ["inv_warehouse_sk"], ["w_warehouse_sk"])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("w_warehouse_sk"),
         col("i_item_sk"), col("d_moy")],
        [_Stddev(col("inv_quantity_on_hand")).alias("stdev"),
         Average(col("inv_quantity_on_hand")).alias("mean")], j)
    cov = If(col("mean") == lit(0.0), _Lit(None, _T.FLOAT64),
             col("stdev") / col("mean"))
    # reference cov > 1 can't fire on uniform synthetic quantities
    # (cov ~= 0.58); 0.5 stands in
    keep = If(col("mean") == lit(0.0), lit(0.0),
              col("stdev") / col("mean")) > lit(0.5)
    return CpuProject(
        [col("w_warehouse_sk"), col("i_item_sk"), col("d_moy"),
         col("mean"), cov.alias("cov")],
        CpuFilter(keep, agg))


def _q39_tail(t, extra_cov):
    inv = _q39_inv(t)
    inv1 = CpuFilter(col("d_moy") == lit(1), inv)
    if extra_cov:
        inv1 = CpuFilter(col("cov") > lit(0.52), inv1)
    inv1 = CpuProject(
        [col("w_warehouse_sk").alias("inv1_w"),
         col("i_item_sk").alias("inv1_i"),
         col("d_moy").alias("inv1_d_moy"),
         col("mean").alias("inv1_mean"), col("cov").alias("inv1_cov")],
        inv1)
    inv2 = CpuFilter(col("d_moy") == lit(2), inv)
    if extra_cov:
        inv2 = CpuFilter(col("cov") > lit(0.52), inv2)
    inv2 = CpuProject(
        [col("w_warehouse_sk").alias("inv2_w"),
         col("i_item_sk").alias("inv2_i"),
         col("d_moy").alias("inv2_d_moy"),
         col("mean").alias("inv2_mean"), col("cov").alias("inv2_cov")],
        inv2)
    j = _join(inv1, inv2, ["inv1_i", "inv1_w"], ["inv2_i", "inv2_w"])
    return CpuSort(
        [asc(col("inv1_w")), asc(col("inv1_i")),
         asc(col("inv1_d_moy")), asc(col("inv1_mean")),
         asc(col("inv1_cov")), asc(col("inv2_d_moy")),
         asc(col("inv2_mean")), asc(col("inv2_cov"))], j)


def q39(t, run):
    """Reference q39a: volatile-inventory item/warehouse pairs across
    consecutive months."""
    return _q39_tail(t, extra_cov=False)


def q39b(t, run):
    """Reference q39b: q39a restricted to the higher-cov slice."""
    return _q39_tail(t, extra_cov=True)


QUERIES.update({"q17": q17, "q29": q29, "q39": q39, "q39b": q39b})


def q95(t, run):
    """Reference q95: shipped web orders split across warehouses AND
    returned, with count-distinct order stats."""
    ws1k = CpuProject(
        [col("ws_order_number").alias("o1"),
         col("ws_warehouse_sk").alias("w1")], t["web_sales"])
    ws2k = CpuProject(
        [col("ws_order_number").alias("o2"),
         col("ws_warehouse_sk").alias("w2")], t["web_sales"])
    ws_wh = CpuFilter(col("w1") != col("w2"),
                      _join(ws1k, ws2k, ["o1"], ["o2"]))
    ws_wh = CpuProject(
        [col("o1")],
        CpuAggregate([col("o1")], [Count(None).alias("_n")], ws_wh))
    ret = _join(CpuProject([col("wr_order_number").alias("ro")],
                           t["web_returns"]),
                ws_wh, ["ro"], ["o1"], jt=J.LEFT_SEMI)
    ret = CpuProject(
        [col("ro")],
        CpuAggregate([col("ro")], [Count(None).alias("_n")], ret))
    dd = CpuFilter(_between(col("d_date"), _date(1999, 2, 1),
                            _date(1999, 4, 2)), t["date_dim"])
    ca = CpuFilter(col("ca_state") == lit("IL"),
                   t["customer_address"])
    web = CpuFilter(col("web_company_name") == lit("pri"),
                    t["web_site"])
    j = _join(dd, t["web_sales"], ["d_date_sk"], ["ws_ship_date_sk"])
    j = _join(j, ca, ["ws_ship_addr_sk"], ["ca_address_sk"])
    j = _join(j, web, ["ws_web_site_sk"], ["web_site_sk"])
    j = _join(j, ws_wh, ["ws_order_number"], ["o1"], jt=J.LEFT_SEMI)
    j = _join(j, ret, ["ws_order_number"], ["ro"], jt=J.LEFT_SEMI)
    sums = CpuAggregate(
        [], [Sum(col("ws_ext_ship_cost")).alias("total_ship_cost"),
             Sum(col("ws_net_profit")).alias("total_net_profit")],
        j)
    dist = CpuAggregate(
        [], [Count(None).alias("order_count")],
        CpuAggregate([col("ws_order_number")],
                     [Count(None).alias("_d")], j))
    both = _join(CpuProject([lit(1).alias("_ka"), col("order_count")],
                            dist),
                 CpuProject([lit(1).alias("_kb"),
                             col("total_ship_cost"),
                             col("total_net_profit")], sums),
                 ["_ka"], ["_kb"])
    return CpuLimit(100, CpuProject(
        [col("order_count"), col("total_ship_cost"),
         col("total_net_profit")], both))


def q72(t, run):
    """Reference q72: catalog orders that outstripped same-week
    inventory for one demographic, promo vs no-promo."""
    d1 = CpuProject([col("d_date_sk").alias("d1_sk"),
                     col("d_week_seq").alias("d1_wk"),
                     col("d_date").alias("d1_date")],
                    CpuFilter(col("d_year") == lit(1999),
                              t["date_dim"]))
    d2 = CpuProject([col("d_date_sk").alias("d2_sk"),
                     col("d_week_seq").alias("d2_wk")], t["date_dim"])
    d3 = CpuProject([col("d_date_sk").alias("d3_sk"),
                     col("d_date").alias("d3_date")], t["date_dim"])
    cd = CpuFilter(col("cd_marital_status") == lit("D"),
                   t["customer_demographics"])
    hd = CpuFilter(col("hd_buy_potential") == lit(">10000"),
                   t["household_demographics"])
    j = _join(t["catalog_sales"], t["inventory"],
              ["cs_item_sk"], ["inv_item_sk"])
    j = _join(j, t["warehouse"], ["inv_warehouse_sk"],
              ["w_warehouse_sk"])
    j = _join(j, t["item"], ["cs_item_sk"], ["i_item_sk"])
    j = _join(j, cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    j = _join(j, hd, ["cs_bill_hdemo_sk"], ["hd_demo_sk"])
    j = _join(j, d1, ["cs_sold_date_sk"], ["d1_sk"])
    j = _join(j, d2, ["inv_date_sk"], ["d2_sk"])
    j = _join(j, d3, ["cs_ship_date_sk"], ["d3_sk"])
    pr = CpuProject([col("p_promo_sk")], t["promotion"])
    j = _join(j, pr, ["cs_promo_sk"], ["p_promo_sk"], jt=J.LEFT_OUTER)
    cr = CpuProject([col("cr_item_sk").alias("cri"),
                     col("cr_order_number").alias("cro")],
                    t["catalog_returns"])
    j = _join(j, cr, ["cs_item_sk", "cs_order_number"],
              ["cri", "cro"], jt=J.LEFT_OUTER)
    from spark_rapids_tpu.exprs.cast import Cast as _Cast
    f = CpuFilter(
        (col("d1_wk") == col("d2_wk")) &
        (col("inv_quantity_on_hand") < col("cs_quantity")) &
        (_Cast(col("d3_date"), _T.INT32) >
         _Cast(col("d1_date"), _T.INT32) + lit(5)), j)
    agg = CpuAggregate(
        [col("i_item_desc"), col("w_warehouse_name"), col("d1_wk")],
        [Sum(If(IsNull(col("p_promo_sk")), lit(1),
                lit(0))).alias("no_promo"),
         Sum(If(IsNotNull(col("p_promo_sk")), lit(1),
                lit(0))).alias("promo"),
         Count(None).alias("total_cnt")], f)
    out = CpuProject(
        [col("i_item_desc"), col("w_warehouse_name"),
         col("d1_wk").alias("d_week_seq"), col("no_promo"),
         col("promo"), col("total_cnt")], agg)
    return CpuLimit(100, CpuSort(
        [desc(col("total_cnt")), asc(col("i_item_desc")),
         asc(col("w_warehouse_name")), asc(col("d_week_seq"))], out))


def _q66_channel(t, sales, date_key, time_key, ship_key, wh_key,
                 price, net):
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    td = CpuFilter(_between(col("t_time"), lit(30838),
                            lit(30838 + 28800)), t["time_dim"])
    # reference carriers DHL/BARIAN stand in as DHL/USPS
    sm = CpuFilter(InSet(col("sm_carrier"), ("DHL", "USPS")),
                   t["ship_mode"])
    j = _join(_join(_join(_join(
        dd, sales, ["d_date_sk"], [date_key]),
        td, [time_key], ["t_time_sk"]),
        sm, [ship_key], ["sm_ship_mode_sk"]),
        t["warehouse"], [wh_key], ["w_warehouse_sk"])

    months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
              "sep", "oct", "nov", "dec"]
    aggs = []
    qty = {"ws_ext_sales_price": "ws_quantity",
           "ws_net_paid": "ws_quantity",
           "cs_ext_sales_price": "cs_quantity",
           "cs_net_paid": "cs_quantity"}
    for i, m in enumerate(months, start=1):
        aggs.append(Sum(If(col("d_moy") == lit(i),
                           col(price) * col(qty[price]),
                           lit(0.0))).alias(f"{m}_sales"))
    for i, m in enumerate(months, start=1):
        aggs.append(Sum(If(col("d_moy") == lit(i),
                           col(net) * col(qty[net]),
                           lit(0.0))).alias(f"{m}_net"))
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("w_warehouse_sq_ft"),
         col("w_city"), col("w_county"), col("w_state"),
         col("w_country"), col("d_year")], aggs, j)
    return CpuProject(
        [col("w_warehouse_name"), col("w_warehouse_sq_ft"),
         col("w_city"), col("w_county"), col("w_state"),
         col("w_country"),
         lit("DHL,USPS").alias("ship_carriers"),
         col("d_year").alias("year")] +
        [col(f"{m}_sales") for m in months] +
        [col(f"{m}_net") for m in months], agg)


def q66(t, run):
    """Reference q66: warehouse monthly sales/net pivot across web and
    catalog channels for chosen carriers and a time-of-day band."""
    ws = _q66_channel(t, t["web_sales"], "ws_sold_date_sk",
                      "ws_sold_time_sk", "ws_ship_mode_sk",
                      "ws_warehouse_sk", "ws_ext_sales_price",
                      "ws_net_paid")
    cs = _q66_channel(t, t["catalog_sales"], "cs_sold_date_sk",
                      "cs_sold_time_sk", "cs_ship_mode_sk",
                      "cs_warehouse_sk", "cs_ext_sales_price",
                      "cs_net_paid")
    u = CpuUnion(ws, cs)
    months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
              "sep", "oct", "nov", "dec"]
    aggs = ([Sum(col(f"{m}_sales")).alias(f"{m}_sales")
             for m in months] +
            [Sum(col(f"{m}_sales") /
                 col("w_warehouse_sq_ft")).alias(f"{m}_sales_per_sqft")
             for m in months] +
            [Sum(col(f"{m}_net")).alias(f"{m}_net") for m in months])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("w_warehouse_sq_ft"),
         col("w_city"), col("w_county"), col("w_state"),
         col("w_country"), col("ship_carriers"), col("year")],
        aggs, u)
    return CpuLimit(100, CpuSort([asc(col("w_warehouse_name"))], agg))


QUERIES.update({"q95": q95, "q72": q72, "q66": q66})


def q75(t, run):
    """Reference q75: net-of-returns category sales by item attrs,
    year-over-year decline (UNION distinct across channels)."""
    def detail(sales, returns, item_key, date_key, skeys, rkeys, qty,
               rqty, amt, ramt):
        it = CpuFilter(col("i_category") == lit("Books"), t["item"])
        j = _join(sales, it, [item_key], ["i_item_sk"])
        j = _join(j, t["date_dim"], [date_key], ["d_date_sk"])
        j = _join(j, returns, skeys, rkeys, jt=J.LEFT_OUTER)
        return CpuProject(
            [col("d_year"), col("i_brand_id"), col("i_class_id"),
             col("i_category_id"), col("i_manufact_id"),
             (col(qty) - Coalesce((col(rqty), lit(0)))
              ).alias("sales_cnt"),
             (col(amt) - Coalesce((col(ramt), lit(0.0)))
              ).alias("sales_amt")], j)

    u = CpuUnion(
        detail(t["catalog_sales"], t["catalog_returns"], "cs_item_sk",
               "cs_sold_date_sk",
               ["cs_order_number", "cs_item_sk"],
               ["cr_order_number", "cr_item_sk"],
               "cs_quantity", "cr_return_quantity",
               "cs_ext_sales_price", "cr_return_amount"),
        detail(t["store_sales"], t["store_returns"], "ss_item_sk",
               "ss_sold_date_sk",
               ["ss_ticket_number", "ss_item_sk"],
               ["sr_ticket_number", "sr_item_sk"],
               "ss_quantity", "sr_return_quantity",
               "ss_ext_sales_price", "sr_return_amt"),
        detail(t["web_sales"], t["web_returns"], "ws_item_sk",
               "ws_sold_date_sk",
               ["ws_order_number", "ws_item_sk"],
               ["wr_order_number", "wr_item_sk"],
               "ws_quantity", "wr_return_quantity",
               "ws_ext_sales_price", "wr_return_amt"))
    # UNION distinct: dedup the detail rows before re-aggregation
    cols = ["d_year", "i_brand_id", "i_class_id", "i_category_id",
            "i_manufact_id", "sales_cnt", "sales_amt"]
    dedup = CpuProject(
        [col(c) for c in cols],
        CpuAggregate([col(c) for c in cols],
                     [Count(None).alias("_n")], u))
    all_sales = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_class_id"),
         col("i_category_id"), col("i_manufact_id")],
        [Sum(col("sales_cnt")).alias("sales_cnt"),
         Sum(col("sales_amt")).alias("sales_amt")], dedup)
    curr = CpuFilter(col("d_year") == lit(2002), all_sales)
    prev = CpuProject(
        [col("d_year").alias("prev_year"),
         col("i_brand_id").alias("pb"), col("i_class_id").alias("pc"),
         col("i_category_id").alias("pg"),
         col("i_manufact_id").alias("pm"),
         col("sales_cnt").alias("prev_yr_cnt"),
         col("sales_amt").alias("prev_amt")],
        CpuFilter(col("d_year") == lit(2001), all_sales))
    j = _join(curr, prev,
              ["i_brand_id", "i_class_id", "i_category_id",
               "i_manufact_id"], ["pb", "pc", "pg", "pm"])
    from spark_rapids_tpu.exprs.cast import Cast as _Cast
    f = CpuFilter(
        _Cast(col("sales_cnt"), _T.FLOAT64) /
        _Cast(col("prev_yr_cnt"), _T.FLOAT64) < lit(0.9), j)
    out = CpuProject(
        [col("prev_year"), col("d_year").alias("year"),
         col("i_brand_id"), col("i_class_id"), col("i_category_id"),
         col("i_manufact_id"), col("prev_yr_cnt"),
         col("sales_cnt").alias("curr_yr_cnt"),
         (col("sales_cnt") - col("prev_yr_cnt")
          ).alias("sales_cnt_diff"),
         (col("sales_amt") - col("prev_amt")).alias("sales_amt_diff")],
        f)
    return CpuLimit(100, CpuSort([asc(col("sales_cnt_diff"))], out))


def q78(t, run):
    """Reference q78: store-loyal purchases (never returned) vs the
    other channels per customer/item/year."""
    def chan(sales, returns, date_key, item_key, cust_key, skeys,
             rkeys, rnull, qty, wc, sp, tag):
        j = _join(t[sales], CpuProject(
            [col(rkeys[0]).alias("_r0"), col(rkeys[1]).alias("_r1")],
            t[returns]), skeys, ["_r0", "_r1"], jt=J.LEFT_OUTER)
        j = CpuFilter(IsNull(col("_r0")), j)
        j = _join(j, t["date_dim"], [date_key], ["d_date_sk"])
        return CpuAggregate(
            [col("d_year").alias(f"{tag}_sold_year"),
             col(item_key).alias(f"{tag}_item"),
             col(cust_key).alias(f"{tag}_cust")],
            [Sum(col(qty)).alias(f"{tag}_qty"),
             Sum(col(wc)).alias(f"{tag}_wc"),
             Sum(col(sp)).alias(f"{tag}_sp")], j)

    ws = chan("web_sales", "web_returns", "ws_sold_date_sk",
              "ws_item_sk", "ws_bill_customer_sk",
              ["ws_order_number", "ws_item_sk"],
              ["wr_order_number", "wr_item_sk"], "wr_order_number",
              "ws_quantity", "ws_wholesale_cost", "ws_sales_price",
              "ws")
    cs = chan("catalog_sales", "catalog_returns", "cs_sold_date_sk",
              "cs_item_sk", "cs_bill_customer_sk",
              ["cs_order_number", "cs_item_sk"],
              ["cr_order_number", "cr_item_sk"], "cr_order_number",
              "cs_quantity", "cs_wholesale_cost", "cs_sales_price",
              "cs")
    ss = chan("store_sales", "store_returns", "ss_sold_date_sk",
              "ss_item_sk", "ss_customer_sk",
              ["ss_ticket_number", "ss_item_sk"],
              ["sr_ticket_number", "sr_item_sk"], "sr_ticket_number",
              "ss_quantity", "ss_wholesale_cost", "ss_sales_price",
              "ss")
    j = _join(ss, ws, ["ss_sold_year", "ss_item", "ss_cust"],
              ["ws_sold_year", "ws_item", "ws_cust"], jt=J.LEFT_OUTER)
    j = _join(j, cs, ["ss_sold_year", "ss_item", "ss_cust"],
              ["cs_sold_year", "cs_item", "cs_cust"], jt=J.LEFT_OUTER)
    other_qty = (Coalesce((col("ws_qty"), lit(0))) +
                 Coalesce((col("cs_qty"), lit(0))))
    f = CpuFilter(
        ((Coalesce((col("ws_qty"), lit(0))) > lit(0)) |
         (Coalesce((col("cs_qty"), lit(0))) > lit(0))) &
        (col("ss_sold_year") == lit(2000)), j)
    from spark_rapids_tpu.exprs.cast import Cast as _Cast
    out = CpuProject(
        [col("ss_sold_year"), col("ss_item").alias("ss_item_sk"),
         col("ss_cust").alias("ss_customer_sk"),
         Round(_Cast(col("ss_qty"), _T.FLOAT64) /
               _Cast(other_qty, _T.FLOAT64), 2).alias("ratio"),
         col("ss_qty").alias("store_qty"),
         col("ss_wc").alias("store_wholesale_cost"),
         col("ss_sp").alias("store_sales_price"),
         other_qty.alias("other_chan_qty"),
         (Coalesce((col("ws_wc"), lit(0.0))) +
          Coalesce((col("cs_wc"), lit(0.0)))
          ).alias("other_chan_wholesale_cost"),
         (Coalesce((col("ws_sp"), lit(0.0))) +
          Coalesce((col("cs_sp"), lit(0.0)))
          ).alias("other_chan_sales_price")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("ss_sold_year")), asc(col("ss_item_sk")),
         asc(col("ss_customer_sk")), desc(col("store_qty")),
         desc(col("store_wholesale_cost")),
         desc(col("store_sales_price")), asc(col("other_chan_qty")),
         asc(col("other_chan_wholesale_cost")),
         asc(col("other_chan_sales_price"))], out))


QUERIES.update({"q75": q75, "q78": q78})


def _q24_ssales(t):
    """q24 ssales CTE: returned store purchases where the customer's
    birth country matches the (upper-cased) address country and the
    store shares the address zip."""
    from spark_rapids_tpu.exprs.string_fns import Upper
    st = CpuFilter(col("s_market_id") == lit(8), t["store"])
    j = _join(t["store_sales"], t["store_returns"],
              ["ss_ticket_number", "ss_item_sk"],
              ["sr_ticket_number", "sr_item_sk"])
    j = _join(j, st, ["ss_store_sk"], ["s_store_sk"])
    j = _join(j, t["item"], ["ss_item_sk"], ["i_item_sk"])
    j = _join(j, t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    ca = CpuProject(
        [Upper(col("ca_country")).alias("ca_country_up"),
         col("ca_zip").alias("ca_zip2"),
         col("ca_state").alias("ca_state")], t["customer_address"])
    j = _join(j, ca, ["c_birth_country", "s_zip"],
              ["ca_country_up", "ca_zip2"])
    return CpuAggregate(
        [col("c_last_name"), col("c_first_name"), col("s_store_name"),
         col("ca_state"), col("s_state"), col("i_color"),
         col("i_current_price"), col("i_manager_id"), col("i_units"),
         col("i_size")],
        [Sum(col("ss_net_paid")).alias("netpaid")], j)


def _q24_tail(t, color):
    ssales = _q24_ssales(t)
    thr = CpuProject(
        [(col("_a") * lit(0.05)).alias("threshold"),
         lit(1).alias("_tk")],
        CpuAggregate([], [Average(col("netpaid")).alias("_a")],
                     ssales))
    sel = CpuFilter(col("i_color") == lit(color), ssales)
    agg = CpuAggregate(
        [col("c_last_name"), col("c_first_name"), col("s_store_name")],
        [Sum(col("netpaid")).alias("paid")], sel)
    keyed = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("s_store_name"),
         col("paid"), lit(1).alias("_pk")], agg)
    f = CpuFilter(col("paid") > col("threshold"),
                  _join(keyed, thr, ["_pk"], ["_tk"]))
    return CpuProject(
        [col("c_last_name"), col("c_first_name"), col("s_store_name"),
         col("paid")], f)


def q24(t, run):
    """Reference q24a: big spenders on one color at market-8 stores
    co-located with their address."""
    return _q24_tail(t, "snow")


def q24b(t, run):
    """Reference q24b: q24a for a second color."""
    return _q24_tail(t, "powder")


def _q23_ctes(t):
    """q23 CTEs: frequent same-day items and best store customers."""
    from spark_rapids_tpu.exprs.aggregates import Max
    dd4 = CpuFilter(InSet(col("d_year"), (2000, 2001, 2002, 2003)),
                    t["date_dim"])
    j = _join(_join(dd4, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    freq = CpuAggregate(
        [_Substring(col("i_item_desc"), lit(1),
                    lit(30)).alias("itemdesc"),
         col("i_item_sk").alias("item_sk"),
         col("d_date").alias("solddate")],
        [Count(None).alias("cnt")], j)
    # reference keeps count > 4 per item-day; > 1 stands in at
    # synthetic scale
    freq = CpuProject(
        [col("item_sk")],
        CpuAggregate([col("item_sk")], [Count(None).alias("_n")],
                     CpuFilter(col("cnt") > lit(1), freq)))
    per_cust = CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_quantity") * col("ss_list_price")).alias("csales")],
        _join(dd4, t["store_sales"], ["d_date_sk"],
              ["ss_sold_date_sk"]))
    cmax = CpuProject(
        [(col("_m") * lit(0.5)).alias("cut"), lit(1).alias("_mk")],
        CpuAggregate(
            [], [Max(col("csales")).alias("_m")], per_cust))
    all_cust = CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_quantity") * col("ss_list_price")
             ).alias("ssales")], t["store_sales"])
    keyed = CpuProject(
        [col("ss_customer_sk"), col("ssales"), lit(1).alias("_ck")],
        all_cust)
    # reference threshold is 95% of the max customer's spend; 50%
    # stands in to keep a non-degenerate best-customer set
    best = CpuProject(
        [col("ss_customer_sk").alias("best_sk")],
        CpuFilter(col("ssales") > col("cut"),
                  _join(keyed, cmax, ["_ck"], ["_mk"])))
    return freq, best


def q23(t, run):
    """Reference q23a: Feb-2000 catalog+web revenue from the best store
    customers on frequently-sold items."""
    freq, best = _q23_ctes(t)
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(2)), t["date_dim"])

    def chan(sales, date_key, item_key, cust_key, qty, lp):
        j = _join(dd, sales, ["d_date_sk"], [date_key])
        j = _join(j, freq, [item_key], ["item_sk"], jt=J.LEFT_SEMI)
        j = _join(j, best, [cust_key], ["best_sk"], jt=J.LEFT_SEMI)
        return CpuProject(
            [(col(qty) * col(lp)).alias("sales")], j)

    u = CpuUnion(
        chan(t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk",
             "cs_bill_customer_sk", "cs_quantity", "cs_list_price"),
        chan(t["web_sales"], "ws_sold_date_sk", "ws_item_sk",
             "ws_bill_customer_sk", "ws_quantity", "ws_list_price"))
    return CpuLimit(100, CpuAggregate(
        [], [Sum(col("sales")).alias("total_sales")], u))


def q23b(t, run):
    """Reference q23b: q23a broken out by best customer."""
    freq, best = _q23_ctes(t)
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(2)), t["date_dim"])

    def chan(sales, date_key, item_key, cust_key, qty, lp):
        j = _join(dd, sales, ["d_date_sk"], [date_key])
        j = _join(j, freq, [item_key], ["item_sk"], jt=J.LEFT_SEMI)
        j = _join(j, best, [cust_key], ["best_sk"], jt=J.LEFT_SEMI)
        j = _join(j, t["customer"], [cust_key], ["c_customer_sk"])
        return CpuProject(
            [col("c_last_name"), col("c_first_name"),
             (col(qty) * col(lp)).alias("sales")], j)

    u = CpuUnion(
        chan(t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk",
             "cs_bill_customer_sk", "cs_quantity", "cs_list_price"),
        chan(t["web_sales"], "ws_sold_date_sk", "ws_item_sk",
             "ws_bill_customer_sk", "ws_quantity", "ws_list_price"))
    agg = CpuAggregate(
        [col("c_last_name"), col("c_first_name")],
        [Sum(col("sales")).alias("sales")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("sales"))], agg))


QUERIES.update({"q24": q24, "q24b": q24b, "q23": q23, "q23b": q23b})


def _q14_cross_items(t):
    """q14 cross_items: items whose (brand, class, category) triple
    sold in ALL three channels in the 3-year window (INTERSECT as
    successive semi joins over the distinct triples)."""
    dd = CpuFilter(InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])

    def triples(sales, date_key, item_key, tag):
        j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
                  t["item"], [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_brand_id").alias(f"{tag}b"),
             col("i_class_id").alias(f"{tag}c"),
             col("i_category_id").alias(f"{tag}g")],
            CpuAggregate(
                [col("i_brand_id"), col("i_class_id"),
                 col("i_category_id")], [Count(None).alias("_n")], j))

    ss = triples(t["store_sales"], "ss_sold_date_sk", "ss_item_sk",
                 "s")
    cs = triples(t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk",
                 "c")
    ws = triples(t["web_sales"], "ws_sold_date_sk", "ws_item_sk", "w")
    both = _join(ss, cs, ["sb", "sc", "sg"], ["cb", "cc", "cg"],
                 jt=J.LEFT_SEMI)
    x = _join(both, ws, ["sb", "sc", "sg"], ["wb", "wc", "wg"],
              jt=J.LEFT_SEMI)
    items = _join(t["item"], x,
                  ["i_brand_id", "i_class_id", "i_category_id"],
                  ["sb", "sc", "sg"], jt=J.LEFT_SEMI)
    return CpuProject([col("i_item_sk").alias("cross_sk")], items)


def _q14_avg_sales(t):
    """q14 avg_sales scalar: mean quantity*list_price across the three
    channels over the window, keyed for a cross join."""
    dd = CpuFilter(InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])

    def chan(sales, date_key, qty, lp):
        j = _join(dd, sales, ["d_date_sk"], [date_key])
        return CpuProject(
            [(col(qty) * col(lp)).alias("qlp")], j)

    u = CpuUnion(
        chan(t["store_sales"], "ss_sold_date_sk", "ss_quantity",
             "ss_list_price"),
        chan(t["catalog_sales"], "cs_sold_date_sk", "cs_quantity",
             "cs_list_price"),
        chan(t["web_sales"], "ws_sold_date_sk", "ws_quantity",
             "ws_list_price"))
    return CpuProject(
        [col("average_sales"), lit(1).alias("_ak")],
        CpuAggregate([], [Average(col("qlp")).alias("average_sales")],
                     u))


def _q14_channel_sales(t, cross, avg, sales, date_key, item_key, qty,
                       lp, chan, date_pred):
    dd = CpuFilter(date_pred, t["date_dim"])
    j = _join(dd, t[sales], ["d_date_sk"], [date_key])
    j = _join(j, cross, [item_key], ["cross_sk"], jt=J.LEFT_SEMI)
    j = _join(j, t["item"], [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_class_id"), col("i_category_id")],
        [Sum(col(qty) * col(lp)).alias("sales"),
         Count(None).alias("number_sales")], j)
    keyed = CpuProject(
        [col("i_brand_id"), col("i_class_id"), col("i_category_id"),
         col("sales"), col("number_sales"), lit(1).alias("_hk")], agg)
    f = CpuFilter(col("sales") > col("average_sales"),
                  _join(keyed, avg, ["_hk"], ["_ak"]))
    return CpuProject(
        [lit(chan).alias("channel"), col("i_brand_id"),
         col("i_class_id"), col("i_category_id"), col("sales"),
         col("number_sales")], f)


def q14(t, run):
    """Reference q14a: above-average cross-channel triples for one
    month, totaled over ROLLUP(channel, brand, class, category)."""
    cross = _q14_cross_items(t)
    avg = _q14_avg_sales(t)
    pred = (col("d_year") == lit(2001)) & (col("d_moy") == lit(11))
    u = CpuUnion(
        _q14_channel_sales(t, cross, avg, "store_sales",
                           "ss_sold_date_sk", "ss_item_sk",
                           "ss_quantity", "ss_list_price", "store",
                           pred),
        _q14_channel_sales(t, cross, avg, "catalog_sales",
                           "cs_sold_date_sk", "cs_item_sk",
                           "cs_quantity", "cs_list_price", "catalog",
                           pred),
        _q14_channel_sales(t, cross, avg, "web_sales",
                           "ws_sold_date_sk", "ws_item_sk",
                           "ws_quantity", "ws_list_price", "web",
                           pred))
    keys = ["channel", "i_brand_id", "i_class_id", "i_category_id"]
    ex = _rollup_expand(u, keys, ["sales", "number_sales"])
    agg = CpuAggregate(
        [col(k) for k in keys] + [col("gid")],
        [Sum(col("sales")).alias("sum_sales"),
         Sum(col("number_sales")).alias("sum_number_sales")], ex)
    out = CpuProject(
        [col(k) for k in keys] +
        [col("sum_sales"), col("sum_number_sales")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col(k)) for k in keys], out))


def q14b(t, run):
    """Reference q14b: this-week vs same-week-last-year store sales of
    cross-channel triples."""
    cross = _q14_cross_items(t)
    avg = _q14_avg_sales(t)

    def week_pred(year):
        wk = CpuProject(
            [col("d_week_seq").alias("sel_wk")],
            CpuAggregate(
                [col("d_week_seq")], [Count(None).alias("_c")],
                CpuFilter((col("d_year") == lit(year)) &
                          (col("d_moy") == lit(12)) &
                          (col("d_dom") == lit(11)), t["date_dim"])))
        return CpuProject(
            [col("d_date_sk")],
            _join(t["date_dim"], wk, ["d_week_seq"], ["sel_wk"],
                  jt=J.LEFT_SEMI))

    def store_week(dates, tag):
        j = _join(dates, t["store_sales"], ["d_date_sk"],
                  ["ss_sold_date_sk"])
        j = _join(j, cross, ["ss_item_sk"], ["cross_sk"],
                  jt=J.LEFT_SEMI)
        j = _join(j, t["item"], ["ss_item_sk"], ["i_item_sk"])
        agg = CpuAggregate(
            [col("i_brand_id"), col("i_class_id"),
             col("i_category_id")],
            [Sum(col("ss_quantity") * col("ss_list_price")
                 ).alias("sales"),
             Count(None).alias("number_sales")], j)
        keyed = CpuProject(
            [col("i_brand_id"), col("i_class_id"),
             col("i_category_id"), col("sales"), col("number_sales"),
             lit(1).alias("_hk")], agg)
        f = CpuFilter(col("sales") > col("average_sales"),
                      _join(keyed, avg, ["_hk"], ["_ak"]))
        return CpuProject(
            [col("i_brand_id").alias(f"{tag}_brand"),
             col("i_class_id").alias(f"{tag}_class"),
             col("i_category_id").alias(f"{tag}_cat"),
             col("sales").alias(f"{tag}_sales"),
             col("number_sales").alias(f"{tag}_number_sales")], f)

    this_year = store_week(week_pred(2000), "ty")
    last_year = store_week(week_pred(1999), "ly")
    j = _join(this_year, last_year,
              ["ty_brand", "ty_class", "ty_cat"],
              ["ly_brand", "ly_class", "ly_cat"])
    out = CpuProject(
        [lit("store").alias("ty_channel"),
         lit("store").alias("ly_channel"),
         col("ty_brand"), col("ly_brand").alias("ly_brand_id"),
         col("ty_cat"), col("ly_cat").alias("ly_cat_id"),
         col("ty_class"), col("ly_class").alias("ly_class_id"),
         col("ty_number_sales"), col("ly_number_sales"),
         col("ty_sales"), col("ly_sales")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ty_brand")), asc(col("ty_class")),
         asc(col("ty_cat"))], out))


QUERIES.update({"q14": q14, "q14b": q14b})


def q64(t, run):
    """Reference q64: repeat same-store purchases of profitable
    catalog-returned items, year over year, with buyer's sale-time and
    current demographics/addresses."""
    cs_ui = CpuFilter(
        col("sale") > lit(2.0) * col("refund"),
        CpuAggregate(
            [col("cs_item_sk")],
            [Sum(col("cs_ext_list_price")).alias("sale"),
             Sum(col("cr_refunded_cash") + col("cr_reversed_charge") +
                 col("cr_store_credit")).alias("refund")],
            _join(t["catalog_sales"], t["catalog_returns"],
                  ["cs_item_sk", "cs_order_number"],
                  ["cr_item_sk", "cr_order_number"])))
    cs_ui = CpuProject([col("cs_item_sk").alias("ui_sk")], cs_ui)

    it = CpuFilter(
        InSet(col("i_color"), ("floral", "deep", "light",
                               "cornflower", "midnight", "snow")) &
        _between(col("i_current_price"), lit(64.0), lit(74.0)) &
        _between(col("i_current_price"), lit(65.0), lit(79.0)),
        t["item"])
    cd1 = CpuProject([col("cd_demo_sk").alias("cd1_sk"),
                      col("cd_marital_status").alias("cd1_ms")],
                     t["customer_demographics"])
    cd2 = CpuProject([col("cd_demo_sk").alias("cd2_sk"),
                      col("cd_marital_status").alias("cd2_ms")],
                     t["customer_demographics"])
    hd1 = CpuProject([col("hd_demo_sk").alias("hd1_sk"),
                      col("hd_income_band_sk").alias("hd1_ib")],
                     t["household_demographics"])
    hd2 = CpuProject([col("hd_demo_sk").alias("hd2_sk"),
                      col("hd_income_band_sk").alias("hd2_ib")],
                     t["household_demographics"])
    ib1 = CpuProject([col("ib_income_band_sk").alias("ib1_sk")],
                     t["income_band"])
    ib2 = CpuProject([col("ib_income_band_sk").alias("ib2_sk")],
                     t["income_band"])
    ad1 = CpuProject(
        [col("ca_address_sk").alias("ad1_sk"),
         col("ca_street_number").alias("b_street_number"),
         col("ca_street_name").alias("b_street_name"),
         col("ca_city").alias("b_city"), col("ca_zip").alias("b_zip")],
        t["customer_address"])
    ad2 = CpuProject(
        [col("ca_address_sk").alias("ad2_sk"),
         col("ca_street_number").alias("c_street_number"),
         col("ca_street_name").alias("c_street_name"),
         col("ca_city").alias("c_city"), col("ca_zip").alias("c_zip")],
        t["customer_address"])
    d1 = CpuProject([col("d_date_sk").alias("d1_sk"),
                     col("d_year").alias("syear")], t["date_dim"])
    d2 = CpuProject([col("d_date_sk").alias("d2_sk"),
                     col("d_year").alias("fsyear")], t["date_dim"])
    d3 = CpuProject([col("d_date_sk").alias("d3_sk"),
                     col("d_year").alias("s2year")], t["date_dim"])

    j = _join(t["store_sales"], t["store_returns"],
              ["ss_item_sk", "ss_ticket_number"],
              ["sr_item_sk", "sr_ticket_number"])
    j = _join(j, cs_ui, ["ss_item_sk"], ["ui_sk"], jt=J.LEFT_SEMI)
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    j = _join(j, d1, ["ss_sold_date_sk"], ["d1_sk"])
    j = _join(j, t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    j = _join(j, cd1, ["ss_cdemo_sk"], ["cd1_sk"])
    j = _join(j, hd1, ["ss_hdemo_sk"], ["hd1_sk"])
    j = _join(j, ad1, ["ss_addr_sk"], ["ad1_sk"])
    j = _join(j, it, ["ss_item_sk"], ["i_item_sk"])
    j = _join(j, cd2, ["c_current_cdemo_sk"], ["cd2_sk"])
    j = _join(j, hd2, ["c_current_hdemo_sk"], ["hd2_sk"])
    j = _join(j, ad2, ["c_current_addr_sk"], ["ad2_sk"])
    j = _join(j, d2, ["c_first_sales_date_sk"], ["d2_sk"])
    j = _join(j, d3, ["c_first_shipto_date_sk"], ["d3_sk"])
    j = _join(j, CpuProject([col("p_promo_sk").alias("pk")],
                            t["promotion"]),
              ["ss_promo_sk"], ["pk"], jt=J.LEFT_SEMI)
    j = _join(j, ib1, ["hd1_ib"], ["ib1_sk"], jt=J.LEFT_SEMI)
    j = _join(j, ib2, ["hd2_ib"], ["ib2_sk"], jt=J.LEFT_SEMI)
    f = CpuFilter(col("cd1_ms") != col("cd2_ms"), j)
    groups = ["i_product_name", "i_item_sk", "s_store_name", "s_zip",
              "b_street_number", "b_street_name", "b_city", "b_zip",
              "c_street_number", "c_street_name", "c_city", "c_zip",
              "syear", "fsyear", "s2year"]
    cross_sales = CpuAggregate(
        [col(g) for g in groups],
        [Count(None).alias("cnt"),
         Sum(col("ss_wholesale_cost")).alias("s1"),
         Sum(col("ss_list_price")).alias("s2"),
         Sum(col("ss_coupon_amt")).alias("s3")], f)
    cs1 = CpuFilter(col("syear") == lit(1999), cross_sales)
    cs2 = CpuProject(
        [col("i_item_sk").alias("k_item"),
         col("s_store_name").alias("k_store"),
         col("s_zip").alias("k_zip"),
         col("cnt").alias("cs2_cnt"), col("s1").alias("cs2_s1"),
         col("s2").alias("cs2_s2"), col("s3").alias("cs2_s3"),
         col("syear").alias("cs2_syear")],
        CpuFilter(col("syear") == lit(2000), cross_sales))
    j2 = _join(cs1, cs2, ["i_item_sk", "s_store_name", "s_zip"],
               ["k_item", "k_store", "k_zip"])
    j2 = CpuFilter(col("cs2_cnt") <= col("cnt"), j2)
    out = CpuProject(
        [col("i_product_name").alias("product_name"),
         col("s_store_name").alias("store_name"),
         col("s_zip").alias("store_zip"), col("b_street_number"),
         col("b_street_name"), col("b_city"), col("b_zip"),
         col("c_street_number"), col("c_street_name"), col("c_city"),
         col("c_zip"), col("syear").alias("cs1_syear"),
         col("cnt").alias("cs1_cnt"), col("s1").alias("cs1_s1"),
         col("s2").alias("cs1_s2"), col("s3").alias("cs1_s3"),
         col("cs2_s1"), col("cs2_s2"), col("cs2_s3"),
         col("cs2_syear"), col("cs2_cnt")], j2)
    return CpuSort(
        [asc(col("product_name")), asc(col("store_name")),
         asc(col("cs2_cnt"))], out)


QUERIES.update({"q64": q64})
