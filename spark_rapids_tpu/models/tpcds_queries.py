"""TPC-DS-like query set (reference
`integration_tests/.../tpcds/TpcdsLikeSpark.scala`).  Same plan-tree
style as tpch_queries; queries marked "-shape" follow the reference
query's operator shape over the engine's v0 type matrix (no decimals,
reduced column sets).  Coverage spans the reference's main families:
star-join reports, returns-vs-average correlated shapes, multi-channel
unions, semi/anti-join existence tests, left-outer returns netting,
shipping-lag bucketing, time-slot pivots, and ratio reports."""
from __future__ import annotations

from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import Coalesce, If
from spark_rapids_tpu.exprs.predicates import InSet, IsNotNull, IsNull
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort, CpuUnion)

J = JoinType


def _join(left, right, lk, rk, jt=J.INNER, condition=None):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right, condition=condition)


def q3(t, run):
    """Brand revenue by year for one manufacturer in December."""
    dd = CpuFilter(col("d_moy") == lit(12), t["date_dim"])
    it = CpuFilter(col("i_manufact_id") == lit(5), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("sum_agg")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("sum_agg")),
         asc(col("i_brand_id"))], agg))


def q19(t, run):
    """Brand revenue for one month/year by manager."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(8), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand"), col("i_manufact_id")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id")),
         asc(col("i_manufact_id"))], agg))


def q42(t, run):
    """Category revenue for one month/year."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_category_id"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("total")), asc(col("d_year")),
         asc(col("i_category_id"))], agg))


def q52(t, run):
    """Brand revenue, one month/year (q42 by brand)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("ext_price")),
         asc(col("i_brand_id"))], agg))


def q55(t, run):
    """Brand revenue for one manager, month, year."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(28), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id"))], agg))


def q7_shape(t, run):
    """Average metrics per item under promotion (q7 without cdemo)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    promo = CpuFilter((col("p_channel_email") == lit("N")) |
                      (col("p_channel_event") == lit("N")),
                      t["promotion"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    promo, ["ss_promo_sk"], ["p_promo_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q27_shape(t, run):
    """State-level item averages (q27 without cdemo rollup)."""
    dd = CpuFilter(col("d_year") == lit(2002), t["date_dim"])
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA", "NY")),
                   t["store"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    st, ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_state")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_state"))], agg))


def q68(t, run):
    """Per-ticket totals for high-dependency households in two cities."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   InSet(col("d_dom"), tuple(range(1, 3))),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          st, ["ss_store_sk"], ["s_store_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_ext_sales_price")).alias("extended_price"),
         Sum(col("ss_ext_list_price")).alias("list_price"),
         Sum(col("ss_ext_wholesale_cost")).alias("extended_tax")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("ss_ticket_number"), col("extended_price"),
         col("extended_tax"), col("list_price")], j2)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))], out))


def q73(t, run):
    """Ticket counts per customer for mid-size baskets."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    hd = CpuFilter(col("hd_buy_potential") == lit(">10000"),
                   t["household_demographics"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    big = CpuFilter((col("cnt") >= lit(2)) & (col("cnt") <= lit(50)),
                    per_ticket)
    j2 = _join(big, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         col("ss_ticket_number"), col("cnt")], j2)
    return CpuSort([desc(col("cnt")), asc(col("c_last_name")),
                    asc(col("ss_ticket_number"))], out)


def q96(t, run):
    """Count of sales in a demographic/time slice."""
    hd = CpuFilter(col("hd_dep_count") == lit(7),
                   t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Count(None).alias("cnt")], j)


def q98_shape(t, run):
    """Revenue by item within categories over one month."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(2)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Sports", "Books", "Home")), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category"), col("i_current_price")],
        [Sum(col("ss_ext_sales_price")).alias("itemrevenue")], j)
    return CpuSort([asc(col("i_category")), asc(col("i_item_id"))], agg)




# ---------------------------------------------------------------------------
# returns / correlated-average shapes
def q1(t, run):
    """Customers whose store-return total exceeds 1.2x their store's
    average (reference q1's correlated subquery, decorrelated into an
    aggregate-join)."""
    ctr = CpuAggregate(
        [col("sr_customer_sk"), col("sr_store_sk")],
        [Sum(col("sr_return_amt")).alias("ctr_total")],
        t["store_returns"])
    avg_ctr = CpuAggregate(
        [col("sr_store_sk")],
        [Average(col("ctr_total")).alias("avg_ret")],
        CpuProject([col("sr_store_sk"), col("ctr_total")], ctr))
    big = CpuFilter(
        col("ctr_total") > col("avg_ret") * lit(1.2),
        _join(ctr, CpuProject(
            [col("sr_store_sk").alias("st2"), col("avg_ret")], avg_ctr),
            ["sr_store_sk"], ["st2"]))
    st = CpuFilter(col("s_state") == lit("TX"), t["store"])
    j = _join(_join(big, st, ["sr_store_sk"], ["s_store_sk"]),
              t["customer"], ["sr_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], j)))


def q6_shape(t, run):
    """States of customers buying items priced 1.2x above their
    category average."""
    avg_cat = CpuAggregate(
        [col("i_category")],
        [Average(col("i_current_price")).alias("avg_p")], t["item"])
    pricey = CpuFilter(
        col("i_current_price") > col("avg_p") * lit(1.2),
        _join(t["item"], CpuProject(
            [col("i_category").alias("cat2"), col("avg_p")], avg_cat),
            ["i_category"], ["cat2"]))
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(1)), t["date_dim"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          pricey, ["ss_item_sk"], ["i_item_sk"]),
                    t["customer"],
                    ["ss_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("cnt")), asc(col("ca_state"))],
        CpuFilter(col("cnt") >= lit(3), agg)))


def q65(t, run):
    """Store items whose revenue is at most 10% of the store's average
    item revenue."""
    sa = CpuAggregate(
        [col("ss_store_sk"), col("ss_item_sk")],
        [Sum(col("ss_sales_price")).alias("revenue")], t["store_sales"])
    sb = CpuAggregate(
        [col("ss_store_sk")],
        [Average(col("revenue")).alias("ave")],
        CpuProject([col("ss_store_sk"), col("revenue")], sa))
    low = CpuFilter(
        col("revenue") <= col("ave") * lit(0.1),
        _join(sa, CpuProject([col("ss_store_sk").alias("sk2"),
                              col("ave")], sb),
              ["ss_store_sk"], ["sk2"]))
    j = _join(_join(low, t["store"], ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("i_item_id"))],
        CpuProject([col("s_store_name"), col("i_item_id"),
                    col("revenue")], j)))


# ---------------------------------------------------------------------------
# catalog / web channel star joins
def q15_shape(t, run):
    """Catalog revenue by customer state for one quarter."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    t["customer"],
                    ["cs_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Sum(col("cs_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q26(t, run):
    """Catalog item averages for one demographic slice (q7's catalog
    twin)."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"]),
              t["item"], ["cs_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_sales_price")).alias("agg3")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q45_shape(t, run):
    """Web revenue by customer state for one quarter."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["web_sales"],
                          ["d_date_sk"], ["ws_sold_date_sk"]),
                    t["customer"],
                    ["ws_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Sum(col("ws_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q48_shape(t, run):
    """Store quantity total across demographic/quantity-band slices."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree"))) |
        ((col("cd_marital_status") == lit("D")) &
         (col("cd_education_status") == lit("2 yr Degree"))),
        t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    sales = CpuFilter(
        ((col("ss_quantity") >= lit(1)) &
         (col("ss_quantity") <= lit(40))) |
        ((col("ss_quantity") >= lit(61)) &
         (col("ss_quantity") <= lit(100))), t["store_sales"])
    j = _join(_join(_join(dd, sales,
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("total")], j)


# ---------------------------------------------------------------------------
# multi-channel unions
def q33_shape(t, run):
    """Manufacturer revenue across all three channels for one month
    (reference q33/q56/q60 family)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(3)), t["date_dim"])
    it = CpuFilter(col("i_category") == lit("Books"), t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_manufact_id"),
             col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_manufact_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort([desc(col("total_sales")),
                                  asc(col("i_manufact_id"))], agg))


def q28_shape(t, run):
    """Six price-band averages over store_sales (reference q28's six
    bucket subqueries, united instead of cross-joined)."""
    bands = [(0, 5, 11), (6, 51, 57), (11, 91, 97),
             (16, 131, 137), (21, 171, 177), (26, 100, 200)]
    parts = []
    for i, (qlo, plo, phi) in enumerate(bands):
        f = CpuFilter(
            (col("ss_quantity") >= lit(qlo)) &
            (col("ss_quantity") <= lit(qlo + 4)) &
            (col("ss_list_price") >= lit(float(plo))) &
            (col("ss_list_price") <= lit(float(phi))),
            t["store_sales"])
        agg = CpuAggregate(
            [], [Average(col("ss_list_price")).alias("avg_price"),
                 Count(col("ss_list_price")).alias("cnt")], f)
        parts.append(CpuProject(
            [lit(i).alias("bucket"), col("avg_price"), col("cnt")], agg))
    return CpuSort([asc(col("bucket"))], CpuUnion(*parts))


# ---------------------------------------------------------------------------
# existence tests (semi/anti joins)
def q16_shape(t, run):
    """Catalog orders in a date window with no returns: order count +
    cost sums (reference q16's `not exists` as a LEFT_ANTI join;
    distinct order count as a per-order pre-aggregate)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") <= lit(4)), t["date_dim"])
    sales = _join(dd, t["catalog_sales"],
                  ["d_date_sk"], ["cs_sold_date_sk"])
    no_ret = CpuHashJoin(
        J.LEFT_ANTI, [col("cs_order_number")], [col("cr_order_number")],
        sales, t["catalog_returns"])
    per_order = CpuAggregate(
        [col("cs_order_number")],
        [Sum(col("cs_ext_ship_cost")).alias("ship_cost"),
         Sum(col("cs_net_profit")).alias("net_profit")], no_ret)
    return CpuAggregate(
        [], [Count(None).alias("order_count"),
             Sum(col("ship_cost")).alias("total_shipping_cost"),
             Sum(col("net_profit")).alias("total_net_profit")],
        per_order)


def q37_shape(t, run):
    """Items in a price band with healthy inventory that sold through
    catalog (reference q37: inventory + semi-join on catalog sales)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(20.0)) &
        (col("i_current_price") <= lit(50.0)), t["item"])
    inv = CpuFilter(
        (col("inv_quantity_on_hand") >= lit(100)) &
        (col("inv_quantity_on_hand") <= lit(500)), t["inventory"])
    stocked = _join(it, inv, ["i_item_sk"], ["inv_item_sk"])
    sold = CpuHashJoin(
        J.LEFT_SEMI, [col("i_item_sk")], [col("cs_item_sk")],
        stocked, t["catalog_sales"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_current_price")],
        [Count(None).alias("stock_rows")], sold)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q97(t, run):
    """Customer-item overlap between store and catalog channels
    (reference q97: FULL OUTER join of deduplicated channel pairs)."""
    ssci = CpuAggregate(
        [col("ss_customer_sk"), col("ss_item_sk")],
        [Count(None).alias("_s")], t["store_sales"])
    csci = CpuAggregate(
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        [Count(None).alias("_c")], t["catalog_sales"])
    j = CpuHashJoin(
        J.FULL_OUTER,
        [col("ss_customer_sk"), col("ss_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")], ssci, csci)
    return CpuAggregate(
        [],
        [Sum(If(IsNotNull(col("_s")) & IsNull(col("_c")),
                lit(1), lit(0))).alias("store_only"),
         Sum(If(IsNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("catalog_only"),
         Sum(If(IsNotNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("store_and_catalog")], j)


# ---------------------------------------------------------------------------
# returns netting / outer joins
def q93_shape(t, run):
    """Actual net paid per customer: sold quantity minus returned
    quantity (reference q93's LEFT OUTER store_returns netting)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    paid = CpuProject(
        [col("ss_customer_sk"),
         If(IsNotNull(col("sr_return_quantity")),
            (col("ss_quantity") - col("sr_return_quantity"))
            * col("ss_sales_price"),
            col("ss_quantity") * col("ss_sales_price")).alias("act_sales")],
        j)
    agg = CpuAggregate([col("ss_customer_sk")],
                       [Sum(col("act_sales")).alias("sumsales")], paid)
    return CpuLimit(100, CpuSort(
        [desc(col("sumsales")), asc(col("ss_customer_sk"))], agg))


def q40_shape(t, run):
    """Catalog sales netted against returns by warehouse state, split
    around a pivot date (reference q40's before/after CASE sums)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("cs_order_number"), col("cs_item_sk")],
        [col("cr_order_number"), col("cr_item_sk")],
        t["catalog_sales"], t["catalog_returns"])
    j = _join(_join(j, t["warehouse"],
                    ["cs_warehouse_sk"], ["w_warehouse_sk"]),
              CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
              ["cs_sold_date_sk"], ["d_date_sk"])
    net = col("cs_sales_price") - Coalesce(
        (col("cr_return_amount"), lit(0.0)))
    agg = CpuAggregate(
        [col("w_state")],
        [Sum(If(col("d_moy") < lit(6), net, lit(0.0))).alias(
            "sales_before"),
         Sum(If(col("d_moy") >= lit(6), net, lit(0.0))).alias(
            "sales_after")], j)
    return CpuSort([asc(col("w_state"))], agg)


def q25_shape(t, run):
    """Items sold, returned, then re-bought on catalog (reference q25's
    three-fact join), with profit rollups."""
    ss = _join(CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
               t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
    sr = CpuHashJoin(
        J.INNER,
        [col("ss_customer_sk"), col("ss_item_sk"),
         col("ss_ticket_number")],
        [col("sr_customer_sk"), col("sr_item_sk"),
         col("sr_ticket_number")], ss, t["store_returns"])
    cs = CpuHashJoin(
        J.INNER,
        [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        sr, t["catalog_sales"])
    j = _join(_join(cs, t["store"], ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_store_id")],
        [Sum(col("ss_net_profit")).alias("store_sales_profit"),
         Sum(col("sr_net_loss")).alias("store_returns_loss"),
         Sum(col("cs_net_profit")).alias("catalog_sales_profit")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_store_id"))], agg))


# ---------------------------------------------------------------------------
# shipping-lag bucketing
def _lag_buckets(lag, prefix):
    b = lambda c: Sum(If(c, lit(1), lit(0)))
    return [
        b(lag <= lit(30)).alias(f"{prefix}30_days"),
        b((lag > lit(30)) & (lag <= lit(60))).alias(f"{prefix}60_days"),
        b((lag > lit(60)) & (lag <= lit(90))).alias(f"{prefix}90_days"),
        b(lag > lit(90)).alias(f"{prefix}more_days"),
    ]


def q62_shape(t, run):
    """Web shipping-lag day buckets per warehouse (reference q62)."""
    j = _join(t["web_sales"], t["warehouse"],
              ["ws_warehouse_sk"], ["w_warehouse_sk"])
    lag = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    agg = CpuAggregate([col("w_warehouse_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q99_shape(t, run):
    """Catalog shipping-lag day buckets per warehouse (reference q99)."""
    j = _join(t["catalog_sales"], t["warehouse"],
              ["cs_warehouse_sk"], ["w_warehouse_sk"])
    lag = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    agg = CpuAggregate([col("w_warehouse_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q50_shape(t, run):
    """Store return-lag day buckets per store (reference q50)."""
    j = CpuHashJoin(
        J.INNER,
        [col("ss_item_sk"), col("ss_ticket_number"),
         col("ss_customer_sk")],
        [col("sr_item_sk"), col("sr_ticket_number"),
         col("sr_customer_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    lag = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    agg = CpuAggregate([col("s_store_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("s_store_name"))], agg)


# ---------------------------------------------------------------------------
# pivots, time slots, ratios
def q43_shape(t, run):
    """Day-of-week sales pivot per store (reference q43)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    day = lambda name: Sum(If(col("d_day_name") == lit(name),
                              col("ss_sales_price"), lit(0.0)))
    agg = CpuAggregate(
        [col("s_store_name"), col("s_store_id")],
        [day("Sunday").alias("sun_sales"),
         day("Monday").alias("mon_sales"),
         day("Tuesday").alias("tue_sales"),
         day("Wednesday").alias("wed_sales"),
         day("Thursday").alias("thu_sales"),
         day("Friday").alias("fri_sales"),
         day("Saturday").alias("sat_sales")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("s_store_id"))], agg))


def q88_shape(t, run):
    """Counts of store sales in four afternoon time slots for one
    demographic (reference q88's eight-way self-join, as one pivot)."""
    hd = CpuFilter(col("hd_dep_count") == lit(3),
                   t["household_demographics"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["time_dim"], ["ss_sold_time_sk"], ["t_time_sk"])
    slot = lambda h: Sum(If((col("t_hour") == lit(h)), lit(1), lit(0)))
    return CpuAggregate(
        [], [slot(12).alias("h12"), slot(13).alias("h13"),
             slot(14).alias("h14"), slot(15).alias("h15")], j)


def q90_shape(t, run):
    """Web AM/PM order ratio (reference q90)."""
    j = _join(t["web_sales"], t["time_dim"],
              ["ws_sold_time_sk"], ["t_time_sk"])
    counts = CpuAggregate(
        [], [Sum(If((col("t_hour") >= lit(8)) & (col("t_hour") < lit(12)),
                    lit(1), lit(0))).alias("amc"),
             Sum(If((col("t_hour") >= lit(14)) &
                    (col("t_hour") < lit(18)),
                    lit(1), lit(0))).alias("pmc")], j)
    return CpuProject(
        [col("amc"), col("pmc"),
         (col("amc") / col("pmc")).alias("am_pm_ratio")], counts)


def q61_shape(t, run):
    """Promotional vs total store revenue ratio for one month
    (reference q61's two-aggregate cross join via a key literal)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    base = _join(dd, t["store_sales"],
                 ["d_date_sk"], ["ss_sold_date_sk"])
    promo_rows = _join(base, CpuFilter(
        (col("p_channel_email") == lit("Y")) |
        (col("p_channel_event") == lit("Y")), t["promotion"]),
        ["ss_promo_sk"], ["p_promo_sk"])
    promos = CpuProject(
        [lit(1).alias("k1"),
         col("promotions")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "promotions")], promo_rows))
    total = CpuProject(
        [lit(1).alias("k2"), col("total")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "total")], base))
    j = _join(promos, total, ["k1"], ["k2"])
    return CpuProject(
        [col("promotions"), col("total"),
         (col("promotions") / col("total") * lit(100.0)).alias(
             "promo_pct")], j)


def q79_shape(t, run):
    """Per-ticket profile for large stores and high-dependency
    households (reference q79's q68 sibling)."""
    dd = CpuFilter(col("d_year") == lit(1999), t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(6)) |
                   (col("hd_vehicle_count") > lit(2)),
                   t["household_demographics"])
    st = CpuFilter(col("s_number_employees") >= lit(200), t["store"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("s_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("s_city"), col("ss_ticket_number"),
                    col("amt"), col("profit")], j2)))


def q46_shape(t, run):
    """Per-ticket city/amount profile on weekend days (reference q46)."""
    dd = CpuFilter(InSet(col("d_day_name"), ("Saturday", "Sunday")) &
                   (col("d_year") == lit(1999)), t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
                    st, ["ss_store_sk"], ["s_store_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("ca_city"), col("ss_ticket_number"),
                    col("amt"), col("profit")], j2)))


def q92_shape(t, run):
    """Web sales with discount above 1.3x the item's average discount
    (reference q92's excess-discount correlated subquery)."""
    avg_disc = CpuAggregate(
        [col("ws_item_sk")],
        [Average(col("ws_ext_discount_amt")).alias("avg_disc")],
        t["web_sales"])
    j = _join(t["web_sales"],
              CpuProject([col("ws_item_sk").alias("isk2"),
                          col("avg_disc")], avg_disc),
              ["ws_item_sk"], ["isk2"])
    excess = CpuFilter(
        col("ws_ext_discount_amt") > col("avg_disc") * lit(1.3), j)
    return CpuAggregate(
        [], [Sum(col("ws_ext_discount_amt")).alias("excess_discount")],
        excess)





def q2_shape(t, run):
    """Week-day revenue share, store vs web channels united (reference
    q2's cross-channel weekly comparison)."""
    u = CpuUnion(
        CpuProject([col("ss_sold_date_sk").alias("sold_date_sk"),
                    col("ss_ext_sales_price").alias("price")],
                   t["store_sales"]),
        CpuProject([col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]))
    j = _join(u, t["date_dim"], ["sold_date_sk"], ["d_date_sk"])
    day = lambda n: Sum(If(col("d_day_name") == lit(n), col("price"),
                           lit(0.0)))
    agg = CpuAggregate(
        [col("d_year")],
        [day("Sunday").alias("sun"), day("Monday").alias("mon"),
         day("Friday").alias("fri"), day("Saturday").alias("sat")], j)
    return CpuSort([asc(col("d_year"))], agg)


def q13_shape(t, run):
    """Store averages across demographic/price-band OR-slices
    (reference q13)."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Advanced Degree"))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College"))),
        t["customer_demographics"])
    hd = CpuFilter(InSet(col("hd_dep_count"), (1, 3)),
                   t["household_demographics"])
    j = _join(_join(_join(
        CpuFilter(col("d_year") == lit(2001), t["date_dim"]),
        t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    return CpuAggregate(
        [], [Average(col("ss_quantity")).alias("avg_qty"),
             Average(col("ss_ext_sales_price")).alias("avg_price"),
             Average(col("ss_ext_wholesale_cost")).alias("avg_cost"),
             Sum(col("ss_ext_wholesale_cost")).alias("sum_cost")], j)


def q18_shape(t, run):
    """Catalog purchase averages by customer state for one demographic
    (reference q18 without the rollup)."""
    cd = CpuFilter(col("cd_gender") == lit("F"),
                   t["customer_demographics"])
    j = _join(_join(_join(_join(
        CpuFilter(col("d_year") == lit(2001), t["date_dim"]),
        t["catalog_sales"], ["d_date_sk"], ["cs_sold_date_sk"]),
        cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"]),
        t["customer"], ["cs_bill_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_state")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_sales_price")).alias("agg3"),
         Average(col("cs_net_profit")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q21ds_shape(t, run):
    """Inventory before/after a pivot date for a price band of items
    (reference q21)."""
    it = CpuFilter((col("i_current_price") >= lit(10.0)) &
                   (col("i_current_price") <= lit(60.0)), t["item"])
    j = _join(_join(_join(t["inventory"], it,
                          ["inv_item_sk"], ["i_item_sk"]),
                    t["warehouse"],
                    ["inv_warehouse_sk"], ["w_warehouse_sk"]),
              CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
              ["inv_date_sk"], ["d_date_sk"])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("i_item_id")],
        [Sum(If(col("d_moy") < lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_moy") >= lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    ok = CpuFilter(
        (col("inv_before") > lit(0)) &
        (col("inv_after") * lit(10) >= col("inv_before") * lit(5)) &
        (col("inv_after") * lit(2) <= col("inv_before") * lit(3)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_name")), asc(col("i_item_id"))], ok))


def q32_shape(t, run):
    """Catalog sales with discount above 1.3x the item's average
    (reference q32, q92's catalog twin)."""
    avg_disc = CpuAggregate(
        [col("cs_item_sk")],
        [Average(col("cs_ext_discount_amt")).alias("avg_disc")],
        t["catalog_sales"])
    j = _join(t["catalog_sales"],
              CpuProject([col("cs_item_sk").alias("isk2"),
                          col("avg_disc")], avg_disc),
              ["cs_item_sk"], ["isk2"])
    excess = CpuFilter(
        col("cs_ext_discount_amt") > col("avg_disc") * lit(1.3), j)
    return CpuAggregate(
        [], [Sum(col("cs_ext_discount_amt")).alias("excess_discount")],
        excess)


def q34_shape(t, run):
    """Mid-size-basket customers for given buy potentials (reference
    q34, q73's sibling; its 15-20 basket band is widened to 3-20 for
    the small-scale synthetic data)."""
    hd = CpuFilter(InSet(col("hd_buy_potential"),
                         (">10000", "5001-10000")),
                   t["household_demographics"])
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    band = CpuFilter((col("cnt") >= lit(3)) & (col("cnt") <= lit(20)),
                     per_ticket)
    j2 = _join(band, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), desc(col("cnt")),
         asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt")], j2)))


def q36_shape(t, run):
    """Gross margin ratio by item category (reference q36 without the
    rollup/window rank)."""
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_category")],
        [Sum(col("ss_net_profit")).alias("profit"),
         Sum(col("ss_ext_sales_price")).alias("sales")], j)
    return CpuSort(
        [asc(col("i_category"))],
        CpuProject([col("i_category"),
                    (col("profit") / col("sales")).alias(
                        "gross_margin")], agg))


def q38_shape(t, run):
    """Customers active in all three channels (reference q38's
    intersect, as chained semi joins over deduplicated customers)."""
    ss_c = CpuAggregate([col("ss_customer_sk")],
                        [Count(None).alias("_a")], t["store_sales"])
    in_web = CpuHashJoin(
        J.LEFT_SEMI, [col("ss_customer_sk")],
        [col("ws_bill_customer_sk")], ss_c, t["web_sales"])
    in_all = CpuHashJoin(
        J.LEFT_SEMI, [col("ss_customer_sk")],
        [col("cs_bill_customer_sk")], in_web, t["catalog_sales"])
    return CpuAggregate([], [Count(None).alias("num_customers")],
                        in_all)


def q60_shape(t, run):
    """Per-item revenue across the three channels for one category and
    month (reference q60, q33's by-item sibling)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(9)), t["date_dim"])
    it = CpuFilter(col("i_category") == lit("Music"), t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_item_id"), col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), desc(col("total_sales"))], agg))


def q69_shape(t, run):
    """Demographics of store customers with no web or catalog activity
    in a window (reference q69's exists/not-exists combination)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") <= lit(3)), t["date_dim"])
    store_c = CpuAggregate(
        [col("ss_customer_sk")], [Count(None).alias("_a")],
        _join(dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]))
    web_c = CpuProject(
        [col("ws_bill_customer_sk")],
        _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"]))
    cat_c = CpuProject(
        [col("cs_bill_customer_sk")],
        _join(dd, t["catalog_sales"],
              ["d_date_sk"], ["cs_sold_date_sk"]))
    only_store = CpuHashJoin(
        J.LEFT_ANTI, [col("ss_customer_sk")],
        [col("cs_bill_customer_sk")],
        CpuHashJoin(J.LEFT_ANTI, [col("ss_customer_sk")],
                    [col("ws_bill_customer_sk")], store_c, web_c),
        cat_c)
    j = _join(_join(only_store, t["customer"],
                    ["ss_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_state"))], agg))


def q87_shape(t, run):
    """Store customers absent from the web channel (reference q87's
    EXCEPT, as a LEFT_ANTI join over deduplicated customers)."""
    ss_c = CpuAggregate([col("ss_customer_sk")],
                        [Count(None).alias("_a")], t["store_sales"])
    not_web = CpuHashJoin(
        J.LEFT_ANTI, [col("ss_customer_sk")],
        [col("ws_bill_customer_sk")], ss_c, t["web_sales"])
    return CpuAggregate([], [Count(None).alias("num_customers")],
                        not_web)


def q41_shape(t, run):
    """Distinct item ids in a price/category slice (reference q41's
    item-only filter query)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(30.0)) &
        (col("i_current_price") <= lit(60.0)) &
        InSet(col("i_category"), ("Women", "Shoes", "Jewelry")),
        t["item"])
    dedup = CpuAggregate([col("i_item_id")],
                         [Count(None).alias("_c")], it)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id"))],
        CpuProject([col("i_item_id")], dedup)))







def q63_shape(t, run):
    """Manager monthly sales vs their average month (reference q63/q53's
    windowed deviation filter)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_manager_id"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col("i_manager_id")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        monthly)
    dev = CpuFilter(
        (col("avg_monthly_sales") > lit(0.0)) &
        ((col("sum_sales") > col("avg_monthly_sales") * lit(1.1)) |
         (col("sum_sales") < col("avg_monthly_sales") * lit(0.9))), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manager_id")), asc(col("d_moy"))],
        CpuProject([col("i_manager_id"), col("d_moy"),
                    col("sum_sales"), col("avg_monthly_sales")], dev)))


def q67_shape(t, run):
    """Top-ranked items by revenue within each category (reference
    q67's windowed rank over rollup, without the rollup)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import (CpuWindow, Rank,
                                              WindowSpec)
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    by_item = CpuAggregate(
        [col("i_category"), col("i_item_id")],
        [Sum(col("ss_ext_sales_price")).alias("sales")], j)
    ranked = CpuWindow(
        [Rank().alias("rk")],
        WindowSpec([col("i_category")], [_desc(col("sales"))]),
        by_item)
    top = CpuFilter(col("rk") <= lit(3), ranked)
    return CpuSort(
        [asc(col("i_category")), asc(col("rk")),
         asc(col("i_item_id"))],
        CpuProject([col("i_category"), col("i_item_id"),
                    col("sales"), col("rk")], top))







def q47_shape(t, run):
    """Brand monthly sales vs neighbors and the brand average
    (reference q47/q57: stacked windows — lag/lead over time plus a
    whole-partition average)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, Lag, Lead,
                                              WindowFrame, WindowSpec,
                                              WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_brand"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    with_neighbors = CpuWindow(
        [Lag(col("sum_sales")).alias("psum"),
         Lead(col("sum_sales")).alias("nsum")],
        WindowSpec([col("i_brand")], [_asc(col("d_moy"))]),
        monthly)
    with_avg = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly")],
        WindowSpec([col("i_brand")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        with_neighbors)
    dev = CpuFilter(
        (col("avg_monthly") > lit(0.0)) &
        (col("sum_sales") > col("avg_monthly") * lit(1.5)), with_avg)
    return CpuLimit(100, CpuSort(
        [asc(col("i_brand")), asc(col("d_moy"))],
        CpuProject([col("i_brand"), col("d_moy"), col("sum_sales"),
                    col("psum"), col("nsum"), col("avg_monthly")], dev)))


def q51_shape(t, run):
    """Running cumulative revenue per item over months, web vs store,
    reporting months where the web cumulative overtakes the store one
    (reference q51's full-outer join of windowed cumulatives)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinSum)

    def cum(sales, date_key, item_key, price, prefix):
        monthly = CpuAggregate(
            [col(item_key), col("d_moy")],
            [Sum(col(price)).alias(f"{prefix}_sales")],
            _join(CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
                  t[sales], ["d_date_sk"], [date_key]))
        w = CpuWindow(
            [WinSum(col(f"{prefix}_sales")).alias(f"{prefix}_cum")],
            WindowSpec([col(item_key)], [_asc(col("d_moy"))],
                       WindowFrame(is_rows=True, lower=None, upper=0)),
            monthly)
        return CpuProject(
            [col(item_key).alias(f"{prefix}_item"),
             col("d_moy").alias(f"{prefix}_moy"),
             col(f"{prefix}_cum")], w)

    web = cum("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_ext_sales_price", "web")
    store = cum("store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_ext_sales_price", "store")
    j = CpuHashJoin(
        J.FULL_OUTER, [col("web_item"), col("web_moy")],
        [col("store_item"), col("store_moy")], web, store)
    ahead = CpuFilter(
        IsNotNull(col("web_cum")) & IsNotNull(col("store_cum")) &
        (col("web_cum") > col("store_cum")), j)
    return CpuLimit(100, CpuSort(
        [asc(col("web_item")), asc(col("web_moy"))],
        CpuProject([col("web_item"), col("web_moy"), col("web_cum"),
                    col("store_cum")], ahead)))







def q44_shape(t, run):
    """Best and worst items by average profit via two window ranks
    (reference q44's asc/desc rank pair)."""
    from spark_rapids_tpu.exec.sort import asc as _asc, desc as _desc
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    by_item = CpuAggregate(
        [col("ss_item_sk")],
        [Average(col("ss_net_profit")).alias("avg_profit")],
        t["store_sales"])
    ranked = CpuWindow(
        [Rank().alias("best_rk")],
        WindowSpec([], [_desc(col("avg_profit"))]), by_item)
    ranked = CpuWindow(
        [Rank().alias("worst_rk")],
        WindowSpec([], [_asc(col("avg_profit"))]), ranked)
    top = CpuFilter((col("best_rk") <= lit(10)) |
                    (col("worst_rk") <= lit(10)), ranked)
    j = _join(top, t["item"], ["ss_item_sk"], ["i_item_sk"])
    return CpuSort(
        [asc(col("best_rk")), asc(col("worst_rk")),
         asc(col("i_item_id"))],
        CpuProject([col("i_item_id"), col("avg_profit"),
                    col("best_rk"), col("worst_rk")], j))


def q58_shape(t, run):
    """Items whose revenue is roughly equal across all three channels
    (reference q58's three-way join with ratio bands)."""
    def chan(sales, item_key, price, name):
        agg = CpuAggregate(
            [col(item_key)], [Sum(col(price)).alias(name)], t[sales])
        return CpuProject(
            [col(item_key).alias(f"{name}_item"), col(name)], agg)

    ss = chan("store_sales", "ss_item_sk", "ss_ext_sales_price",
              "ss_rev")
    cs = chan("catalog_sales", "cs_item_sk", "cs_ext_sales_price",
              "cs_rev")
    ws = chan("web_sales", "ws_item_sk", "ws_ext_sales_price", "ws_rev")
    j = _join(_join(ss, cs, ["ss_rev_item"], ["cs_rev_item"]),
              ws, ["ss_rev_item"], ["ws_rev_item"])
    avg3 = (col("ss_rev") + col("cs_rev") + col("ws_rev")) / lit(3.0)
    close = CpuFilter(
        (col("ss_rev") >= avg3 * lit(0.6)) &
        (col("ss_rev") <= avg3 * lit(1.4)) &
        (col("cs_rev") >= avg3 * lit(0.6)) &
        (col("cs_rev") <= avg3 * lit(1.4)) &
        (col("ws_rev") >= avg3 * lit(0.6)) &
        (col("ws_rev") <= avg3 * lit(1.4)), j)
    return CpuLimit(100, CpuSort(
        [asc(col("ss_rev_item"))],
        CpuProject([col("ss_rev_item"), col("ss_rev"), col("cs_rev"),
                    col("ws_rev")], close)))


def q59_shape(t, run):
    """Week-day store revenue pivot compared year over year (reference
    q59's self-join of weekly pivots)."""
    def pivot(year, suffix):
        j = _join(CpuFilter(col("d_year") == lit(year), t["date_dim"]),
                  t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
        day = lambda n: Sum(If(col("d_day_name") == lit(n),
                               col("ss_sales_price"), lit(0.0)))
        agg = CpuAggregate(
            [col("ss_store_sk")],
            [day("Sunday").alias(f"sun{suffix}"),
             day("Wednesday").alias(f"wed{suffix}"),
             day("Saturday").alias(f"sat{suffix}")], j)
        return CpuProject(
            [col("ss_store_sk").alias(f"store{suffix}"),
             col(f"sun{suffix}"), col(f"wed{suffix}"),
             col(f"sat{suffix}")], agg)

    y1 = pivot(2000, "1")
    y2 = pivot(2001, "2")
    j = _join(y1, y2, ["store1"], ["store2"])
    safe = CpuFilter((col("sun2") > lit(0.0)) &
                     (col("wed2") > lit(0.0)) &
                     (col("sat2") > lit(0.0)), j)
    return CpuSort(
        [asc(col("store1"))],
        CpuProject([col("store1"),
                    (col("sun1") / col("sun2")).alias("sun_ratio"),
                    (col("wed1") / col("wed2")).alias("wed_ratio"),
                    (col("sat1") / col("sat2")).alias("sat_ratio")],
                   safe))


def q66_shape(t, run):
    """Warehouse monthly revenue pivot, web + catalog united
    (reference q66's 12-month If-sum pivot)."""
    u = CpuUnion(
        CpuProject([col("ws_warehouse_sk").alias("wh"),
                    col("ws_sold_date_sk").alias("sold"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]),
        CpuProject([col("cs_warehouse_sk").alias("wh"),
                    col("cs_sold_date_sk").alias("sold"),
                    col("cs_ext_sales_price").alias("price")],
                   t["catalog_sales"]))
    j = _join(_join(u, CpuFilter(col("d_year") == lit(2001),
                                 t["date_dim"]),
                    ["sold"], ["d_date_sk"]),
              t["warehouse"], ["wh"], ["w_warehouse_sk"])
    mo = lambda m: Sum(If(col("d_moy") == lit(m), col("price"),
                          lit(0.0)))
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("w_warehouse_sq_ft")],
        [mo(m).alias(f"m{m:02d}_sales") for m in range(1, 13)], j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q70_shape(t, run):
    """States ranked by store profit, top 5 (reference q70's windowed
    state rank without the rollup)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    by_state = CpuAggregate(
        [col("s_state")],
        [Sum(col("ss_net_profit")).alias("total_profit")], j)
    ranked = CpuWindow([Rank().alias("rk")],
                       WindowSpec([], [_desc(col("total_profit"))]),
                       by_state)
    return CpuSort(
        [asc(col("rk")), asc(col("s_state"))],
        CpuFilter(col("rk") <= lit(5), ranked))


def q75_shape(t, run):
    """Year-over-year quantity change per category across all channels
    (reference q75's union + prior-year self-join)."""
    def year_qty(year):
        u = CpuUnion(
            CpuProject([col("ss_sold_date_sk").alias("sold"),
                        col("ss_item_sk").alias("it"),
                        col("ss_quantity").alias("qty")],
                       t["store_sales"]),
            CpuProject([col("cs_sold_date_sk").alias("sold"),
                        col("cs_item_sk").alias("it"),
                        col("cs_quantity").alias("qty")],
                       t["catalog_sales"]),
            CpuProject([col("ws_sold_date_sk").alias("sold"),
                        col("ws_item_sk").alias("it"),
                        col("ws_quantity").alias("qty")],
                       t["web_sales"]))
        j = _join(_join(u, CpuFilter(col("d_year") == lit(year),
                                     t["date_dim"]),
                        ["sold"], ["d_date_sk"]),
                  t["item"], ["it"], ["i_item_sk"])
        return CpuAggregate([col("i_category_id")],
                            [Sum(col("qty")).alias(f"qty_{year}")], j)

    cur = year_qty(2001)
    prev = CpuProject([col("i_category_id").alias("cat_prev"),
                       col("qty_2000")], year_qty(2000))
    j = _join(cur, prev, ["i_category_id"], ["cat_prev"])
    decline = CpuFilter(
        (col("qty_2000") > lit(0)) &
        (col("qty_2001") < col("qty_2000")), j)
    return CpuSort(
        [asc(col("i_category_id"))],
        CpuProject([col("i_category_id"), col("qty_2000"),
                    col("qty_2001")], decline))


def q77_shape(t, run):
    """Profit and returns per channel, united into one report
    (reference q77's channel union with loss netting)."""
    def channel(name, sales_profit, returns_amt):
        return CpuProject(
            [lit(name).alias("channel"), col("profit"),
             col("returns_amt")],
            _join(sales_profit, returns_amt, ["k1"], ["k2"]))

    def one_row(node, alias_, key):
        return CpuProject(
            [lit(1).alias(key), col(alias_)],
            node)

    ss = one_row(CpuAggregate(
        [], [Sum(col("ss_net_profit")).alias("profit")],
        t["store_sales"]), "profit", "k1")
    sr = one_row(CpuAggregate(
        [], [Sum(col("sr_return_amt")).alias("returns_amt")],
        t["store_returns"]), "returns_amt", "k2")
    cs = one_row(CpuAggregate(
        [], [Sum(col("cs_net_profit")).alias("profit")],
        t["catalog_sales"]), "profit", "k1")
    cr = one_row(CpuAggregate(
        [], [Sum(col("cr_return_amount")).alias("returns_amt")],
        t["catalog_returns"]), "returns_amt", "k2")
    ws = one_row(CpuAggregate(
        [], [Sum(col("ws_net_profit")).alias("profit")],
        t["web_sales"]), "profit", "k1")
    wr = one_row(CpuAggregate(
        [], [Sum(col("wr_return_amt")).alias("returns_amt")],
        t["web_returns"]), "returns_amt", "k2")
    u = CpuUnion(channel("store", ss, sr),
                 channel("catalog", cs, cr),
                 channel("web", ws, wr))
    return CpuSort([asc(col("channel"))], u)


def q80_shape(t, run):
    """Per-store revenue net of returns with promo split (reference
    q80's store-channel report)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    net = col("ss_ext_sales_price") - Coalesce(
        (col("sr_return_amt"), lit(0.0)))
    agg = CpuAggregate(
        [col("s_store_id")],
        [Sum(net).alias("sales_net"),
         Sum(Coalesce((col("sr_return_amt"), lit(0.0)))).alias(
             "returns_amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    return CpuSort([asc(col("s_store_id"))], agg)







def q8_shape(t, run):
    """Store revenue limited to customer states with enough customers
    (reference q8's zip-list filter, by state)."""
    by_state = CpuAggregate(
        [col("ca_state")], [Count(None).alias("n_cust")],
        t["customer_address"])
    big = CpuFilter(col("n_cust") >= lit(10), by_state)
    j = _join(_join(_join(
        CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
        t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["customer"], ["ss_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"])
    j = CpuHashJoin(J.LEFT_SEMI, [col("ca_state")], [col("ca_state")],
                    j, CpuProject([col("ca_state")], big))
    agg = CpuAggregate(
        [col("ca_state")],
        [Sum(col("ss_net_profit")).alias("net_profit")], j)
    return CpuSort([asc(col("ca_state"))], agg)


def q10_shape(t, run):
    """Demographics of customers active in web or catalog (reference
    q10's exists-any-channel, as a semi join over a union)."""
    active = CpuUnion(
        CpuProject([col("ws_bill_customer_sk").alias("cust")],
                   t["web_sales"]),
        CpuProject([col("cs_bill_customer_sk").alias("cust")],
                   t["catalog_sales"]))
    store = _join(t["store_sales"], t["customer_demographics"],
                  ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = CpuHashJoin(J.LEFT_SEMI, [col("ss_customer_sk")], [col("cust")],
                    store, active)
    agg = CpuAggregate(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status")],
        [Count(None).alias("cnt")], j)
    return CpuSort(
        [asc(col("cd_gender")), asc(col("cd_marital_status")),
         asc(col("cd_education_status"))], agg)


def q23_shape(t, run):
    """Catalog revenue from frequent store items bought by the best
    store customers (reference q23's two semi-join subqueries)."""
    freq_items = CpuFilter(
        col("n_sold") >= lit(8),
        CpuAggregate([col("ss_item_sk")],
                     [Count(None).alias("n_sold")], t["store_sales"]))
    spend = CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_net_paid")).alias("spend")], t["store_sales"])
    avg_spend = CpuProject(
        [lit(1).alias("k"), col("avg_spend")],
        CpuAggregate([], [Average(col("spend")).alias("avg_spend")],
                     CpuProject([col("spend")], spend)))
    best = CpuFilter(
        col("spend") > col("avg_spend") * lit(1.2),
        _join(CpuProject([col("ss_customer_sk"), col("spend"),
                          lit(1).alias("k2")], spend),
              avg_spend, ["k2"], ["k"]))
    cs = CpuHashJoin(
        J.LEFT_SEMI, [col("cs_item_sk")], [col("ss_item_sk")],
        t["catalog_sales"],
        CpuProject([col("ss_item_sk")], freq_items))
    cs = CpuHashJoin(
        J.LEFT_SEMI, [col("cs_bill_customer_sk")],
        [col("ss_customer_sk")], cs,
        CpuProject([col("ss_customer_sk")], best))
    return CpuAggregate(
        [], [Sum(col("cs_ext_sales_price")).alias("sales")], cs)


def q30_shape(t, run):
    """Customers whose web-return total exceeds 1.2x their state's
    average (reference q30, q1's web twin)."""
    ctr = CpuAggregate(
        [col("wr_returning_customer_sk")],
        [Sum(col("wr_return_amt")).alias("ctr_total")],
        t["web_returns"])
    j = _join(_join(ctr, t["customer"],
                    ["wr_returning_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    avg_state = CpuAggregate(
        [col("ca_state")],
        [Average(col("ctr_total")).alias("avg_ret")],
        CpuProject([col("ca_state"), col("ctr_total")], j))
    big = CpuFilter(
        col("ctr_total") > col("avg_ret") * lit(1.2),
        _join(j, CpuProject([col("ca_state").alias("st2"),
                             col("avg_ret")], avg_state),
              ["ca_state"], ["st2"]))
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id"), col("ca_state"),
                    col("ctr_total")], big)))


def q31_shape(t, run):
    """States where web revenue grew faster than store revenue between
    quarters (reference q31's growth-ratio comparison)."""
    def qrev(sales, date_key, cust_key, price, qoy, name):
        j = _join(_join(_join(
            CpuFilter((col("d_year") == lit(2000)) &
                      (col("d_qoy") == lit(qoy)), t["date_dim"]),
            t[sales], ["d_date_sk"], [date_key]),
            t["customer"], [cust_key], ["c_customer_sk"]),
            t["customer_address"],
            ["c_current_addr_sk"], ["ca_address_sk"])
        agg = CpuAggregate([col("ca_state")],
                           [Sum(col(price)).alias(name)], j)
        return CpuProject(
            [col("ca_state").alias(f"{name}_state"), col(name)], agg)

    ss1 = qrev("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_ext_sales_price", 1, "ss1")
    ss2 = qrev("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_ext_sales_price", 2, "ss2")
    ws1 = qrev("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_sales_price", 1, "ws1")
    ws2 = qrev("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_sales_price", 2, "ws2")
    j = _join(_join(_join(ss1, ss2, ["ss1_state"], ["ss2_state"]),
                    ws1, ["ss1_state"], ["ws1_state"]),
              ws2, ["ss1_state"], ["ws2_state"])
    grew = CpuFilter(
        (col("ss1") > lit(0.0)) & (col("ws1") > lit(0.0)) &
        (col("ws2") * col("ss1") > col("ss2") * col("ws1")), j)
    return CpuSort(
        [asc(col("ss1_state"))],
        CpuProject([col("ss1_state"), col("ss1"), col("ss2"),
                    col("ws1"), col("ws2")], grew))


def q71_shape(t, run):
    """Brand revenue by hour band across all channels for one month
    (reference q71's time-of-day breakdown)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    u = CpuUnion(
        CpuProject([col("ss_sold_date_sk").alias("sold"),
                    col("ss_sold_time_sk").alias("tsk"),
                    col("ss_item_sk").alias("it"),
                    col("ss_ext_sales_price").alias("price")],
                   t["store_sales"]),
        CpuProject([col("cs_sold_date_sk").alias("sold"),
                    col("cs_sold_time_sk").alias("tsk"),
                    col("cs_item_sk").alias("it"),
                    col("cs_ext_sales_price").alias("price")],
                   t["catalog_sales"]),
        CpuProject([col("ws_sold_date_sk").alias("sold"),
                    col("ws_sold_time_sk").alias("tsk"),
                    col("ws_item_sk").alias("it"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]))
    j = _join(_join(_join(u, dd, ["sold"], ["d_date_sk"]),
                    t["item"], ["it"], ["i_item_sk"]),
              t["time_dim"], ["tsk"], ["t_time_sk"])
    agg = CpuAggregate(
        [col("i_brand_id")],
        [Sum(If((col("t_hour") >= lit(8)) & (col("t_hour") < lit(12)),
                col("price"), lit(0.0))).alias("morning"),
         Sum(If((col("t_hour") >= lit(12)) & (col("t_hour") < lit(18)),
                col("price"), lit(0.0))).alias("afternoon"),
         Sum(If((col("t_hour") >= lit(18)),
                col("price"), lit(0.0))).alias("evening")], j)
    return CpuSort([asc(col("i_brand_id"))], agg)


def q82_shape(t, run):
    """Items in a price band with healthy inventory sold in stores
    (reference q82, q37's store twin)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(30.0)) &
        (col("i_current_price") <= lit(70.0)), t["item"])
    inv = CpuFilter(
        (col("inv_quantity_on_hand") >= lit(100)) &
        (col("inv_quantity_on_hand") <= lit(500)), t["inventory"])
    stocked = _join(it, inv, ["i_item_sk"], ["inv_item_sk"])
    sold = CpuHashJoin(
        J.LEFT_SEMI, [col("i_item_sk")], [col("ss_item_sk")],
        stocked, t["store_sales"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_current_price")],
        [Count(None).alias("stock_rows")], sold)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q94_shape(t, run):
    """Web orders in a window with no returns: order count + cost sums
    (reference q94, q16's web twin)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") <= lit(4)), t["date_dim"])
    sales = _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"])
    no_ret = CpuHashJoin(
        J.LEFT_ANTI, [col("ws_order_number")], [col("wr_order_number")],
        sales, t["web_returns"])
    per_order = CpuAggregate(
        [col("ws_order_number")],
        [Sum(col("ws_ext_ship_cost")).alias("ship_cost"),
         Sum(col("ws_net_profit")).alias("net_profit")], no_ret)
    return CpuAggregate(
        [], [Count(None).alias("order_count"),
             Sum(col("ship_cost")).alias("total_shipping_cost"),
             Sum(col("net_profit")).alias("total_net_profit")],
        per_order)





QUERIES = {
    "q1": q1, "q2": q2_shape, "q3": q3, "q6": q6_shape, "q7": q7_shape,
    "q8": q8_shape, "q10": q10_shape, "q23": q23_shape,
    "q30": q30_shape, "q31": q31_shape, "q71": q71_shape,
    "q82": q82_shape, "q94": q94_shape,
    "q13": q13_shape, "q18": q18_shape, "q21": q21ds_shape,
    "q32": q32_shape, "q34": q34_shape, "q36": q36_shape,
    "q38": q38_shape, "q41": q41_shape, "q60": q60_shape,
    "q44": q44_shape, "q47": q47_shape, "q51": q51_shape,
    "q58": q58_shape, "q59": q59_shape, "q66": q66_shape,
    "q70": q70_shape, "q75": q75_shape, "q77": q77_shape,
    "q80": q80_shape,
    "q63": q63_shape, "q67": q67_shape,
    "q69": q69_shape, "q87": q87_shape,
    "q15": q15_shape, "q16": q16_shape, "q19": q19, "q25": q25_shape,
    "q26": q26, "q27": q27_shape, "q28": q28_shape, "q33": q33_shape,
    "q37": q37_shape, "q40": q40_shape, "q42": q42, "q43": q43_shape,
    "q45": q45_shape, "q46": q46_shape, "q48": q48_shape,
    "q50": q50_shape, "q52": q52, "q55": q55, "q61": q61_shape,
    "q62": q62_shape, "q65": q65, "q68": q68, "q73": q73,
    "q79": q79_shape, "q88": q88_shape, "q90": q90_shape,
    "q92": q92_shape, "q93": q93_shape, "q96": q96, "q97": q97,
    "q98": q98_shape, "q99": q99_shape,
}


# ---------------------------------------------------------------------------
# round-2 growth toward the reference's 103 (TpcdsLikeSpark.scala:709+):
# year-over-year ratio family (q4/q11/q74), ROLLUP grouping-sets through
# CpuExpand (q5/q22/q86), channel unions (q56/q76), windowed deviation
# reports (q53/q57/q89), returns chains (q17/q24/q29/q49/q78/q81/q83/q85),
# inventory (q39/q72), existence/self-join shapes (q14/q35/q95).
from spark_rapids_tpu import types as _T
from spark_rapids_tpu.exprs.base import Literal as _Lit
from spark_rapids_tpu.plan.nodes import CpuExpand as _CpuExpand


def _rollup_expand(child, keys, passthrough):
    """Spark ROLLUP(keys...) lowering: CpuExpand with one projection per
    key prefix plus the grand total, carrying a grouping id — the exact
    shape Spark's planner feeds ExpandExec (reference GpuExpandExec)."""
    cs = child.output_schema()
    n = len(keys)
    projs = []
    for level in range(n, -1, -1):
        proj = [col(k) if i < level else _Lit(None, cs.field(k).dtype)
                for i, k in enumerate(keys)]
        proj.append(_Lit((1 << (n - level)) - 1, _T.INT32))
        proj.extend(col(p) for p in passthrough)
        projs.append(proj)
    names = list(keys) + ["gid"] + list(passthrough)
    return _CpuExpand(projs, names, child)


def _yoy_growth(t, sales, date_key, cust_key, val, year1=1999):
    """Per-customer totals for two consecutive years, joined: the
    q4/q11/q74 year-over-year scaffold."""
    def year_total(y, alias):
        dd = CpuFilter(col("d_year") == lit(y), t["date_dim"])
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  t["customer"], [cust_key], ["c_customer_sk"])
        return CpuAggregate([col("c_customer_id")],
                            [Sum(col(val)).alias(alias)], j)
    y1 = year_total(year1, "total1")
    y2 = CpuProject([col("c_customer_id").alias("cid2"),
                     col("total2")],
                    year_total(year1 + 1, "total2"))
    j = _join(CpuFilter(col("total1") > lit(0.0), y1), y2,
              ["c_customer_id"], ["cid2"])
    return CpuProject([col("c_customer_id"),
                       (col("total2") / col("total1")).alias("growth")], j)


def q4_shape(t, run):
    """Customers whose catalog growth beats their store growth
    (reference q4's 3-channel year-over-year self-joins, 2 channels in
    the v0 shape)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_net_paid")
    cs = CpuProject([col("c_customer_id").alias("ccid"),
                     col("growth").alias("c_growth")],
                    _yoy_growth(t, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk", "cs_net_paid"))
    j = _join(ss, cs, ["c_customer_id"], ["ccid"])
    keep = CpuFilter(col("c_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q11_shape(t, run):
    """Web growth beats store growth (reference q11)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_ext_list_price")
    ws = CpuProject([col("c_customer_id").alias("wcid"),
                     col("growth").alias("w_growth")],
                    _yoy_growth(t, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk",
                                "ws_ext_list_price"))
    j = _join(ss, ws, ["c_customer_id"], ["wcid"])
    keep = CpuFilter(col("w_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q74_shape(t, run):
    """q11's sibling over net_paid sums (reference q74)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_net_paid", year1=2000)
    ws = CpuProject([col("c_customer_id").alias("wcid"),
                     col("growth").alias("w_growth")],
                    _yoy_growth(t, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk", "ws_net_paid",
                                year1=2000))
    j = _join(ss, ws, ["c_customer_id"], ["wcid"])
    keep = CpuFilter(col("w_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q5_shape(t, run):
    """Per-channel sales/returns/profit report with ROLLUP(channel, id)
    through CpuExpand (reference q5)."""
    def channel(label, sales, skey, sval, sprofit, rets, rkey, rval):
        s = CpuProject([lit(label).alias("channel"),
                        col(skey).alias("id"),
                        col(sval).alias("sales"),
                        lit(0.0).alias("returns_amt"),
                        col(sprofit).alias("profit")], t[sales])
        r = CpuProject([lit(label).alias("channel"),
                        col(rkey).alias("id"),
                        lit(0.0).alias("sales"),
                        col(rval).alias("returns_amt"),
                        lit(0.0).alias("profit")], t[rets])
        return CpuUnion(s, r)

    u = CpuUnion(
        channel("store channel", "store_sales", "ss_store_sk",
                "ss_ext_sales_price", "ss_net_profit",
                "store_returns", "sr_store_sk", "sr_return_amt"),
        channel("catalog channel", "catalog_sales", "cs_item_sk",
                "cs_ext_sales_price", "cs_net_profit",
                "catalog_returns", "cr_item_sk", "cr_return_amount"),
        channel("web channel", "web_sales", "ws_web_site_sk",
                "ws_ext_sales_price", "ws_net_profit",
                "web_returns", "wr_item_sk", "wr_return_amt"))
    ex = _rollup_expand(u, ["channel", "id"],
                        ["sales", "returns_amt", "profit"])
    agg = CpuAggregate(
        [col("channel"), col("id"), col("gid")],
        [Sum(col("sales")).alias("sales"),
         Sum(col("returns_amt")).alias("returns_amt"),
         Sum(col("profit")).alias("profit")], ex)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("id")), asc(col("gid"))], agg))


def q22_rollup(t, run):
    """Inventory average quantity on hand, ROLLUP(category, brand) — a
    true grouping-sets plan through CpuExpand (reference q22)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
              t["item"], ["inv_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"],
                        ["inv_quantity_on_hand"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Average(col("inv_quantity_on_hand")).alias("qoh")], ex)
    return CpuLimit(100, CpuSort(
        [asc(col("qoh")), asc(col("i_category")), asc(col("i_brand")),
         asc(col("gid"))], agg))


def q86_rollup(t, run):
    """Web revenue ROLLUP(category, brand) report (reference q86 uses
    category/class; the v0 item schema carries brand)."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(dd, t["web_sales"], ["d_date_sk"],
                    ["ws_sold_date_sk"]),
              t["item"], ["ws_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"], ["ws_net_paid"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Sum(col("ws_net_paid")).alias("total_sum")], ex)
    return CpuLimit(100, CpuSort(
        [desc(col("total_sum")), asc(col("i_category")),
         asc(col("i_brand")), asc(col("gid"))], agg))


def q9_shape(t, run):
    """Quantity-range bucket statistics as one reduction over
    store_sales (reference q9's CASE WHEN scalar subqueries)."""
    ss = t["store_sales"]
    aggs = []
    for i, (lo, hi) in enumerate(((1, 10), (11, 20), (21, 30),
                                  (31, 40), (41, 50))):
        inb = (col("ss_quantity") >= lit(lo)) & \
            (col("ss_quantity") <= lit(hi))
        aggs.append(Sum(If(inb, lit(1), lit(0))).alias(f"cnt_{i}"))
        aggs.append(Sum(If(inb, col("ss_ext_discount_amt"),
                           lit(0.0))).alias(f"disc_{i}"))
    return CpuAggregate([], aggs, ss)


def _cat_ratio(t, sales, date_key, item_key, price, year, moy):
    """q12/q20/q98 scaffold: item revenue + windowed share of its
    category's revenue."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinSum)
    dd = CpuFilter((col("d_year") == lit(year)) &
                   (col("d_moy") == lit(moy)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Books", "Music", "Home")), t["item"])
    j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
              it, [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category")],
        [Sum(col(price)).alias("itemrevenue")], j)
    w = CpuWindow(
        [WinSum(col("itemrevenue")).alias("cat_rev")],
        WindowSpec([col("i_category")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    share = CpuProject(
        [col("i_item_id"), col("i_category"), col("itemrevenue"),
         (col("itemrevenue") * lit(100.0) / col("cat_rev"))
         .alias("revenueratio")], w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_category")), asc(col("i_item_id")),
         asc(col("revenueratio"))], share))


def q12_shape(t, run):
    return _cat_ratio(t, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                      "ws_ext_sales_price", 1999, 2)


def q20_shape(t, run):
    return _cat_ratio(t, "catalog_sales", "cs_sold_date_sk",
                      "cs_item_sk", "cs_ext_sales_price", 2000, 3)


def q14_shape(t, run):
    """Items selling in ALL three channels: chained semi joins, then a
    brand revenue report (reference q14's cross-channel intersection)."""
    it = t["item"]
    in_ss = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                        [col("ss_item_sk")], it, t["store_sales"])
    in_cs = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                        [col("cs_item_sk")], in_ss, t["catalog_sales"])
    in_all = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                         [col("ws_item_sk")], in_cs, t["web_sales"])
    j = _join(in_all, t["store_sales"], ["i_item_sk"], ["ss_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_category_id")],
        [Sum(col("ss_ext_sales_price")).alias("sales"),
         Count(col("ss_ext_sales_price")).alias("number_sales")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("sales")), asc(col("i_brand_id")),
         asc(col("i_category_id"))], agg))


def q17_shape(t, run):
    """Store sale -> return -> catalog repurchase chain: per-item
    quantity statistics (reference q17; stddev reduced to avg/min/max,
    outside the v0 aggregate set like the reference's own gates)."""
    from spark_rapids_tpu.exprs.aggregates import Max, Min
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    chain = CpuHashJoin(
        J.INNER, [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        ssr, t["catalog_sales"])
    j = _join(chain, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Count(col("ss_quantity")).alias("store_sales_cnt"),
         Average(col("ss_quantity")).alias("store_sales_avg"),
         Min(col("sr_return_quantity")).alias("ret_min"),
         Max(col("cs_quantity")).alias("cat_max")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q29_shape(t, run):
    """q17's quantity-sum sibling (reference q29)."""
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    chain = CpuHashJoin(
        J.INNER, [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        ssr, t["catalog_sales"])
    j = _join(chain, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_brand")],
        [Sum(col("ss_quantity")).alias("store_qty"),
         Sum(col("sr_return_quantity")).alias("return_qty"),
         Sum(col("cs_quantity")).alias("catalog_qty")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_brand"))], agg))


def q24_shape(t, run):
    """Returned-ticket net paid by customer/store/brand, kept when above
    5% of the overall average (reference q24's HAVING-over-subquery via
    an unpartitioned window average)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["store"], ["ss_store_sk"],
                          ["s_store_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    agg = CpuAggregate(
        [col("c_last_name"), col("s_store_name"), col("i_brand")],
        [Sum(col("ss_net_paid")).alias("netpaid")], j)
    w = CpuWindow(
        [WinAvg(col("netpaid")).alias("avg_netpaid")],
        WindowSpec([], [], WindowFrame(is_rows=True, lower=None,
                                       upper=None)), agg)
    keep = CpuFilter(col("netpaid") > col("avg_netpaid") * lit(0.05), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("s_store_name")),
         asc(col("i_brand"))],
        CpuProject([col("c_last_name"), col("s_store_name"),
                    col("i_brand"), col("netpaid")], keep)))


def q35_shape(t, run):
    """Customer-demographic profile of store customers who also bought
    through catalog or web (reference q35's EXISTS shapes as semi
    joins)."""
    cust = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                       [col("ss_customer_sk")], t["customer"],
                       t["store_sales"])
    cs_side = CpuProject([col("cs_bill_customer_sk").alias("buyer")],
                         t["catalog_sales"])
    ws_side = CpuProject([col("ws_bill_customer_sk").alias("buyer")],
                         t["web_sales"])
    cust2 = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                        [col("buyer")], cust,
                        CpuUnion(cs_side, ws_side))
    j = _join(cust2, t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_state")],
        [Count(col("c_customer_sk")).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_state"))], agg))


def q39_shape(t, run):
    """Inventory monthly mean by warehouse/item, self-joined on the next
    month (reference q39's consecutive-month covariance pairs; variance
    reduced to avg like the reference's own gating of unsupported
    aggs)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
              t["warehouse"], ["inv_warehouse_sk"], ["w_warehouse_sk"])
    monthly = CpuAggregate(
        [col("w_warehouse_sk"), col("inv_item_sk"), col("d_moy")],
        [Average(col("inv_quantity_on_hand")).alias("qoh")], j)
    m1 = CpuProject([col("w_warehouse_sk"), col("inv_item_sk"),
                     (col("d_moy") + lit(1)).alias("next_moy"),
                     col("qoh").alias("qoh1")], monthly)
    m2 = CpuProject([col("w_warehouse_sk").alias("w2"),
                     col("inv_item_sk").alias("i2"),
                     col("d_moy").alias("moy2"),
                     col("qoh").alias("qoh2")], monthly)
    pair = CpuHashJoin(
        J.INNER, [col("w_warehouse_sk"), col("inv_item_sk"),
                  col("next_moy")],
        [col("w2"), col("i2"), col("moy2")], m1, m2)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_sk")), asc(col("inv_item_sk")),
         asc(col("next_moy"))],
        CpuProject([col("w_warehouse_sk"), col("inv_item_sk"),
                    col("next_moy"), col("qoh1"), col("qoh2")], pair)))


def q49_shape(t, run):
    """Per-channel return ratios with a rank window, worst offenders
    first (reference q49's three ranked channel blocks)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import (CpuWindow, Rank,
                                              WindowSpec)

    def channel(label, sales, skey_o, skey_i, qty, rets, rkey_o,
                rkey_i, rqty):
        j = CpuHashJoin(
            J.INNER, [col(skey_o), col(skey_i)],
            [col(rkey_o), col(rkey_i)], t[sales], t[rets])
        agg = CpuAggregate(
            [col(skey_i)],
            [Sum(col(rqty)).alias("ret"), Sum(col(qty)).alias("sold")], j)
        ratio = CpuProject(
            [lit(label).alias("channel"), col(skey_i).alias("item"),
             (col("ret") / col("sold")).alias("return_ratio")],
            CpuFilter(col("sold") > lit(0), agg))
        ranked = CpuWindow(
            [Rank().alias("return_rank")],
            WindowSpec([], [_desc(col("return_ratio"))]), ratio)
        return CpuFilter(col("return_rank") <= lit(10), ranked)

    u = CpuUnion(
        channel("web", "web_sales", "ws_order_number", "ws_item_sk",
                "ws_quantity", "web_returns", "wr_order_number",
                "wr_item_sk", "wr_return_quantity"),
        channel("catalog", "catalog_sales", "cs_order_number",
                "cs_item_sk", "cs_quantity", "catalog_returns",
                "cr_order_number", "cr_item_sk", "cr_return_quantity"),
        channel("store", "store_sales", "ss_ticket_number",
                "ss_item_sk", "ss_quantity", "store_returns",
                "sr_ticket_number", "sr_item_sk", "sr_return_quantity"))
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("return_rank")),
         asc(col("item"))], u))


def q53_shape(t, run):
    """Manufacturer quarterly revenue vs its own average (reference
    q53/q63 family; q63 already covers the monthly variant)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_manufact_id"), col("d_qoy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_quarterly")],
        WindowSpec([col("i_manufact_id")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    from spark_rapids_tpu.exprs.arithmetic import Abs as _Abs
    keep = CpuFilter(
        (col("avg_quarterly") > lit(0.0)) &
        (_Abs(col("sum_sales") - col("avg_quarterly")) /
         col("avg_quarterly") > lit(0.1)), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manufact_id")), asc(col("d_qoy"))],
        CpuProject([col("i_manufact_id"), col("d_qoy"),
                    col("sum_sales"), col("avg_quarterly")], keep)))


def _cast_i64(e):
    from spark_rapids_tpu.exprs.cast import Cast
    return Cast(e, _T.INT64)


def q54_shape(t, run):
    """Revenue buckets of customers who bought a target category through
    catalog or web (reference q54's cohort + bucketed histogram)."""
    it = CpuFilter(col("i_category") == lit("Books"), t["item"])
    cs_b = CpuProject([col("cs_bill_customer_sk").alias("buyer")],
                      _join(it, t["catalog_sales"], ["i_item_sk"],
                            ["cs_item_sk"]))
    ws_b = CpuProject([col("ws_bill_customer_sk").alias("buyer")],
                      _join(it, t["web_sales"], ["i_item_sk"],
                            ["ws_item_sk"]))
    cohort = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                         [col("buyer")], t["customer"],
                         CpuUnion(cs_b, ws_b))
    rev = CpuAggregate(
        [col("c_customer_sk")],
        [Sum(col("ss_ext_sales_price")).alias("revenue")],
        _join(cohort, t["store_sales"], ["c_customer_sk"],
              ["ss_customer_sk"]))
    bucket = CpuProject(
        [_cast_i64(col("revenue") / lit(50.0)).alias("segment")], rev)
    agg = CpuAggregate([col("segment")],
                       [Count(col("segment")).alias("num_customers")],
                       bucket)
    return CpuLimit(100, CpuSort(
        [asc(col("segment")), asc(col("num_customers"))], agg))


def q56_shape(t, run):
    """Per-item revenue across the three channels for address-filtered
    sales (reference q56, the q33/q60 sibling keyed by item_id)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(2)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"), ("Home", "Shoes")),
                   t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_item_id"), col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("total_sales")), asc(col("i_item_id"))], agg))


def q57_shape(t, run):
    """Catalog monthly brand revenue vs neighbors (reference q57 — the
    catalog sibling of q47's stacked windows)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, Lag, Lead,
                                              WindowFrame, WindowSpec,
                                              WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(1999),
                              t["date_dim"]),
                    t["catalog_sales"], ["d_date_sk"],
                    ["cs_sold_date_sk"]),
              t["item"], ["cs_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_brand"), col("d_moy")],
        [Sum(col("cs_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [Lag(col("sum_sales")).alias("psum"),
         Lead(col("sum_sales")).alias("nsum")],
        WindowSpec([col("i_brand")], [_asc(col("d_moy"))]), monthly)
    wavg = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly")],
        WindowSpec([col("i_brand")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_brand")), asc(col("d_moy"))],
        CpuProject([col("i_brand"), col("d_moy"), col("sum_sales"),
                    col("psum"), col("nsum"), col("avg_monthly")],
                   wavg)))


def q64_shape(t, run):
    """Returned store purchases by city and brand (reference q64's
    cross-sale pairs, reduced to the store arm over the v0 schema)."""
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["item"], ["ss_item_sk"],
                          ["i_item_sk"]),
                    t["customer"], ["ss_customer_sk"],
                    ["c_customer_sk"]),
              t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_city"), col("i_brand")],
        [Count(col("ss_ticket_number")).alias("cnt"),
         Sum(col("ss_net_paid")).alias("paid"),
         Sum(col("sr_return_amt")).alias("returned")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_city")), asc(col("i_brand"))], agg))


def q72_shape(t, run):
    """Catalog orders vs on-hand inventory, promo split (reference q72's
    inventory shortage join)."""
    j = CpuHashJoin(J.INNER, [col("cs_item_sk")], [col("inv_item_sk")],
                    t["catalog_sales"], t["inventory"],
                    condition=col("inv_quantity_on_hand") <
                    col("cs_quantity"))
    p = CpuHashJoin(J.LEFT_OUTER, [col("cs_promo_sk")],
                    [col("p_promo_sk")], j, t["promotion"])
    flagged = CpuProject(
        [col("cs_item_sk"),
         If(IsNull(col("p_promo_sk")), lit(1), lit(0)).alias("no_promo"),
         If(IsNotNull(col("p_promo_sk")), lit(1), lit(0)).alias("promo")],
        p)
    agg = CpuAggregate(
        [col("cs_item_sk")],
        [Sum(col("no_promo")).alias("no_promo"),
         Sum(col("promo")).alias("promo"),
         Count(col("cs_item_sk")).alias("total_cnt")], flagged)
    return CpuLimit(100, CpuSort(
        [desc(col("total_cnt")), asc(col("cs_item_sk"))], agg))


def q76_shape(t, run):
    """Channel/year/category sales counts over the union of all three
    channels (reference q76's null-key audit, keyed by channel here)."""
    def channel(label, sales, date_key, item_key, price):
        j = _join(_join(t["date_dim"], t[sales], ["d_date_sk"],
                        [date_key]),
                  t["item"], [item_key], ["i_item_sk"])
        return CpuProject(
            [lit(label).alias("channel"), col("d_year"),
             col("i_category"), col(price).alias("ext_sales_price")], j)

    u = CpuUnion(
        channel("store", "store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_ext_sales_price"),
        channel("web", "web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price"),
        channel("catalog", "catalog_sales", "cs_sold_date_sk",
                "cs_item_sk", "cs_ext_sales_price"))
    agg = CpuAggregate(
        [col("channel"), col("d_year"), col("i_category")],
        [Count(col("ext_sales_price")).alias("sales_cnt"),
         Sum(col("ext_sales_price")).alias("sales_amt")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("d_year")),
         asc(col("i_category"))], agg))


def q78_shape(t, run):
    """Unreturned web sales per item/year vs store equivalents
    (reference q78's returns-netting left outer + null filter)."""
    def unreturned(sales, okey, ikey, dkey, qty, rets, rokey, rikey):
        jo = CpuHashJoin(
            J.LEFT_OUTER, [col(okey), col(ikey)],
            [col(rokey), col(rikey)], t[sales], t[rets])
        kept = CpuFilter(IsNull(col(rokey)), jo)
        jd = _join(t["date_dim"], kept, ["d_date_sk"], [dkey])
        return CpuAggregate(
            [col("d_year"), col(ikey)],
            [Sum(col(qty)).alias("qty")], jd)

    ws = unreturned("web_sales", "ws_order_number", "ws_item_sk",
                    "ws_sold_date_sk", "ws_quantity",
                    "web_returns", "wr_order_number", "wr_item_sk")
    ss = CpuProject(
        [col("d_year").alias("ss_year"),
         col("ss_item_sk").alias("s_item"),
         col("qty").alias("ss_qty")],
        unreturned("store_sales", "ss_ticket_number", "ss_item_sk",
                   "ss_sold_date_sk", "ss_quantity",
                   "store_returns", "sr_ticket_number", "sr_item_sk"))
    j = CpuHashJoin(J.INNER, [col("d_year"), col("ws_item_sk")],
                    [col("ss_year"), col("s_item")], ws, ss)
    out = CpuProject(
        [col("d_year"), col("ws_item_sk"), col("qty"), col("ss_qty"),
         (col("qty") / col("ss_qty")).alias("ratio")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ratio")), asc(col("ws_item_sk")),
         asc(col("d_year"))], out))


def q81_shape(t, run):
    """Catalog returners above 1.2x their state's average return amount
    (reference q81's correlated HAVING via a per-state window)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(t["catalog_returns"], t["customer"],
                    ["cr_returning_customer_sk"], ["c_customer_sk"]),
              t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    per_cust = CpuAggregate(
        [col("c_customer_id"), col("ca_state")],
        [Sum(col("cr_return_amount")).alias("ctr_total_return")], j)
    w = CpuWindow(
        [WinAvg(col("ctr_total_return")).alias("state_avg")],
        WindowSpec([col("ca_state")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        per_cust)
    keep = CpuFilter(
        col("ctr_total_return") > col("state_avg") * lit(1.2), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id"), col("ca_state"),
                    col("ctr_total_return")], keep)))


def q83_shape(t, run):
    """Return quantities by item across the three return tables
    (reference q83's three-way item join)."""
    sr = CpuAggregate([col("sr_item_sk")],
                      [Sum(col("sr_return_quantity")).alias("sr_qty")],
                      t["store_returns"])
    cr = CpuProject([col("cr_item_sk").alias("c_item"),
                     col("cr_qty")],
                    CpuAggregate(
                        [col("cr_item_sk")],
                        [Sum(col("cr_return_quantity")).alias("cr_qty")],
                        t["catalog_returns"]))
    wr = CpuProject([col("wr_item_sk").alias("w_item"),
                     col("wr_qty")],
                    CpuAggregate(
                        [col("wr_item_sk")],
                        [Sum(col("wr_return_quantity")).alias("wr_qty")],
                        t["web_returns"]))
    j = CpuHashJoin(J.INNER, [col("sr_item_sk")], [col("c_item")],
                    sr, cr)
    j = CpuHashJoin(J.INNER, [col("sr_item_sk")], [col("w_item")],
                    j, wr)
    out = CpuProject(
        [col("sr_item_sk"), col("sr_qty"), col("cr_qty"), col("wr_qty"),
         ((col("sr_qty") + col("cr_qty") + col("wr_qty")) / lit(3.0))
         .alias("average")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("sr_item_sk"))], out))


def q84_shape(t, run):
    """Customer directory for one city, names concatenated (reference
    q84's customer/address/demographics lookup)."""
    from spark_rapids_tpu.exprs.string_fns import ConcatStrings
    ca = CpuFilter(col("ca_city") == lit("Midway"),
                   t["customer_address"])
    j = _join(t["customer"], ca, ["c_current_addr_sk"],
              ["ca_address_sk"])
    out = CpuProject(
        [col("c_customer_id").alias("customer_id"),
         ConcatStrings((col("c_last_name"), lit(", "),
                        col("c_first_name"))).alias("customername")], j)
    return CpuLimit(100, CpuSort([asc(col("customer_id"))], out))


def q85_shape(t, run):
    """Catalog returns profiled by buyer demographics (reference q85's
    reason-bucketed web returns, carried by the catalog arm where the
    v0 schema has the demographics link)."""
    j = CpuHashJoin(
        J.INNER, [col("cs_order_number"), col("cs_item_sk")],
        [col("cr_order_number"), col("cr_item_sk")],
        t["catalog_sales"], t["catalog_returns"])
    jd = _join(j, t["customer_demographics"], ["cs_bill_cdemo_sk"],
               ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("cd_marital_status"), col("cd_education_status")],
        [Average(col("cs_quantity")).alias("avg_qty"),
         Average(col("cr_return_quantity")).alias("avg_ret_qty"),
         Count(col("cs_order_number")).alias("cnt")], jd)
    return CpuLimit(100, CpuSort(
        [asc(col("cd_marital_status")),
         asc(col("cd_education_status"))], agg))


def q89_shape(t, run):
    """Monthly category/brand/store revenue vs the yearly average
    (reference q89)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(_join(CpuFilter(col("d_year") == lit(2000),
                                    t["date_dim"]),
                          t["store_sales"], ["d_date_sk"],
                          ["ss_sold_date_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    monthly = CpuAggregate(
        [col("i_category"), col("i_brand"), col("s_store_name"),
         col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col("i_category"), col("i_brand"),
                    col("s_store_name")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        monthly)
    keep = CpuFilter(
        col("sum_sales") > col("avg_monthly_sales") * lit(1.1), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_category")), asc(col("i_brand")),
         asc(col("s_store_name")), asc(col("d_moy"))],
        CpuProject([col("i_category"), col("i_brand"),
                    col("s_store_name"), col("d_moy"), col("sum_sales"),
                    col("avg_monthly_sales")], keep)))


def q95_shape(t, run):
    """Web orders shipped from more than one warehouse that were also
    returned (reference q95's double-EXISTS over ws self-join + wr)."""
    ws2 = CpuProject([col("ws_order_number").alias("o2"),
                      col("ws_warehouse_sk").alias("w2")],
                     t["web_sales"])
    multi = CpuHashJoin(
        J.LEFT_SEMI, [col("ws_order_number")], [col("o2")],
        t["web_sales"], ws2,
        condition=col("ws_warehouse_sk") != col("w2"))
    returned = CpuHashJoin(
        J.LEFT_SEMI, [col("ws_order_number")], [col("wr_order_number")],
        multi, t["web_returns"])
    per_order = CpuAggregate(
        [col("ws_order_number")],
        [Sum(col("ws_ext_ship_cost")).alias("ship_cost"),
         Sum(col("ws_net_profit")).alias("profit")], returned)
    total = CpuAggregate(
        [],
        [Count(col("ws_order_number")).alias("order_count"),
         Sum(col("ship_cost")).alias("total_shipping"),
         Sum(col("profit")).alias("total_profit")], per_order)
    return total


QUERIES.update({
    "q4": q4_shape, "q5": q5_shape, "q9": q9_shape, "q11": q11_shape,
    "q12": q12_shape, "q14": q14_shape, "q17": q17_shape,
    "q20": q20_shape, "q22": q22_rollup, "q24": q24_shape,
    "q29": q29_shape, "q35": q35_shape, "q39": q39_shape,
    "q49": q49_shape, "q53": q53_shape, "q54": q54_shape,
    "q56": q56_shape, "q57": q57_shape, "q64": q64_shape,
    "q72": q72_shape, "q74": q74_shape, "q76": q76_shape,
    "q78": q78_shape, "q81": q81_shape, "q83": q83_shape,
    "q84": q84_shape, "q85": q85_shape, "q86": q86_rollup,
    "q89": q89_shape, "q95": q95_shape,
})


# a/b variants (the reference counts q14a/b, q23a/b, q24a/b, q39a/b as
# separate queries — TpcdsLikeSpark.scala) + q91.
def q14b_shape(t, run):
    """Cross-channel items: this-year vs last-year sales comparison for
    items sold in both store and catalog (reference q14b's
    year-over-year arm; q14(a) covers the 3-channel intersection)."""
    both = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                       [col("cs_item_sk")],
                       CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                                   [col("ss_item_sk")], t["item"],
                                   t["store_sales"]),
                       t["catalog_sales"])

    def year_sales(y, alias):
        dd = CpuFilter(col("d_year") == lit(y), t["date_dim"])
        j = _join(_join(dd, t["store_sales"], ["d_date_sk"],
                        ["ss_sold_date_sk"]),
                  both, ["ss_item_sk"], ["i_item_sk"])
        return CpuAggregate(
            [col("i_brand_id")],
            [Sum(col("ss_ext_sales_price")).alias(alias)], j)

    this_y = year_sales(2000, "this_year")
    last_y = CpuProject([col("i_brand_id").alias("b2"),
                         col("last_year")],
                        year_sales(1999, "last_year"))
    j = CpuHashJoin(J.INNER, [col("i_brand_id")], [col("b2")],
                    this_y, last_y)
    return CpuLimit(100, CpuSort(
        [desc(col("this_year")), asc(col("i_brand_id"))],
        CpuProject([col("i_brand_id"), col("this_year"),
                    col("last_year")], j)))


def q23b_shape(t, run):
    """Best store customers' catalog spend on frequently-sold items
    (reference q23b; q23(a) covers the frequent-item monthly totals)."""
    freq = CpuFilter(col("cnt") > lit(4), CpuAggregate(
        [col("ss_item_sk")], [Count(None).alias("cnt")],
        t["store_sales"]))
    best = CpuFilter(col("spend") > lit(1000.0), CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_net_paid")).alias("spend")], t["store_sales"]))
    cs = CpuHashJoin(J.LEFT_SEMI, [col("cs_item_sk")],
                     [col("ss_item_sk")], t["catalog_sales"], freq)
    cs = CpuHashJoin(J.LEFT_SEMI, [col("cs_bill_customer_sk")],
                     [col("ss_customer_sk")], cs, best)
    agg = CpuAggregate(
        [col("cs_bill_customer_sk")],
        [Sum(col("cs_sales_price")).alias("sales")], cs)
    return CpuLimit(100, CpuSort(
        [desc(col("sales")), asc(col("cs_bill_customer_sk"))], agg))


def q24b_shape(t, run):
    """q24's sibling keyed by category instead of brand (the reference
    differs only in the color filter; the v0 item schema has no color)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["store"], ["ss_store_sk"],
                          ["s_store_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    agg = CpuAggregate(
        [col("c_last_name"), col("s_store_name"), col("i_category")],
        [Sum(col("ss_net_paid")).alias("netpaid")], j)
    w = CpuWindow(
        [WinAvg(col("netpaid")).alias("avg_netpaid")],
        WindowSpec([], [], WindowFrame(is_rows=True, lower=None,
                                       upper=None)), agg)
    keep = CpuFilter(col("netpaid") > col("avg_netpaid") * lit(0.05), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("s_store_name")),
         asc(col("i_category"))],
        CpuProject([col("c_last_name"), col("s_store_name"),
                    col("i_category"), col("netpaid")], keep)))


def q39b_shape(t, run):
    """q39's second arm: only pairs whose month-over-month quantity
    swing is large (reference q39b tightens the covariance filter)."""
    base = q39_shape(t, run)
    # re-filter the paired report: keep rows with a >30% swing
    from spark_rapids_tpu.exprs.arithmetic import Abs as _Abs
    inner = base.child.child if isinstance(base, CpuLimit) else base
    swing = CpuFilter(
        (col("qoh1") > lit(0.0)) &
        (_Abs(col("qoh2") - col("qoh1")) / col("qoh1") > lit(0.3)),
        inner)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_sk")), asc(col("inv_item_sk")),
         asc(col("next_moy"))], swing))


def q91_shape(t, run):
    """Catalog returns profiled by buyer demographics and customer state
    (reference q91 groups by call center — outside the v0 table set;
    the demographic link rides the originating catalog sale's
    cs_bill_cdemo_sk, the same path q85 uses)."""
    ret = CpuHashJoin(
        J.INNER, [col("cr_order_number"), col("cr_item_sk")],
        [col("cs_order_number"), col("cs_item_sk")],
        t["catalog_returns"], t["catalog_sales"])
    j = _join(_join(_join(ret, t["customer"],
                          ["cr_returning_customer_sk"],
                          ["c_customer_sk"]),
                    t["customer_address"], ["c_current_addr_sk"],
                    ["ca_address_sk"]),
              t["customer_demographics"],
              ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("ca_state"), col("cd_marital_status")],
        [Sum(col("cr_return_amount")).alias("returns_loss"),
         Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("returns_loss")), asc(col("ca_state")),
         asc(col("cd_marital_status"))], agg))


QUERIES.update({
    "q14b": q14b_shape, "q23b": q23b_shape, "q24b": q24b_shape,
    "q39b": q39b_shape, "q91": q91_shape,
})


# ---------------------------------------------------------------------------
# round-3 faithful upgrades: full reference query text
# (TpcdsLikeSpark.scala:709+) over the extended generator schemas —
# replacing the corresponding *_shape reductions query-for-query.
from spark_rapids_tpu.exprs.string_fns import Like, Substring as _Substring


def _date(y, m, d):
    """DATE32 literal: days since unix epoch."""
    import datetime
    days = (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days
    return _Lit(days, _T.DATE32)


def _between(c, lo, hi):
    return (c >= lo) & (c <= hi)


def q7(t, run):
    """Reference q7: item averages for one demographic slice + promo."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    promo = CpuFilter((col("p_channel_email") == lit("N")) |
                      (col("p_channel_event") == lit("N")),
                      t["promotion"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
                    promo, ["ss_promo_sk"], ["p_promo_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q13(t, run):
    """Reference q13: averages under OR-of-AND demographic/address
    bands (join keys inner, band predicates as a post-join filter)."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"]),
        t["household_demographics"], ["ss_hdemo_sk"], ["hd_demo_sk"]),
        t["customer_demographics"], ["ss_cdemo_sk"], ["cd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    demo = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Advanced Degree")) &
         _between(col("ss_sales_price"), lit(100.0), lit(150.0)) &
         (col("hd_dep_count") == lit(3))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         _between(col("ss_sales_price"), lit(50.0), lit(100.0)) &
         (col("hd_dep_count") == lit(1))) |
        ((col("cd_marital_status") == lit("W")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         _between(col("ss_sales_price"), lit(150.0), lit(200.0)) &
         (col("hd_dep_count") == lit(1))))
    addr = (
        (col("ca_country") == lit("United States")) &
        (InSet(col("ca_state"), ("TX", "NY")) &
         _between(col("ss_net_profit"), lit(100), lit(200)) |
         InSet(col("ca_state"), ("CA", "IL")) &
         _between(col("ss_net_profit"), lit(150), lit(300)) |
         InSet(col("ca_state"), ("WA", "GA")) &
         _between(col("ss_net_profit"), lit(50), lit(250))))
    f = CpuFilter(demo & addr, j)
    return CpuAggregate(
        [], [Average(col("ss_quantity")).alias("avg_qty"),
             Average(col("ss_ext_sales_price")).alias("avg_esp"),
             Average(col("ss_ext_wholesale_cost")).alias("avg_ewc"),
             Sum(col("ss_ext_wholesale_cost")).alias("sum_ewc")], f)


def q15(t, run):
    """Reference q15: catalog revenue by zip (zip/state/price OR)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    t["customer"],
                    ["cs_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    zips = ("85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792")
    f = CpuFilter(
        InSet(_Substring(col("ca_zip"), lit(1), lit(5)), zips) |
        InSet(col("ca_state"), ("CA", "WA", "GA")) |
        (col("cs_sales_price") > lit(500.0)), j)
    agg = CpuAggregate([col("ca_zip")],
                       [Sum(col("cs_sales_price")).alias("total")], f)
    return CpuLimit(100, CpuSort([asc(col("ca_zip"))], agg))


def q25(t, run):
    """Reference q25: store profit / returns loss / catalog profit per
    item+store across the d1/d2/d3 date windows."""
    d1 = CpuFilter(_between(col("d_moy"), lit(1), lit(6)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    d2 = CpuFilter(_between(col("d_moy"), lit(1), lit(12)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    d3 = CpuFilter(_between(col("d_moy"), lit(1), lit(12)) &
                   (col("d_year") == lit(2001)), t["date_dim"])
    ss = _join(d1, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
    sr = _join(CpuProject([col("d_date_sk").alias("d2_sk")], d2),
               t["store_returns"], ["d2_sk"], ["sr_returned_date_sk"])
    cs = _join(CpuProject([col("d_date_sk").alias("d3_sk")], d3),
               t["catalog_sales"], ["d3_sk"], ["cs_sold_date_sk"])
    j = _join(ss, sr, ["ss_customer_sk", "ss_item_sk",
                       "ss_ticket_number"],
              ["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = _join(j, cs, ["sr_customer_sk", "sr_item_sk"],
              ["cs_bill_customer_sk", "cs_item_sk"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    j = _join(j, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("s_store_id"),
         col("s_store_name")],
        [Sum(col("ss_net_profit")).alias("store_sales_profit"),
         Sum(col("sr_net_loss")).alias("store_returns_loss"),
         Sum(col("cs_net_profit")).alias("catalog_sales_profit")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("s_store_id")), asc(col("s_store_name"))], agg))


def q27(t, run):
    """Reference q27: state-level item averages over ROLLUP
    (i_item_id, s_state) with the grouping flag."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2002), t["date_dim"])
    # reference lists TN; the generator's state domain stands in
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA")),
                   t["store"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        t["item"], ["ss_item_sk"], ["i_item_sk"])
    pre = CpuProject(
        [col("i_item_id"), col("s_state"), col("ss_quantity"),
         col("ss_list_price"), col("ss_coupon_amt"),
         col("ss_sales_price")], j)
    ex = _rollup_expand(pre, ["i_item_id", "s_state"],
                        ["ss_quantity", "ss_list_price",
                         "ss_coupon_amt", "ss_sales_price"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_state"), col("gid")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], ex)
    out = CpuProject(
        [col("i_item_id"), col("s_state"),
         If(col("gid") >= lit(1), lit(1), lit(0)).alias("g_state"),
         col("agg1"), col("agg2"), col("agg3"), col("agg4")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_state"))], out))


def _q28_block(t, qlo, qhi, lp, ca, wc, tag):
    base = CpuFilter(
        _between(col("ss_quantity"), lit(qlo), lit(qhi)) &
        (_between(col("ss_list_price"), lit(float(lp)),
                  lit(float(lp + 10))) |
         _between(col("ss_coupon_amt"), lit(float(ca)),
                  lit(float(ca + 1000))) |
         _between(col("ss_wholesale_cost"), lit(float(wc)),
                  lit(float(wc + 20)))), t["store_sales"])
    main = CpuProject(
        [lit(1).alias(f"_k{tag}"),
         col(f"{tag}_LP"), col(f"{tag}_CNT")],
        CpuAggregate(
            [], [Average(col("ss_list_price")).alias(f"{tag}_LP"),
                 Count(col("ss_list_price")).alias(f"{tag}_CNT")],
            base))
    dist = CpuProject(
        [lit(1).alias(f"_kd{tag}"), col(f"{tag}_CNTD")],
        CpuAggregate(
            [], [Count(col("ss_list_price")).alias(f"{tag}_CNTD")],
            CpuAggregate([col("ss_list_price")],
                         [Count(None).alias("_d")], base)))
    return _join(main, dist, [f"_k{tag}"], [f"_kd{tag}"])


def q28(t, run):
    """Reference q28: six quantity-band stats blocks cross-joined
    (count distinct via two-level aggregate)."""
    blocks = [
        _q28_block(t, 0, 5, 8, 459, 57, "B1"),
        _q28_block(t, 6, 10, 90, 2323, 31, "B2"),
        _q28_block(t, 11, 15, 142, 12214, 79, "B3"),
        _q28_block(t, 16, 20, 135, 6071, 38, "B4"),
        _q28_block(t, 21, 25, 122, 836, 17, "B5"),
        _q28_block(t, 26, 30, 154, 7326, 7, "B6"),
    ]
    out = blocks[0]
    for i, b in enumerate(blocks[1:], start=2):
        out = _join(out, b, [f"_kB{i - 1}"], [f"_kB{i}"])
    names = [c for tag in ("B1", "B2", "B3", "B4", "B5", "B6")
             for c in (f"{tag}_LP", f"{tag}_CNT", f"{tag}_CNTD")]
    return CpuLimit(100, CpuProject([col(c) for c in names], out))


def _q33_channel(t, sales, date_key, addr_key, item_key, val):
    manuf = CpuAggregate(
        [col("i_manufact_id")], [Count(None).alias("_c")],
        CpuFilter(InSet(col("i_category"), ("Electronics",)),
                  t["item"]))
    it = _join(t["item"], manuf, ["i_manufact_id"], ["i_manufact_id"],
               jt=J.LEFT_SEMI)
    dd = CpuFilter((col("d_year") == lit(1998)) &
                   (col("d_moy") == lit(5)), t["date_dim"])
    ca = CpuFilter(col("ca_gmt_offset") == lit(-5.0),
                   t["customer_address"])
    j = _join(_join(_join(dd, sales, ["d_date_sk"], [date_key]),
                    ca, [addr_key], ["ca_address_sk"]),
              it, [item_key], ["i_item_sk"])
    return CpuAggregate([col("i_manufact_id")],
                        [Sum(col(val)).alias("total_sales")], j)


def q33(t, run):
    """Reference q33: Electronics manufacturer revenue across the three
    channels, unioned and re-aggregated."""
    ss = _q33_channel(t, t["store_sales"], "ss_sold_date_sk",
                      "ss_addr_sk", "ss_item_sk", "ss_ext_sales_price")
    cs = _q33_channel(t, t["catalog_sales"], "cs_sold_date_sk",
                      "cs_bill_addr_sk", "cs_item_sk",
                      "cs_ext_sales_price")
    ws = _q33_channel(t, t["web_sales"], "ws_sold_date_sk",
                      "ws_bill_addr_sk", "ws_item_sk",
                      "ws_ext_sales_price")
    u = CpuUnion(ss, cs, ws)
    agg = CpuAggregate([col("i_manufact_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort([desc(col("total_sales"))], agg))


def q37(t, run):
    """Reference q37: in-stock catalog items in a price band."""
    it = CpuFilter(
        _between(col("i_current_price"), lit(20.0), lit(90.0)) &
        InSet(col("i_manufact_id"),
              tuple(range(1, 41))), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 1),
                            _date(2000, 12, 31)), t["date_dim"])
    inv = CpuFilter(_between(col("inv_quantity_on_hand"),
                             lit(100), lit(500)), t["inventory"])
    j = _join(_join(_join(it, inv, ["i_item_sk"], ["inv_item_sk"]),
                    dd, ["inv_date_sk"], ["d_date_sk"]),
              t["catalog_sales"], ["i_item_sk"], ["cs_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_current_price")],
        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_item_id"), col("i_item_desc"),
                      col("i_current_price")], agg)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], out))


def q40(t, run):
    """Reference q40: warehouse sales before/after one date, catalog
    left-outer returns netting."""
    j = _join(t["catalog_sales"], t["catalog_returns"],
              ["cs_order_number", "cs_item_sk"],
              ["cr_order_number", "cr_item_sk"], jt=J.LEFT_OUTER)
    it = CpuFilter(_between(col("i_current_price"),
                            lit(0.99), lit(1.49)), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 2, 10),
                            _date(2000, 4, 10)), t["date_dim"])
    j = _join(_join(_join(j, it, ["cs_item_sk"], ["i_item_sk"]),
                    t["warehouse"], ["cs_warehouse_sk"],
                    ["w_warehouse_sk"]),
              dd, ["cs_sold_date_sk"], ["d_date_sk"])
    net = col("cs_sales_price") - Coalesce((col("cr_refunded_cash"),
                                            lit(0.0)))
    agg = CpuAggregate(
        [col("w_state"), col("i_item_id")],
        [Sum(If(col("d_date") < _date(2000, 3, 11), net,
                lit(0.0))).alias("sales_before"),
         Sum(If(col("d_date") >= _date(2000, 3, 11), net,
                lit(0.0))).alias("sales_after")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("w_state")), asc(col("i_item_id"))], agg))


def q43(t, run):
    """Reference q43: store weekday sales pivot for one year/offset."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    st = CpuFilter(col("s_gmt_offset") == lit(-5.0), t["store"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])

    def day_sum(name, alias):
        return Sum(If(col("d_day_name") == lit(name),
                      col("ss_sales_price"), lit(0.0))).alias(alias)
    agg = CpuAggregate(
        [col("s_store_name"), col("s_store_id")],
        [day_sum("Sunday", "sun_sales"), day_sum("Monday", "mon_sales"),
         day_sum("Tuesday", "tue_sales"),
         day_sum("Wednesday", "wed_sales"),
         day_sum("Thursday", "thu_sales"),
         day_sum("Friday", "fri_sales"),
         day_sum("Saturday", "sat_sales")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("s_store_id")),
         asc(col("sun_sales")), asc(col("mon_sales"))], agg))


def q45(t, run):
    """Reference q45: web revenue by zip/city; zip prefix OR item-id
    semi-join on the primes item list."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"]),
        t["customer"], ["ws_bill_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"]),
        t["item"], ["ws_item_sk"], ["i_item_sk"])
    prime_ids = CpuAggregate(
        [col("prime_id")], [Count(None).alias("_c")],
        CpuProject(
            [col("i_item_id").alias("prime_id")],
            CpuFilter(InSet(col("i_item_sk"),
                            (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)),
                      t["item"])))
    prime_ids = CpuProject([col("prime_id")], prime_ids)
    j = _join(j, prime_ids, ["i_item_id"], ["prime_id"],
              jt=J.LEFT_OUTER)
    zips = ("85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792")
    f = CpuFilter(
        InSet(_Substring(col("ca_zip"), lit(1), lit(5)), zips) |
        IsNotNull(col("prime_id")), j)
    agg = CpuAggregate([col("ca_zip"), col("ca_city")],
                       [Sum(col("ws_sales_price")).alias("total")], f)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_zip")), asc(col("ca_city"))], agg))


def q48(t, run):
    """Reference q48: quantity total across demographic price bands and
    address profit bands."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"]),
        t["customer_demographics"], ["ss_cdemo_sk"], ["cd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    demo = (
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree")) &
         _between(col("ss_sales_price"), lit(100.0), lit(150.0))) |
        ((col("cd_marital_status") == lit("D")) &
         (col("cd_education_status") == lit("2 yr Degree")) &
         _between(col("ss_sales_price"), lit(50.0), lit(100.0))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College")) &
         _between(col("ss_sales_price"), lit(150.0), lit(200.0))))
    addr = (
        (col("ca_country") == lit("United States")) &
        (InSet(col("ca_state"), ("NY", "IL", "TX")) &
         _between(col("ss_net_profit"), lit(0), lit(2000)) |
         InSet(col("ca_state"), ("CA", "GA")) &
         _between(col("ss_net_profit"), lit(150), lit(3000)) |
         InSet(col("ca_state"), ("WA",)) &
         _between(col("ss_net_profit"), lit(50), lit(25000))))
    f = CpuFilter(demo & addr, j)
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("total")], f)


QUERIES.update({
    "q7": q7, "q13": q13, "q15": q15, "q25": q25, "q27": q27,
    "q28": q28, "q33": q33, "q37": q37, "q40": q40, "q43": q43,
    "q45": q45, "q48": q48,
})


def q34(t, run):
    """Reference q34: 15-20-item tickets for high-buy-potential
    households on month boundaries."""
    dd = CpuFilter(
        (_between(col("d_dom"), lit(1), lit(3)) |
         _between(col("d_dom"), lit(25), lit(28))) &
        InSet(col("d_year"), (1999, 2000, 2001)), t["date_dim"])
    hd = CpuFilter(
        ((col("hd_buy_potential") == lit(">10000")) |
         (col("hd_buy_potential") == lit("Unknown"))) &
        (col("hd_vehicle_count") > lit(0)) &
        (If(col("hd_vehicle_count") > lit(0),
            col("hd_dep_count") / col("hd_vehicle_count"),
            _Lit(None, _T.FLOAT64)) > lit(1.2)),
        t["household_demographics"])
    st = CpuFilter(InSet(col("s_county"), ("Williamson County",)),
                   t["store"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    dn = CpuAggregate([col("ss_ticket_number"), col("ss_customer_sk")],
                      [Count(None).alias("cnt")], j)
    # reference band is 15-20; the generator's post-filter per-ticket
    # counts are 1-3, so the band scales down
    dn = CpuFilter(_between(col("cnt"), lit(1), lit(20)), dn)
    out = _join(dn, t["customer"], ["ss_customer_sk"],
                ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("c_salutation"),
         col("c_preferred_cust_flag"), col("ss_ticket_number"),
         col("cnt")], out)
    return CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("c_salutation")), desc(col("c_preferred_cust_flag")),
         asc(col("ss_ticket_number"))], out)


def q46(t, run):
    """Reference q46: weekend coupon/profit per ticket where the bought
    city differs from the customer's current city."""
    dd = CpuFilter(InSet(col("d_dow"), (6, 0)) &
                   InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Fairview", "Midway")),
                   t["store"])
    j = _join(_join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
        t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    dn = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ss_addr_sk"), col("ca_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    dn = CpuProject(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city").alias("bought_city"), col("amt"),
         col("profit")], dn)
    out = _join(_join(dn, t["customer"], ["ss_customer_sk"],
                      ["c_customer_sk"]),
                t["customer_address"], ["c_current_addr_sk"],
                ["ca_address_sk"])
    out = CpuFilter(col("ca_city") != col("bought_city"), out)
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("bought_city"), col("ss_ticket_number"), col("amt"),
         col("profit")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("ca_city")), asc(col("bought_city")),
         asc(col("ss_ticket_number"))], out))


def _lag_buckets(diff, prefix=""):
    return [
        Sum(If(diff <= lit(30), lit(1), lit(0))).alias(
            f"{prefix}d30"),
        Sum(If((diff > lit(30)) & (diff <= lit(60)), lit(1),
               lit(0))).alias(f"{prefix}d31_60"),
        Sum(If((diff > lit(60)) & (diff <= lit(90)), lit(1),
               lit(0))).alias(f"{prefix}d61_90"),
        Sum(If((diff > lit(90)) & (diff <= lit(120)), lit(1),
               lit(0))).alias(f"{prefix}d91_120"),
        Sum(If(diff > lit(120), lit(1), lit(0))).alias(
            f"{prefix}d120plus"),
    ]


def q50(t, run):
    """Reference q50: return-lag buckets per store (full store column
    list) for one return month."""
    d2 = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(8)), t["date_dim"])
    j = _join(t["store_sales"], t["store_returns"],
              ["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
              ["sr_ticket_number", "sr_item_sk", "sr_customer_sk"])
    j = _join(j, CpuProject([col("d_date_sk").alias("d2_sk")], d2),
              ["sr_returned_date_sk"], ["d2_sk"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    diff = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    keys = ["s_store_name", "s_company_id", "s_street_number",
            "s_street_name", "s_street_type", "s_suite_number",
            "s_city", "s_county", "s_state", "s_zip"]
    agg = CpuAggregate([col(k) for k in keys], _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort([asc(col(k)) for k in keys], agg))


def q61(t, run):
    """Reference q61: promotional vs total revenue (two scalar branches
    joined on a constant key)."""
    def branch(with_promo, tag):
        dd = CpuFilter((col("d_year") == lit(1998)) &
                       (col("d_moy") == lit(11)), t["date_dim"])
        st = CpuFilter(col("s_gmt_offset") == lit(-5.0), t["store"])
        it = CpuFilter(col("i_category") == lit("Jewelry"), t["item"])
        ca = CpuFilter(col("ca_gmt_offset") == lit(-5.0),
                       t["customer_address"])
        j = _join(_join(dd, t["store_sales"],
                        ["d_date_sk"], ["ss_sold_date_sk"]),
                  st, ["ss_store_sk"], ["s_store_sk"])
        if with_promo:
            pr = CpuFilter((col("p_channel_dmail") == lit("Y")) |
                           (col("p_channel_email") == lit("Y")) |
                           (col("p_channel_tv") == lit("Y")),
                           t["promotion"])
            j = _join(j, pr, ["ss_promo_sk"], ["p_promo_sk"])
        j = _join(_join(_join(j, t["customer"], ["ss_customer_sk"],
                              ["c_customer_sk"]),
                        ca, ["c_current_addr_sk"], ["ca_address_sk"]),
                  it, ["ss_item_sk"], ["i_item_sk"])
        return CpuProject(
            [lit(1).alias(f"_k{tag}"), col(tag)],
            CpuAggregate(
                [], [Sum(col("ss_ext_sales_price")).alias(tag)], j))
    promo = branch(True, "promotions")
    total = branch(False, "total")
    j = _join(promo, total, ["_kpromotions"], ["_ktotal"])
    out = CpuProject(
        [col("promotions"), col("total"),
         (col("promotions") / col("total") * lit(100.0)).alias("ratio")],
        j)
    return CpuLimit(100, CpuSort(
        [asc(col("promotions")), asc(col("total"))], out))


def q62(t, run):
    """Reference q62: web shipping-lag buckets by warehouse prefix /
    ship mode / site."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_ship_date_sk"]),
        t["warehouse"], ["ws_warehouse_sk"], ["w_warehouse_sk"]),
        t["ship_mode"], ["ws_ship_mode_sk"], ["sm_ship_mode_sk"]),
        t["web_site"], ["ws_web_site_sk"], ["web_site_sk"])
    j = CpuProject(
        [_Substring(col("w_warehouse_name"), lit(1),
                    lit(20)).alias("wh_prefix"),
         col("sm_type"), col("web_name"), col("ws_ship_date_sk"),
         col("ws_sold_date_sk")], j)
    diff = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    agg = CpuAggregate(
        [col("wh_prefix"), col("sm_type"), col("web_name")],
        _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort(
        [asc(col("wh_prefix")), asc(col("sm_type")),
         asc(col("web_name"))], agg))


def q63(t, run):
    """Reference q63: manager monthly sales vs their cross-month
    average (window avg expressed as an aggregate re-join — identical
    semantics)."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    it = CpuFilter(
        (InSet(col("i_category"), ("Books", "Electronics", "Home")) &
         InSet(col("i_class"), tuple(f"class{i:02d}" for i in
                                     range(8)))) |
        (InSet(col("i_category"), ("Women", "Music", "Shoes")) &
         InSet(col("i_class"), tuple(f"class{i:02d}" for i in
                                     range(8, 16)))), t["item"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        it, ["ss_item_sk"], ["i_item_sk"]),
        t["store"], ["ss_store_sk"], ["s_store_sk"])
    monthly = CpuAggregate(
        [col("i_manager_id"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    avg = CpuProject(
        [col("i_manager_id").alias("_mgr"),
         col("avg_monthly_sales")],
        CpuAggregate(
            [col("i_manager_id")],
            [Average(col("sum_sales")).alias("avg_monthly_sales")],
            monthly))
    out = _join(monthly, avg, ["i_manager_id"], ["_mgr"])
    dev = (col("sum_sales") - col("avg_monthly_sales"))
    absdev = If(dev < lit(0.0), lit(0.0) - dev, dev)
    out = CpuFilter(
        If(col("avg_monthly_sales") > lit(0.0),
           absdev / col("avg_monthly_sales"),
           _Lit(None, _T.FLOAT64)) > lit(0.1), out)
    out = CpuProject([col("i_manager_id"), col("sum_sales"),
                      col("avg_monthly_sales")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manager_id")), asc(col("avg_monthly_sales")),
         asc(col("sum_sales"))], out))


def q69(t, run):
    """Reference q69: demographics of store-only shoppers in a quarter
    (EXISTS store AND NOT EXISTS web/catalog as semi/anti joins)."""
    ca = CpuFilter(InSet(col("ca_state"), ("GA", "NY", "TX")),
                   t["customer_address"])
    c = _join(t["customer"], ca, ["c_current_addr_sk"],
              ["ca_address_sk"])
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   _between(col("d_moy"), lit(4), lit(6)),
                   t["date_dim"])
    ss = _join(dd, t["store_sales"], ["d_date_sk"],
               ["ss_sold_date_sk"])
    ws = _join(CpuProject([col("d_date_sk").alias("dw_sk")], dd),
               t["web_sales"], ["dw_sk"], ["ws_sold_date_sk"])
    cs = _join(CpuProject([col("d_date_sk").alias("dc_sk")], dd),
               t["catalog_sales"], ["dc_sk"], ["cs_sold_date_sk"])
    c = _join(c, ss, ["c_customer_sk"], ["ss_customer_sk"],
              jt=J.LEFT_SEMI)
    c = _join(c, ws, ["c_customer_sk"], ["ws_bill_customer_sk"],
              jt=J.LEFT_ANTI)
    c = _join(c, cs, ["c_customer_sk"], ["cs_ship_customer_sk"],
              jt=J.LEFT_ANTI)
    j = _join(c, t["customer_demographics"], ["c_current_cdemo_sk"],
              ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cd_purchase_estimate"),
         col("cd_credit_rating")],
        [Count(None).alias("cnt1")], j)
    out = CpuProject(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status"), col("cnt1"),
         col("cd_purchase_estimate"), col("cnt1").alias("cnt2"),
         col("cd_credit_rating"), col("cnt1").alias("cnt3")], agg)
    return CpuLimit(100, CpuSort(
        [asc(col("cd_gender")), asc(col("cd_marital_status")),
         asc(col("cd_education_status")),
         asc(col("cd_purchase_estimate")),
         asc(col("cd_credit_rating"))], out))


def q79(t, run):
    """Reference q79: Monday coupon/profit per ticket for large
    stores."""
    dd = CpuFilter((col("d_dow") == lit(1)) &
                   InSet(col("d_year"), (1999, 2000, 2001)),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(6)) |
                   (col("hd_vehicle_count") > lit(2)),
                   t["household_demographics"])
    st = CpuFilter(_between(col("s_number_employees"),
                            lit(200), lit(295)), t["store"])
    j = _join(_join(_join(
        dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    ms = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ss_addr_sk"), col("s_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    out = _join(ms, t["customer"], ["ss_customer_sk"],
                ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         _Substring(col("s_city"), lit(1), lit(30)).alias("city30"),
         col("ss_ticket_number"), col("amt"), col("profit")], out)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("city30")), asc(col("profit"))], out))


def _q88_slot(t, h, half, tag):
    """one time-slot count(*) block (reference q88 s1..s8)."""
    td = CpuFilter((col("t_hour") == lit(h)) &
                   ((col("t_minute") < lit(30)) if half == 0 else
                    (col("t_minute") >= lit(30))), t["time_dim"])
    hd = CpuFilter(
        ((col("hd_dep_count") == lit(4)) &
         (col("hd_vehicle_count") <= lit(6))) |
        ((col("hd_dep_count") == lit(2)) &
         (col("hd_vehicle_count") <= lit(4))) |
        ((col("hd_dep_count") == lit(0)) &
         (col("hd_vehicle_count") <= lit(2))),
        t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(_join(
        td, t["store_sales"], ["t_time_sk"], ["ss_sold_time_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
        st, ["ss_store_sk"], ["s_store_sk"])
    return CpuProject(
        [lit(1).alias(f"_k{tag}"), col(tag)],
        CpuAggregate([], [Count(None).alias(tag)], j))


def q88(t, run):
    """Reference q88: eight half-hour slot counts cross-joined."""
    slots = [("h8_30", 8, 1), ("h9", 9, 0), ("h9_30", 9, 1),
             ("h10", 10, 0), ("h10_30", 10, 1), ("h11", 11, 0),
             ("h11_30", 11, 1), ("h12", 12, 0)]
    blocks = [_q88_slot(t, h, half, tag) for tag, h, half in slots]
    out = blocks[0]
    prev_tag = slots[0][0]
    for b, (tag, _, _) in zip(blocks[1:], slots[1:]):
        out = _join(out, b, [f"_k{prev_tag}"], [f"_k{tag}"])
        prev_tag = tag
    return CpuProject([col(tag) for tag, _, _ in slots], out)


def q90(t, run):
    """Reference q90: am/pm web sales ratio for a dependent-count
    band."""
    def half(h_lo, h_hi, tag):
        td = CpuFilter(_between(col("t_hour"), lit(h_lo), lit(h_hi)),
                       t["time_dim"])
        hd = CpuFilter(col("hd_dep_count") == lit(6),
                       t["household_demographics"])
        wp = CpuFilter(_between(col("wp_char_count"),
                                lit(5000), lit(5200)), t["web_page"])
        j = _join(_join(_join(
            td, t["web_sales"], ["t_time_sk"], ["ws_sold_time_sk"]),
            hd, ["ws_ship_hdemo_sk"], ["hd_demo_sk"]),
            wp, ["ws_web_page_sk"], ["wp_web_page_sk"])
        return CpuProject(
            [lit(1).alias(f"_k{tag}"), col(tag)],
            CpuAggregate([], [Count(None).alias(tag)], j))
    am = half(8, 9, "amc")
    pm = half(19, 20, "pmc")
    j = _join(am, pm, ["_kamc"], ["_kpmc"])
    out = CpuProject(
        [(col("amc") / col("pmc")).alias("am_pm_ratio")], j)
    return CpuLimit(100, CpuSort([asc(col("am_pm_ratio"))], out))


def q93(t, run):
    """Reference q93: actual sales net of returns for one reason."""
    r = CpuFilter(col("r_reason_desc") == lit("reason 1"), t["reason"])
    j = _join(t["store_sales"], _join(
        t["store_returns"], r, ["sr_reason_sk"], ["r_reason_sk"]),
        ["ss_item_sk", "ss_ticket_number"],
        ["sr_item_sk", "sr_ticket_number"], jt=J.LEFT_OUTER)
    act = If(IsNotNull(col("sr_ticket_number")),
             (col("ss_quantity") - col("sr_return_quantity")) *
             col("ss_sales_price"),
             col("ss_quantity") * col("ss_sales_price"))
    pre = CpuProject([col("ss_customer_sk"), act.alias("act_sales")], j)
    agg = CpuAggregate([col("ss_customer_sk")],
                       [Sum(col("act_sales")).alias("sumsales")], pre)
    return CpuLimit(100, CpuSort(
        [asc(col("sumsales")), asc(col("ss_customer_sk"))], agg))


def q98(t, run):
    """Reference q98: store item/class revenue ratio (no limit)."""
    return _item_class_revenue(t, t["store_sales"], "ss_sold_date_sk",
                               "ss_item_sk", "ss_ext_sales_price",
                               limit=None)


def q99(t, run):
    """Reference q99: catalog shipping-lag buckets by warehouse prefix /
    ship mode / call center."""
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(_join(_join(
        dd, t["catalog_sales"], ["d_date_sk"], ["cs_ship_date_sk"]),
        t["warehouse"], ["cs_warehouse_sk"], ["w_warehouse_sk"]),
        t["ship_mode"], ["cs_ship_mode_sk"], ["sm_ship_mode_sk"]),
        t["call_center"], ["cs_call_center_sk"], ["cc_call_center_sk"])
    j = CpuProject(
        [_Substring(col("w_warehouse_name"), lit(1),
                    lit(20)).alias("wh_prefix"),
         col("sm_type"), col("cc_name"), col("cs_ship_date_sk"),
         col("cs_sold_date_sk")], j)
    diff = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    agg = CpuAggregate(
        [col("wh_prefix"), col("sm_type"), col("cc_name")],
        _lag_buckets(diff), j)
    return CpuLimit(100, CpuSort(
        [asc(col("wh_prefix")), asc(col("sm_type")),
         asc(col("cc_name"))], agg))


QUERIES.update({
    "q34": q34, "q46": q46, "q50": q50, "q61": q61, "q62": q62,
    "q63": q63, "q69": q69, "q79": q79, "q88": q88, "q90": q90,
    "q93": q93, "q98": q98, "q99": q99,
})


def _item_class_revenue(t, sales, date_key, item_key, val,
                        limit=100):
    """q12/q20/q98 family: item revenue + class revenue ratio over one
    30-day window (window sum as aggregate re-join)."""
    dd = CpuFilter(_between(col("d_date"), _date(1999, 2, 22),
                            _date(1999, 3, 24)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Sports", "Books", "Home")), t["item"])
    j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
              it, [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_category"),
         col("i_class"), col("i_current_price")],
        [Sum(col(val)).alias("itemrevenue")], j)
    cls = CpuProject(
        [col("i_class").alias("_cls"), col("classrev")],
        CpuAggregate([col("i_class")],
                     [Sum(col("itemrevenue")).alias("classrev")], agg))
    out = _join(agg, cls, ["i_class"], ["_cls"])
    out = CpuProject(
        [col("i_item_id"), col("i_item_desc"), col("i_category"),
         col("i_class"), col("i_current_price"), col("itemrevenue"),
         (col("itemrevenue") * lit(100.0) /
          col("classrev")).alias("revenueratio")], out)
    srt = CpuSort(
        [asc(col("i_category")), asc(col("i_class")),
         asc(col("i_item_id")), asc(col("i_item_desc")),
         asc(col("revenueratio"))], out)
    return srt if limit is None else CpuLimit(limit, srt)


def q12(t, run):
    """Reference q12: web item/class revenue ratio."""
    return _item_class_revenue(t, t["web_sales"], "ws_sold_date_sk",
                               "ws_item_sk", "ws_ext_sales_price")


def q20(t, run):
    """Reference q20: catalog item/class revenue ratio."""
    return _item_class_revenue(t, t["catalog_sales"],
                               "cs_sold_date_sk", "cs_item_sk",
                               "cs_ext_sales_price")


def q82(t, run):
    """Reference q82: in-stock store items in a price band."""
    it = CpuFilter(
        _between(col("i_current_price"), lit(30.0), lit(95.0)) &
        InSet(col("i_manufact_id"), tuple(range(20, 61))), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 5, 25),
                            _date(2000, 11, 25)), t["date_dim"])
    inv = CpuFilter(_between(col("inv_quantity_on_hand"),
                             lit(100), lit(500)), t["inventory"])
    j = _join(_join(_join(it, inv, ["i_item_sk"], ["inv_item_sk"]),
                    dd, ["inv_date_sk"], ["d_date_sk"]),
              t["store_sales"], ["i_item_sk"], ["ss_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_item_desc"), col("i_current_price")],
        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_item_id"), col("i_item_desc"),
                      col("i_current_price")], agg)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], out))


def q91(t, run):
    """Reference q91: call-center returns loss for one demographic
    slice."""
    dd = CpuFilter((col("d_year") == lit(1998)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Unknown"))) |
        ((col("cd_marital_status") == lit("W")) &
         (col("cd_education_status") == lit("Advanced Degree"))),
        t["customer_demographics"])
    hd = CpuFilter(Like(col("hd_buy_potential"), lit("Unknown%")),
                   t["household_demographics"])
    ca = CpuFilter(col("ca_gmt_offset") == lit(-7.0),
                   t["customer_address"])
    j = _join(_join(dd, t["catalog_returns"],
                    ["d_date_sk"], ["cr_returned_date_sk"]),
              t["call_center"], ["cr_call_center_sk"],
              ["cc_call_center_sk"])
    j = _join(j, t["customer"], ["cr_returning_customer_sk"],
              ["c_customer_sk"])
    j = _join(_join(_join(j, cd, ["c_current_cdemo_sk"],
                          ["cd_demo_sk"]),
                    hd, ["c_current_hdemo_sk"], ["hd_demo_sk"]),
              ca, ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate(
        [col("cc_call_center_id"), col("cc_name"), col("cc_manager"),
         col("cd_marital_status"), col("cd_education_status")],
        [Sum(col("cr_net_loss")).alias("Returns_Loss")], j)
    out = CpuProject(
        [col("cc_call_center_id").alias("Call_Center"),
         col("cc_name").alias("Call_Center_Name"),
         col("cc_manager").alias("Manager"), col("Returns_Loss")], agg)
    return CpuSort([desc(col("Returns_Loss"))], out)


def q92(t, run):
    """Reference q92: web discounts exceeding 1.3x the per-item window
    average (correlated subquery as aggregate re-join)."""
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 27),
                            _date(2000, 4, 26)), t["date_dim"])
    ws = _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"])
    it = CpuFilter(InSet(col("i_manufact_id"),
                         tuple(range(30, 40))), t["item"])
    j = _join(ws, it, ["ws_item_sk"], ["i_item_sk"])
    avg = CpuProject(
        [col("ws_item_sk").alias("_isk"),
         (col("a") * lit(1.3)).alias("threshold")],
        CpuAggregate(
            [col("ws_item_sk")],
            [Average(col("ws_ext_discount_amt")).alias("a")], ws))
    out = _join(j, avg, ["ws_item_sk"], ["_isk"])
    out = CpuFilter(col("ws_ext_discount_amt") > col("threshold"), out)
    agg = CpuAggregate(
        [], [Sum(col("ws_ext_discount_amt")).alias("excess")], out)
    return CpuLimit(100, agg)


def q94(t, run):
    """Reference q94: multi-warehouse never-returned web orders (EXISTS
    as a >1-warehouse-order semi join, NOT EXISTS as anti join)."""
    dd = CpuFilter(_between(col("d_date"), _date(1999, 2, 1),
                            _date(1999, 4, 2)), t["date_dim"])
    ca = CpuFilter(col("ca_state") == lit("IL"),
                   t["customer_address"])
    site = CpuFilter(col("web_company_name") == lit("pri"),
                     t["web_site"])
    ws1 = _join(_join(_join(
        dd, t["web_sales"], ["d_date_sk"], ["ws_ship_date_sk"]),
        ca, ["ws_ship_addr_sk"], ["ca_address_sk"]),
        site, ["ws_web_site_sk"], ["web_site_sk"])
    multi_wh = CpuFilter(
        col("nwh") > lit(1),
        CpuAggregate(
            [col("morder")], [Count(None).alias("nwh")],
            CpuAggregate(
                [col("ws_order_number").alias("morder"),
                 col("ws_warehouse_sk")],
                [Count(None).alias("_c")], t["web_sales"])))
    ws1 = _join(ws1, multi_wh, ["ws_order_number"], ["morder"],
                jt=J.LEFT_SEMI)
    ws1 = _join(ws1, t["web_returns"], ["ws_order_number"],
                ["wr_order_number"], jt=J.LEFT_ANTI)
    dist = CpuAggregate(
        [], [Count(col("dorder")).alias("order_count")],
        CpuAggregate([col("ws_order_number").alias("dorder")],
                     [Count(None).alias("_d")], ws1))
    sums = CpuAggregate(
        [], [Sum(col("ws_ext_ship_cost")).alias("total_ship_cost"),
             Sum(col("ws_net_profit")).alias("total_net_profit")], ws1)
    j = _join(CpuProject([lit(1).alias("_ka"), col("order_count")],
                         dist),
              CpuProject([lit(1).alias("_kb"), col("total_ship_cost"),
                          col("total_net_profit")], sums),
              ["_ka"], ["_kb"])
    return CpuLimit(100, CpuProject(
        [col("order_count"), col("total_ship_cost"),
         col("total_net_profit")], j))


def _distinct_channel_triples(t, sales, date_key, cust_key):
    dd = CpuFilter(_between(col("d_month_seq"), lit(24), lit(35)),
                   t["date_dim"])
    j = _join(_join(dd, sales, ["d_date_sk"], [date_key]),
              t["customer"], [cust_key], ["c_customer_sk"])
    return CpuAggregate(
        [col("c_last_name"), col("c_first_name"), col("d_date")],
        [Count(None).alias("_n")], j)


def q38(t, run):
    """Reference q38: customers active in ALL three channels
    (INTERSECT as successive semi joins on the distinct triples)."""
    ss = _distinct_channel_triples(t, t["store_sales"],
                                   "ss_sold_date_sk", "ss_customer_sk")
    cs = CpuProject(
        [col("c_last_name").alias("cl"), col("c_first_name").alias("cf"),
         col("d_date").alias("cd")],
        _distinct_channel_triples(t, t["catalog_sales"],
                                  "cs_sold_date_sk",
                                  "cs_bill_customer_sk"))
    ws = CpuProject(
        [col("c_last_name").alias("wl"), col("c_first_name").alias("wf"),
         col("d_date").alias("wd")],
        _distinct_channel_triples(t, t["web_sales"],
                                  "ws_sold_date_sk",
                                  "ws_bill_customer_sk"))
    both = _join(ss, cs, ["c_last_name", "c_first_name", "d_date"],
                 ["cl", "cf", "cd"], jt=J.LEFT_SEMI)
    allc = _join(both, ws, ["c_last_name", "c_first_name", "d_date"],
                 ["wl", "wf", "wd"], jt=J.LEFT_SEMI)
    return CpuLimit(100, CpuAggregate(
        [], [Count(None).alias("cnt")], allc))


def q87(t, run):
    """Reference q87: store-only customer/date triples (EXCEPT as
    successive anti joins)."""
    ss = _distinct_channel_triples(t, t["store_sales"],
                                   "ss_sold_date_sk", "ss_customer_sk")
    cs = CpuProject(
        [col("c_last_name").alias("cl"), col("c_first_name").alias("cf"),
         col("d_date").alias("cd")],
        _distinct_channel_triples(t, t["catalog_sales"],
                                  "cs_sold_date_sk",
                                  "cs_bill_customer_sk"))
    ws = CpuProject(
        [col("c_last_name").alias("wl"), col("c_first_name").alias("wf"),
         col("d_date").alias("wd")],
        _distinct_channel_triples(t, t["web_sales"],
                                  "ws_sold_date_sk",
                                  "ws_bill_customer_sk"))
    no_cs = _join(ss, cs, ["c_last_name", "c_first_name", "d_date"],
                  ["cl", "cf", "cd"], jt=J.LEFT_ANTI)
    only_ss = _join(no_cs, ws, ["c_last_name", "c_first_name",
                                "d_date"],
                    ["wl", "wf", "wd"], jt=J.LEFT_ANTI)
    return CpuAggregate([], [Count(None).alias("cnt")], only_ss)


QUERIES.update({
    "q12": q12, "q20": q20, "q82": q82, "q92": q92,
    "q94": q94, "q38": q38, "q87": q87,
})


def q9(t, run):
    """Reference q9: five quantity-band CASE buckets from scalar
    subqueries (run() materializes each, the CASE picks avg discount vs
    avg net_paid by count threshold; thresholds scaled to the
    generator's volumes)."""
    bands = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    exprs = []
    for i, (lo, hi) in enumerate(bands, start=1):
        stats = run(CpuAggregate(
            [], [Count(None).alias("c"),
                 Average(col("ss_ext_discount_amt")).alias("ad"),
                 Average(col("ss_net_paid")).alias("ap")],
            CpuFilter(_between(col("ss_quantity"), lit(lo), lit(hi)),
                      t["store_sales"])))
        cnt = int(stats["c"].iloc[0])
        val = float(stats["ad"].iloc[0] if cnt > 1200
                    else stats["ap"].iloc[0])
        exprs.append(lit(val).alias(f"bucket{i}"))
    one = CpuFilter(col("r_reason_sk") == lit(1), t["reason"])
    return CpuProject(exprs, one)


def q41(t, run):
    """Reference q41: distinct product names whose manufacturer also
    makes items in the listed color/unit/size combinations."""
    arms = (
        (InSet(col("i_category"), ("Women",)) &
         InSet(col("i_color"), ("powder", "khaki")) &
         InSet(col("i_units"), ("Ounce", "Oz")) &
         InSet(col("i_size"), ("medium", "extra large"))) |
        (InSet(col("i_category"), ("Music",)) &
         InSet(col("i_color"), ("floral", "deep")) &
         InSet(col("i_units"), ("N/A", "Dozen")) &
         InSet(col("i_size"), ("petite", "large"))) |
        (InSet(col("i_category"), ("Shoes",)) &
         InSet(col("i_color"), ("light", "cornflower")) &
         InSet(col("i_units"), ("Box", "Pound")) &
         InSet(col("i_size"), ("medium", "extra large"))) |
        (InSet(col("i_category"), ("Books",)) &
         InSet(col("i_color"), ("midnight", "snow")) &
         InSet(col("i_units"), ("Ounce", "Oz")) &
         InSet(col("i_size"), ("petite", "large"))))
    match_manufact = CpuProject(
        [col("i_manufact").alias("_mf")],
        CpuAggregate([col("i_manufact")], [Count(None).alias("_c")],
                     CpuFilter(arms, t["item"])))
    i1 = CpuFilter(_between(col("i_manufact_id"), lit(1), lit(40)),
                   t["item"])
    j = _join(i1, match_manufact, ["i_manufact"], ["_mf"],
              jt=J.LEFT_SEMI)
    dist = CpuAggregate([col("i_product_name")],
                        [Count(None).alias("_c")], j)
    out = CpuProject([col("i_product_name")], dist)
    return CpuLimit(100, CpuSort([asc(col("i_product_name"))], out))


def q16(t, run):
    """Reference q16: multi-warehouse never-returned catalog orders for
    one county/state window (EXISTS/NOT EXISTS as semi/anti joins)."""
    dd = CpuFilter(_between(col("d_date"), _date(2002, 2, 1),
                            _date(2002, 4, 2)), t["date_dim"])
    ca = CpuFilter(col("ca_state") == lit("GA"),
                   t["customer_address"])
    cc = CpuFilter(InSet(col("cc_county"), ("Williamson County",)),
                   t["call_center"])
    cs1 = _join(_join(_join(
        dd, t["catalog_sales"], ["d_date_sk"], ["cs_ship_date_sk"]),
        ca, ["cs_ship_addr_sk"], ["ca_address_sk"]),
        cc, ["cs_call_center_sk"], ["cc_call_center_sk"])
    multi_wh = CpuFilter(
        col("nwh") > lit(1),
        CpuAggregate(
            [col("morder")], [Count(None).alias("nwh")],
            CpuAggregate(
                [col("cs_order_number").alias("morder"),
                 col("cs_warehouse_sk")],
                [Count(None).alias("_c")], t["catalog_sales"])))
    cs1 = _join(cs1, multi_wh, ["cs_order_number"], ["morder"],
                jt=J.LEFT_SEMI)
    cs1 = _join(cs1, t["catalog_returns"], ["cs_order_number"],
                ["cr_order_number"], jt=J.LEFT_ANTI)
    dist = CpuAggregate(
        [], [Count(col("dorder")).alias("order_count")],
        CpuAggregate([col("cs_order_number").alias("dorder")],
                     [Count(None).alias("_d")], cs1))
    sums = CpuAggregate(
        [], [Sum(col("cs_ext_ship_cost")).alias("total_ship_cost"),
             Sum(col("cs_net_profit")).alias("total_net_profit")], cs1)
    j = _join(CpuProject([lit(1).alias("_ka"), col("order_count")],
                         dist),
              CpuProject([lit(1).alias("_kb"), col("total_ship_cost"),
                          col("total_net_profit")], sums),
              ["_ka"], ["_kb"])
    return CpuLimit(100, CpuProject(
        [col("order_count"), col("total_ship_cost"),
         col("total_net_profit")], j))


QUERIES.update({"q9": q9, "q41": q41, "q16": q16})


def q21(t, run):
    """Reference q21: warehouse inventory before/after one cutover date
    for a price band, keeping ratio-bounded rows."""
    it = CpuFilter(_between(col("i_current_price"),
                            lit(10.0), lit(60.0)), t["item"])
    dd = CpuFilter(_between(col("d_date"), _date(2000, 1, 1),
                            _date(2000, 6, 30)), t["date_dim"])
    j = _join(_join(_join(
        dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
        t["warehouse"], ["inv_warehouse_sk"], ["w_warehouse_sk"]),
        it, ["inv_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("i_item_id")],
        [Sum(If(col("d_date") < _date(2000, 3, 11),
                col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_date") >= _date(2000, 3, 11),
                col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    ratio = If(col("inv_before") > lit(0),
               col("inv_after") / col("inv_before"),
               _Lit(None, _T.FLOAT64))
    # reference band is 2/3..3/2; the sparse synthetic inventory
    # needs a wider one to keep rows
    out = CpuFilter((ratio >= lit(0.1)) & (ratio <= lit(10.0)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_name")), asc(col("i_item_id"))], out))


QUERIES.update({"q21": q21})
