"""TPC-DS-like query subset (reference
`integration_tests/.../tpcds/TpcdsLikeSpark.scala` — the classic
star-join report set: q3, q7-shape, q19, q27-shape, q42, q52, q55, q68,
q73, q96, q98-shape).  Same plan-tree style as tpch_queries."""
from __future__ import annotations

from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.predicates import InSet
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort)

J = JoinType


def _join(left, right, lk, rk, jt=J.INNER, condition=None):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right, condition=condition)


def q3(t, run):
    """Brand revenue by year for one manufacturer in December."""
    dd = CpuFilter(col("d_moy") == lit(12), t["date_dim"])
    it = CpuFilter(col("i_manufact_id") == lit(5), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("sum_agg")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("sum_agg")),
         asc(col("i_brand_id"))], agg))


def q19(t, run):
    """Brand revenue for one month/year by manager."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(8), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand"), col("i_manufact_id")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id")),
         asc(col("i_manufact_id"))], agg))


def q42(t, run):
    """Category revenue for one month/year."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_category_id"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("total")), asc(col("d_year")),
         asc(col("i_category_id"))], agg))


def q52(t, run):
    """Brand revenue, one month/year (q42 by brand)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("ext_price")),
         asc(col("i_brand_id"))], agg))


def q55(t, run):
    """Brand revenue for one manager, month, year."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(28), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id"))], agg))


def q7_shape(t, run):
    """Average metrics per item under promotion (q7 without cdemo)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    promo = CpuFilter((col("p_channel_email") == lit("N")) |
                      (col("p_channel_event") == lit("N")),
                      t["promotion"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    promo, ["ss_promo_sk"], ["p_promo_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q27_shape(t, run):
    """State-level item averages (q27 without cdemo rollup)."""
    dd = CpuFilter(col("d_year") == lit(2002), t["date_dim"])
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA", "NY")),
                   t["store"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    st, ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_state")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_state"))], agg))


def q68(t, run):
    """Per-ticket totals for high-dependency households in two cities."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   InSet(col("d_dom"), tuple(range(1, 3))),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          st, ["ss_store_sk"], ["s_store_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_ext_sales_price")).alias("extended_price"),
         Sum(col("ss_ext_list_price")).alias("list_price"),
         Sum(col("ss_ext_wholesale_cost")).alias("extended_tax")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("ss_ticket_number"), col("extended_price"),
         col("extended_tax"), col("list_price")], j2)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))], out))


def q73(t, run):
    """Ticket counts per customer for mid-size baskets."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    hd = CpuFilter(col("hd_buy_potential") == lit(">10000"),
                   t["household_demographics"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    big = CpuFilter((col("cnt") >= lit(2)) & (col("cnt") <= lit(50)),
                    per_ticket)
    j2 = _join(big, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         col("ss_ticket_number"), col("cnt")], j2)
    return CpuSort([desc(col("cnt")), asc(col("c_last_name")),
                    asc(col("ss_ticket_number"))], out)


def q96(t, run):
    """Count of sales in a demographic/time slice."""
    hd = CpuFilter(col("hd_dep_count") == lit(7),
                   t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Count(None).alias("cnt")], j)


def q98_shape(t, run):
    """Revenue by item within categories over one month."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(2)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Sports", "Books", "Home")), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category"), col("i_current_price")],
        [Sum(col("ss_ext_sales_price")).alias("itemrevenue")], j)
    return CpuSort([asc(col("i_category")), asc(col("i_item_id"))], agg)


QUERIES = {
    "q3": q3, "q7": q7_shape, "q19": q19, "q27": q27_shape,
    "q42": q42, "q52": q52, "q55": q55, "q68": q68, "q73": q73,
    "q96": q96, "q98": q98_shape,
}
