"""TPC-DS-like query set (reference
`integration_tests/.../tpcds/TpcdsLikeSpark.scala`).  Same plan-tree
style as tpch_queries; queries marked "-shape" follow the reference
query's operator shape over the engine's v0 type matrix (no decimals,
reduced column sets).  Coverage spans the reference's main families:
star-join reports, returns-vs-average correlated shapes, multi-channel
unions, semi/anti-join existence tests, left-outer returns netting,
shipping-lag bucketing, time-slot pivots, and ratio reports."""
from __future__ import annotations

from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Average, Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import Coalesce, If
from spark_rapids_tpu.exprs.predicates import InSet, IsNotNull, IsNull
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort, CpuUnion)

J = JoinType


def _join(left, right, lk, rk, jt=J.INNER, condition=None):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right, condition=condition)


def q3(t, run):
    """Brand revenue by year for one manufacturer in December."""
    dd = CpuFilter(col("d_moy") == lit(12), t["date_dim"])
    it = CpuFilter(col("i_manufact_id") == lit(5), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("sum_agg")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("sum_agg")),
         asc(col("i_brand_id"))], agg))


def q19(t, run):
    """Brand revenue for one month/year by manager."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(8), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand"), col("i_manufact_id")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id")),
         asc(col("i_manufact_id"))], agg))


def q42(t, run):
    """Category revenue for one month/year."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_category_id"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("total")), asc(col("d_year")),
         asc(col("i_category_id"))], agg))


def q52(t, run):
    """Brand revenue, one month/year (q42 by brand)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("d_year"), col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("d_year")), desc(col("ext_price")),
         asc(col("i_brand_id"))], agg))


def q55(t, run):
    """Brand revenue for one manager, month, year."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    it = CpuFilter(col("i_manager_id") == lit(28), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_brand")],
        [Sum(col("ss_ext_sales_price")).alias("ext_price")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ext_price")), asc(col("i_brand_id"))], agg))


def q7_shape(t, run):
    """Average metrics per item under promotion (q7 without cdemo)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    promo = CpuFilter((col("p_channel_email") == lit("N")) |
                      (col("p_channel_event") == lit("N")),
                      t["promotion"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    promo, ["ss_promo_sk"], ["p_promo_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q27_shape(t, run):
    """State-level item averages (q27 without cdemo rollup)."""
    dd = CpuFilter(col("d_year") == lit(2002), t["date_dim"])
    st = CpuFilter(InSet(col("s_state"), ("TX", "CA", "WA", "NY")),
                   t["store"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    st, ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_state")],
        [Average(col("ss_quantity")).alias("agg1"),
         Average(col("ss_list_price")).alias("agg2"),
         Average(col("ss_coupon_amt")).alias("agg3"),
         Average(col("ss_sales_price")).alias("agg4")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_state"))], agg))


def q68(t, run):
    """Per-ticket totals for high-dependency households in two cities."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   InSet(col("d_dom"), tuple(range(1, 3))),
                   t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          st, ["ss_store_sk"], ["s_store_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_ext_sales_price")).alias("extended_price"),
         Sum(col("ss_ext_list_price")).alias("list_price"),
         Sum(col("ss_ext_wholesale_cost")).alias("extended_tax")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"), col("ca_city"),
         col("ss_ticket_number"), col("extended_price"),
         col("extended_tax"), col("list_price")], j2)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))], out))


def q73(t, run):
    """Ticket counts per customer for mid-size baskets."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    hd = CpuFilter(col("hd_buy_potential") == lit(">10000"),
                   t["household_demographics"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    big = CpuFilter((col("cnt") >= lit(2)) & (col("cnt") <= lit(50)),
                    per_ticket)
    j2 = _join(big, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    out = CpuProject(
        [col("c_last_name"), col("c_first_name"),
         col("ss_ticket_number"), col("cnt")], j2)
    return CpuSort([desc(col("cnt")), asc(col("c_last_name")),
                    asc(col("ss_ticket_number"))], out)


def q96(t, run):
    """Count of sales in a demographic/time slice."""
    hd = CpuFilter(col("hd_dep_count") == lit(7),
                   t["household_demographics"])
    st = CpuFilter(col("s_store_name") == lit("ese"), t["store"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Count(None).alias("cnt")], j)


def q98_shape(t, run):
    """Revenue by item within categories over one month."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(2)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Sports", "Books", "Home")), t["item"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              it, ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category"), col("i_current_price")],
        [Sum(col("ss_ext_sales_price")).alias("itemrevenue")], j)
    return CpuSort([asc(col("i_category")), asc(col("i_item_id"))], agg)




# ---------------------------------------------------------------------------
# returns / correlated-average shapes
def q1(t, run):
    """Customers whose store-return total exceeds 1.2x their store's
    average (reference q1's correlated subquery, decorrelated into an
    aggregate-join)."""
    ctr = CpuAggregate(
        [col("sr_customer_sk"), col("sr_store_sk")],
        [Sum(col("sr_return_amt")).alias("ctr_total")],
        t["store_returns"])
    avg_ctr = CpuAggregate(
        [col("sr_store_sk")],
        [Average(col("ctr_total")).alias("avg_ret")],
        CpuProject([col("sr_store_sk"), col("ctr_total")], ctr))
    big = CpuFilter(
        col("ctr_total") > col("avg_ret") * lit(1.2),
        _join(ctr, CpuProject(
            [col("sr_store_sk").alias("st2"), col("avg_ret")], avg_ctr),
            ["sr_store_sk"], ["st2"]))
    st = CpuFilter(col("s_state") == lit("TX"), t["store"])
    j = _join(_join(big, st, ["sr_store_sk"], ["s_store_sk"]),
              t["customer"], ["sr_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], j)))


def q6_shape(t, run):
    """States of customers buying items priced 1.2x above their
    category average."""
    avg_cat = CpuAggregate(
        [col("i_category")],
        [Average(col("i_current_price")).alias("avg_p")], t["item"])
    pricey = CpuFilter(
        col("i_current_price") > col("avg_p") * lit(1.2),
        _join(t["item"], CpuProject(
            [col("i_category").alias("cat2"), col("avg_p")], avg_cat),
            ["i_category"], ["cat2"]))
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(1)), t["date_dim"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          pricey, ["ss_item_sk"], ["i_item_sk"]),
                    t["customer"],
                    ["ss_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("cnt")), asc(col("ca_state"))],
        CpuFilter(col("cnt") >= lit(3), agg)))


def q65(t, run):
    """Store items whose revenue is at most 10% of the store's average
    item revenue."""
    sa = CpuAggregate(
        [col("ss_store_sk"), col("ss_item_sk")],
        [Sum(col("ss_sales_price")).alias("revenue")], t["store_sales"])
    sb = CpuAggregate(
        [col("ss_store_sk")],
        [Average(col("revenue")).alias("ave")],
        CpuProject([col("ss_store_sk"), col("revenue")], sa))
    low = CpuFilter(
        col("revenue") <= col("ave") * lit(0.1),
        _join(sa, CpuProject([col("ss_store_sk").alias("sk2"),
                              col("ave")], sb),
              ["ss_store_sk"], ["sk2"]))
    j = _join(_join(low, t["store"], ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("i_item_id"))],
        CpuProject([col("s_store_name"), col("i_item_id"),
                    col("revenue")], j)))


# ---------------------------------------------------------------------------
# catalog / web channel star joins
def q15_shape(t, run):
    """Catalog revenue by customer state for one quarter."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    t["customer"],
                    ["cs_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Sum(col("cs_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q26(t, run):
    """Catalog item averages for one demographic slice (q7's catalog
    twin)."""
    cd = CpuFilter((col("cd_gender") == lit("M")) &
                   (col("cd_marital_status") == lit("S")) &
                   (col("cd_education_status") == lit("College")),
                   t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(_join(dd, t["catalog_sales"],
                          ["d_date_sk"], ["cs_sold_date_sk"]),
                    cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"]),
              t["item"], ["cs_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_sales_price")).alias("agg3")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q45_shape(t, run):
    """Web revenue by customer state for one quarter."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_qoy") == lit(2)), t["date_dim"])
    j = _join(_join(_join(dd, t["web_sales"],
                          ["d_date_sk"], ["ws_sold_date_sk"]),
                    t["customer"],
                    ["ws_bill_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Sum(col("ws_sales_price")).alias("total")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q48_shape(t, run):
    """Store quantity total across demographic/quantity-band slices."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree"))) |
        ((col("cd_marital_status") == lit("D")) &
         (col("cd_education_status") == lit("2 yr Degree"))),
        t["customer_demographics"])
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    sales = CpuFilter(
        ((col("ss_quantity") >= lit(1)) &
         (col("ss_quantity") <= lit(40))) |
        ((col("ss_quantity") >= lit(61)) &
         (col("ss_quantity") <= lit(100))), t["store_sales"])
    j = _join(_join(_join(dd, sales,
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("total")], j)


# ---------------------------------------------------------------------------
# multi-channel unions
def q33_shape(t, run):
    """Manufacturer revenue across all three channels for one month
    (reference q33/q56/q60 family)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(3)), t["date_dim"])
    it = CpuFilter(col("i_category") == lit("Books"), t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_manufact_id"),
             col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_manufact_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort([desc(col("total_sales")),
                                  asc(col("i_manufact_id"))], agg))


def q28_shape(t, run):
    """Six price-band averages over store_sales (reference q28's six
    bucket subqueries, united instead of cross-joined)."""
    bands = [(0, 5, 11), (6, 51, 57), (11, 91, 97),
             (16, 131, 137), (21, 171, 177), (26, 100, 200)]
    parts = []
    for i, (qlo, plo, phi) in enumerate(bands):
        f = CpuFilter(
            (col("ss_quantity") >= lit(qlo)) &
            (col("ss_quantity") <= lit(qlo + 4)) &
            (col("ss_list_price") >= lit(float(plo))) &
            (col("ss_list_price") <= lit(float(phi))),
            t["store_sales"])
        agg = CpuAggregate(
            [], [Average(col("ss_list_price")).alias("avg_price"),
                 Count(col("ss_list_price")).alias("cnt")], f)
        parts.append(CpuProject(
            [lit(i).alias("bucket"), col("avg_price"), col("cnt")], agg))
    return CpuSort([asc(col("bucket"))], CpuUnion(*parts))


# ---------------------------------------------------------------------------
# existence tests (semi/anti joins)
def q16_shape(t, run):
    """Catalog orders in a date window with no returns: order count +
    cost sums (reference q16's `not exists` as a LEFT_ANTI join;
    distinct order count as a per-order pre-aggregate)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") <= lit(4)), t["date_dim"])
    sales = _join(dd, t["catalog_sales"],
                  ["d_date_sk"], ["cs_sold_date_sk"])
    no_ret = CpuHashJoin(
        J.LEFT_ANTI, [col("cs_order_number")], [col("cr_order_number")],
        sales, t["catalog_returns"])
    per_order = CpuAggregate(
        [col("cs_order_number")],
        [Sum(col("cs_ext_ship_cost")).alias("ship_cost"),
         Sum(col("cs_net_profit")).alias("net_profit")], no_ret)
    return CpuAggregate(
        [], [Count(None).alias("order_count"),
             Sum(col("ship_cost")).alias("total_shipping_cost"),
             Sum(col("net_profit")).alias("total_net_profit")],
        per_order)


def q37_shape(t, run):
    """Items in a price band with healthy inventory that sold through
    catalog (reference q37: inventory + semi-join on catalog sales)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(20.0)) &
        (col("i_current_price") <= lit(50.0)), t["item"])
    inv = CpuFilter(
        (col("inv_quantity_on_hand") >= lit(100)) &
        (col("inv_quantity_on_hand") <= lit(500)), t["inventory"])
    stocked = _join(it, inv, ["i_item_sk"], ["inv_item_sk"])
    sold = CpuHashJoin(
        J.LEFT_SEMI, [col("i_item_sk")], [col("cs_item_sk")],
        stocked, t["catalog_sales"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_current_price")],
        [Count(None).alias("stock_rows")], sold)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q97(t, run):
    """Customer-item overlap between store and catalog channels
    (reference q97: FULL OUTER join of deduplicated channel pairs)."""
    ssci = CpuAggregate(
        [col("ss_customer_sk"), col("ss_item_sk")],
        [Count(None).alias("_s")], t["store_sales"])
    csci = CpuAggregate(
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        [Count(None).alias("_c")], t["catalog_sales"])
    j = CpuHashJoin(
        J.FULL_OUTER,
        [col("ss_customer_sk"), col("ss_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")], ssci, csci)
    return CpuAggregate(
        [],
        [Sum(If(IsNotNull(col("_s")) & IsNull(col("_c")),
                lit(1), lit(0))).alias("store_only"),
         Sum(If(IsNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("catalog_only"),
         Sum(If(IsNotNull(col("_s")) & IsNotNull(col("_c")),
                lit(1), lit(0))).alias("store_and_catalog")], j)


# ---------------------------------------------------------------------------
# returns netting / outer joins
def q93_shape(t, run):
    """Actual net paid per customer: sold quantity minus returned
    quantity (reference q93's LEFT OUTER store_returns netting)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    paid = CpuProject(
        [col("ss_customer_sk"),
         If(IsNotNull(col("sr_return_quantity")),
            (col("ss_quantity") - col("sr_return_quantity"))
            * col("ss_sales_price"),
            col("ss_quantity") * col("ss_sales_price")).alias("act_sales")],
        j)
    agg = CpuAggregate([col("ss_customer_sk")],
                       [Sum(col("act_sales")).alias("sumsales")], paid)
    return CpuLimit(100, CpuSort(
        [desc(col("sumsales")), asc(col("ss_customer_sk"))], agg))


def q40_shape(t, run):
    """Catalog sales netted against returns by warehouse state, split
    around a pivot date (reference q40's before/after CASE sums)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("cs_order_number"), col("cs_item_sk")],
        [col("cr_order_number"), col("cr_item_sk")],
        t["catalog_sales"], t["catalog_returns"])
    j = _join(_join(j, t["warehouse"],
                    ["cs_warehouse_sk"], ["w_warehouse_sk"]),
              CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
              ["cs_sold_date_sk"], ["d_date_sk"])
    net = col("cs_sales_price") - Coalesce(
        (col("cr_return_amount"), lit(0.0)))
    agg = CpuAggregate(
        [col("w_state")],
        [Sum(If(col("d_moy") < lit(6), net, lit(0.0))).alias(
            "sales_before"),
         Sum(If(col("d_moy") >= lit(6), net, lit(0.0))).alias(
            "sales_after")], j)
    return CpuSort([asc(col("w_state"))], agg)


def q25_shape(t, run):
    """Items sold, returned, then re-bought on catalog (reference q25's
    three-fact join), with profit rollups."""
    ss = _join(CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
               t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
    sr = CpuHashJoin(
        J.INNER,
        [col("ss_customer_sk"), col("ss_item_sk"),
         col("ss_ticket_number")],
        [col("sr_customer_sk"), col("sr_item_sk"),
         col("sr_ticket_number")], ss, t["store_returns"])
    cs = CpuHashJoin(
        J.INNER,
        [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        sr, t["catalog_sales"])
    j = _join(_join(cs, t["store"], ["ss_store_sk"], ["s_store_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("s_store_id")],
        [Sum(col("ss_net_profit")).alias("store_sales_profit"),
         Sum(col("sr_net_loss")).alias("store_returns_loss"),
         Sum(col("cs_net_profit")).alias("catalog_sales_profit")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("s_store_id"))], agg))


# ---------------------------------------------------------------------------
# shipping-lag bucketing
def _lag_buckets(lag, prefix):
    b = lambda c: Sum(If(c, lit(1), lit(0)))
    return [
        b(lag <= lit(30)).alias(f"{prefix}30_days"),
        b((lag > lit(30)) & (lag <= lit(60))).alias(f"{prefix}60_days"),
        b((lag > lit(60)) & (lag <= lit(90))).alias(f"{prefix}90_days"),
        b(lag > lit(90)).alias(f"{prefix}more_days"),
    ]


def q62_shape(t, run):
    """Web shipping-lag day buckets per warehouse (reference q62)."""
    j = _join(t["web_sales"], t["warehouse"],
              ["ws_warehouse_sk"], ["w_warehouse_sk"])
    lag = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    agg = CpuAggregate([col("w_warehouse_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q99_shape(t, run):
    """Catalog shipping-lag day buckets per warehouse (reference q99)."""
    j = _join(t["catalog_sales"], t["warehouse"],
              ["cs_warehouse_sk"], ["w_warehouse_sk"])
    lag = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    agg = CpuAggregate([col("w_warehouse_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q50_shape(t, run):
    """Store return-lag day buckets per store (reference q50)."""
    j = CpuHashJoin(
        J.INNER,
        [col("ss_item_sk"), col("ss_ticket_number"),
         col("ss_customer_sk")],
        [col("sr_item_sk"), col("sr_ticket_number"),
         col("sr_customer_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    lag = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    agg = CpuAggregate([col("s_store_name")],
                       _lag_buckets(lag, ""), j)
    return CpuSort([asc(col("s_store_name"))], agg)


# ---------------------------------------------------------------------------
# pivots, time slots, ratios
def q43_shape(t, run):
    """Day-of-week sales pivot per store (reference q43)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["store_sales"],
                    ["d_date_sk"], ["ss_sold_date_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    day = lambda name: Sum(If(col("d_day_name") == lit(name),
                              col("ss_sales_price"), lit(0.0)))
    agg = CpuAggregate(
        [col("s_store_name"), col("s_store_id")],
        [day("Sunday").alias("sun_sales"),
         day("Monday").alias("mon_sales"),
         day("Tuesday").alias("tue_sales"),
         day("Wednesday").alias("wed_sales"),
         day("Thursday").alias("thu_sales"),
         day("Friday").alias("fri_sales"),
         day("Saturday").alias("sat_sales")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("s_store_name")), asc(col("s_store_id"))], agg))


def q88_shape(t, run):
    """Counts of store sales in four afternoon time slots for one
    demographic (reference q88's eight-way self-join, as one pivot)."""
    hd = CpuFilter(col("hd_dep_count") == lit(3),
                   t["household_demographics"])
    j = _join(_join(t["store_sales"], hd,
                    ["ss_hdemo_sk"], ["hd_demo_sk"]),
              t["time_dim"], ["ss_sold_time_sk"], ["t_time_sk"])
    slot = lambda h: Sum(If((col("t_hour") == lit(h)), lit(1), lit(0)))
    return CpuAggregate(
        [], [slot(12).alias("h12"), slot(13).alias("h13"),
             slot(14).alias("h14"), slot(15).alias("h15")], j)


def q90_shape(t, run):
    """Web AM/PM order ratio (reference q90)."""
    j = _join(t["web_sales"], t["time_dim"],
              ["ws_sold_time_sk"], ["t_time_sk"])
    counts = CpuAggregate(
        [], [Sum(If((col("t_hour") >= lit(8)) & (col("t_hour") < lit(12)),
                    lit(1), lit(0))).alias("amc"),
             Sum(If((col("t_hour") >= lit(14)) &
                    (col("t_hour") < lit(18)),
                    lit(1), lit(0))).alias("pmc")], j)
    return CpuProject(
        [col("amc"), col("pmc"),
         (col("amc") / col("pmc")).alias("am_pm_ratio")], counts)


def q61_shape(t, run):
    """Promotional vs total store revenue ratio for one month
    (reference q61's two-aggregate cross join via a key literal)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(11)), t["date_dim"])
    base = _join(dd, t["store_sales"],
                 ["d_date_sk"], ["ss_sold_date_sk"])
    promo_rows = _join(base, CpuFilter(
        (col("p_channel_email") == lit("Y")) |
        (col("p_channel_event") == lit("Y")), t["promotion"]),
        ["ss_promo_sk"], ["p_promo_sk"])
    promos = CpuProject(
        [lit(1).alias("k1"),
         col("promotions")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "promotions")], promo_rows))
    total = CpuProject(
        [lit(1).alias("k2"), col("total")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "total")], base))
    j = _join(promos, total, ["k1"], ["k2"])
    return CpuProject(
        [col("promotions"), col("total"),
         (col("promotions") / col("total") * lit(100.0)).alias(
             "promo_pct")], j)


def q79_shape(t, run):
    """Per-ticket profile for large stores and high-dependency
    households (reference q79's q68 sibling)."""
    dd = CpuFilter(col("d_year") == lit(1999), t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(6)) |
                   (col("hd_vehicle_count") > lit(2)),
                   t["household_demographics"])
    st = CpuFilter(col("s_number_employees") >= lit(200), t["store"])
    j = _join(_join(_join(dd, t["store_sales"],
                          ["d_date_sk"], ["ss_sold_date_sk"]),
                    hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
              st, ["ss_store_sk"], ["s_store_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("s_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("c_first_name")),
         asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("s_city"), col("ss_ticket_number"),
                    col("amt"), col("profit")], j2)))


def q46_shape(t, run):
    """Per-ticket city/amount profile on weekend days (reference q46)."""
    dd = CpuFilter(InSet(col("d_day_name"), ("Saturday", "Sunday")) &
                   (col("d_year") == lit(1999)), t["date_dim"])
    hd = CpuFilter((col("hd_dep_count") == lit(4)) |
                   (col("hd_vehicle_count") == lit(3)),
                   t["household_demographics"])
    st = CpuFilter(InSet(col("s_city"), ("Midway", "Fairview")),
                   t["store"])
    j = _join(_join(_join(_join(dd, t["store_sales"],
                                ["d_date_sk"], ["ss_sold_date_sk"]),
                          hd, ["ss_hdemo_sk"], ["hd_demo_sk"]),
                    st, ["ss_store_sk"], ["s_store_sk"]),
              t["customer_address"], ["ss_addr_sk"], ["ca_address_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk"),
         col("ca_city")],
        [Sum(col("ss_coupon_amt")).alias("amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    j2 = _join(per_ticket, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("ca_city"), col("ss_ticket_number"),
                    col("amt"), col("profit")], j2)))


def q92_shape(t, run):
    """Web sales with discount above 1.3x the item's average discount
    (reference q92's excess-discount correlated subquery)."""
    avg_disc = CpuAggregate(
        [col("ws_item_sk")],
        [Average(col("ws_ext_discount_amt")).alias("avg_disc")],
        t["web_sales"])
    j = _join(t["web_sales"],
              CpuProject([col("ws_item_sk").alias("isk2"),
                          col("avg_disc")], avg_disc),
              ["ws_item_sk"], ["isk2"])
    excess = CpuFilter(
        col("ws_ext_discount_amt") > col("avg_disc") * lit(1.3), j)
    return CpuAggregate(
        [], [Sum(col("ws_ext_discount_amt")).alias("excess_discount")],
        excess)





def q2_shape(t, run):
    """Week-day revenue share, store vs web channels united (reference
    q2's cross-channel weekly comparison)."""
    u = CpuUnion(
        CpuProject([col("ss_sold_date_sk").alias("sold_date_sk"),
                    col("ss_ext_sales_price").alias("price")],
                   t["store_sales"]),
        CpuProject([col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]))
    j = _join(u, t["date_dim"], ["sold_date_sk"], ["d_date_sk"])
    day = lambda n: Sum(If(col("d_day_name") == lit(n), col("price"),
                           lit(0.0)))
    agg = CpuAggregate(
        [col("d_year")],
        [day("Sunday").alias("sun"), day("Monday").alias("mon"),
         day("Friday").alias("fri"), day("Saturday").alias("sat")], j)
    return CpuSort([asc(col("d_year"))], agg)


def q13_shape(t, run):
    """Store averages across demographic/price-band OR-slices
    (reference q13)."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("Advanced Degree"))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("College"))),
        t["customer_demographics"])
    hd = CpuFilter(InSet(col("hd_dep_count"), (1, 3)),
                   t["household_demographics"])
    j = _join(_join(_join(
        CpuFilter(col("d_year") == lit(2001), t["date_dim"]),
        t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        cd, ["ss_cdemo_sk"], ["cd_demo_sk"]),
        hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    return CpuAggregate(
        [], [Average(col("ss_quantity")).alias("avg_qty"),
             Average(col("ss_ext_sales_price")).alias("avg_price"),
             Average(col("ss_ext_wholesale_cost")).alias("avg_cost"),
             Sum(col("ss_ext_wholesale_cost")).alias("sum_cost")], j)


def q18_shape(t, run):
    """Catalog purchase averages by customer state for one demographic
    (reference q18 without the rollup)."""
    cd = CpuFilter(col("cd_gender") == lit("F"),
                   t["customer_demographics"])
    j = _join(_join(_join(_join(
        CpuFilter(col("d_year") == lit(2001), t["date_dim"]),
        t["catalog_sales"], ["d_date_sk"], ["cs_sold_date_sk"]),
        cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"]),
        t["customer"], ["cs_bill_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_state")],
        [Average(col("cs_quantity")).alias("agg1"),
         Average(col("cs_list_price")).alias("agg2"),
         Average(col("cs_sales_price")).alias("agg3"),
         Average(col("cs_net_profit")).alias("agg4")], j)
    return CpuLimit(100, CpuSort([asc(col("ca_state"))], agg))


def q21ds_shape(t, run):
    """Inventory before/after a pivot date for a price band of items
    (reference q21)."""
    it = CpuFilter((col("i_current_price") >= lit(10.0)) &
                   (col("i_current_price") <= lit(60.0)), t["item"])
    j = _join(_join(_join(t["inventory"], it,
                          ["inv_item_sk"], ["i_item_sk"]),
                    t["warehouse"],
                    ["inv_warehouse_sk"], ["w_warehouse_sk"]),
              CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
              ["inv_date_sk"], ["d_date_sk"])
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("i_item_id")],
        [Sum(If(col("d_moy") < lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_moy") >= lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    ok = CpuFilter(
        (col("inv_before") > lit(0)) &
        (col("inv_after") * lit(10) >= col("inv_before") * lit(5)) &
        (col("inv_after") * lit(2) <= col("inv_before") * lit(3)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_name")), asc(col("i_item_id"))], ok))


def q32_shape(t, run):
    """Catalog sales with discount above 1.3x the item's average
    (reference q32, q92's catalog twin)."""
    avg_disc = CpuAggregate(
        [col("cs_item_sk")],
        [Average(col("cs_ext_discount_amt")).alias("avg_disc")],
        t["catalog_sales"])
    j = _join(t["catalog_sales"],
              CpuProject([col("cs_item_sk").alias("isk2"),
                          col("avg_disc")], avg_disc),
              ["cs_item_sk"], ["isk2"])
    excess = CpuFilter(
        col("cs_ext_discount_amt") > col("avg_disc") * lit(1.3), j)
    return CpuAggregate(
        [], [Sum(col("cs_ext_discount_amt")).alias("excess_discount")],
        excess)


def q34_shape(t, run):
    """Mid-size-basket customers for given buy potentials (reference
    q34, q73's sibling; its 15-20 basket band is widened to 3-20 for
    the small-scale synthetic data)."""
    hd = CpuFilter(InSet(col("hd_buy_potential"),
                         (">10000", "5001-10000")),
                   t["household_demographics"])
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    per_ticket = CpuAggregate(
        [col("ss_ticket_number"), col("ss_customer_sk")],
        [Count(None).alias("cnt")], j)
    band = CpuFilter((col("cnt") >= lit(3)) & (col("cnt") <= lit(20)),
                     per_ticket)
    j2 = _join(band, t["customer"],
               ["ss_customer_sk"], ["c_customer_sk"])
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), desc(col("cnt")),
         asc(col("ss_ticket_number"))],
        CpuProject([col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt")], j2)))


def q36_shape(t, run):
    """Gross margin ratio by item category (reference q36 without the
    rollup/window rank)."""
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_category")],
        [Sum(col("ss_net_profit")).alias("profit"),
         Sum(col("ss_ext_sales_price")).alias("sales")], j)
    return CpuSort(
        [asc(col("i_category"))],
        CpuProject([col("i_category"),
                    (col("profit") / col("sales")).alias(
                        "gross_margin")], agg))


def q38_shape(t, run):
    """Customers active in all three channels (reference q38's
    intersect, as chained semi joins over deduplicated customers)."""
    ss_c = CpuAggregate([col("ss_customer_sk")],
                        [Count(None).alias("_a")], t["store_sales"])
    in_web = CpuHashJoin(
        J.LEFT_SEMI, [col("ss_customer_sk")],
        [col("ws_bill_customer_sk")], ss_c, t["web_sales"])
    in_all = CpuHashJoin(
        J.LEFT_SEMI, [col("ss_customer_sk")],
        [col("cs_bill_customer_sk")], in_web, t["catalog_sales"])
    return CpuAggregate([], [Count(None).alias("num_customers")],
                        in_all)


def q60_shape(t, run):
    """Per-item revenue across the three channels for one category and
    month (reference q60, q33's by-item sibling)."""
    dd = CpuFilter((col("d_year") == lit(1999)) &
                   (col("d_moy") == lit(9)), t["date_dim"])
    it = CpuFilter(col("i_category") == lit("Music"), t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_item_id"), col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), desc(col("total_sales"))], agg))


def q69_shape(t, run):
    """Demographics of store customers with no web or catalog activity
    in a window (reference q69's exists/not-exists combination)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") <= lit(3)), t["date_dim"])
    store_c = CpuAggregate(
        [col("ss_customer_sk")], [Count(None).alias("_a")],
        _join(dd, t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]))
    web_c = CpuProject(
        [col("ws_bill_customer_sk")],
        _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"]))
    cat_c = CpuProject(
        [col("cs_bill_customer_sk")],
        _join(dd, t["catalog_sales"],
              ["d_date_sk"], ["cs_sold_date_sk"]))
    only_store = CpuHashJoin(
        J.LEFT_ANTI, [col("ss_customer_sk")],
        [col("cs_bill_customer_sk")],
        CpuHashJoin(J.LEFT_ANTI, [col("ss_customer_sk")],
                    [col("ws_bill_customer_sk")], store_c, web_c),
        cat_c)
    j = _join(_join(only_store, t["customer"],
                    ["ss_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_state"))], agg))


def q87_shape(t, run):
    """Store customers absent from the web channel (reference q87's
    EXCEPT, as a LEFT_ANTI join over deduplicated customers)."""
    ss_c = CpuAggregate([col("ss_customer_sk")],
                        [Count(None).alias("_a")], t["store_sales"])
    not_web = CpuHashJoin(
        J.LEFT_ANTI, [col("ss_customer_sk")],
        [col("ws_bill_customer_sk")], ss_c, t["web_sales"])
    return CpuAggregate([], [Count(None).alias("num_customers")],
                        not_web)


def q41_shape(t, run):
    """Distinct item ids in a price/category slice (reference q41's
    item-only filter query)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(30.0)) &
        (col("i_current_price") <= lit(60.0)) &
        InSet(col("i_category"), ("Women", "Shoes", "Jewelry")),
        t["item"])
    dedup = CpuAggregate([col("i_item_id")],
                         [Count(None).alias("_c")], it)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id"))],
        CpuProject([col("i_item_id")], dedup)))







def q63_shape(t, run):
    """Manager monthly sales vs their average month (reference q63/q53's
    windowed deviation filter)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_manager_id"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col("i_manager_id")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        monthly)
    dev = CpuFilter(
        (col("avg_monthly_sales") > lit(0.0)) &
        ((col("sum_sales") > col("avg_monthly_sales") * lit(1.1)) |
         (col("sum_sales") < col("avg_monthly_sales") * lit(0.9))), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manager_id")), asc(col("d_moy"))],
        CpuProject([col("i_manager_id"), col("d_moy"),
                    col("sum_sales"), col("avg_monthly_sales")], dev)))


def q67_shape(t, run):
    """Top-ranked items by revenue within each category (reference
    q67's windowed rank over rollup, without the rollup)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import (CpuWindow, Rank,
                                              WindowSpec)
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    by_item = CpuAggregate(
        [col("i_category"), col("i_item_id")],
        [Sum(col("ss_ext_sales_price")).alias("sales")], j)
    ranked = CpuWindow(
        [Rank().alias("rk")],
        WindowSpec([col("i_category")], [_desc(col("sales"))]),
        by_item)
    top = CpuFilter(col("rk") <= lit(3), ranked)
    return CpuSort(
        [asc(col("i_category")), asc(col("rk")),
         asc(col("i_item_id"))],
        CpuProject([col("i_category"), col("i_item_id"),
                    col("sales"), col("rk")], top))







def q47_shape(t, run):
    """Brand monthly sales vs neighbors and the brand average
    (reference q47/q57: stacked windows — lag/lead over time plus a
    whole-partition average)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, Lag, Lead,
                                              WindowFrame, WindowSpec,
                                              WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_brand"), col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    with_neighbors = CpuWindow(
        [Lag(col("sum_sales")).alias("psum"),
         Lead(col("sum_sales")).alias("nsum")],
        WindowSpec([col("i_brand")], [_asc(col("d_moy"))]),
        monthly)
    with_avg = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly")],
        WindowSpec([col("i_brand")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        with_neighbors)
    dev = CpuFilter(
        (col("avg_monthly") > lit(0.0)) &
        (col("sum_sales") > col("avg_monthly") * lit(1.5)), with_avg)
    return CpuLimit(100, CpuSort(
        [asc(col("i_brand")), asc(col("d_moy"))],
        CpuProject([col("i_brand"), col("d_moy"), col("sum_sales"),
                    col("psum"), col("nsum"), col("avg_monthly")], dev)))


def q51_shape(t, run):
    """Running cumulative revenue per item over months, web vs store,
    reporting months where the web cumulative overtakes the store one
    (reference q51's full-outer join of windowed cumulatives)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinSum)

    def cum(sales, date_key, item_key, price, prefix):
        monthly = CpuAggregate(
            [col(item_key), col("d_moy")],
            [Sum(col(price)).alias(f"{prefix}_sales")],
            _join(CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
                  t[sales], ["d_date_sk"], [date_key]))
        w = CpuWindow(
            [WinSum(col(f"{prefix}_sales")).alias(f"{prefix}_cum")],
            WindowSpec([col(item_key)], [_asc(col("d_moy"))],
                       WindowFrame(is_rows=True, lower=None, upper=0)),
            monthly)
        return CpuProject(
            [col(item_key).alias(f"{prefix}_item"),
             col("d_moy").alias(f"{prefix}_moy"),
             col(f"{prefix}_cum")], w)

    web = cum("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_ext_sales_price", "web")
    store = cum("store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_ext_sales_price", "store")
    j = CpuHashJoin(
        J.FULL_OUTER, [col("web_item"), col("web_moy")],
        [col("store_item"), col("store_moy")], web, store)
    ahead = CpuFilter(
        IsNotNull(col("web_cum")) & IsNotNull(col("store_cum")) &
        (col("web_cum") > col("store_cum")), j)
    return CpuLimit(100, CpuSort(
        [asc(col("web_item")), asc(col("web_moy"))],
        CpuProject([col("web_item"), col("web_moy"), col("web_cum"),
                    col("store_cum")], ahead)))







def q44_shape(t, run):
    """Best and worst items by average profit via two window ranks
    (reference q44's asc/desc rank pair)."""
    from spark_rapids_tpu.exec.sort import asc as _asc, desc as _desc
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    by_item = CpuAggregate(
        [col("ss_item_sk")],
        [Average(col("ss_net_profit")).alias("avg_profit")],
        t["store_sales"])
    ranked = CpuWindow(
        [Rank().alias("best_rk")],
        WindowSpec([], [_desc(col("avg_profit"))]), by_item)
    ranked = CpuWindow(
        [Rank().alias("worst_rk")],
        WindowSpec([], [_asc(col("avg_profit"))]), ranked)
    top = CpuFilter((col("best_rk") <= lit(10)) |
                    (col("worst_rk") <= lit(10)), ranked)
    j = _join(top, t["item"], ["ss_item_sk"], ["i_item_sk"])
    return CpuSort(
        [asc(col("best_rk")), asc(col("worst_rk")),
         asc(col("i_item_id"))],
        CpuProject([col("i_item_id"), col("avg_profit"),
                    col("best_rk"), col("worst_rk")], j))


def q58_shape(t, run):
    """Items whose revenue is roughly equal across all three channels
    (reference q58's three-way join with ratio bands)."""
    def chan(sales, item_key, price, name):
        agg = CpuAggregate(
            [col(item_key)], [Sum(col(price)).alias(name)], t[sales])
        return CpuProject(
            [col(item_key).alias(f"{name}_item"), col(name)], agg)

    ss = chan("store_sales", "ss_item_sk", "ss_ext_sales_price",
              "ss_rev")
    cs = chan("catalog_sales", "cs_item_sk", "cs_ext_sales_price",
              "cs_rev")
    ws = chan("web_sales", "ws_item_sk", "ws_ext_sales_price", "ws_rev")
    j = _join(_join(ss, cs, ["ss_rev_item"], ["cs_rev_item"]),
              ws, ["ss_rev_item"], ["ws_rev_item"])
    avg3 = (col("ss_rev") + col("cs_rev") + col("ws_rev")) / lit(3.0)
    close = CpuFilter(
        (col("ss_rev") >= avg3 * lit(0.6)) &
        (col("ss_rev") <= avg3 * lit(1.4)) &
        (col("cs_rev") >= avg3 * lit(0.6)) &
        (col("cs_rev") <= avg3 * lit(1.4)) &
        (col("ws_rev") >= avg3 * lit(0.6)) &
        (col("ws_rev") <= avg3 * lit(1.4)), j)
    return CpuLimit(100, CpuSort(
        [asc(col("ss_rev_item"))],
        CpuProject([col("ss_rev_item"), col("ss_rev"), col("cs_rev"),
                    col("ws_rev")], close)))


def q59_shape(t, run):
    """Week-day store revenue pivot compared year over year (reference
    q59's self-join of weekly pivots)."""
    def pivot(year, suffix):
        j = _join(CpuFilter(col("d_year") == lit(year), t["date_dim"]),
                  t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"])
        day = lambda n: Sum(If(col("d_day_name") == lit(n),
                               col("ss_sales_price"), lit(0.0)))
        agg = CpuAggregate(
            [col("ss_store_sk")],
            [day("Sunday").alias(f"sun{suffix}"),
             day("Wednesday").alias(f"wed{suffix}"),
             day("Saturday").alias(f"sat{suffix}")], j)
        return CpuProject(
            [col("ss_store_sk").alias(f"store{suffix}"),
             col(f"sun{suffix}"), col(f"wed{suffix}"),
             col(f"sat{suffix}")], agg)

    y1 = pivot(2000, "1")
    y2 = pivot(2001, "2")
    j = _join(y1, y2, ["store1"], ["store2"])
    safe = CpuFilter((col("sun2") > lit(0.0)) &
                     (col("wed2") > lit(0.0)) &
                     (col("sat2") > lit(0.0)), j)
    return CpuSort(
        [asc(col("store1"))],
        CpuProject([col("store1"),
                    (col("sun1") / col("sun2")).alias("sun_ratio"),
                    (col("wed1") / col("wed2")).alias("wed_ratio"),
                    (col("sat1") / col("sat2")).alias("sat_ratio")],
                   safe))


def q66_shape(t, run):
    """Warehouse monthly revenue pivot, web + catalog united
    (reference q66's 12-month If-sum pivot)."""
    u = CpuUnion(
        CpuProject([col("ws_warehouse_sk").alias("wh"),
                    col("ws_sold_date_sk").alias("sold"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]),
        CpuProject([col("cs_warehouse_sk").alias("wh"),
                    col("cs_sold_date_sk").alias("sold"),
                    col("cs_ext_sales_price").alias("price")],
                   t["catalog_sales"]))
    j = _join(_join(u, CpuFilter(col("d_year") == lit(2001),
                                 t["date_dim"]),
                    ["sold"], ["d_date_sk"]),
              t["warehouse"], ["wh"], ["w_warehouse_sk"])
    mo = lambda m: Sum(If(col("d_moy") == lit(m), col("price"),
                          lit(0.0)))
    agg = CpuAggregate(
        [col("w_warehouse_name"), col("w_warehouse_sq_ft")],
        [mo(m).alias(f"m{m:02d}_sales") for m in range(1, 13)], j)
    return CpuSort([asc(col("w_warehouse_name"))], agg)


def q70_shape(t, run):
    """States ranked by store profit, top 5 (reference q70's windowed
    state rank without the rollup)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import CpuWindow, Rank, WindowSpec
    j = _join(_join(CpuFilter(col("d_year") == lit(2000),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    by_state = CpuAggregate(
        [col("s_state")],
        [Sum(col("ss_net_profit")).alias("total_profit")], j)
    ranked = CpuWindow([Rank().alias("rk")],
                       WindowSpec([], [_desc(col("total_profit"))]),
                       by_state)
    return CpuSort(
        [asc(col("rk")), asc(col("s_state"))],
        CpuFilter(col("rk") <= lit(5), ranked))


def q75_shape(t, run):
    """Year-over-year quantity change per category across all channels
    (reference q75's union + prior-year self-join)."""
    def year_qty(year):
        u = CpuUnion(
            CpuProject([col("ss_sold_date_sk").alias("sold"),
                        col("ss_item_sk").alias("it"),
                        col("ss_quantity").alias("qty")],
                       t["store_sales"]),
            CpuProject([col("cs_sold_date_sk").alias("sold"),
                        col("cs_item_sk").alias("it"),
                        col("cs_quantity").alias("qty")],
                       t["catalog_sales"]),
            CpuProject([col("ws_sold_date_sk").alias("sold"),
                        col("ws_item_sk").alias("it"),
                        col("ws_quantity").alias("qty")],
                       t["web_sales"]))
        j = _join(_join(u, CpuFilter(col("d_year") == lit(year),
                                     t["date_dim"]),
                        ["sold"], ["d_date_sk"]),
                  t["item"], ["it"], ["i_item_sk"])
        return CpuAggregate([col("i_category_id")],
                            [Sum(col("qty")).alias(f"qty_{year}")], j)

    cur = year_qty(2001)
    prev = CpuProject([col("i_category_id").alias("cat_prev"),
                       col("qty_2000")], year_qty(2000))
    j = _join(cur, prev, ["i_category_id"], ["cat_prev"])
    decline = CpuFilter(
        (col("qty_2000") > lit(0)) &
        (col("qty_2001") * lit(10) < col("qty_2000") * lit(9)), j)
    return CpuSort(
        [asc(col("i_category_id"))],
        CpuProject([col("i_category_id"), col("qty_2000"),
                    col("qty_2001")], decline))


def q77_shape(t, run):
    """Profit and returns per channel, united into one report
    (reference q77's channel union with loss netting)."""
    def channel(name, sales_profit, returns_amt):
        return CpuProject(
            [lit(name).alias("channel"), col("profit"),
             col("returns_amt")],
            _join(sales_profit, returns_amt, ["k1"], ["k2"]))

    def one_row(node, alias_, key):
        return CpuProject(
            [lit(1).alias(key), col(alias_)],
            node)

    ss = one_row(CpuAggregate(
        [], [Sum(col("ss_net_profit")).alias("profit")],
        t["store_sales"]), "profit", "k1")
    sr = one_row(CpuAggregate(
        [], [Sum(col("sr_return_amt")).alias("returns_amt")],
        t["store_returns"]), "returns_amt", "k2")
    cs = one_row(CpuAggregate(
        [], [Sum(col("cs_net_profit")).alias("profit")],
        t["catalog_sales"]), "profit", "k1")
    cr = one_row(CpuAggregate(
        [], [Sum(col("cr_return_amount")).alias("returns_amt")],
        t["catalog_returns"]), "returns_amt", "k2")
    ws = one_row(CpuAggregate(
        [], [Sum(col("ws_net_profit")).alias("profit")],
        t["web_sales"]), "profit", "k1")
    wr = one_row(CpuAggregate(
        [], [Sum(col("wr_return_amt")).alias("returns_amt")],
        t["web_returns"]), "returns_amt", "k2")
    u = CpuUnion(channel("store", ss, sr),
                 channel("catalog", cs, cr),
                 channel("web", ws, wr))
    return CpuSort([asc(col("channel"))], u)


def q80_shape(t, run):
    """Per-store revenue net of returns with promo split (reference
    q80's store-channel report)."""
    j = CpuHashJoin(
        J.LEFT_OUTER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    j = _join(j, t["store"], ["ss_store_sk"], ["s_store_sk"])
    net = col("ss_ext_sales_price") - Coalesce(
        (col("sr_return_amt"), lit(0.0)))
    agg = CpuAggregate(
        [col("s_store_id")],
        [Sum(net).alias("sales_net"),
         Sum(Coalesce((col("sr_return_amt"), lit(0.0)))).alias(
             "returns_amt"),
         Sum(col("ss_net_profit")).alias("profit")], j)
    return CpuSort([asc(col("s_store_id"))], agg)







def q8_shape(t, run):
    """Store revenue limited to customer states with enough customers
    (reference q8's zip-list filter, by state)."""
    by_state = CpuAggregate(
        [col("ca_state")], [Count(None).alias("n_cust")],
        t["customer_address"])
    big = CpuFilter(col("n_cust") >= lit(10), by_state)
    j = _join(_join(_join(
        CpuFilter(col("d_year") == lit(2000), t["date_dim"]),
        t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
        t["customer"], ["ss_customer_sk"], ["c_customer_sk"]),
        t["customer_address"], ["c_current_addr_sk"], ["ca_address_sk"])
    j = CpuHashJoin(J.LEFT_SEMI, [col("ca_state")], [col("ca_state")],
                    j, CpuProject([col("ca_state")], big))
    agg = CpuAggregate(
        [col("ca_state")],
        [Sum(col("ss_net_profit")).alias("net_profit")], j)
    return CpuSort([asc(col("ca_state"))], agg)


def q10_shape(t, run):
    """Demographics of customers active in web or catalog (reference
    q10's exists-any-channel, as a semi join over a union)."""
    active = CpuUnion(
        CpuProject([col("ws_bill_customer_sk").alias("cust")],
                   t["web_sales"]),
        CpuProject([col("cs_bill_customer_sk").alias("cust")],
                   t["catalog_sales"]))
    store = _join(t["store_sales"], t["customer_demographics"],
                  ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = CpuHashJoin(J.LEFT_SEMI, [col("ss_customer_sk")], [col("cust")],
                    store, active)
    agg = CpuAggregate(
        [col("cd_gender"), col("cd_marital_status"),
         col("cd_education_status")],
        [Count(None).alias("cnt")], j)
    return CpuSort(
        [asc(col("cd_gender")), asc(col("cd_marital_status")),
         asc(col("cd_education_status"))], agg)


def q23_shape(t, run):
    """Catalog revenue from frequent store items bought by the best
    store customers (reference q23's two semi-join subqueries)."""
    freq_items = CpuFilter(
        col("n_sold") >= lit(8),
        CpuAggregate([col("ss_item_sk")],
                     [Count(None).alias("n_sold")], t["store_sales"]))
    spend = CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_net_paid")).alias("spend")], t["store_sales"])
    avg_spend = CpuProject(
        [lit(1).alias("k"), col("avg_spend")],
        CpuAggregate([], [Average(col("spend")).alias("avg_spend")],
                     CpuProject([col("spend")], spend)))
    best = CpuFilter(
        col("spend") > col("avg_spend") * lit(1.2),
        _join(CpuProject([col("ss_customer_sk"), col("spend"),
                          lit(1).alias("k2")], spend),
              avg_spend, ["k2"], ["k"]))
    cs = CpuHashJoin(
        J.LEFT_SEMI, [col("cs_item_sk")], [col("ss_item_sk")],
        t["catalog_sales"],
        CpuProject([col("ss_item_sk")], freq_items))
    cs = CpuHashJoin(
        J.LEFT_SEMI, [col("cs_bill_customer_sk")],
        [col("ss_customer_sk")], cs,
        CpuProject([col("ss_customer_sk")], best))
    return CpuAggregate(
        [], [Sum(col("cs_ext_sales_price")).alias("sales")], cs)


def q30_shape(t, run):
    """Customers whose web-return total exceeds 1.2x their state's
    average (reference q30, q1's web twin)."""
    ctr = CpuAggregate(
        [col("wr_returning_customer_sk")],
        [Sum(col("wr_return_amt")).alias("ctr_total")],
        t["web_returns"])
    j = _join(_join(ctr, t["customer"],
                    ["wr_returning_customer_sk"], ["c_customer_sk"]),
              t["customer_address"],
              ["c_current_addr_sk"], ["ca_address_sk"])
    avg_state = CpuAggregate(
        [col("ca_state")],
        [Average(col("ctr_total")).alias("avg_ret")],
        CpuProject([col("ca_state"), col("ctr_total")], j))
    big = CpuFilter(
        col("ctr_total") > col("avg_ret") * lit(1.2),
        _join(j, CpuProject([col("ca_state").alias("st2"),
                             col("avg_ret")], avg_state),
              ["ca_state"], ["st2"]))
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id"), col("ca_state"),
                    col("ctr_total")], big)))


def q31_shape(t, run):
    """States where web revenue grew faster than store revenue between
    quarters (reference q31's growth-ratio comparison)."""
    def qrev(sales, date_key, cust_key, price, qoy, name):
        j = _join(_join(_join(
            CpuFilter((col("d_year") == lit(2000)) &
                      (col("d_qoy") == lit(qoy)), t["date_dim"]),
            t[sales], ["d_date_sk"], [date_key]),
            t["customer"], [cust_key], ["c_customer_sk"]),
            t["customer_address"],
            ["c_current_addr_sk"], ["ca_address_sk"])
        agg = CpuAggregate([col("ca_state")],
                           [Sum(col(price)).alias(name)], j)
        return CpuProject(
            [col("ca_state").alias(f"{name}_state"), col(name)], agg)

    ss1 = qrev("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_ext_sales_price", 1, "ss1")
    ss2 = qrev("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_ext_sales_price", 2, "ss2")
    ws1 = qrev("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_sales_price", 1, "ws1")
    ws2 = qrev("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_sales_price", 2, "ws2")
    j = _join(_join(_join(ss1, ss2, ["ss1_state"], ["ss2_state"]),
                    ws1, ["ss1_state"], ["ws1_state"]),
              ws2, ["ss1_state"], ["ws2_state"])
    grew = CpuFilter(
        (col("ss1") > lit(0.0)) & (col("ws1") > lit(0.0)) &
        (col("ws2") * col("ss1") > col("ss2") * col("ws1")), j)
    return CpuSort(
        [asc(col("ss1_state"))],
        CpuProject([col("ss1_state"), col("ss1"), col("ss2"),
                    col("ws1"), col("ws2")], grew))


def q71_shape(t, run):
    """Brand revenue by hour band across all channels for one month
    (reference q71's time-of-day breakdown)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    u = CpuUnion(
        CpuProject([col("ss_sold_date_sk").alias("sold"),
                    col("ss_sold_time_sk").alias("tsk"),
                    col("ss_item_sk").alias("it"),
                    col("ss_ext_sales_price").alias("price")],
                   t["store_sales"]),
        CpuProject([col("cs_sold_date_sk").alias("sold"),
                    col("cs_sold_time_sk").alias("tsk"),
                    col("cs_item_sk").alias("it"),
                    col("cs_ext_sales_price").alias("price")],
                   t["catalog_sales"]),
        CpuProject([col("ws_sold_date_sk").alias("sold"),
                    col("ws_sold_time_sk").alias("tsk"),
                    col("ws_item_sk").alias("it"),
                    col("ws_ext_sales_price").alias("price")],
                   t["web_sales"]))
    j = _join(_join(_join(u, dd, ["sold"], ["d_date_sk"]),
                    t["item"], ["it"], ["i_item_sk"]),
              t["time_dim"], ["tsk"], ["t_time_sk"])
    agg = CpuAggregate(
        [col("i_brand_id")],
        [Sum(If((col("t_hour") >= lit(8)) & (col("t_hour") < lit(12)),
                col("price"), lit(0.0))).alias("morning"),
         Sum(If((col("t_hour") >= lit(12)) & (col("t_hour") < lit(18)),
                col("price"), lit(0.0))).alias("afternoon"),
         Sum(If((col("t_hour") >= lit(18)),
                col("price"), lit(0.0))).alias("evening")], j)
    return CpuSort([asc(col("i_brand_id"))], agg)


def q82_shape(t, run):
    """Items in a price band with healthy inventory sold in stores
    (reference q82, q37's store twin)."""
    it = CpuFilter(
        (col("i_current_price") >= lit(30.0)) &
        (col("i_current_price") <= lit(70.0)), t["item"])
    inv = CpuFilter(
        (col("inv_quantity_on_hand") >= lit(100)) &
        (col("inv_quantity_on_hand") <= lit(500)), t["inventory"])
    stocked = _join(it, inv, ["i_item_sk"], ["inv_item_sk"])
    sold = CpuHashJoin(
        J.LEFT_SEMI, [col("i_item_sk")], [col("ss_item_sk")],
        stocked, t["store_sales"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_current_price")],
        [Count(None).alias("stock_rows")], sold)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q94_shape(t, run):
    """Web orders in a window with no returns: order count + cost sums
    (reference q94, q16's web twin)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") <= lit(4)), t["date_dim"])
    sales = _join(dd, t["web_sales"], ["d_date_sk"], ["ws_sold_date_sk"])
    no_ret = CpuHashJoin(
        J.LEFT_ANTI, [col("ws_order_number")], [col("wr_order_number")],
        sales, t["web_returns"])
    per_order = CpuAggregate(
        [col("ws_order_number")],
        [Sum(col("ws_ext_ship_cost")).alias("ship_cost"),
         Sum(col("ws_net_profit")).alias("net_profit")], no_ret)
    return CpuAggregate(
        [], [Count(None).alias("order_count"),
             Sum(col("ship_cost")).alias("total_shipping_cost"),
             Sum(col("net_profit")).alias("total_net_profit")],
        per_order)





QUERIES = {
    "q1": q1, "q2": q2_shape, "q3": q3, "q6": q6_shape, "q7": q7_shape,
    "q8": q8_shape, "q10": q10_shape, "q23": q23_shape,
    "q30": q30_shape, "q31": q31_shape, "q71": q71_shape,
    "q82": q82_shape, "q94": q94_shape,
    "q13": q13_shape, "q18": q18_shape, "q21": q21ds_shape,
    "q32": q32_shape, "q34": q34_shape, "q36": q36_shape,
    "q38": q38_shape, "q41": q41_shape, "q60": q60_shape,
    "q44": q44_shape, "q47": q47_shape, "q51": q51_shape,
    "q58": q58_shape, "q59": q59_shape, "q66": q66_shape,
    "q70": q70_shape, "q75": q75_shape, "q77": q77_shape,
    "q80": q80_shape,
    "q63": q63_shape, "q67": q67_shape,
    "q69": q69_shape, "q87": q87_shape,
    "q15": q15_shape, "q16": q16_shape, "q19": q19, "q25": q25_shape,
    "q26": q26, "q27": q27_shape, "q28": q28_shape, "q33": q33_shape,
    "q37": q37_shape, "q40": q40_shape, "q42": q42, "q43": q43_shape,
    "q45": q45_shape, "q46": q46_shape, "q48": q48_shape,
    "q50": q50_shape, "q52": q52, "q55": q55, "q61": q61_shape,
    "q62": q62_shape, "q65": q65, "q68": q68, "q73": q73,
    "q79": q79_shape, "q88": q88_shape, "q90": q90_shape,
    "q92": q92_shape, "q93": q93_shape, "q96": q96, "q97": q97,
    "q98": q98_shape, "q99": q99_shape,
}


# ---------------------------------------------------------------------------
# round-2 growth toward the reference's 103 (TpcdsLikeSpark.scala:709+):
# year-over-year ratio family (q4/q11/q74), ROLLUP grouping-sets through
# CpuExpand (q5/q22/q86), channel unions (q56/q76), windowed deviation
# reports (q53/q57/q89), returns chains (q17/q24/q29/q49/q78/q81/q83/q85),
# inventory (q39/q72), existence/self-join shapes (q14/q35/q95).
from spark_rapids_tpu import types as _T
from spark_rapids_tpu.exprs.base import Literal as _Lit
from spark_rapids_tpu.plan.nodes import CpuExpand as _CpuExpand


def _rollup_expand(child, keys, passthrough):
    """Spark ROLLUP(keys...) lowering: CpuExpand with one projection per
    key prefix plus the grand total, carrying a grouping id — the exact
    shape Spark's planner feeds ExpandExec (reference GpuExpandExec)."""
    cs = child.output_schema()
    n = len(keys)
    projs = []
    for level in range(n, -1, -1):
        proj = [col(k) if i < level else _Lit(None, cs.field(k).dtype)
                for i, k in enumerate(keys)]
        proj.append(_Lit((1 << (n - level)) - 1, _T.INT32))
        proj.extend(col(p) for p in passthrough)
        projs.append(proj)
    names = list(keys) + ["gid"] + list(passthrough)
    return _CpuExpand(projs, names, child)


def _yoy_growth(t, sales, date_key, cust_key, val, year1=1999):
    """Per-customer totals for two consecutive years, joined: the
    q4/q11/q74 year-over-year scaffold."""
    def year_total(y, alias):
        dd = CpuFilter(col("d_year") == lit(y), t["date_dim"])
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  t["customer"], [cust_key], ["c_customer_sk"])
        return CpuAggregate([col("c_customer_id")],
                            [Sum(col(val)).alias(alias)], j)
    y1 = year_total(year1, "total1")
    y2 = CpuProject([col("c_customer_id").alias("cid2"),
                     col("total2")],
                    year_total(year1 + 1, "total2"))
    j = _join(CpuFilter(col("total1") > lit(0.0), y1), y2,
              ["c_customer_id"], ["cid2"])
    return CpuProject([col("c_customer_id"),
                       (col("total2") / col("total1")).alias("growth")], j)


def q4_shape(t, run):
    """Customers whose catalog growth beats their store growth
    (reference q4's 3-channel year-over-year self-joins, 2 channels in
    the v0 shape)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_net_paid")
    cs = CpuProject([col("c_customer_id").alias("ccid"),
                     col("growth").alias("c_growth")],
                    _yoy_growth(t, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk", "cs_net_paid"))
    j = _join(ss, cs, ["c_customer_id"], ["ccid"])
    keep = CpuFilter(col("c_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q11_shape(t, run):
    """Web growth beats store growth (reference q11)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_ext_list_price")
    ws = CpuProject([col("c_customer_id").alias("wcid"),
                     col("growth").alias("w_growth")],
                    _yoy_growth(t, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk",
                                "ws_ext_list_price"))
    j = _join(ss, ws, ["c_customer_id"], ["wcid"])
    keep = CpuFilter(col("w_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q74_shape(t, run):
    """q11's sibling over net_paid sums (reference q74)."""
    ss = _yoy_growth(t, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk", "ss_net_paid", year1=2000)
    ws = CpuProject([col("c_customer_id").alias("wcid"),
                     col("growth").alias("w_growth")],
                    _yoy_growth(t, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk", "ws_net_paid",
                                year1=2000))
    j = _join(ss, ws, ["c_customer_id"], ["wcid"])
    keep = CpuFilter(col("w_growth") > col("growth"), j)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id")], keep)))


def q5_shape(t, run):
    """Per-channel sales/returns/profit report with ROLLUP(channel, id)
    through CpuExpand (reference q5)."""
    def channel(label, sales, skey, sval, sprofit, rets, rkey, rval):
        s = CpuProject([lit(label).alias("channel"),
                        col(skey).alias("id"),
                        col(sval).alias("sales"),
                        lit(0.0).alias("returns_amt"),
                        col(sprofit).alias("profit")], t[sales])
        r = CpuProject([lit(label).alias("channel"),
                        col(rkey).alias("id"),
                        lit(0.0).alias("sales"),
                        col(rval).alias("returns_amt"),
                        lit(0.0).alias("profit")], t[rets])
        return CpuUnion(s, r)

    u = CpuUnion(
        channel("store channel", "store_sales", "ss_store_sk",
                "ss_ext_sales_price", "ss_net_profit",
                "store_returns", "sr_store_sk", "sr_return_amt"),
        channel("catalog channel", "catalog_sales", "cs_item_sk",
                "cs_ext_sales_price", "cs_net_profit",
                "catalog_returns", "cr_item_sk", "cr_return_amount"),
        channel("web channel", "web_sales", "ws_web_site_sk",
                "ws_ext_sales_price", "ws_net_profit",
                "web_returns", "wr_item_sk", "wr_return_amt"))
    ex = _rollup_expand(u, ["channel", "id"],
                        ["sales", "returns_amt", "profit"])
    agg = CpuAggregate(
        [col("channel"), col("id"), col("gid")],
        [Sum(col("sales")).alias("sales"),
         Sum(col("returns_amt")).alias("returns_amt"),
         Sum(col("profit")).alias("profit")], ex)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("id")), asc(col("gid"))], agg))


def q22_rollup(t, run):
    """Inventory average quantity on hand, ROLLUP(category, brand) — a
    true grouping-sets plan through CpuExpand (reference q22)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
              t["item"], ["inv_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"],
                        ["inv_quantity_on_hand"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Average(col("inv_quantity_on_hand")).alias("qoh")], ex)
    return CpuLimit(100, CpuSort(
        [asc(col("qoh")), asc(col("i_category")), asc(col("i_brand")),
         asc(col("gid"))], agg))


def q86_rollup(t, run):
    """Web revenue ROLLUP(category, brand) report (reference q86 uses
    category/class; the v0 item schema carries brand)."""
    dd = CpuFilter(col("d_year") == lit(2001), t["date_dim"])
    j = _join(_join(dd, t["web_sales"], ["d_date_sk"],
                    ["ws_sold_date_sk"]),
              t["item"], ["ws_item_sk"], ["i_item_sk"])
    ex = _rollup_expand(j, ["i_category", "i_brand"], ["ws_net_paid"])
    agg = CpuAggregate(
        [col("i_category"), col("i_brand"), col("gid")],
        [Sum(col("ws_net_paid")).alias("total_sum")], ex)
    return CpuLimit(100, CpuSort(
        [desc(col("total_sum")), asc(col("i_category")),
         asc(col("i_brand")), asc(col("gid"))], agg))


def q9_shape(t, run):
    """Quantity-range bucket statistics as one reduction over
    store_sales (reference q9's CASE WHEN scalar subqueries)."""
    ss = t["store_sales"]
    aggs = []
    for i, (lo, hi) in enumerate(((1, 10), (11, 20), (21, 30),
                                  (31, 40), (41, 50))):
        inb = (col("ss_quantity") >= lit(lo)) & \
            (col("ss_quantity") <= lit(hi))
        aggs.append(Sum(If(inb, lit(1), lit(0))).alias(f"cnt_{i}"))
        aggs.append(Sum(If(inb, col("ss_ext_discount_amt"),
                           lit(0.0))).alias(f"disc_{i}"))
    return CpuAggregate([], aggs, ss)


def _cat_ratio(t, sales, date_key, item_key, price, year, moy):
    """q12/q20/q98 scaffold: item revenue + windowed share of its
    category's revenue."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinSum)
    dd = CpuFilter((col("d_year") == lit(year)) &
                   (col("d_moy") == lit(moy)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"),
                         ("Books", "Music", "Home")), t["item"])
    j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
              it, [item_key], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_category")],
        [Sum(col(price)).alias("itemrevenue")], j)
    w = CpuWindow(
        [WinSum(col("itemrevenue")).alias("cat_rev")],
        WindowSpec([col("i_category")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    share = CpuProject(
        [col("i_item_id"), col("i_category"), col("itemrevenue"),
         (col("itemrevenue") * lit(100.0) / col("cat_rev"))
         .alias("revenueratio")], w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_category")), asc(col("i_item_id")),
         asc(col("revenueratio"))], share))


def q12_shape(t, run):
    return _cat_ratio(t, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                      "ws_ext_sales_price", 1999, 2)


def q20_shape(t, run):
    return _cat_ratio(t, "catalog_sales", "cs_sold_date_sk",
                      "cs_item_sk", "cs_ext_sales_price", 2000, 3)


def q14_shape(t, run):
    """Items selling in ALL three channels: chained semi joins, then a
    brand revenue report (reference q14's cross-channel intersection)."""
    it = t["item"]
    in_ss = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                        [col("ss_item_sk")], it, t["store_sales"])
    in_cs = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                        [col("cs_item_sk")], in_ss, t["catalog_sales"])
    in_all = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                         [col("ws_item_sk")], in_cs, t["web_sales"])
    j = _join(in_all, t["store_sales"], ["i_item_sk"], ["ss_item_sk"])
    agg = CpuAggregate(
        [col("i_brand_id"), col("i_category_id")],
        [Sum(col("ss_ext_sales_price")).alias("sales"),
         Count(col("ss_ext_sales_price")).alias("number_sales")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("sales")), asc(col("i_brand_id")),
         asc(col("i_category_id"))], agg))


def q17_shape(t, run):
    """Store sale -> return -> catalog repurchase chain: per-item
    quantity statistics (reference q17; stddev reduced to avg/min/max,
    outside the v0 aggregate set like the reference's own gates)."""
    from spark_rapids_tpu.exprs.aggregates import Max, Min
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    chain = CpuHashJoin(
        J.INNER, [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        ssr, t["catalog_sales"])
    j = _join(chain, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id")],
        [Count(col("ss_quantity")).alias("store_sales_cnt"),
         Average(col("ss_quantity")).alias("store_sales_avg"),
         Min(col("sr_return_quantity")).alias("ret_min"),
         Max(col("cs_quantity")).alias("cat_max")], j)
    return CpuLimit(100, CpuSort([asc(col("i_item_id"))], agg))


def q29_shape(t, run):
    """q17's quantity-sum sibling (reference q29)."""
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    chain = CpuHashJoin(
        J.INNER, [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        ssr, t["catalog_sales"])
    j = _join(chain, t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_item_id"), col("i_brand")],
        [Sum(col("ss_quantity")).alias("store_qty"),
         Sum(col("sr_return_quantity")).alias("return_qty"),
         Sum(col("cs_quantity")).alias("catalog_qty")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("i_item_id")), asc(col("i_brand"))], agg))


def q24_shape(t, run):
    """Returned-ticket net paid by customer/store/brand, kept when above
    5% of the overall average (reference q24's HAVING-over-subquery via
    an unpartitioned window average)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["store"], ["ss_store_sk"],
                          ["s_store_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    agg = CpuAggregate(
        [col("c_last_name"), col("s_store_name"), col("i_brand")],
        [Sum(col("ss_net_paid")).alias("netpaid")], j)
    w = CpuWindow(
        [WinAvg(col("netpaid")).alias("avg_netpaid")],
        WindowSpec([], [], WindowFrame(is_rows=True, lower=None,
                                       upper=None)), agg)
    keep = CpuFilter(col("netpaid") > col("avg_netpaid") * lit(0.05), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("s_store_name")),
         asc(col("i_brand"))],
        CpuProject([col("c_last_name"), col("s_store_name"),
                    col("i_brand"), col("netpaid")], keep)))


def q35_shape(t, run):
    """Customer-demographic profile of store customers who also bought
    through catalog or web (reference q35's EXISTS shapes as semi
    joins)."""
    cust = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                       [col("ss_customer_sk")], t["customer"],
                       t["store_sales"])
    cs_side = CpuProject([col("cs_bill_customer_sk").alias("buyer")],
                         t["catalog_sales"])
    ws_side = CpuProject([col("ws_bill_customer_sk").alias("buyer")],
                         t["web_sales"])
    cust2 = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                        [col("buyer")], cust,
                        CpuUnion(cs_side, ws_side))
    j = _join(cust2, t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_state")],
        [Count(col("c_customer_sk")).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_state"))], agg))


def q39_shape(t, run):
    """Inventory monthly mean by warehouse/item, self-joined on the next
    month (reference q39's consecutive-month covariance pairs; variance
    reduced to avg like the reference's own gating of unsupported
    aggs)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = _join(_join(dd, t["inventory"], ["d_date_sk"], ["inv_date_sk"]),
              t["warehouse"], ["inv_warehouse_sk"], ["w_warehouse_sk"])
    monthly = CpuAggregate(
        [col("w_warehouse_sk"), col("inv_item_sk"), col("d_moy")],
        [Average(col("inv_quantity_on_hand")).alias("qoh")], j)
    m1 = CpuProject([col("w_warehouse_sk"), col("inv_item_sk"),
                     (col("d_moy") + lit(1)).alias("next_moy"),
                     col("qoh").alias("qoh1")], monthly)
    m2 = CpuProject([col("w_warehouse_sk").alias("w2"),
                     col("inv_item_sk").alias("i2"),
                     col("d_moy").alias("moy2"),
                     col("qoh").alias("qoh2")], monthly)
    pair = CpuHashJoin(
        J.INNER, [col("w_warehouse_sk"), col("inv_item_sk"),
                  col("next_moy")],
        [col("w2"), col("i2"), col("moy2")], m1, m2)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_sk")), asc(col("inv_item_sk")),
         asc(col("next_moy"))],
        CpuProject([col("w_warehouse_sk"), col("inv_item_sk"),
                    col("next_moy"), col("qoh1"), col("qoh2")], pair)))


def q49_shape(t, run):
    """Per-channel return ratios with a rank window, worst offenders
    first (reference q49's three ranked channel blocks)."""
    from spark_rapids_tpu.exec.sort import desc as _desc
    from spark_rapids_tpu.exec.window import (CpuWindow, Rank,
                                              WindowSpec)

    def channel(label, sales, skey_o, skey_i, qty, rets, rkey_o,
                rkey_i, rqty):
        j = CpuHashJoin(
            J.INNER, [col(skey_o), col(skey_i)],
            [col(rkey_o), col(rkey_i)], t[sales], t[rets])
        agg = CpuAggregate(
            [col(skey_i)],
            [Sum(col(rqty)).alias("ret"), Sum(col(qty)).alias("sold")], j)
        ratio = CpuProject(
            [lit(label).alias("channel"), col(skey_i).alias("item"),
             (col("ret") / col("sold")).alias("return_ratio")],
            CpuFilter(col("sold") > lit(0), agg))
        ranked = CpuWindow(
            [Rank().alias("return_rank")],
            WindowSpec([], [_desc(col("return_ratio"))]), ratio)
        return CpuFilter(col("return_rank") <= lit(10), ranked)

    u = CpuUnion(
        channel("web", "web_sales", "ws_order_number", "ws_item_sk",
                "ws_quantity", "web_returns", "wr_order_number",
                "wr_item_sk", "wr_return_quantity"),
        channel("catalog", "catalog_sales", "cs_order_number",
                "cs_item_sk", "cs_quantity", "catalog_returns",
                "cr_order_number", "cr_item_sk", "cr_return_quantity"),
        channel("store", "store_sales", "ss_ticket_number",
                "ss_item_sk", "ss_quantity", "store_returns",
                "sr_ticket_number", "sr_item_sk", "sr_return_quantity"))
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("return_rank")),
         asc(col("item"))], u))


def q53_shape(t, run):
    """Manufacturer quarterly revenue vs its own average (reference
    q53/q63 family; q63 already covers the monthly variant)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(2001),
                              t["date_dim"]),
                    t["store_sales"], ["d_date_sk"], ["ss_sold_date_sk"]),
              t["item"], ["ss_item_sk"], ["i_item_sk"])
    agg = CpuAggregate(
        [col("i_manufact_id"), col("d_qoy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_quarterly")],
        WindowSpec([col("i_manufact_id")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        agg)
    from spark_rapids_tpu.exprs.arithmetic import Abs as _Abs
    keep = CpuFilter(
        (col("avg_quarterly") > lit(0.0)) &
        (_Abs(col("sum_sales") - col("avg_quarterly")) /
         col("avg_quarterly") > lit(0.1)), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_manufact_id")), asc(col("d_qoy"))],
        CpuProject([col("i_manufact_id"), col("d_qoy"),
                    col("sum_sales"), col("avg_quarterly")], keep)))


def _cast_i64(e):
    from spark_rapids_tpu.exprs.cast import Cast
    return Cast(e, _T.INT64)


def q54_shape(t, run):
    """Revenue buckets of customers who bought a target category through
    catalog or web (reference q54's cohort + bucketed histogram)."""
    it = CpuFilter(col("i_category") == lit("Books"), t["item"])
    cs_b = CpuProject([col("cs_bill_customer_sk").alias("buyer")],
                      _join(it, t["catalog_sales"], ["i_item_sk"],
                            ["cs_item_sk"]))
    ws_b = CpuProject([col("ws_bill_customer_sk").alias("buyer")],
                      _join(it, t["web_sales"], ["i_item_sk"],
                            ["ws_item_sk"]))
    cohort = CpuHashJoin(J.LEFT_SEMI, [col("c_customer_sk")],
                         [col("buyer")], t["customer"],
                         CpuUnion(cs_b, ws_b))
    rev = CpuAggregate(
        [col("c_customer_sk")],
        [Sum(col("ss_ext_sales_price")).alias("revenue")],
        _join(cohort, t["store_sales"], ["c_customer_sk"],
              ["ss_customer_sk"]))
    bucket = CpuProject(
        [_cast_i64(col("revenue") / lit(50.0)).alias("segment")], rev)
    agg = CpuAggregate([col("segment")],
                       [Count(col("segment")).alias("num_customers")],
                       bucket)
    return CpuLimit(100, CpuSort(
        [asc(col("segment")), asc(col("num_customers"))], agg))


def q56_shape(t, run):
    """Per-item revenue across the three channels for address-filtered
    sales (reference q56, the q33/q60 sibling keyed by item_id)."""
    dd = CpuFilter((col("d_year") == lit(2001)) &
                   (col("d_moy") == lit(2)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"), ("Home", "Shoes")),
                   t["item"])

    def channel(sales, date_key, item_key, price):
        j = _join(_join(dd, t[sales], ["d_date_sk"], [date_key]),
                  it, [item_key], ["i_item_sk"])
        return CpuProject(
            [col("i_item_id"), col(price).alias("total_sales")], j)

    u = CpuUnion(channel("store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
                 channel("catalog_sales", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
                 channel("web_sales", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"))
    agg = CpuAggregate([col("i_item_id")],
                       [Sum(col("total_sales")).alias("total_sales")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("total_sales")), asc(col("i_item_id"))], agg))


def q57_shape(t, run):
    """Catalog monthly brand revenue vs neighbors (reference q57 — the
    catalog sibling of q47's stacked windows)."""
    from spark_rapids_tpu.exec.sort import asc as _asc
    from spark_rapids_tpu.exec.window import (CpuWindow, Lag, Lead,
                                              WindowFrame, WindowSpec,
                                              WinAvg)
    j = _join(_join(CpuFilter(col("d_year") == lit(1999),
                              t["date_dim"]),
                    t["catalog_sales"], ["d_date_sk"],
                    ["cs_sold_date_sk"]),
              t["item"], ["cs_item_sk"], ["i_item_sk"])
    monthly = CpuAggregate(
        [col("i_brand"), col("d_moy")],
        [Sum(col("cs_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [Lag(col("sum_sales")).alias("psum"),
         Lead(col("sum_sales")).alias("nsum")],
        WindowSpec([col("i_brand")], [_asc(col("d_moy"))]), monthly)
    wavg = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly")],
        WindowSpec([col("i_brand")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_brand")), asc(col("d_moy"))],
        CpuProject([col("i_brand"), col("d_moy"), col("sum_sales"),
                    col("psum"), col("nsum"), col("avg_monthly")],
                   wavg)))


def q64_shape(t, run):
    """Returned store purchases by city and brand (reference q64's
    cross-sale pairs, reduced to the store arm over the v0 schema)."""
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["item"], ["ss_item_sk"],
                          ["i_item_sk"]),
                    t["customer"], ["ss_customer_sk"],
                    ["c_customer_sk"]),
              t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    agg = CpuAggregate(
        [col("ca_city"), col("i_brand")],
        [Count(col("ss_ticket_number")).alias("cnt"),
         Sum(col("ss_net_paid")).alias("paid"),
         Sum(col("sr_return_amt")).alias("returned")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("ca_city")), asc(col("i_brand"))], agg))


def q72_shape(t, run):
    """Catalog orders vs on-hand inventory, promo split (reference q72's
    inventory shortage join)."""
    j = CpuHashJoin(J.INNER, [col("cs_item_sk")], [col("inv_item_sk")],
                    t["catalog_sales"], t["inventory"],
                    condition=col("inv_quantity_on_hand") <
                    col("cs_quantity"))
    p = CpuHashJoin(J.LEFT_OUTER, [col("cs_promo_sk")],
                    [col("p_promo_sk")], j, t["promotion"])
    flagged = CpuProject(
        [col("cs_item_sk"),
         If(IsNull(col("p_promo_sk")), lit(1), lit(0)).alias("no_promo"),
         If(IsNotNull(col("p_promo_sk")), lit(1), lit(0)).alias("promo")],
        p)
    agg = CpuAggregate(
        [col("cs_item_sk")],
        [Sum(col("no_promo")).alias("no_promo"),
         Sum(col("promo")).alias("promo"),
         Count(col("cs_item_sk")).alias("total_cnt")], flagged)
    return CpuLimit(100, CpuSort(
        [desc(col("total_cnt")), asc(col("cs_item_sk"))], agg))


def q76_shape(t, run):
    """Channel/year/category sales counts over the union of all three
    channels (reference q76's null-key audit, keyed by channel here)."""
    def channel(label, sales, date_key, item_key, price):
        j = _join(_join(t["date_dim"], t[sales], ["d_date_sk"],
                        [date_key]),
                  t["item"], [item_key], ["i_item_sk"])
        return CpuProject(
            [lit(label).alias("channel"), col("d_year"),
             col("i_category"), col(price).alias("ext_sales_price")], j)

    u = CpuUnion(
        channel("store", "store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_ext_sales_price"),
        channel("web", "web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price"),
        channel("catalog", "catalog_sales", "cs_sold_date_sk",
                "cs_item_sk", "cs_ext_sales_price"))
    agg = CpuAggregate(
        [col("channel"), col("d_year"), col("i_category")],
        [Count(col("ext_sales_price")).alias("sales_cnt"),
         Sum(col("ext_sales_price")).alias("sales_amt")], u)
    return CpuLimit(100, CpuSort(
        [asc(col("channel")), asc(col("d_year")),
         asc(col("i_category"))], agg))


def q78_shape(t, run):
    """Unreturned web sales per item/year vs store equivalents
    (reference q78's returns-netting left outer + null filter)."""
    def unreturned(sales, okey, ikey, dkey, qty, rets, rokey, rikey):
        jo = CpuHashJoin(
            J.LEFT_OUTER, [col(okey), col(ikey)],
            [col(rokey), col(rikey)], t[sales], t[rets])
        kept = CpuFilter(IsNull(col(rokey)), jo)
        jd = _join(t["date_dim"], kept, ["d_date_sk"], [dkey])
        return CpuAggregate(
            [col("d_year"), col(ikey)],
            [Sum(col(qty)).alias("qty")], jd)

    ws = unreturned("web_sales", "ws_order_number", "ws_item_sk",
                    "ws_sold_date_sk", "ws_quantity",
                    "web_returns", "wr_order_number", "wr_item_sk")
    ss = CpuProject(
        [col("d_year").alias("ss_year"),
         col("ss_item_sk").alias("s_item"),
         col("qty").alias("ss_qty")],
        unreturned("store_sales", "ss_ticket_number", "ss_item_sk",
                   "ss_sold_date_sk", "ss_quantity",
                   "store_returns", "sr_ticket_number", "sr_item_sk"))
    j = CpuHashJoin(J.INNER, [col("d_year"), col("ws_item_sk")],
                    [col("ss_year"), col("s_item")], ws, ss)
    out = CpuProject(
        [col("d_year"), col("ws_item_sk"), col("qty"), col("ss_qty"),
         (col("qty") / col("ss_qty")).alias("ratio")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("ratio")), asc(col("ws_item_sk")),
         asc(col("d_year"))], out))


def q81_shape(t, run):
    """Catalog returners above 1.2x their state's average return amount
    (reference q81's correlated HAVING via a per-state window)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(t["catalog_returns"], t["customer"],
                    ["cr_returning_customer_sk"], ["c_customer_sk"]),
              t["customer_address"], ["c_current_addr_sk"],
              ["ca_address_sk"])
    per_cust = CpuAggregate(
        [col("c_customer_id"), col("ca_state")],
        [Sum(col("cr_return_amount")).alias("ctr_total_return")], j)
    w = CpuWindow(
        [WinAvg(col("ctr_total_return")).alias("state_avg")],
        WindowSpec([col("ca_state")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        per_cust)
    keep = CpuFilter(
        col("ctr_total_return") > col("state_avg") * lit(1.2), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_customer_id"))],
        CpuProject([col("c_customer_id"), col("ca_state"),
                    col("ctr_total_return")], keep)))


def q83_shape(t, run):
    """Return quantities by item across the three return tables
    (reference q83's three-way item join)."""
    sr = CpuAggregate([col("sr_item_sk")],
                      [Sum(col("sr_return_quantity")).alias("sr_qty")],
                      t["store_returns"])
    cr = CpuProject([col("cr_item_sk").alias("c_item"),
                     col("cr_qty")],
                    CpuAggregate(
                        [col("cr_item_sk")],
                        [Sum(col("cr_return_quantity")).alias("cr_qty")],
                        t["catalog_returns"]))
    wr = CpuProject([col("wr_item_sk").alias("w_item"),
                     col("wr_qty")],
                    CpuAggregate(
                        [col("wr_item_sk")],
                        [Sum(col("wr_return_quantity")).alias("wr_qty")],
                        t["web_returns"]))
    j = CpuHashJoin(J.INNER, [col("sr_item_sk")], [col("c_item")],
                    sr, cr)
    j = CpuHashJoin(J.INNER, [col("sr_item_sk")], [col("w_item")],
                    j, wr)
    out = CpuProject(
        [col("sr_item_sk"), col("sr_qty"), col("cr_qty"), col("wr_qty"),
         ((col("sr_qty") + col("cr_qty") + col("wr_qty")) / lit(3.0))
         .alias("average")], j)
    return CpuLimit(100, CpuSort(
        [asc(col("sr_item_sk"))], out))


def q84_shape(t, run):
    """Customer directory for one city, names concatenated (reference
    q84's customer/address/demographics lookup)."""
    from spark_rapids_tpu.exprs.string_fns import ConcatStrings
    ca = CpuFilter(col("ca_city") == lit("Midway"),
                   t["customer_address"])
    j = _join(t["customer"], ca, ["c_current_addr_sk"],
              ["ca_address_sk"])
    out = CpuProject(
        [col("c_customer_id").alias("customer_id"),
         ConcatStrings((col("c_last_name"), lit(", "),
                        col("c_first_name"))).alias("customername")], j)
    return CpuLimit(100, CpuSort([asc(col("customer_id"))], out))


def q85_shape(t, run):
    """Catalog returns profiled by buyer demographics (reference q85's
    reason-bucketed web returns, carried by the catalog arm where the
    v0 schema has the demographics link)."""
    j = CpuHashJoin(
        J.INNER, [col("cs_order_number"), col("cs_item_sk")],
        [col("cr_order_number"), col("cr_item_sk")],
        t["catalog_sales"], t["catalog_returns"])
    jd = _join(j, t["customer_demographics"], ["cs_bill_cdemo_sk"],
               ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("cd_marital_status"), col("cd_education_status")],
        [Average(col("cs_quantity")).alias("avg_qty"),
         Average(col("cr_return_quantity")).alias("avg_ret_qty"),
         Count(col("cs_order_number")).alias("cnt")], jd)
    return CpuLimit(100, CpuSort(
        [asc(col("cd_marital_status")),
         asc(col("cd_education_status"))], agg))


def q89_shape(t, run):
    """Monthly category/brand/store revenue vs the yearly average
    (reference q89)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    j = _join(_join(_join(CpuFilter(col("d_year") == lit(2000),
                                    t["date_dim"]),
                          t["store_sales"], ["d_date_sk"],
                          ["ss_sold_date_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["store"], ["ss_store_sk"], ["s_store_sk"])
    monthly = CpuAggregate(
        [col("i_category"), col("i_brand"), col("s_store_name"),
         col("d_moy")],
        [Sum(col("ss_sales_price")).alias("sum_sales")], j)
    w = CpuWindow(
        [WinAvg(col("sum_sales")).alias("avg_monthly_sales")],
        WindowSpec([col("i_category"), col("i_brand"),
                    col("s_store_name")], [],
                   WindowFrame(is_rows=True, lower=None, upper=None)),
        monthly)
    keep = CpuFilter(
        col("sum_sales") > col("avg_monthly_sales") * lit(1.1), w)
    return CpuLimit(100, CpuSort(
        [asc(col("i_category")), asc(col("i_brand")),
         asc(col("s_store_name")), asc(col("d_moy"))],
        CpuProject([col("i_category"), col("i_brand"),
                    col("s_store_name"), col("d_moy"), col("sum_sales"),
                    col("avg_monthly_sales")], keep)))


def q95_shape(t, run):
    """Web orders shipped from more than one warehouse that were also
    returned (reference q95's double-EXISTS over ws self-join + wr)."""
    ws2 = CpuProject([col("ws_order_number").alias("o2"),
                      col("ws_warehouse_sk").alias("w2")],
                     t["web_sales"])
    multi = CpuHashJoin(
        J.LEFT_SEMI, [col("ws_order_number")], [col("o2")],
        t["web_sales"], ws2,
        condition=col("ws_warehouse_sk") != col("w2"))
    returned = CpuHashJoin(
        J.LEFT_SEMI, [col("ws_order_number")], [col("wr_order_number")],
        multi, t["web_returns"])
    per_order = CpuAggregate(
        [col("ws_order_number")],
        [Sum(col("ws_ext_ship_cost")).alias("ship_cost"),
         Sum(col("ws_net_profit")).alias("profit")], returned)
    total = CpuAggregate(
        [],
        [Count(col("ws_order_number")).alias("order_count"),
         Sum(col("ship_cost")).alias("total_shipping"),
         Sum(col("profit")).alias("total_profit")], per_order)
    return total


QUERIES.update({
    "q4": q4_shape, "q5": q5_shape, "q9": q9_shape, "q11": q11_shape,
    "q12": q12_shape, "q14": q14_shape, "q17": q17_shape,
    "q20": q20_shape, "q22": q22_rollup, "q24": q24_shape,
    "q29": q29_shape, "q35": q35_shape, "q39": q39_shape,
    "q49": q49_shape, "q53": q53_shape, "q54": q54_shape,
    "q56": q56_shape, "q57": q57_shape, "q64": q64_shape,
    "q72": q72_shape, "q74": q74_shape, "q76": q76_shape,
    "q78": q78_shape, "q81": q81_shape, "q83": q83_shape,
    "q84": q84_shape, "q85": q85_shape, "q86": q86_rollup,
    "q89": q89_shape, "q95": q95_shape,
})


# a/b variants (the reference counts q14a/b, q23a/b, q24a/b, q39a/b as
# separate queries — TpcdsLikeSpark.scala) + q91.
def q14b_shape(t, run):
    """Cross-channel items: this-year vs last-year sales comparison for
    items sold in both store and catalog (reference q14b's
    year-over-year arm; q14(a) covers the 3-channel intersection)."""
    both = CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                       [col("cs_item_sk")],
                       CpuHashJoin(J.LEFT_SEMI, [col("i_item_sk")],
                                   [col("ss_item_sk")], t["item"],
                                   t["store_sales"]),
                       t["catalog_sales"])

    def year_sales(y, alias):
        dd = CpuFilter(col("d_year") == lit(y), t["date_dim"])
        j = _join(_join(dd, t["store_sales"], ["d_date_sk"],
                        ["ss_sold_date_sk"]),
                  both, ["ss_item_sk"], ["i_item_sk"])
        return CpuAggregate(
            [col("i_brand_id")],
            [Sum(col("ss_ext_sales_price")).alias(alias)], j)

    this_y = year_sales(2000, "this_year")
    last_y = CpuProject([col("i_brand_id").alias("b2"),
                         col("last_year")],
                        year_sales(1999, "last_year"))
    j = CpuHashJoin(J.INNER, [col("i_brand_id")], [col("b2")],
                    this_y, last_y)
    return CpuLimit(100, CpuSort(
        [desc(col("this_year")), asc(col("i_brand_id"))],
        CpuProject([col("i_brand_id"), col("this_year"),
                    col("last_year")], j)))


def q23b_shape(t, run):
    """Best store customers' catalog spend on frequently-sold items
    (reference q23b; q23(a) covers the frequent-item monthly totals)."""
    freq = CpuFilter(col("cnt") > lit(4), CpuAggregate(
        [col("ss_item_sk")], [Count(None).alias("cnt")],
        t["store_sales"]))
    best = CpuFilter(col("spend") > lit(1000.0), CpuAggregate(
        [col("ss_customer_sk")],
        [Sum(col("ss_net_paid")).alias("spend")], t["store_sales"]))
    cs = CpuHashJoin(J.LEFT_SEMI, [col("cs_item_sk")],
                     [col("ss_item_sk")], t["catalog_sales"], freq)
    cs = CpuHashJoin(J.LEFT_SEMI, [col("cs_bill_customer_sk")],
                     [col("ss_customer_sk")], cs, best)
    agg = CpuAggregate(
        [col("cs_bill_customer_sk")],
        [Sum(col("cs_sales_price")).alias("sales")], cs)
    return CpuLimit(100, CpuSort(
        [desc(col("sales")), asc(col("cs_bill_customer_sk"))], agg))


def q24b_shape(t, run):
    """q24's sibling keyed by category instead of brand (the reference
    differs only in the color filter; the v0 item schema has no color)."""
    from spark_rapids_tpu.exec.window import (CpuWindow, WindowFrame,
                                              WindowSpec, WinAvg)
    ssr = CpuHashJoin(
        J.INNER, [col("ss_ticket_number"), col("ss_item_sk")],
        [col("sr_ticket_number"), col("sr_item_sk")],
        t["store_sales"], t["store_returns"])
    j = _join(_join(_join(ssr, t["store"], ["ss_store_sk"],
                          ["s_store_sk"]),
                    t["item"], ["ss_item_sk"], ["i_item_sk"]),
              t["customer"], ["ss_customer_sk"], ["c_customer_sk"])
    agg = CpuAggregate(
        [col("c_last_name"), col("s_store_name"), col("i_category")],
        [Sum(col("ss_net_paid")).alias("netpaid")], j)
    w = CpuWindow(
        [WinAvg(col("netpaid")).alias("avg_netpaid")],
        WindowSpec([], [], WindowFrame(is_rows=True, lower=None,
                                       upper=None)), agg)
    keep = CpuFilter(col("netpaid") > col("avg_netpaid") * lit(0.05), w)
    return CpuLimit(100, CpuSort(
        [asc(col("c_last_name")), asc(col("s_store_name")),
         asc(col("i_category"))],
        CpuProject([col("c_last_name"), col("s_store_name"),
                    col("i_category"), col("netpaid")], keep)))


def q39b_shape(t, run):
    """q39's second arm: only pairs whose month-over-month quantity
    swing is large (reference q39b tightens the covariance filter)."""
    base = q39_shape(t, run)
    # re-filter the paired report: keep rows with a >30% swing
    from spark_rapids_tpu.exprs.arithmetic import Abs as _Abs
    inner = base.child.child if isinstance(base, CpuLimit) else base
    swing = CpuFilter(
        (col("qoh1") > lit(0.0)) &
        (_Abs(col("qoh2") - col("qoh1")) / col("qoh1") > lit(0.3)),
        inner)
    return CpuLimit(100, CpuSort(
        [asc(col("w_warehouse_sk")), asc(col("inv_item_sk")),
         asc(col("next_moy"))], swing))


def q91_shape(t, run):
    """Catalog returns profiled by buyer demographics and customer state
    (reference q91 groups by call center — outside the v0 table set;
    the demographic link rides the originating catalog sale's
    cs_bill_cdemo_sk, the same path q85 uses)."""
    ret = CpuHashJoin(
        J.INNER, [col("cr_order_number"), col("cr_item_sk")],
        [col("cs_order_number"), col("cs_item_sk")],
        t["catalog_returns"], t["catalog_sales"])
    j = _join(_join(_join(ret, t["customer"],
                          ["cr_returning_customer_sk"],
                          ["c_customer_sk"]),
                    t["customer_address"], ["c_current_addr_sk"],
                    ["ca_address_sk"]),
              t["customer_demographics"],
              ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    agg = CpuAggregate(
        [col("ca_state"), col("cd_marital_status")],
        [Sum(col("cr_return_amount")).alias("returns_loss"),
         Count(None).alias("cnt")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("returns_loss")), asc(col("ca_state")),
         asc(col("cd_marital_status"))], agg))


QUERIES.update({
    "q14b": q14b_shape, "q23b": q23b_shape, "q24b": q24b_shape,
    "q39b": q39b_shape, "q91": q91_shape,
})
