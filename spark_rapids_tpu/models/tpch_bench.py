"""TPC-H bench driver (reference `TpcxbbLikeBench.runBench`
`TpcxbbLikeBench.scala:26-40` / `TpcdsLikeBench.scala`): cold runs
(compile) + hot runs, per-query wall-clock, CPU-engine baseline ratio.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from spark_rapids_tpu import config as C
from spark_rapids_tpu.models.tpch_data import gen_tables, sources
from spark_rapids_tpu.models.tpch_queries import QUERIES


def _tpu_runner(conf):
    from spark_rapids_tpu.plan.overrides import accelerate, collect

    def run(plan):
        return collect(accelerate(plan, conf), conf)
    return run


def _cpu_runner():
    return lambda plan: plan.collect()


#: bench conf mirrors how the reference runs its TPC suites: incompat /
#: order-sensitive float aggregation enabled (results differ from CPU only
#: in float rounding order)
BENCH_CONF = {
    "spark.rapids.sql.variableFloatAgg.enabled": True,
    "spark.rapids.sql.incompatibleOps.enabled": True,
}


def run_query(n: int, tables, engine: str = "tpu",
              conf: Optional[C.RapidsConf] = None,
              num_partitions: int = 2):
    t = sources(tables, num_partitions)
    if engine == "cpu":
        run = _cpu_runner()
        return QUERIES[n](t, run).collect()
    conf = conf or C.RapidsConf(dict(BENCH_CONF))
    run = _tpu_runner(conf)
    return run(QUERIES[n](t, run))


def run_bench(queries: Sequence[int] = tuple(QUERIES),
              scale: int = 100_000, num_cold_runs: int = 1,
              num_hot_runs: int = 3, engine: str = "tpu",
              conf: Optional[C.RapidsConf] = None) -> dict:
    """Cold+hot timing per query; returns {query: {cold_s, hot_s}}."""
    rng = np.random.default_rng(0)
    tables = gen_tables(rng, scale)
    results = {}
    for n in queries:
        cold = []
        for _ in range(num_cold_runs):
            t0 = time.perf_counter()
            run_query(n, tables, engine, conf)
            cold.append(time.perf_counter() - t0)
        hot = []
        for _ in range(num_hot_runs):
            t0 = time.perf_counter()
            run_query(n, tables, engine, conf)
            hot.append(time.perf_counter() - t0)
        results[n] = {"cold_s": min(cold) if cold else None,
                      "hot_s": min(hot) if hot else None}
        fmt = lambda v: "-" if v is None else f"{v:.3f}s"
        print(f"q{n}: cold={fmt(results[n]['cold_s'])} "
              f"hot={fmt(results[n]['hot_s'])}")
    return results


if __name__ == "__main__":
    import argparse
    import json
    ap = argparse.ArgumentParser()
    ap.add_argument("--queries", type=str, default="1,3,5,6")
    ap.add_argument("--scale", type=int, default=100_000)
    ap.add_argument("--engine", type=str, default="tpu")
    args = ap.parse_args()
    qs = [int(x) for x in args.queries.split(",")]
    out = run_bench(qs, scale=args.scale, engine=args.engine)
    print(json.dumps(out))
