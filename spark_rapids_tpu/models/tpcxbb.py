"""TPCx-BB-like workload subset (reference
`integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala` + the
`TpcxbbLikeBench` driver that produced the headline chart —
README.md:12-19).  Clickstream + sales analytics shapes: co-browsed
categories, per-item view counts before purchase, category sales share.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import Coalesce, If
from spark_rapids_tpu.exprs.predicates import InSet
from spark_rapids_tpu.models.tpcds_data import CATEGORIES
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort)

CLICKS_SCHEMA = T.Schema.of(
    ("wcs_click_date_sk", T.INT64), ("wcs_user_sk", T.INT64),
    ("wcs_item_sk", T.INT64), ("wcs_sales_sk", T.INT64))


def gen_clickstream(rng: np.random.Generator, n: int, n_items: int,
                    n_users: int, n_dates: int) -> pd.DataFrame:
    bought = rng.random(n) < 0.1
    return pd.DataFrame({
        "wcs_click_date_sk": rng.integers(0, n_dates, n).astype(np.int64),
        "wcs_user_sk": rng.integers(0, n_users, n).astype(np.int64),
        "wcs_item_sk": rng.integers(0, n_items, n).astype(np.int64),
        # -1 marks a view without purchase (nullable FK in the reference)
        "wcs_sales_sk": np.where(bought, rng.integers(0, n, n), -1)
        .astype(np.int64),
    })


def gen_tables(rng: np.random.Generator, scale: int = 10_000):
    """TPC-DS tables + a clickstream sized 3x store_sales."""
    from spark_rapids_tpu.models import tpcds_data
    tables = tpcds_data.gen_tables(rng, scale)
    n_items = len(tables["item"])
    n_users = len(tables["customer"])
    tables["web_clickstreams"] = gen_clickstream(
        rng, scale * 3, n_items, n_users, 365 * 5)
    return tables


def sources(tables, num_partitions: int = 1):
    from spark_rapids_tpu.models import tpcds_data
    from spark_rapids_tpu.models.data_util import make_sources
    clicks = {"web_clickstreams": tables["web_clickstreams"]}
    rest = {k: v for k, v in tables.items()
            if k != "web_clickstreams"}
    out = tpcds_data.sources(rest, num_partitions)
    out.update(make_sources(clicks, {"web_clickstreams": CLICKS_SCHEMA},
                            num_partitions))
    return out


def q01_shape(t, run):
    """Top viewed categories (q01: frequently browsed together shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    agg = CpuAggregate([col("i_category")],
                       [Count(None).alias("views")], j)
    return CpuSort([desc(col("views")), asc(col("i_category"))], agg)


def q05_shape(t, run):
    """Per-user views of a category vs purchases (logistic-features
    shape of q05)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    flt = CpuFilter(InSet(col("i_category"),
                          ("Books", "Electronics")), j)
    agg = CpuAggregate(
        [col("wcs_user_sk")],
        [Count(None).alias("clicks"),
         Sum(_purchased()).alias("purchases")], flt)
    return CpuLimit(100, CpuSort(
        [desc(col("clicks")), asc(col("wcs_user_sk"))], agg))


def _purchased():
    from spark_rapids_tpu.exprs.conditional import CaseWhen
    return CaseWhen((((col("wcs_sales_sk") >= lit(0)), lit(1)),), lit(0))


def q12_shape(t, run):
    """Users who browsed then bought in a category window (semi join)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    viewed = CpuFilter(
        InSet(col("i_category"), ("Home", "Music")) &
        (col("wcs_sales_sk") < lit(0)), j)
    buyers = CpuProject(
        [col("ss_customer_sk").alias("buyer_sk")],
        t["store_sales"])
    out = CpuHashJoin(JoinType.LEFT_SEMI, [col("wcs_user_sk")],
                      [col("buyer_sk")], viewed, buyers)
    agg = CpuAggregate([col("wcs_user_sk")],
                       [Count(None).alias("views")], out)
    return CpuLimit(100, CpuSort(
        [desc(col("views")), asc(col("wcs_user_sk"))], agg))


def q15_shape(t, run):
    """Category share of sales per store (q15 trend shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("ss_item_sk")],
                    [col("i_item_sk")], t["store_sales"], t["item"])
    agg = CpuAggregate(
        [col("ss_store_sk"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("sales")], j)
    return CpuSort([asc(col("ss_store_sk")), desc(col("sales")),
                    asc(col("i_category"))], agg)


def q06_shape(t, run):
    """Customers whose second-half web spend grew vs the first half
    (reference q06's period-over-period ratio)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = CpuHashJoin(JoinType.INNER, [col("d_date_sk")],
                    [col("ws_sold_date_sk")], dd, t["web_sales"])
    agg = CpuAggregate(
        [col("ws_bill_customer_sk")],
        [Sum(If(col("d_moy") <= lit(6), col("ws_net_paid"),
                lit(0.0))).alias("first_half"),
         Sum(If(col("d_moy") > lit(6), col("ws_net_paid"),
                lit(0.0))).alias("second_half")], j)
    grew = CpuFilter((col("first_half") > lit(0.0)) &
                     (col("second_half") > col("first_half")), agg)
    return CpuLimit(100, CpuSort(
        [desc(col("second_half")), asc(col("ws_bill_customer_sk"))],
        grew))


def q09_shape(t, run):
    """Store quantity over demographic x price-band slices (reference
    q09's OR'd slice sums)."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree"))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("Secondary"))),
        t["customer_demographics"])
    sales = CpuFilter(
        ((col("ss_sales_price") >= lit(50.0)) &
         (col("ss_sales_price") <= lit(100.0))) |
        ((col("ss_sales_price") >= lit(150.0)) &
         (col("ss_sales_price") <= lit(200.0))), t["store_sales"])
    j = CpuHashJoin(JoinType.INNER, [col("ss_cdemo_sk")],
                    [col("cd_demo_sk")], sales, cd)
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("qty")], j)


def q14_shape(t, run):
    """Morning vs evening web order ratio (reference q14)."""
    j = CpuHashJoin(JoinType.INNER, [col("ws_sold_time_sk")],
                    [col("t_time_sk")], t["web_sales"], t["time_dim"])
    counts = CpuAggregate(
        [], [Sum(If((col("t_hour") >= lit(7)) & (col("t_hour") < lit(9)),
                    lit(1), lit(0))).alias("am_cnt"),
             Sum(If((col("t_hour") >= lit(19)) &
                    (col("t_hour") < lit(21)),
                    lit(1), lit(0))).alias("pm_cnt")], j)
    return CpuProject(
        [col("am_cnt"), col("pm_cnt"),
         (col("am_cnt") / col("pm_cnt")).alias("am_pm_ratio")], counts)


def q16_shape(t, run):
    """Web sales netted against returns around a pivot date (reference
    q16's before/after sums)."""
    j = CpuHashJoin(
        JoinType.LEFT_OUTER,
        [col("ws_order_number"), col("ws_item_sk")],
        [col("wr_order_number"), col("wr_item_sk")],
        t["web_sales"], t["web_returns"])
    j = CpuHashJoin(JoinType.INNER, [col("ws_sold_date_sk")],
                    [col("d_date_sk")], j,
                    CpuFilter(col("d_year") == lit(2001), t["date_dim"]))
    net = col("ws_sales_price") - Coalesce(
        (col("wr_return_amt"), lit(0.0)))
    return CpuAggregate(
        [], [Sum(If(col("d_moy") < lit(7), net, lit(0.0))).alias(
            "before"),
             Sum(If(col("d_moy") >= lit(7), net, lit(0.0))).alias(
            "after")], j)


def q17_shape(t, run):
    """Promotional share of store revenue in one category/month
    (reference q17's ratio of filtered to total sales)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"), ("Books", "Music")),
                   t["item"])
    base = CpuHashJoin(
        JoinType.INNER, [col("ss_item_sk")], [col("i_item_sk")],
        CpuHashJoin(JoinType.INNER, [col("d_date_sk")],
                    [col("ss_sold_date_sk")], dd, t["store_sales"]),
        it)
    promo = CpuHashJoin(
        JoinType.INNER, [col("ss_promo_sk")], [col("p_promo_sk")],
        base, CpuFilter((col("p_channel_email") == lit("Y")) |
                        (col("p_channel_event") == lit("Y")),
                        t["promotion"]))
    p_sum = CpuProject(
        [lit(1).alias("k1"), col("promotional")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "promotional")], promo))
    t_sum = CpuProject(
        [lit(1).alias("k2"), col("total")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "total")], base))
    j = CpuHashJoin(JoinType.INNER, [col("k1")], [col("k2")],
                    p_sum, t_sum)
    return CpuProject(
        [col("promotional"), col("total"),
         (col("promotional") / col("total") * lit(100.0)).alias(
             "promo_pct")], j)


def q20_shape(t, run):
    """Per-customer return-rate features for clustering (reference
    q20's order/amount return ratios)."""
    sales = CpuAggregate(
        [col("ss_customer_sk")],
        [Count(None).alias("orders"),
         Sum(col("ss_net_paid")).alias("spend")], t["store_sales"])
    rets = CpuAggregate(
        [col("sr_customer_sk")],
        [Count(None).alias("returns"),
         Sum(col("sr_return_amt")).alias("returned")],
        t["store_returns"])
    j = CpuHashJoin(JoinType.LEFT_OUTER, [col("ss_customer_sk")],
                    [col("sr_customer_sk")], sales, rets)
    out = CpuProject(
        [col("ss_customer_sk"),
         (Coalesce((col("returns"), lit(0))) * lit(1.0)
          / col("orders")).alias("return_order_ratio"),
         (Coalesce((col("returned"), lit(0.0)))
          / col("spend")).alias("return_amt_ratio")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("return_amt_ratio")), asc(col("ss_customer_sk"))],
        out))


def q21_shape(t, run):
    """Items a customer returned and then re-bought through the
    catalog channel (reference q21's store->return->rebuy chain, with
    catalog as the re-buy channel)."""
    sr = CpuHashJoin(
        JoinType.INNER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    re_buy = CpuHashJoin(
        JoinType.INNER,
        [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        sr, t["catalog_sales"])
    j = CpuHashJoin(JoinType.INNER, [col("sr_item_sk")],
                    [col("i_item_sk")], re_buy, t["item"])
    agg = CpuAggregate([col("i_item_id")],
                       [Count(None).alias("rebuys")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("rebuys")), asc(col("i_item_id"))], agg))


def q22_shape(t, run):
    """Inventory on hand before vs after a pivot date per warehouse
    (reference q22's ratio-banded report)."""
    j = CpuHashJoin(JoinType.INNER, [col("inv_date_sk")],
                    [col("d_date_sk")], t["inventory"],
                    CpuFilter(col("d_year") == lit(2000), t["date_dim"]))
    agg = CpuAggregate(
        [col("inv_warehouse_sk"), col("inv_item_sk")],
        [Sum(If(col("d_moy") < lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_moy") >= lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    banded = CpuFilter(
        (col("inv_before") > lit(0)) &
        (col("inv_after") * lit(3) >= col("inv_before") * lit(2)) &
        (col("inv_after") * lit(2) <= col("inv_before") * lit(3)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("inv_warehouse_sk")), asc(col("inv_item_sk"))], banded))


def q29_shape(t, run):
    """Item pairs bought in the same catalog order (reference q29/q30
    affinity self-join)."""
    left = CpuProject(
        [col("cs_order_number").alias("o1"),
         col("cs_item_sk").alias("item_l")], t["catalog_sales"])
    right = CpuProject(
        [col("cs_order_number").alias("o2"),
         col("cs_item_sk").alias("item_r")], t["catalog_sales"])
    pairs = CpuFilter(
        col("item_l") < col("item_r"),
        CpuHashJoin(JoinType.INNER, [col("o1")], [col("o2")],
                    left, right))
    agg = CpuAggregate([col("item_l"), col("item_r")],
                       [Count(None).alias("cnt")], pairs)
    return CpuLimit(100, CpuSort(
        [desc(col("cnt")), asc(col("item_l")), asc(col("item_r"))], agg))


QUERIES = {"q01": q01_shape, "q05": q05_shape, "q06": q06_shape,
           "q09": q09_shape, "q12": q12_shape, "q14": q14_shape,
           "q15": q15_shape, "q16": q16_shape, "q17": q17_shape,
           "q20": q20_shape, "q21": q21_shape, "q22": q22_shape,
           "q29": q29_shape}
