"""TPCx-BB-like workload subset (reference
`integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala` + the
`TpcxbbLikeBench` driver that produced the headline chart —
README.md:12-19).  Clickstream + sales analytics shapes: co-browsed
categories, per-item view counts before purchase, category sales share.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.predicates import InSet
from spark_rapids_tpu.models.tpcds_data import CATEGORIES
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort)

CLICKS_SCHEMA = T.Schema.of(
    ("wcs_click_date_sk", T.INT64), ("wcs_user_sk", T.INT64),
    ("wcs_item_sk", T.INT64), ("wcs_sales_sk", T.INT64))


def gen_clickstream(rng: np.random.Generator, n: int, n_items: int,
                    n_users: int, n_dates: int) -> pd.DataFrame:
    bought = rng.random(n) < 0.1
    return pd.DataFrame({
        "wcs_click_date_sk": rng.integers(0, n_dates, n).astype(np.int64),
        "wcs_user_sk": rng.integers(0, n_users, n).astype(np.int64),
        "wcs_item_sk": rng.integers(0, n_items, n).astype(np.int64),
        # -1 marks a view without purchase (nullable FK in the reference)
        "wcs_sales_sk": np.where(bought, rng.integers(0, n, n), -1)
        .astype(np.int64),
    })


def gen_tables(rng: np.random.Generator, scale: int = 10_000):
    """TPC-DS tables + a clickstream sized 3x store_sales."""
    from spark_rapids_tpu.models import tpcds_data
    tables = tpcds_data.gen_tables(rng, scale)
    n_items = len(tables["item"])
    n_users = len(tables["customer"])
    tables["web_clickstreams"] = gen_clickstream(
        rng, scale * 3, n_items, n_users, 365 * 5)
    return tables


def sources(tables, num_partitions: int = 1):
    from spark_rapids_tpu.models import tpcds_data
    from spark_rapids_tpu.models.data_util import make_sources
    clicks = {"web_clickstreams": tables["web_clickstreams"]}
    rest = {k: v for k, v in tables.items()
            if k != "web_clickstreams"}
    out = tpcds_data.sources(rest, num_partitions)
    out.update(make_sources(clicks, {"web_clickstreams": CLICKS_SCHEMA},
                            num_partitions))
    return out


def q01_shape(t, run):
    """Top viewed categories (q01: frequently browsed together shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    agg = CpuAggregate([col("i_category")],
                       [Count(None).alias("views")], j)
    return CpuSort([desc(col("views")), asc(col("i_category"))], agg)


def q05_shape(t, run):
    """Per-user views of a category vs purchases (logistic-features
    shape of q05)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    flt = CpuFilter(InSet(col("i_category"),
                          ("Books", "Electronics")), j)
    agg = CpuAggregate(
        [col("wcs_user_sk")],
        [Count(None).alias("clicks"),
         Sum(_purchased()).alias("purchases")], flt)
    return CpuLimit(100, CpuSort(
        [desc(col("clicks")), asc(col("wcs_user_sk"))], agg))


def _purchased():
    from spark_rapids_tpu.exprs.conditional import CaseWhen
    return CaseWhen((((col("wcs_sales_sk") >= lit(0)), lit(1)),), lit(0))


def q12_shape(t, run):
    """Users who browsed then bought in a category window (semi join)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    viewed = CpuFilter(
        InSet(col("i_category"), ("Home", "Music")) &
        (col("wcs_sales_sk") < lit(0)), j)
    buyers = CpuProject(
        [col("ss_customer_sk").alias("buyer_sk")],
        t["store_sales"])
    out = CpuHashJoin(JoinType.LEFT_SEMI, [col("wcs_user_sk")],
                      [col("buyer_sk")], viewed, buyers)
    agg = CpuAggregate([col("wcs_user_sk")],
                       [Count(None).alias("views")], out)
    return CpuLimit(100, CpuSort(
        [desc(col("views")), asc(col("wcs_user_sk"))], agg))


def q15_shape(t, run):
    """Category share of sales per store (q15 trend shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("ss_item_sk")],
                    [col("i_item_sk")], t["store_sales"], t["item"])
    agg = CpuAggregate(
        [col("ss_store_sk"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("sales")], j)
    return CpuSort([asc(col("ss_store_sk")), desc(col("sales")),
                    asc(col("i_category"))], agg)


QUERIES = {"q01": q01_shape, "q05": q05_shape, "q12": q12_shape,
           "q15": q15_shape}
