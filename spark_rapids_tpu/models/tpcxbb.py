"""TPCx-BB-like workload subset (reference
`integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala` + the
`TpcxbbLikeBench` driver that produced the headline chart —
README.md:12-19).  Clickstream + sales analytics shapes: co-browsed
categories, per-item view counts before purchase, category sales share.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Count, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import Coalesce, If
from spark_rapids_tpu.exprs.predicates import InSet
from spark_rapids_tpu.models.tpcds_data import CATEGORIES
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort)

CLICKS_SCHEMA = T.Schema.of(
    ("wcs_click_date_sk", T.INT64), ("wcs_user_sk", T.INT64),
    ("wcs_item_sk", T.INT64), ("wcs_sales_sk", T.INT64))


def gen_clickstream(rng: np.random.Generator, n: int, n_items: int,
                    n_users: int, n_dates: int) -> pd.DataFrame:
    bought = rng.random(n) < 0.1
    return pd.DataFrame({
        "wcs_click_date_sk": rng.integers(0, n_dates, n).astype(np.int64),
        "wcs_user_sk": rng.integers(0, n_users, n).astype(np.int64),
        "wcs_item_sk": rng.integers(0, n_items, n).astype(np.int64),
        # -1 marks a view without purchase (nullable FK in the reference)
        "wcs_sales_sk": np.where(bought, rng.integers(0, n, n), -1)
        .astype(np.int64),
    })


def gen_tables(rng: np.random.Generator, scale: int = 10_000):
    """TPC-DS tables + a clickstream sized 3x store_sales."""
    from spark_rapids_tpu.models import tpcds_data
    tables = tpcds_data.gen_tables(rng, scale)
    n_items = len(tables["item"])
    n_users = len(tables["customer"])
    tables["web_clickstreams"] = gen_clickstream(
        rng, scale * 3, n_items, n_users, 365 * 5)
    return tables


def sources(tables, num_partitions: int = 1):
    from spark_rapids_tpu.models import tpcds_data
    from spark_rapids_tpu.models.data_util import make_sources
    clicks = {"web_clickstreams": tables["web_clickstreams"]}
    rest = {k: v for k, v in tables.items()
            if k != "web_clickstreams"}
    out = tpcds_data.sources(rest, num_partitions)
    out.update(make_sources(clicks, {"web_clickstreams": CLICKS_SCHEMA},
                            num_partitions))
    return out


def q01_shape(t, run):
    """Top viewed categories (q01: frequently browsed together shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    agg = CpuAggregate([col("i_category")],
                       [Count(None).alias("views")], j)
    return CpuSort([desc(col("views")), asc(col("i_category"))], agg)


def q05_shape(t, run):
    """Per-user views of a category vs purchases (logistic-features
    shape of q05)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    flt = CpuFilter(InSet(col("i_category"),
                          ("Books", "Electronics")), j)
    agg = CpuAggregate(
        [col("wcs_user_sk")],
        [Count(None).alias("clicks"),
         Sum(_purchased()).alias("purchases")], flt)
    return CpuLimit(100, CpuSort(
        [desc(col("clicks")), asc(col("wcs_user_sk"))], agg))


def _purchased():
    from spark_rapids_tpu.exprs.conditional import CaseWhen
    return CaseWhen((((col("wcs_sales_sk") >= lit(0)), lit(1)),), lit(0))


def q12_shape(t, run):
    """Users who browsed then bought in a category window (semi join)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    viewed = CpuFilter(
        InSet(col("i_category"), ("Home", "Music")) &
        (col("wcs_sales_sk") < lit(0)), j)
    buyers = CpuProject(
        [col("ss_customer_sk").alias("buyer_sk")],
        t["store_sales"])
    out = CpuHashJoin(JoinType.LEFT_SEMI, [col("wcs_user_sk")],
                      [col("buyer_sk")], viewed, buyers)
    agg = CpuAggregate([col("wcs_user_sk")],
                       [Count(None).alias("views")], out)
    return CpuLimit(100, CpuSort(
        [desc(col("views")), asc(col("wcs_user_sk"))], agg))


def q15_shape(t, run):
    """Category share of sales per store (q15 trend shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("ss_item_sk")],
                    [col("i_item_sk")], t["store_sales"], t["item"])
    agg = CpuAggregate(
        [col("ss_store_sk"), col("i_category")],
        [Sum(col("ss_ext_sales_price")).alias("sales")], j)
    return CpuSort([asc(col("ss_store_sk")), desc(col("sales")),
                    asc(col("i_category"))], agg)


def q06_shape(t, run):
    """Customers whose second-half web spend grew vs the first half
    (reference q06's period-over-period ratio)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = CpuHashJoin(JoinType.INNER, [col("d_date_sk")],
                    [col("ws_sold_date_sk")], dd, t["web_sales"])
    agg = CpuAggregate(
        [col("ws_bill_customer_sk")],
        [Sum(If(col("d_moy") <= lit(6), col("ws_net_paid"),
                lit(0.0))).alias("first_half"),
         Sum(If(col("d_moy") > lit(6), col("ws_net_paid"),
                lit(0.0))).alias("second_half")], j)
    grew = CpuFilter((col("first_half") > lit(0.0)) &
                     (col("second_half") > col("first_half")), agg)
    return CpuLimit(100, CpuSort(
        [desc(col("second_half")), asc(col("ws_bill_customer_sk"))],
        grew))


def q09_shape(t, run):
    """Store quantity over demographic x price-band slices (reference
    q09's OR'd slice sums)."""
    cd = CpuFilter(
        ((col("cd_marital_status") == lit("M")) &
         (col("cd_education_status") == lit("4 yr Degree"))) |
        ((col("cd_marital_status") == lit("S")) &
         (col("cd_education_status") == lit("Secondary"))),
        t["customer_demographics"])
    sales = CpuFilter(
        ((col("ss_sales_price") >= lit(50.0)) &
         (col("ss_sales_price") <= lit(100.0))) |
        ((col("ss_sales_price") >= lit(150.0)) &
         (col("ss_sales_price") <= lit(200.0))), t["store_sales"])
    j = CpuHashJoin(JoinType.INNER, [col("ss_cdemo_sk")],
                    [col("cd_demo_sk")], sales, cd)
    return CpuAggregate([], [Sum(col("ss_quantity")).alias("qty")], j)


def q14_shape(t, run):
    """Morning vs evening web order ratio (reference q14)."""
    j = CpuHashJoin(JoinType.INNER, [col("ws_sold_time_sk")],
                    [col("t_time_sk")], t["web_sales"], t["time_dim"])
    counts = CpuAggregate(
        [], [Sum(If((col("t_hour") >= lit(7)) & (col("t_hour") < lit(9)),
                    lit(1), lit(0))).alias("am_cnt"),
             Sum(If((col("t_hour") >= lit(19)) &
                    (col("t_hour") < lit(21)),
                    lit(1), lit(0))).alias("pm_cnt")], j)
    return CpuProject(
        [col("am_cnt"), col("pm_cnt"),
         (col("am_cnt") / col("pm_cnt")).alias("am_pm_ratio")], counts)


def q16_shape(t, run):
    """Web sales netted against returns around a pivot date (reference
    q16's before/after sums)."""
    j = CpuHashJoin(
        JoinType.LEFT_OUTER,
        [col("ws_order_number"), col("ws_item_sk")],
        [col("wr_order_number"), col("wr_item_sk")],
        t["web_sales"], t["web_returns"])
    j = CpuHashJoin(JoinType.INNER, [col("ws_sold_date_sk")],
                    [col("d_date_sk")], j,
                    CpuFilter(col("d_year") == lit(2001), t["date_dim"]))
    net = col("ws_sales_price") - Coalesce(
        (col("wr_return_amt"), lit(0.0)))
    return CpuAggregate(
        [], [Sum(If(col("d_moy") < lit(7), net, lit(0.0))).alias(
            "before"),
             Sum(If(col("d_moy") >= lit(7), net, lit(0.0))).alias(
            "after")], j)


def q17_shape(t, run):
    """Promotional share of store revenue in one category/month
    (reference q17's ratio of filtered to total sales)."""
    dd = CpuFilter((col("d_year") == lit(2000)) &
                   (col("d_moy") == lit(12)), t["date_dim"])
    it = CpuFilter(InSet(col("i_category"), ("Books", "Music")),
                   t["item"])
    base = CpuHashJoin(
        JoinType.INNER, [col("ss_item_sk")], [col("i_item_sk")],
        CpuHashJoin(JoinType.INNER, [col("d_date_sk")],
                    [col("ss_sold_date_sk")], dd, t["store_sales"]),
        it)
    promo = CpuHashJoin(
        JoinType.INNER, [col("ss_promo_sk")], [col("p_promo_sk")],
        base, CpuFilter((col("p_channel_email") == lit("Y")) |
                        (col("p_channel_event") == lit("Y")),
                        t["promotion"]))
    p_sum = CpuProject(
        [lit(1).alias("k1"), col("promotional")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "promotional")], promo))
    t_sum = CpuProject(
        [lit(1).alias("k2"), col("total")],
        CpuAggregate([], [Sum(col("ss_ext_sales_price")).alias(
            "total")], base))
    j = CpuHashJoin(JoinType.INNER, [col("k1")], [col("k2")],
                    p_sum, t_sum)
    return CpuProject(
        [col("promotional"), col("total"),
         (col("promotional") / col("total") * lit(100.0)).alias(
             "promo_pct")], j)


def q20_shape(t, run):
    """Per-customer return-rate features for clustering (reference
    q20's order/amount return ratios)."""
    sales = CpuAggregate(
        [col("ss_customer_sk")],
        [Count(None).alias("orders"),
         Sum(col("ss_net_paid")).alias("spend")], t["store_sales"])
    rets = CpuAggregate(
        [col("sr_customer_sk")],
        [Count(None).alias("returns"),
         Sum(col("sr_return_amt")).alias("returned")],
        t["store_returns"])
    j = CpuHashJoin(JoinType.LEFT_OUTER, [col("ss_customer_sk")],
                    [col("sr_customer_sk")], sales, rets)
    out = CpuProject(
        [col("ss_customer_sk"),
         (Coalesce((col("returns"), lit(0))) * lit(1.0)
          / col("orders")).alias("return_order_ratio"),
         (Coalesce((col("returned"), lit(0.0)))
          / col("spend")).alias("return_amt_ratio")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("return_amt_ratio")), asc(col("ss_customer_sk"))],
        out))


def q21_shape(t, run):
    """Items a customer returned and then re-bought through the
    catalog channel (reference q21's store->return->rebuy chain, with
    catalog as the re-buy channel)."""
    sr = CpuHashJoin(
        JoinType.INNER,
        [col("ss_item_sk"), col("ss_ticket_number")],
        [col("sr_item_sk"), col("sr_ticket_number")],
        t["store_sales"], t["store_returns"])
    re_buy = CpuHashJoin(
        JoinType.INNER,
        [col("sr_customer_sk"), col("sr_item_sk")],
        [col("cs_bill_customer_sk"), col("cs_item_sk")],
        sr, t["catalog_sales"])
    j = CpuHashJoin(JoinType.INNER, [col("sr_item_sk")],
                    [col("i_item_sk")], re_buy, t["item"])
    agg = CpuAggregate([col("i_item_id")],
                       [Count(None).alias("rebuys")], j)
    return CpuLimit(100, CpuSort(
        [desc(col("rebuys")), asc(col("i_item_id"))], agg))


def q22_shape(t, run):
    """Inventory on hand before vs after a pivot date per warehouse
    (reference q22's ratio-banded report)."""
    j = CpuHashJoin(JoinType.INNER, [col("inv_date_sk")],
                    [col("d_date_sk")], t["inventory"],
                    CpuFilter(col("d_year") == lit(2000), t["date_dim"]))
    agg = CpuAggregate(
        [col("inv_warehouse_sk"), col("inv_item_sk")],
        [Sum(If(col("d_moy") < lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_before"),
         Sum(If(col("d_moy") >= lit(6), col("inv_quantity_on_hand"),
                lit(0))).alias("inv_after")], j)
    banded = CpuFilter(
        (col("inv_before") > lit(0)) &
        (col("inv_after") * lit(3) >= col("inv_before") * lit(2)) &
        (col("inv_after") * lit(2) <= col("inv_before") * lit(3)), agg)
    return CpuLimit(100, CpuSort(
        [asc(col("inv_warehouse_sk")), asc(col("inv_item_sk"))], banded))


def q29_shape(t, run):
    """Item pairs bought in the same catalog order (reference q29/q30
    affinity self-join)."""
    left = CpuProject(
        [col("cs_order_number").alias("o1"),
         col("cs_item_sk").alias("item_l")], t["catalog_sales"])
    right = CpuProject(
        [col("cs_order_number").alias("o2"),
         col("cs_item_sk").alias("item_r")], t["catalog_sales"])
    pairs = CpuFilter(
        col("item_l") < col("item_r"),
        CpuHashJoin(JoinType.INNER, [col("o1")], [col("o2")],
                    left, right))
    agg = CpuAggregate([col("item_l"), col("item_r")],
                       [Count(None).alias("cnt")], pairs)
    return CpuLimit(100, CpuSort(
        [desc(col("cnt")), asc(col("item_l")), asc(col("item_r"))], agg))


QUERIES = {"q01": q01_shape, "q05": q05_shape, "q06": q06_shape,
           "q09": q09_shape, "q12": q12_shape, "q14": q14_shape,
           "q15": q15_shape, "q16": q16_shape, "q17": q17_shape,
           "q20": q20_shape, "q21": q21_shape, "q22": q22_shape,
           "q29": q29_shape}


# ---------------------------------------------------------------------------
# round-2 growth toward the reference's 30 queries
# (TpcxbbLikeSpark.scala:785-2065): clickstream self-join shapes
# (q02/q03/q30), session/abandonment funnels (q04/q08), pricing and
# segmentation (q07/q24/q25/q26), ratio reports (q11/q13/q23), and the
# NLP-ish review-sentiment family (q10/q19/q28) over a synthesized
# product_reviews table — the reference runs these as text UDFs over
# review bodies; the v0 shape uses literal-pattern Contains sentiment.
REVIEWS_SCHEMA = T.Schema.of(
    ("pr_review_sk", T.INT64), ("pr_item_sk", T.INT64),
    ("pr_user_sk", T.INT64), ("pr_rating", T.INT32),
    ("pr_content", T.STRING))

_GOOD = ["good", "great", "excellent"]
_BAD = ["bad", "poor", "terrible"]


def gen_reviews(rng: np.random.Generator, n: int, n_items: int,
                n_users: int) -> pd.DataFrame:
    rating = rng.integers(1, 6, n)
    adj = [(_GOOD if r >= 4 else _BAD)[int(rng.integers(0, 3))]
           if r != 3 else "okay" for r in rating]
    noun = rng.choice(["value", "quality", "shipping", "design"], n)
    content = [f"{a} {b} overall" for a, b in zip(adj, noun)]
    return pd.DataFrame({
        "pr_review_sk": np.arange(n, dtype=np.int64),
        "pr_item_sk": rng.integers(0, n_items, n).astype(np.int64),
        "pr_user_sk": rng.integers(0, n_users, n).astype(np.int64),
        "pr_rating": rating.astype(np.int32),
        "pr_content": content,
    })


_BASE_GEN_TABLES = gen_tables


def gen_tables(rng: np.random.Generator, scale: int = 10_000):
    tables = _BASE_GEN_TABLES(rng, scale)
    n_items = len(tables["item"])
    n_users = len(tables["customer"])
    tables["product_reviews"] = gen_reviews(
        rng, max(scale // 2, 64), n_items, n_users)
    return tables


_BASE_SOURCES = sources


def sources(tables, num_partitions: int = 1):
    base = {k: v for k, v in tables.items() if k != "product_reviews"}
    out = _BASE_SOURCES(base, num_partitions)
    if "product_reviews" in tables:
        from spark_rapids_tpu.models.data_util import make_sources
        out.update(make_sources(
            {"product_reviews": tables["product_reviews"]},
            {"product_reviews": REVIEWS_SCHEMA}, num_partitions))
    return out


def _sentiment():
    """Contains-based polarity: +1 per good word, -1 per bad word —
    the literal-pattern stand-in for the reference's text UDF."""
    from spark_rapids_tpu.exprs.string_fns import Contains
    expr = lit(0)
    for w in _GOOD:
        expr = expr + If(Contains(col("pr_content"), lit(w)),
                         lit(1), lit(0))
    for w in _BAD:
        expr = expr - If(Contains(col("pr_content"), lit(w)),
                         lit(1), lit(0))
    return expr


def q02_shape(t, run):
    """Items co-viewed by the same user with a target item (reference
    q02's sessionized pair counts, user-keyed in the v0 shape)."""
    target = CpuProject(
        [col("wcs_user_sk").alias("tu")],
        CpuFilter(col("wcs_item_sk") == lit(7), t["web_clickstreams"]))
    co = CpuHashJoin(JoinType.LEFT_SEMI, [col("wcs_user_sk")],
                     [col("tu")], t["web_clickstreams"], target)
    other = CpuFilter(col("wcs_item_sk") != lit(7), co)
    agg = CpuAggregate([col("wcs_item_sk")],
                       [Count(None).alias("cnt")], other)
    return CpuLimit(30, CpuSort(
        [desc(col("cnt")), asc(col("wcs_item_sk"))], agg))


def q03_shape(t, run):
    """Views that preceded a purchase of the same item by the same user
    (reference q03's last-N-clicks-before-purchase funnel)."""
    buys = CpuProject(
        [col("wcs_user_sk").alias("bu"), col("wcs_item_sk").alias("bi"),
         col("wcs_click_date_sk").alias("bd")],
        CpuFilter(col("wcs_sales_sk") >= lit(0), t["web_clickstreams"]))
    views = CpuFilter(col("wcs_sales_sk") < lit(0),
                      t["web_clickstreams"])
    pair = CpuHashJoin(
        JoinType.INNER, [col("wcs_user_sk"), col("wcs_item_sk")],
        [col("bu"), col("bi")], views, buys,
        condition=col("wcs_click_date_sk") <= col("bd"))
    agg = CpuAggregate([col("wcs_item_sk")],
                       [Count(None).alias("prior_views")], pair)
    return CpuLimit(100, CpuSort(
        [desc(col("prior_views")), asc(col("wcs_item_sk"))], agg))


def q04_shape(t, run):
    """Per-user abandonment: users with views but zero purchases
    (reference q04's cart-abandonment funnel)."""
    agg = CpuAggregate(
        [col("wcs_user_sk")],
        [Count(None).alias("views"),
         Sum(_purchased()).alias("purchases")], t["web_clickstreams"])
    abandoned = CpuFilter(col("purchases") == lit(0), agg)
    return CpuLimit(100, CpuSort(
        [desc(col("views")), asc(col("wcs_user_sk"))], abandoned))


def q07_shape(t, run):
    """States whose customers buy high-priced items (reference q07)."""
    pricey = CpuFilter(col("i_current_price") > lit(60.0), t["item"])
    j = CpuHashJoin(JoinType.INNER, [col("i_item_sk")],
                    [col("ss_item_sk")], pricey, t["store_sales"])
    jc = CpuHashJoin(JoinType.INNER, [col("ss_customer_sk")],
                     [col("c_customer_sk")], j, t["customer"])
    ja = CpuHashJoin(JoinType.INNER, [col("c_current_addr_sk")],
                     [col("ca_address_sk")], jc, t["customer_address"])
    agg = CpuAggregate([col("ca_state")],
                       [Count(None).alias("cnt")], ja)
    return CpuLimit(10, CpuSort(
        [desc(col("cnt")), asc(col("ca_state"))],
        CpuFilter(col("cnt") >= lit(2), agg)))


def q08_shape(t, run):
    """Web sales from users who browsed first vs not (reference q08's
    reviewed-then-bought split)."""
    viewers = CpuProject(
        [col("wcs_user_sk").alias("vu")],
        CpuFilter(col("wcs_sales_sk") < lit(0), t["web_clickstreams"]))
    sales = CpuProject(
        [col("ws_bill_customer_sk").alias("cust"),
         col("ws_net_paid").alias("paid")], t["web_sales"])
    browsed = CpuHashJoin(JoinType.LEFT_SEMI, [col("cust")],
                          [col("vu")], sales, viewers)
    not_browsed = CpuHashJoin(JoinType.LEFT_ANTI, [col("cust")],
                              [col("vu")], sales, viewers)
    from spark_rapids_tpu.plan.nodes import CpuUnion

    def summarize(label, side):
        return CpuProject(
            [lit(label).alias("cohort"), col("paid_sum"), col("cnt")],
            CpuAggregate([], [Sum(col("paid")).alias("paid_sum"),
                              Count(None).alias("cnt")], side))

    return CpuSort([asc(col("cohort"))],
                   CpuUnion(summarize("browsed", browsed),
                            summarize("other", not_browsed)))


def q10_shape(t, run):
    """Review sentiment per category (reference q10's sentiment UDF —
    literal-pattern polarity here)."""
    j = CpuHashJoin(JoinType.INNER, [col("pr_item_sk")],
                    [col("i_item_sk")], t["product_reviews"], t["item"])
    scored = CpuProject(
        [col("i_category"), _sentiment().alias("polarity"),
         col("pr_rating")], j)
    agg = CpuAggregate(
        [col("i_category")],
        [Sum(col("polarity")).alias("sentiment"),
         Count(None).alias("reviews")], scored)
    return CpuSort([asc(col("i_category"))], agg)


def q11_shape(t, run):
    """Review count vs sales per item (reference q11's correlation
    prep)."""
    r = CpuAggregate([col("pr_item_sk")],
                     [Count(None).alias("reviews"),
                      Sum(col("pr_rating")).alias("rating_sum")],
                     t["product_reviews"])
    s = CpuProject([col("ss_item_sk").alias("si"), col("sales")],
                   CpuAggregate(
                       [col("ss_item_sk")],
                       [Sum(col("ss_ext_sales_price")).alias("sales")],
                       t["store_sales"]))
    j = CpuHashJoin(JoinType.INNER, [col("pr_item_sk")], [col("si")],
                    r, s)
    return CpuLimit(100, CpuSort(
        [desc(col("sales")), asc(col("pr_item_sk"))],
        CpuProject([col("pr_item_sk"), col("reviews"),
                    col("rating_sum"), col("sales")], j)))


def q13_shape(t, run):
    """Customers' web vs store spend ratio (reference q13)."""
    w = CpuAggregate([col("ws_bill_customer_sk")],
                     [Sum(col("ws_net_paid")).alias("web_paid")],
                     t["web_sales"])
    s = CpuProject([col("ss_customer_sk").alias("sc"),
                    col("store_paid")],
                   CpuAggregate(
                       [col("ss_customer_sk")],
                       [Sum(col("ss_net_paid")).alias("store_paid")],
                       t["store_sales"]))
    j = CpuHashJoin(JoinType.INNER, [col("ws_bill_customer_sk")],
                    [col("sc")], w, s)
    keep = CpuFilter(col("store_paid") > lit(0.0), j)
    out = CpuProject(
        [col("ws_bill_customer_sk"),
         (col("web_paid") / col("store_paid")).alias("ratio")], keep)
    return CpuLimit(100, CpuSort(
        [desc(col("ratio")), asc(col("ws_bill_customer_sk"))], out))


def q19_shape(t, run):
    """Sentiment of reviews for returned items (reference q19)."""
    returned = CpuProject([col("sr_item_sk").alias("ri")],
                          t["store_returns"])
    rr = CpuHashJoin(JoinType.LEFT_SEMI, [col("pr_item_sk")],
                     [col("ri")], t["product_reviews"], returned)
    scored = CpuProject([col("pr_item_sk"),
                         _sentiment().alias("polarity")], rr)
    agg = CpuAggregate([col("pr_item_sk")],
                       [Sum(col("polarity")).alias("sentiment"),
                        Count(None).alias("reviews")], scored)
    return CpuLimit(100, CpuSort(
        [asc(col("sentiment")), asc(col("pr_item_sk"))], agg))


def q23_shape(t, run):
    """Inventory month-over-month swing per warehouse/item (reference
    q23's variance screen, avg-based in the v0 aggregate set)."""
    dd = CpuFilter(col("d_year") == lit(2000), t["date_dim"])
    j = CpuHashJoin(JoinType.INNER, [col("d_date_sk")],
                    [col("inv_date_sk")], dd, t["inventory"])
    monthly = CpuAggregate(
        [col("inv_warehouse_sk"), col("inv_item_sk"), col("d_moy")],
        [Sum(col("inv_quantity_on_hand")).alias("qty")], j)
    stats = CpuAggregate(
        [col("inv_warehouse_sk"), col("inv_item_sk")],
        [Sum(col("qty")).alias("total"), Count(None).alias("months")],
        monthly)
    return CpuLimit(100, CpuSort(
        [desc(col("total")), asc(col("inv_warehouse_sk")),
         asc(col("inv_item_sk"))],
        CpuFilter(col("months") >= lit(2), stats)))


def q24_shape(t, run):
    """Price sensitivity: sales volume of expensive vs cheap items per
    category (reference q24's elasticity shape)."""
    j = CpuHashJoin(JoinType.INNER, [col("ss_item_sk")],
                    [col("i_item_sk")], t["store_sales"], t["item"])
    flagged = CpuProject(
        [col("i_category"),
         If(col("i_current_price") > lit(50.0), col("ss_quantity"),
            lit(0)).alias("pricey_qty"),
         If(col("i_current_price") <= lit(50.0), col("ss_quantity"),
            lit(0)).alias("cheap_qty")], j)
    agg = CpuAggregate(
        [col("i_category")],
        [Sum(col("pricey_qty")).alias("pricey_qty"),
         Sum(col("cheap_qty")).alias("cheap_qty")], flagged)
    return CpuSort([asc(col("i_category"))], agg)


def q25_shape(t, run):
    """Customer recency/frequency/monetary segmentation prep (reference
    q25's k-means feature build)."""
    from spark_rapids_tpu.exprs.aggregates import Max
    agg = CpuAggregate(
        [col("ss_customer_sk")],
        [Max(col("ss_sold_date_sk")).alias("recency"),
         Count(None).alias("frequency"),
         Sum(col("ss_net_paid")).alias("monetary")], t["store_sales"])
    return CpuLimit(100, CpuSort(
        [desc(col("monetary")), asc(col("ss_customer_sk"))], agg))


def q26_shape(t, run):
    """Per-customer category spend pivot (reference q26's cluster
    features: one column per category via conditional sums)."""
    j = CpuHashJoin(JoinType.INNER, [col("ss_item_sk")],
                    [col("i_item_sk")], t["store_sales"], t["item"])
    aggs = []
    for c in CATEGORIES[:5]:
        aggs.append(Sum(If(col("i_category") == lit(c),
                           col("ss_net_paid"), lit(0.0)))
                    .alias(f"spend_{c.lower()}"))
    agg = CpuAggregate([col("ss_customer_sk")], aggs, j)
    return CpuLimit(100, CpuSort(
        [asc(col("ss_customer_sk"))], agg))


def q28_shape(t, run):
    """Classifier data prep: deterministic hash split of reviews into
    train/test with per-split rating histograms (reference q28's naive
    bayes prep)."""
    split = CpuProject(
        [col("pr_rating"),
         If((col("pr_review_sk") % lit(10)) < lit(8),
            lit("train"), lit("test")).alias("part")],
        t["product_reviews"])
    agg = CpuAggregate([col("part"), col("pr_rating")],
                       [Count(None).alias("cnt")], split)
    return CpuSort([asc(col("part")), asc(col("pr_rating"))], agg)


def q30_shape(t, run):
    """Category affinity: pairs of categories viewed by the same user
    (reference q30's co-occurrence matrix)."""
    j = CpuHashJoin(JoinType.INNER, [col("wcs_item_sk")],
                    [col("i_item_sk")], t["web_clickstreams"], t["item"])
    a = CpuProject([col("wcs_user_sk").alias("ua"),
                    col("i_category_id").alias("cat_a")], j)
    b = CpuProject([col("wcs_user_sk").alias("ub"),
                    col("i_category_id").alias("cat_b")], j)
    pairs = CpuHashJoin(JoinType.INNER, [col("ua")], [col("ub")], a, b,
                        condition=col("cat_a") < col("cat_b"))
    agg = CpuAggregate([col("cat_a"), col("cat_b")],
                       [Count(None).alias("cnt")], pairs)
    return CpuLimit(100, CpuSort(
        [desc(col("cnt")), asc(col("cat_a")), asc(col("cat_b"))], agg))


QUERIES.update({
    "q02": q02_shape, "q03": q03_shape, "q04": q04_shape,
    "q07": q07_shape, "q08": q08_shape, "q10": q10_shape,
    "q11": q11_shape, "q13": q13_shape, "q19": q19_shape,
    "q23": q23_shape, "q24": q24_shape, "q25": q25_shape,
    "q26": q26_shape, "q28": q28_shape, "q30": q30_shape,
})


# ---------------------------------------------------------------------------
# round-3: q18 + q27 — the reference's Q18Like/Q27Like THROW
# ("uses UDF", TpcxbbLikeSpark.scala:1455,1993); here the text analysis
# runs through the udf-compiler (BASELINE milestone 5): a Python UDF over
# review content compiles to the expression AST and executes on TPU.
from spark_rapids_tpu import types as _T2
from spark_rapids_tpu.exprs.aggregates import Average
from spark_rapids_tpu.udf import tpu_udf

J = JoinType


def _join(left, right, lk, rk, jt=JoinType.INNER):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right)


@tpu_udf(_T2.INT64)
def review_sentiment(content):
    """BigBench q18-style sentiment: -1 negative, +1 positive, else 0."""
    if content is None:
        return 0
    if (content.find("bad") >= 0 or content.find("poor") >= 0 or
            content.find("terrible") >= 0):
        return -1
    if (content.find("good") >= 0 or content.find("great") >= 0 or
            content.find("excellent") >= 0):
        return 1
    return 0


@tpu_udf(_T2.INT64)
def mentions_aspect(content):
    """BigBench q27-style extraction flag: does the review call out the
    product aspect competitors fight on (quality/value)."""
    if content is None:
        return 0
    if content.find("quality") >= 0 or content.find("value") >= 0:
        return 1
    return 0


def q18(t, run):
    """q18-like: sentiment of reviews for items sold by DECLINING
    stores (Q1 vs Q2 sales), via the compiled sentiment UDF."""
    # Q1 vs Q2 (not half-years: the generator's December holiday
    # concentration would make every store "grow" in H2)
    dd1 = CpuFilter((col("d_year") == lit(1999)) &
                    (col("d_moy") <= lit(3)), t["date_dim"])
    dd2 = CpuFilter((col("d_year") == lit(1999)) &
                    (col("d_moy") >= lit(4)) &
                    (col("d_moy") <= lit(6)), t["date_dim"])

    def half(dd, alias, key):
        j = _join(CpuProject([col("d_date_sk").alias(key)], dd),
                  t["store_sales"], [key], ["ss_sold_date_sk"])
        return CpuAggregate([col("ss_store_sk").alias(f"sk_{alias}")],
                            [Sum(col("ss_net_paid")).alias(alias)], j)

    h1 = half(dd1, "h1", "d1sk")
    h2 = half(dd2, "h2", "d2sk")
    declining = CpuFilter(
        col("h2") < col("h1"),
        _join(h1, h2, ["sk_h1"], ["sk_h2"]))
    # items those stores sold in the window
    items = CpuAggregate(
        [col("it")], [Count(None).alias("_c")],
        _join(CpuProject([col("sk_h1").alias("decl_sk")], declining),
              CpuProject([col("ss_store_sk").alias("st"),
                          col("ss_item_sk").alias("it")],
                         t["store_sales"]),
              ["decl_sk"], ["st"]))
    rv = _join(t["product_reviews"], items, ["pr_item_sk"], ["it"],
               jt=J.LEFT_SEMI)
    scored = CpuProject(
        [col("pr_item_sk"),
         review_sentiment(col("pr_content")).alias("sentiment")], rv)
    agg = CpuAggregate(
        [col("sentiment")], [Count(None).alias("review_count")], scored)
    return CpuSort([asc(col("sentiment"))], agg)


def q27(t, run):
    """q27-like: per-item competitive-aspect mention counts and rating,
    via the compiled extraction UDF (BASELINE milestone 5's query)."""
    flagged = CpuProject(
        [col("pr_item_sk"), col("pr_rating"),
         mentions_aspect(col("pr_content")).alias("mention")],
        t["product_reviews"])
    agg = CpuAggregate(
        [col("pr_item_sk")],
        [Sum(col("mention")).alias("mentions"),
         Count(None).alias("n_reviews"),
         Average(col("pr_rating")).alias("avg_rating")], flagged)
    out = CpuFilter(col("mentions") > lit(0), agg)
    return CpuLimit(100, CpuSort(
        [desc(col("mentions")), asc(col("pr_item_sk"))], out))


QUERIES.update({"q18": q18, "q27": q27})
