"""All 22 TPC-H queries as engine plan trees (reference
`integration_tests/src/main/scala/.../tpch/TpchLikeSpark.scala` Q1-Q22
DataFrame implementations).

Each query is `qN(t, run) -> CpuNode`: `t` maps table name -> fresh source
plan; `run(plan) -> DataFrame` executes a sub-plan on the engine under
test (used only for scalar subqueries, mirroring how the reference's
DataFrame code computes scalars driver-side: Q11/Q15/Q17/Q22).

Correlated subqueries are decorrelated the way Catalyst does: as
aggregate-then-join (Q2/Q17/Q20) or semi/anti joins (Q4/Q16/Q18/Q21/Q22).
Dates are DATE32 int-day literals via `tpch_data.days`.
"""
from __future__ import annotations

from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc, desc
from spark_rapids_tpu.exprs.aggregates import Average, Count, Max, Min, Sum
from spark_rapids_tpu.exprs.base import Literal, col, lit
from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.conditional import CaseWhen
from spark_rapids_tpu.exprs.predicates import InSet, Not
from spark_rapids_tpu.exprs.string_fns import (Contains, Like, StartsWith,
                                               Substring)
from spark_rapids_tpu.models.tpch_data import days
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuLimit, CpuProject,
                                         CpuSort)

J = JoinType


def dlit(s: str):
    """DATE32 literal from 'YYYY-MM-DD' (date comparisons need matching
    dtypes; plain lit() would make an int literal)."""
    return Literal(days(s), T.DATE32)



def _join(jt, left, right, lk, rk, condition=None, broadcast=False):
    return CpuHashJoin(jt, [col(k) for k in lk], [col(k) for k in rk],
                       left, right, condition=condition,
                       broadcast=broadcast)


def _rename(node, mapping):
    """Project that renames `mapping` keys and keeps only them."""
    return CpuProject([col(a).alias(b) for a, b in mapping.items()], node)


def _cols(node, *names):
    return CpuProject([col(n) for n in names], node)


# ---------------------------------------------------------------------------
def q1(t, run):
    """Pricing summary report."""
    li = CpuFilter(col("l_shipdate") <= dlit("1998-09-02"),
                   t["lineitem"])
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    charge = disc * (lit(1.0) + col("l_tax"))
    agg = CpuAggregate(
        [col("l_returnflag"), col("l_linestatus")],
        [Sum(col("l_quantity")).alias("sum_qty"),
         Sum(col("l_extendedprice")).alias("sum_base_price"),
         Sum(disc).alias("sum_disc_price"),
         Sum(charge).alias("sum_charge"),
         Average(col("l_quantity")).alias("avg_qty"),
         Average(col("l_extendedprice")).alias("avg_price"),
         Average(col("l_discount")).alias("avg_disc"),
         Count(None).alias("count_order")], li)
    return CpuSort([asc(col("l_returnflag")), asc(col("l_linestatus"))],
                   agg)


def q2(t, run):
    """Minimum cost supplier (correlated min decorrelated as agg-join)."""
    eu_supp = _join(J.INNER,
                    _join(J.INNER, t["supplier"],
                          _join(J.INNER, t["nation"],
                                CpuFilter(col("r_name") == lit("EUROPE"),
                                          t["region"]),
                                ["n_regionkey"], ["r_regionkey"]),
                          ["s_nationkey"], ["n_nationkey"]),
                    t["partsupp"], ["s_suppkey"], ["ps_suppkey"])
    min_cost = CpuProject(
        [col("ps_partkey").alias("mc_key"), col("min_cost")],
        CpuAggregate(
            [col("ps_partkey")],
            [Min(col("ps_supplycost")).alias("min_cost")],
            _cols(eu_supp, "ps_partkey", "ps_supplycost")))
    part = CpuFilter((col("p_size") == lit(15)) &
                     Like(col("p_type"), lit("%BRASS")), t["part"])
    joined = _join(J.INNER, _join(J.INNER, eu_supp, part,
                                  ["ps_partkey"], ["p_partkey"]),
                   min_cost, ["ps_partkey"], ["mc_key"],
                   condition=(col("ps_supplycost") == col("min_cost")))
    out = CpuProject([col("s_acctbal"), col("s_name"), col("n_name"),
                      col("p_partkey"), col("p_mfgr"), col("s_address"),
                      col("s_phone"), col("s_comment")], joined)
    return CpuLimit(100, CpuSort(
        [desc(col("s_acctbal")), asc(col("n_name")), asc(col("s_name")),
         asc(col("p_partkey"))], out))


def q3(t, run):
    """Shipping priority."""
    cust = CpuFilter(col("c_mktsegment") == lit("BUILDING"),
                     t["customer"])
    orders = CpuFilter(col("o_orderdate") < dlit("1995-03-15"),
                       t["orders"])
    li = CpuFilter(col("l_shipdate") > dlit("1995-03-15"),
                   t["lineitem"])
    joined = _join(J.INNER,
                   _join(J.INNER, cust, orders,
                         ["c_custkey"], ["o_custkey"]),
                   li, ["o_orderkey"], ["l_orderkey"])
    agg = CpuAggregate(
        [col("l_orderkey"), col("o_orderdate"), col("o_shippriority")],
        [Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             ).alias("revenue")], joined)
    return CpuLimit(10, CpuSort(
        [desc(col("revenue")), asc(col("o_orderdate"))], agg))


def q4(t, run):
    """Order priority checking (EXISTS -> left semi join)."""
    orders = CpuFilter(
        (col("o_orderdate") >= dlit("1993-07-01")) &
        (col("o_orderdate") < dlit("1993-10-01")), t["orders"])
    late = CpuFilter(col("l_commitdate") < col("l_receiptdate"),
                     t["lineitem"])
    semi = _join(J.LEFT_SEMI, orders, late,
                 ["o_orderkey"], ["l_orderkey"])
    agg = CpuAggregate([col("o_orderpriority")],
                       [Count(None).alias("order_count")], semi)
    return CpuSort([asc(col("o_orderpriority"))], agg)


def q5(t, run):
    """Local supplier volume."""
    region = CpuFilter(col("r_name") == lit("ASIA"), t["region"])
    orders = CpuFilter(
        (col("o_orderdate") >= dlit("1994-01-01")) &
        (col("o_orderdate") < dlit("1995-01-01")), t["orders"])
    joined = _join(
        J.INNER,
        _join(J.INNER,
              _join(J.INNER,
                    _join(J.INNER, t["customer"], orders,
                          ["c_custkey"], ["o_custkey"]),
                    t["lineitem"], ["o_orderkey"], ["l_orderkey"]),
              t["supplier"], ["l_suppkey", "c_nationkey"],
              ["s_suppkey", "s_nationkey"]),
        _join(J.INNER, t["nation"], region,
              ["n_regionkey"], ["r_regionkey"]),
        ["s_nationkey"], ["n_nationkey"])
    agg = CpuAggregate(
        [col("n_name")],
        [Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             ).alias("revenue")], joined)
    return CpuSort([desc(col("revenue"))], agg)


def q6(t, run):
    """Forecast revenue change."""
    li = CpuFilter(
        (col("l_shipdate") >= dlit("1994-01-01")) &
        (col("l_shipdate") < dlit("1995-01-01")) &
        (col("l_discount") >= lit(0.05)) &
        (col("l_discount") <= lit(0.07)) &
        (col("l_quantity") < lit(24.0)), t["lineitem"])
    return CpuAggregate(
        [], [Sum(col("l_extendedprice") * col("l_discount"))
             .alias("revenue")], li)


def _year_of(day_col):
    """year(DATE32) without a calendar op on the agg path: push the date
    through the Year expression (cpu+tpu both implement it)."""
    from spark_rapids_tpu.exprs.datetime_exprs import Year
    return Year(day_col)


def q7(t, run):
    """Volume shipping between FRANCE and GERMANY."""
    n1 = _rename(t["nation"], {"n_nationkey": "n1_key",
                               "n_name": "supp_nation"})
    n2 = _rename(t["nation"], {"n_nationkey": "n2_key",
                               "n_name": "cust_nation"})
    li = CpuFilter(
        (col("l_shipdate") >= dlit("1995-01-01")) &
        (col("l_shipdate") <= dlit("1996-12-31")), t["lineitem"])
    joined = _join(
        J.INNER,
        _join(J.INNER,
              _join(J.INNER,
                    _join(J.INNER,
                          _join(J.INNER, t["supplier"], li,
                                ["s_suppkey"], ["l_suppkey"]),
                          t["orders"], ["l_orderkey"], ["o_orderkey"]),
                    t["customer"], ["o_custkey"], ["c_custkey"]),
              n1, ["s_nationkey"], ["n1_key"]),
        n2, ["c_nationkey"], ["n2_key"])
    joined = CpuFilter(
        ((col("supp_nation") == lit("FRANCE")) &
         (col("cust_nation") == lit("GERMANY"))) |
        ((col("supp_nation") == lit("GERMANY")) &
         (col("cust_nation") == lit("FRANCE"))), joined)
    proj = CpuProject(
        [col("supp_nation"), col("cust_nation"),
         _year_of(col("l_shipdate")).alias("l_year"),
         (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
          ).alias("volume")], joined)
    agg = CpuAggregate(
        [col("supp_nation"), col("cust_nation"), col("l_year")],
        [Sum(col("volume")).alias("revenue")], proj)
    return CpuSort([asc(col("supp_nation")), asc(col("cust_nation")),
                    asc(col("l_year"))], agg)


def q8(t, run):
    """National market share of BRAZIL in AMERICA."""
    n1 = _rename(t["nation"], {"n_nationkey": "n1_key",
                               "n_regionkey": "n1_region"})
    n2 = _rename(t["nation"], {"n_nationkey": "n2_key",
                               "n_name": "nation_name"})
    part = CpuFilter(col("p_type") == lit("ECONOMY ANODIZED STEEL"),
                     t["part"])
    orders = CpuFilter(
        (col("o_orderdate") >= dlit("1995-01-01")) &
        (col("o_orderdate") <= dlit("1996-12-31")), t["orders"])
    region = CpuFilter(col("r_name") == lit("AMERICA"), t["region"])
    joined = _join(
        J.INNER,
        _join(J.INNER,
              _join(J.INNER,
                    _join(J.INNER,
                          _join(J.INNER,
                                _join(J.INNER, part, t["lineitem"],
                                      ["p_partkey"], ["l_partkey"]),
                                t["supplier"], ["l_suppkey"],
                                ["s_suppkey"]),
                          orders, ["l_orderkey"], ["o_orderkey"]),
                    t["customer"], ["o_custkey"], ["c_custkey"]),
              _join(J.INNER, n1, region, ["n1_region"], ["r_regionkey"]),
              ["c_nationkey"], ["n1_key"]),
        n2, ["s_nationkey"], ["n2_key"])
    proj = CpuProject(
        [_year_of(col("o_orderdate")).alias("o_year"),
         (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
          ).alias("volume"),
         col("nation_name")], joined)
    brazil_vol = CaseWhen(
        (((col("nation_name") == lit("BRAZIL")), col("volume")),),
        lit(0.0))
    agg = CpuAggregate(
        [col("o_year")],
        [Sum(brazil_vol).alias("brazil"), Sum(col("volume")).alias("all")],
        proj)
    share = CpuProject(
        [col("o_year"), (col("brazil") / col("all")).alias("mkt_share")],
        agg)
    return CpuSort([asc(col("o_year"))], share)


def q9(t, run):
    """Product type profit measure."""
    part = CpuFilter(Contains(col("p_name"), lit("green")), t["part"])
    joined = _join(
        J.INNER,
        _join(J.INNER,
              _join(J.INNER,
                    _join(J.INNER,
                          _join(J.INNER, part, t["lineitem"],
                                ["p_partkey"], ["l_partkey"]),
                          t["supplier"], ["l_suppkey"], ["s_suppkey"]),
                    t["partsupp"], ["l_suppkey", "l_partkey"],
                    ["ps_suppkey", "ps_partkey"]),
              t["orders"], ["l_orderkey"], ["o_orderkey"]),
        t["nation"], ["s_nationkey"], ["n_nationkey"])
    proj = CpuProject(
        [col("n_name").alias("nation"),
         _year_of(col("o_orderdate")).alias("o_year"),
         (col("l_extendedprice") * (lit(1.0) - col("l_discount")) -
          col("ps_supplycost") * col("l_quantity")).alias("amount")],
        joined)
    agg = CpuAggregate([col("nation"), col("o_year")],
                       [Sum(col("amount")).alias("sum_profit")], proj)
    return CpuSort([asc(col("nation")), desc(col("o_year"))], agg)


def q10(t, run):
    """Returned item reporting."""
    orders = CpuFilter(
        (col("o_orderdate") >= dlit("1993-10-01")) &
        (col("o_orderdate") < dlit("1994-01-01")), t["orders"])
    li = CpuFilter(col("l_returnflag") == lit("R"), t["lineitem"])
    joined = _join(
        J.INNER,
        _join(J.INNER,
              _join(J.INNER, t["customer"], orders,
                    ["c_custkey"], ["o_custkey"]),
              li, ["o_orderkey"], ["l_orderkey"]),
        t["nation"], ["c_nationkey"], ["n_nationkey"])
    agg = CpuAggregate(
        [col("c_custkey"), col("c_name"), col("c_acctbal"),
         col("c_phone"), col("n_name"), col("c_address"),
         col("c_comment")],
        [Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             ).alias("revenue")], joined)
    return CpuLimit(20, CpuSort([desc(col("revenue")),
                                 asc(col("c_custkey"))], agg))


def q11(t, run):
    """Important stock identification (HAVING scalar via run())."""
    de = CpuFilter(col("n_name") == lit("GERMANY"), t["nation"])
    base = _join(J.INNER,
                 _join(J.INNER, t["partsupp"], t["supplier"],
                       ["ps_suppkey"], ["s_suppkey"]),
                 de, ["s_nationkey"], ["n_nationkey"])
    value = col("ps_supplycost") * col("ps_availqty")
    total = run(CpuAggregate([], [Sum(value).alias("total")], base))
    v = total["total"].iloc[0]
    threshold = 0.0 if v is None or v != v else float(v) * 0.0001
    agg = CpuAggregate([col("ps_partkey")],
                       [Sum(value).alias("value")], base)
    return CpuSort([desc(col("value"))],
                   CpuFilter(col("value") > lit(threshold), agg))


def q12(t, run):
    """Shipping modes and order priority."""
    li = CpuFilter(
        InSet(col("l_shipmode"), ("MAIL", "SHIP")) &
        (col("l_commitdate") < col("l_receiptdate")) &
        (col("l_shipdate") < col("l_commitdate")) &
        (col("l_receiptdate") >= dlit("1994-01-01")) &
        (col("l_receiptdate") < dlit("1995-01-01")), t["lineitem"])
    joined = _join(J.INNER, t["orders"], li,
                   ["o_orderkey"], ["l_orderkey"])
    urgent = InSet(col("o_orderpriority"), ("1-URGENT", "2-HIGH"))
    agg = CpuAggregate(
        [col("l_shipmode")],
        [Sum(CaseWhen(((urgent, lit(1)),), lit(0))).alias("high_line"),
         Sum(CaseWhen(((urgent, lit(0)),), lit(1))).alias("low_line")],
        joined)
    return CpuSort([asc(col("l_shipmode"))], agg)


def q13(t, run):
    """Customer distribution (left outer join + double aggregate)."""
    orders = CpuFilter(
        Not(Like(col("o_comment"), lit("%special%requests%"))), t["orders"])
    joined = _join(J.LEFT_OUTER, t["customer"], orders,
                   ["c_custkey"], ["o_custkey"])
    per_cust = CpuAggregate([col("c_custkey")],
                            [Count(col("o_orderkey")).alias("c_count")],
                            _cols(joined, "c_custkey", "o_orderkey"))
    dist = CpuAggregate([col("c_count")],
                        [Count(None).alias("custdist")], per_cust)
    return CpuSort([desc(col("custdist")), desc(col("c_count"))], dist)


def q14(t, run):
    """Promotion effect."""
    li = CpuFilter(
        (col("l_shipdate") >= dlit("1995-09-01")) &
        (col("l_shipdate") < dlit("1995-10-01")), t["lineitem"])
    joined = _join(J.INNER, li, t["part"], ["l_partkey"], ["p_partkey"])
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    promo = CaseWhen(
        ((StartsWith(col("p_type"), lit("PROMO")), rev),), lit(0.0))
    agg = CpuAggregate(
        [], [Sum(promo).alias("promo"), Sum(rev).alias("total")], joined)
    return CpuProject(
        [(lit(100.0) * col("promo") / col("total"))
         .alias("promo_revenue")], agg)


def _q15_revenue(t):
    li = CpuFilter(
        (col("l_shipdate") >= dlit("1996-01-01")) &
        (col("l_shipdate") < dlit("1996-04-01")), t["lineitem"])
    return CpuAggregate(
        [col("l_suppkey")],
        [Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             ).alias("total_revenue")], li)


def q15(t, run):
    """Top supplier (max over a revenue view via run())."""
    revenue = _q15_revenue(t)
    max_rev = float(run(CpuAggregate(
        [], [Max(col("total_revenue")).alias("m")],
        _q15_revenue(t)))["m"].iloc[0])
    top = CpuFilter(col("total_revenue") >= lit(max_rev), revenue)
    joined = _join(J.INNER, t["supplier"], top,
                   ["s_suppkey"], ["l_suppkey"])
    out = CpuProject([col("s_suppkey"), col("s_name"), col("s_address"),
                      col("s_phone"), col("total_revenue")], joined)
    return CpuSort([asc(col("s_suppkey"))], out)


def q16(t, run):
    """Parts/supplier relationship (NOT IN -> anti join; count distinct
    via two-level aggregate)."""
    bad_supp = CpuFilter(
        Like(col("s_comment"), lit("%Customer%Complaints%")),
        t["supplier"])
    ps = _join(J.LEFT_ANTI, t["partsupp"], bad_supp,
               ["ps_suppkey"], ["s_suppkey"])
    part = CpuFilter(
        (col("p_brand") != lit("Brand#45")) &
        Not(Like(col("p_type"), lit("MEDIUM POLISHED%"))) &
        InSet(col("p_size"), (49, 14, 23, 45, 19, 3, 36, 9)), t["part"])
    joined = _join(J.INNER, part, ps, ["p_partkey"], ["ps_partkey"])
    distinct = CpuAggregate(
        [col("p_brand"), col("p_type"), col("p_size"),
         col("ps_suppkey")], [Count(None).alias("_dup")], joined)
    agg = CpuAggregate(
        [col("p_brand"), col("p_type"), col("p_size")],
        [Count(col("ps_suppkey")).alias("supplier_cnt")], distinct)
    return CpuSort([desc(col("supplier_cnt")), asc(col("p_brand")),
                    asc(col("p_type")), asc(col("p_size"))], agg)


def q17(t, run):
    """Small-quantity-order revenue (correlated avg via agg-join)."""
    part = CpuFilter(
        (col("p_brand") == lit("Brand#23")) &
        (col("p_container") == lit("MED BOX")), t["part"])
    li_part = _join(J.INNER, t["lineitem"], part,
                    ["l_partkey"], ["p_partkey"])
    avg_qty = CpuAggregate(
        [col("ap_key")],
        [Average(col("l_quantity")).alias("avg_qty")],
        CpuProject([col("l_partkey").alias("ap_key"),
                    col("l_quantity")],
                   _join(J.INNER, t["lineitem"], part,
                         ["l_partkey"], ["p_partkey"])))
    joined = _join(J.INNER, li_part, avg_qty, ["l_partkey"], ["ap_key"],
                   condition=(col("l_quantity") <
                              lit(0.2) * col("avg_qty")))
    agg = CpuAggregate(
        [], [Sum(col("l_extendedprice")).alias("s")], joined)
    return CpuProject([(col("s") / lit(7.0)).alias("avg_yearly")], agg)


def q18(t, run):
    """Large volume customers.  Threshold lowered 300 -> 150 so the
    synthetic ~4-lines-per-order data produces qualifying orders."""
    big = CpuFilter(
        col("sum_qty") > lit(150.0),
        CpuAggregate([col("big_key")],
                     [Sum(col("l_quantity")).alias("sum_qty")],
                     CpuProject([col("l_orderkey").alias("big_key"),
                                 col("l_quantity")], t["lineitem"])))
    orders = _join(J.LEFT_SEMI, t["orders"], big,
                   ["o_orderkey"], ["big_key"])
    joined = _join(J.INNER,
                   _join(J.INNER, t["customer"], orders,
                         ["c_custkey"], ["o_custkey"]),
                   t["lineitem"], ["o_orderkey"], ["l_orderkey"])
    agg = CpuAggregate(
        [col("c_name"), col("c_custkey"), col("o_orderkey"),
         col("o_orderdate"), col("o_totalprice")],
        [Sum(col("l_quantity")).alias("sum_qty")], joined)
    return CpuLimit(100, CpuSort(
        [desc(col("o_totalprice")), asc(col("o_orderdate")),
         asc(col("o_orderkey"))], agg))


def q19(t, run):
    """Discounted revenue: OR of three brand/container/quantity brackets."""
    joined = _join(J.INNER, t["lineitem"], t["part"],
                   ["l_partkey"], ["p_partkey"])
    sm = (col("p_brand") == lit("Brand#12")) & \
        InSet(col("p_container"), ("SM CASE", "SM BOX", "SM PACK",
                                   "SM PKG")) & \
        (col("l_quantity") >= lit(1.0)) & \
        (col("l_quantity") <= lit(11.0)) & (col("p_size") <= lit(5))
    med = (col("p_brand") == lit("Brand#23")) & \
        InSet(col("p_container"), ("MED BAG", "MED BOX", "MED PKG",
                                   "MED PACK")) & \
        (col("l_quantity") >= lit(10.0)) & \
        (col("l_quantity") <= lit(20.0)) & (col("p_size") <= lit(10))
    lg = (col("p_brand") == lit("Brand#34")) & \
        InSet(col("p_container"), ("LG CASE", "LG BOX", "LG PACK",
                                   "LG PKG")) & \
        (col("l_quantity") >= lit(20.0)) & \
        (col("l_quantity") <= lit(30.0)) & (col("p_size") <= lit(15))
    common = (col("p_size") >= lit(1)) & \
        InSet(col("l_shipmode"), ("AIR", "REG AIR")) & \
        (col("l_shipinstruct") == lit("DELIVER IN PERSON"))
    filt = CpuFilter(common & (sm | med | lg), joined)
    return CpuAggregate(
        [], [Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))
                 ).alias("revenue")], filt)


def q20(t, run):
    """Potential part promotion (nested IN -> semi joins + agg-join)."""
    forest = CpuFilter(StartsWith(col("p_name"), lit("forest")),
                       t["part"])
    shipped = CpuAggregate(
        [col("sk_part"), col("sk_supp")],
        [Sum(col("l_quantity")).alias("qty")],
        CpuProject([col("l_partkey").alias("sk_part"),
                    col("l_suppkey").alias("sk_supp"),
                    col("l_quantity")],
                   CpuFilter(
                       (col("l_shipdate") >= dlit("1994-01-01")) &
                       (col("l_shipdate") < dlit("1995-01-01")),
                       t["lineitem"])))
    ps = _join(J.LEFT_SEMI, t["partsupp"], forest,
               ["ps_partkey"], ["p_partkey"])
    qualified = CpuFilter(
        col("ps_availqty").cast(T.FLOAT64) > lit(0.5) * col("qty"),
        _join(J.INNER, ps, shipped, ["ps_partkey", "ps_suppkey"],
              ["sk_part", "sk_supp"]))
    supp = _join(J.LEFT_SEMI, t["supplier"], qualified,
                 ["s_suppkey"], ["ps_suppkey"])
    canada = CpuFilter(col("n_name") == lit("CANADA"), t["nation"])
    out = _join(J.INNER, supp, canada, ["s_nationkey"], ["n_nationkey"])
    return CpuSort([asc(col("s_name"))],
                   _cols(out, "s_name", "s_address"))


def q21(t, run):
    """Suppliers who kept orders waiting (EXISTS/NOT EXISTS with
    inequality -> semi/anti joins with conditions)."""
    sa = CpuFilter(col("n_name") == lit("SAUDI ARABIA"), t["nation"])
    late = CpuFilter(col("l_receiptdate") > col("l_commitdate"),
                     t["lineitem"])
    f_orders = CpuFilter(col("o_orderstatus") == lit("F"), t["orders"])
    l1 = _join(J.INNER,
               _join(J.INNER,
                     _join(J.INNER, t["supplier"], sa,
                           ["s_nationkey"], ["n_nationkey"]),
                     late, ["s_suppkey"], ["l_suppkey"]),
               f_orders, ["l_orderkey"], ["o_orderkey"])
    l2 = _rename(t["lineitem"], {"l_orderkey": "l2_order",
                                 "l_suppkey": "l2_supp"})
    l3 = _rename(late, {"l_orderkey": "l3_order",
                        "l_suppkey": "l3_supp"})
    with_other = _join(J.LEFT_SEMI, l1, l2, ["l_orderkey"], ["l2_order"],
                       condition=(col("l_suppkey") != col("l2_supp")))
    no_other_late = _join(J.LEFT_ANTI, with_other, l3,
                          ["l_orderkey"], ["l3_order"],
                          condition=(col("l_suppkey") != col("l3_supp")))
    agg = CpuAggregate([col("s_name")],
                       [Count(None).alias("numwait")], no_other_late)
    return CpuLimit(100, CpuSort(
        [desc(col("numwait")), asc(col("s_name"))], agg))


def q22(t, run):
    """Global sales opportunity (scalar avg via run(), NOT EXISTS ->
    anti join)."""
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cntry = Substring(col("c_phone"), lit(1), lit(2))
    cust = CpuFilter(InSet(cntry, codes), t["customer"])
    avg_bal = float(run(CpuAggregate(
        [], [Average(col("c_acctbal")).alias("a")],
        CpuFilter(InSet(cntry, codes) & (col("c_acctbal") > lit(0.0)),
                  t["customer"])))["a"].iloc[0])
    rich = CpuFilter(col("c_acctbal") > lit(avg_bal), cust)
    no_orders = _join(J.LEFT_ANTI, rich, t["orders"],
                      ["c_custkey"], ["o_custkey"])
    proj = CpuProject(
        [Substring(col("c_phone"), lit(1), lit(2)).alias("cntrycode"),
         col("c_acctbal")], no_orders)
    agg = CpuAggregate(
        [col("cntrycode")],
        [Count(None).alias("numcust"),
         Sum(col("c_acctbal")).alias("totacctbal")], proj)
    return CpuSort([asc(col("cntrycode"))], agg)


QUERIES = {i: fn for i, fn in enumerate(
    [q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15,
     q16, q17, q18, q19, q20, q21, q22], start=1)}
