"""TPC-DS table synthetic data (reference
`integration_tests/.../tpcds/TpcdsLikeSpark.scala` table readers — the
full 24-table catalog: all three sales channels with their returns
tables, inventory, and every dimension the 103-query suite touches —
generated in-memory).

Dates use the TPC-DS surrogate-key convention (d_date_sk joins, d_year /
d_moy predicates).  Correlations the faithful query suite depends on at
test scale (all swept in round 3): zipf item popularity, December
holiday sales concentration, a three-week returns spike, county as a
function of state, stores sharing the address zip pool, weekly
inventory snapshots of the hot items, refunder == returner
demographics, and ~2% missing channel fks.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T

SCHEMAS = {
    "date_dim": T.Schema.of(
        ("d_date_sk", T.INT64), ("d_year", T.INT32),
        ("d_moy", T.INT32), ("d_dom", T.INT32),
        ("d_day_name", T.STRING), ("d_qoy", T.INT32),
        ("d_dow", T.INT32), ("d_date", T.DATE32),
        ("d_month_seq", T.INT32), ("d_week_seq", T.INT32)),
    "item": T.Schema.of(
        ("i_item_sk", T.INT64), ("i_item_id", T.STRING),
        ("i_brand_id", T.INT32), ("i_brand", T.STRING),
        ("i_category_id", T.INT32), ("i_category", T.STRING),
        ("i_manufact_id", T.INT32), ("i_manager_id", T.INT32),
        ("i_current_price", T.FLOAT64), ("i_item_desc", T.STRING),
        ("i_class_id", T.INT32),
        ("i_class", T.STRING), ("i_manufact", T.STRING),
        ("i_product_name", T.STRING), ("i_color", T.STRING),
        ("i_units", T.STRING), ("i_size", T.STRING)),
    "store": T.Schema.of(
        ("s_store_sk", T.INT64), ("s_store_id", T.STRING),
        ("s_store_name", T.STRING), ("s_number_employees", T.INT32),
        ("s_city", T.STRING), ("s_state", T.STRING),
        ("s_county", T.STRING), ("s_gmt_offset", T.FLOAT64),
        ("s_company_id", T.INT32), ("s_company_name", T.STRING),
        ("s_market_id", T.INT32),
        ("s_street_number", T.STRING),
        ("s_street_name", T.STRING), ("s_street_type", T.STRING),
        ("s_suite_number", T.STRING), ("s_zip", T.STRING)),
    "customer": T.Schema.of(
        ("c_customer_sk", T.INT64), ("c_customer_id", T.STRING),
        ("c_first_name", T.STRING), ("c_last_name", T.STRING),
        ("c_current_addr_sk", T.INT64),
        ("c_current_cdemo_sk", T.INT64),
        ("c_current_hdemo_sk", T.INT64),
        ("c_birth_day", T.INT32),
        ("c_birth_month", T.INT32), ("c_birth_year", T.INT32),
        ("c_birth_country", T.STRING),
        ("c_preferred_cust_flag", T.STRING),
        ("c_salutation", T.STRING),
        ("c_login", T.STRING), ("c_email_address", T.STRING),
        ("c_last_review_date", T.STRING),
        ("c_first_sales_date_sk", T.INT64),
        ("c_first_shipto_date_sk", T.INT64)),
    "customer_address": T.Schema.of(
        ("ca_address_sk", T.INT64), ("ca_city", T.STRING),
        ("ca_state", T.STRING), ("ca_country", T.STRING),
        ("ca_zip", T.STRING), ("ca_county", T.STRING),
        ("ca_gmt_offset", T.FLOAT64),
        ("ca_street_number", T.STRING), ("ca_street_name", T.STRING),
        ("ca_street_type", T.STRING), ("ca_suite_number", T.STRING),
        ("ca_location_type", T.STRING)),
    "household_demographics": T.Schema.of(
        ("hd_demo_sk", T.INT64), ("hd_dep_count", T.INT32),
        ("hd_vehicle_count", T.INT32), ("hd_buy_potential", T.STRING),
        ("hd_income_band_sk", T.INT64)),
    "income_band": T.Schema.of(
        ("ib_income_band_sk", T.INT64), ("ib_lower_bound", T.INT32),
        ("ib_upper_bound", T.INT32)),
    "promotion": T.Schema.of(
        ("p_promo_sk", T.INT64), ("p_channel_email", T.STRING),
        ("p_channel_event", T.STRING), ("p_channel_dmail", T.STRING),
        ("p_channel_tv", T.STRING)),
    "store_sales": T.Schema.of(
        ("ss_sold_date_sk", T.INT64), ("ss_sold_time_sk", T.INT64),
        ("ss_item_sk", T.INT64),
        ("ss_customer_sk", T.INT64), ("ss_cdemo_sk", T.INT64),
        ("ss_hdemo_sk", T.INT64), ("ss_addr_sk", T.INT64),
        ("ss_store_sk", T.INT64), ("ss_promo_sk", T.INT64),
        ("ss_ticket_number", T.INT64), ("ss_quantity", T.INT32),
        ("ss_list_price", T.FLOAT64), ("ss_sales_price", T.FLOAT64),
        ("ss_ext_sales_price", T.FLOAT64),
        ("ss_ext_discount_amt", T.FLOAT64),
        ("ss_ext_list_price", T.FLOAT64),
        ("ss_coupon_amt", T.FLOAT64), ("ss_net_profit", T.FLOAT64),
        ("ss_ext_wholesale_cost", T.FLOAT64),
        ("ss_net_paid", T.FLOAT64),
        ("ss_wholesale_cost", T.FLOAT64)),
    "time_dim": T.Schema.of(
        ("t_time_sk", T.INT64), ("t_hour", T.INT32),
        ("t_minute", T.INT32), ("t_meal_time", T.STRING),
        ("t_time", T.INT32)),
    "customer_demographics": T.Schema.of(
        ("cd_demo_sk", T.INT64), ("cd_gender", T.STRING),
        ("cd_marital_status", T.STRING),
        ("cd_education_status", T.STRING), ("cd_dep_count", T.INT32),
        ("cd_purchase_estimate", T.INT32),
        ("cd_credit_rating", T.STRING),
        ("cd_dep_employed_count", T.INT32),
        ("cd_dep_college_count", T.INT32)),
    "warehouse": T.Schema.of(
        ("w_warehouse_sk", T.INT64), ("w_warehouse_name", T.STRING),
        ("w_state", T.STRING), ("w_warehouse_sq_ft", T.INT32),
        ("w_city", T.STRING), ("w_county", T.STRING),
        ("w_country", T.STRING)),
    "catalog_sales": T.Schema.of(
        ("cs_sold_date_sk", T.INT64), ("cs_sold_time_sk", T.INT64),
        ("cs_ship_date_sk", T.INT64),
        ("cs_bill_customer_sk", T.INT64), ("cs_bill_cdemo_sk", T.INT64),
        ("cs_item_sk", T.INT64), ("cs_order_number", T.INT64),
        ("cs_warehouse_sk", T.INT64), ("cs_promo_sk", T.INT64),
        ("cs_quantity", T.INT32), ("cs_list_price", T.FLOAT64),
        ("cs_sales_price", T.FLOAT64),
        ("cs_ext_sales_price", T.FLOAT64),
        ("cs_ext_discount_amt", T.FLOAT64),
        ("cs_ext_list_price", T.FLOAT64),
        ("cs_ext_ship_cost", T.FLOAT64), ("cs_net_profit", T.FLOAT64),
        ("cs_net_paid", T.FLOAT64),
        ("cs_ship_addr_sk", T.INT64), ("cs_bill_addr_sk", T.INT64),
        ("cs_ship_customer_sk", T.INT64),
        ("cs_call_center_sk", T.INT64),
        ("cs_ship_mode_sk", T.INT64), ("cs_coupon_amt", T.FLOAT64),
        ("cs_wholesale_cost", T.FLOAT64),
        ("cs_catalog_page_sk", T.INT64),
        ("cs_bill_hdemo_sk", T.INT64)),
    "web_sales": T.Schema.of(
        ("ws_sold_date_sk", T.INT64), ("ws_sold_time_sk", T.INT64),
        ("ws_ship_date_sk", T.INT64),
        ("ws_bill_customer_sk", T.INT64),
        ("ws_ship_customer_sk", T.INT64), ("ws_item_sk", T.INT64),
        ("ws_order_number", T.INT64), ("ws_warehouse_sk", T.INT64),
        ("ws_web_site_sk", T.INT64), ("ws_promo_sk", T.INT64),
        ("ws_quantity", T.INT32), ("ws_list_price", T.FLOAT64),
        ("ws_sales_price", T.FLOAT64),
        ("ws_ext_sales_price", T.FLOAT64),
        ("ws_ext_discount_amt", T.FLOAT64),
        ("ws_ext_list_price", T.FLOAT64),
        ("ws_ext_ship_cost", T.FLOAT64), ("ws_net_profit", T.FLOAT64),
        ("ws_net_paid", T.FLOAT64), ("ws_wholesale_cost", T.FLOAT64),
        ("ws_ship_addr_sk", T.INT64), ("ws_bill_addr_sk", T.INT64),
        ("ws_ship_hdemo_sk", T.INT64), ("ws_web_page_sk", T.INT64),
        ("ws_ship_mode_sk", T.INT64)),
    "store_returns": T.Schema.of(
        ("sr_returned_date_sk", T.INT64), ("sr_item_sk", T.INT64),
        ("sr_customer_sk", T.INT64), ("sr_ticket_number", T.INT64),
        ("sr_store_sk", T.INT64), ("sr_return_quantity", T.INT32),
        ("sr_return_amt", T.FLOAT64), ("sr_net_loss", T.FLOAT64),
        ("sr_reason_sk", T.INT64), ("sr_cdemo_sk", T.INT64)),
    "catalog_returns": T.Schema.of(
        ("cr_returned_date_sk", T.INT64), ("cr_item_sk", T.INT64),
        ("cr_order_number", T.INT64),
        ("cr_returning_customer_sk", T.INT64),
        ("cr_returning_addr_sk", T.INT64),
        ("cr_return_quantity", T.INT32),
        ("cr_return_amount", T.FLOAT64),
        ("cr_return_amt_inc_tax", T.FLOAT64),
        ("cr_refunded_cash", T.FLOAT64),
        ("cr_reversed_charge", T.FLOAT64),
        ("cr_store_credit", T.FLOAT64),
        ("cr_call_center_sk", T.INT64),
        ("cr_net_loss", T.FLOAT64),
        ("cr_catalog_page_sk", T.INT64)),
    "web_returns": T.Schema.of(
        ("wr_returned_date_sk", T.INT64), ("wr_item_sk", T.INT64),
        ("wr_order_number", T.INT64),
        ("wr_returning_customer_sk", T.INT64),
        ("wr_returning_addr_sk", T.INT64),
        ("wr_refunded_cdemo_sk", T.INT64),
        ("wr_returning_cdemo_sk", T.INT64),
        ("wr_refunded_addr_sk", T.INT64),
        ("wr_reason_sk", T.INT64), ("wr_fee", T.FLOAT64),
        ("wr_refunded_cash", T.FLOAT64),
        ("wr_net_loss", T.FLOAT64), ("wr_web_page_sk", T.INT64),
        ("wr_return_quantity", T.INT32), ("wr_return_amt", T.FLOAT64)),
    "inventory": T.Schema.of(
        ("inv_date_sk", T.INT64), ("inv_item_sk", T.INT64),
        ("inv_warehouse_sk", T.INT64),
        ("inv_quantity_on_hand", T.INT32)),
    "call_center": T.Schema.of(
        ("cc_call_center_sk", T.INT64), ("cc_call_center_id", T.STRING),
        ("cc_name", T.STRING), ("cc_county", T.STRING),
        ("cc_manager", T.STRING)),
    "ship_mode": T.Schema.of(
        ("sm_ship_mode_sk", T.INT64), ("sm_type", T.STRING),
        ("sm_carrier", T.STRING)),
    "web_site": T.Schema.of(
        ("web_site_sk", T.INT64), ("web_site_id", T.STRING),
        ("web_name", T.STRING), ("web_company_name", T.STRING)),
    "catalog_page": T.Schema.of(
        ("cp_catalog_page_sk", T.INT64),
        ("cp_catalog_page_id", T.STRING)),
    "web_page": T.Schema.of(
        ("wp_web_page_sk", T.INT64), ("wp_char_count", T.INT32)),
    "reason": T.Schema.of(
        ("r_reason_sk", T.INT64), ("r_reason_desc", T.STRING)),
}

COUNTIES = ["Williamson County", "Ziebach County", "Walker County",
            "Barrow County", "Daviess County"]

CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
              "Shoes", "Sports", "Women"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA"]
CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]


def _q4(x):
    """Quantize money values to quarters (exact dyadic f64).  TPC-DS
    money columns are DECIMAL(7,2) in the reference, whose sums are
    exact; modeled as f64, cent-quantized values accumulate
    summation-order ulp drift, which silently splits float-sum ties in
    rank windows (q67/q70) between the engine's partial/merge order and
    the golden's sequential order.  Quarter-quantized values make every
    sum EXACT in f64 at test scale, restoring decimal-like
    order-independence."""
    return np.round(np.asarray(x) * 4.0) / 4.0


def _money(rng, lo, hi, n):
    return _q4(rng.uniform(lo, hi, n))


def _holiday_respike(rng, sold: np.ndarray, n_dates: int
                     ) -> np.ndarray:
    """Move ~10% of sales into December days (holiday concentration):
    same-week-across-years comparisons (q14b) need repeatable weekly
    mass, which uniform dates never give at test scale."""
    m = rng.random(len(sold)) < 0.10
    years = rng.integers(0, n_dates // 365, int(m.sum()))
    dec = years * 365 + rng.integers(341, 365, int(m.sum()))
    out = sold.copy()
    out[m] = dec
    return out


def _item_popularity(n_items: int) -> np.ndarray:
    """Zipf-ish sales popularity over items: a few hot items appear in
    every channel every week, which cross-channel per-item queries
    (q14/q23/q58) require for support at test scale."""
    w = 1.0 / (np.arange(n_items) + 3.0) ** 1.2
    return w / w.sum()


def gen_tables(rng: np.random.Generator, scale: int = 10_000
               ) -> dict[str, pd.DataFrame]:
    """`scale` ~ store_sales rows; dimensions scale down dbgen-style."""
    n_dates = 365 * 5  # 1998-2002
    n_items = max(scale // 20, 50)
    n_stores = max(scale // 2000, 4)
    n_cust = max(scale // 10, 100)
    n_addr = n_cust
    n_hd = 60
    n_promo = max(scale // 500, 10)

    sk = np.arange(n_dates, dtype=np.int64)
    date_dim = pd.DataFrame({
        "d_date_sk": sk,
        "d_year": (1998 + sk // 365).astype(np.int32),
        "d_moy": ((sk % 365) // 31 + 1).clip(1, 12).astype(np.int32),
        # day-of-month aligned with the 31-day moy blocks, so any
        # (year, moy, dom) triple exists every year
        "d_dom": (((sk % 365) % 31) + 1).astype(np.int32),
        "d_day_name": np.array(DAY_NAMES, dtype=object)[sk % 7],
        "d_qoy": (((sk % 365) // 92) + 1).clip(1, 4).astype(np.int32),
        "d_dow": (sk % 7).astype(np.int32),
        # days since unix epoch: 1998-01-01 is day 10227
        "d_date": (sk + 10227).astype(np.int32),
        "d_month_seq": ((sk // 365) * 12 +
                        ((sk % 365) // 31).clip(0, 11)).astype(np.int32),
        "d_week_seq": (sk // 7).astype(np.int32),
    })
    item = pd.DataFrame({
        "i_item_sk": np.arange(n_items, dtype=np.int64),
        "i_item_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_items)], dtype=object),
        "i_brand_id": rng.integers(1, 10, n_items).astype(np.int32),
        "i_brand": np.array(
            [f"brand#{rng.integers(1, 10)}" for _ in range(n_items)],
            dtype=object),
        "i_category_id": rng.integers(0, len(CATEGORIES),
                                      n_items).astype(np.int32),
        "i_category": np.array(CATEGORIES, dtype=object)[
            rng.integers(0, len(CATEGORIES), n_items)],
        # manufacturer cycles deterministically like manager (below)
        "i_manufact_id": ((np.arange(n_items) % 100) + 1
                          ).astype(np.int32),
        # manager cycles deterministically so every manager id owns a
        # slice of the zipf-hot head items (q19/q55/q71 filter on one)
        "i_manager_id": ((np.arange(n_items) % 40) + 1
                         ).astype(np.int32),
        # prices sweep the range deterministically so every price band
        # contains hot items (q37/q40/q64 band filters)
        "i_current_price": _q4(
            (np.arange(n_items) * 7.3) % 99 + 1.0 +
            rng.uniform(0, 0.99, n_items)),
        "i_item_desc": np.array(
            [f"Item description {i % 251}" for i in range(n_items)],
            dtype=object),
        "i_class_id": rng.integers(1, 17, n_items).astype(np.int32),
        "i_class": np.array(
            [f"class{i % 16:02d}" for i in
             rng.integers(0, 16, n_items)], dtype=object),
        "i_manufact": np.array(
            [f"manufact#{i}" for i in
             rng.integers(1, 100, n_items)], dtype=object),
        "i_product_name": np.array(
            [f"product{i:06d}" for i in range(n_items)], dtype=object),
        "i_color": np.array(
            ["floral", "deep", "light", "cornflower", "midnight",
             "snow", "powder", "khaki"], dtype=object)[
            rng.integers(0, 8, n_items)],
        "i_units": np.array(
            ["N/A", "Dozen", "Box", "Pound", "Ounce", "Oz"],
            dtype=object)[rng.integers(0, 6, n_items)],
        "i_size": np.array(
            ["petite", "large", "medium", "extra large", "small"],
            dtype=object)[rng.integers(0, 5, n_items)],
    })
    store = pd.DataFrame({
        "s_store_sk": np.arange(n_stores, dtype=np.int64),
        "s_store_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_stores)], dtype=object),
        "s_store_name": np.array(
            ["ese", "ought", "able", "pri", "bar", "anti"][:n_stores]
            * (n_stores // 6 + 1), dtype=object)[:n_stores],
        "s_number_employees": rng.integers(200, 301,
                                           n_stores).astype(np.int32),
        "s_city": np.array(CITIES, dtype=object)[
            rng.integers(0, len(CITIES), n_stores)],
        "s_state": np.array(STATES, dtype=object)[
            (_s_state_idx := rng.integers(0, len(STATES), n_stores))],
        "s_county": np.array(COUNTIES, dtype=object)[
            _s_state_idx % len(COUNTIES)],
        "s_gmt_offset": np.array([-5.0, -6.0, -7.0, -8.0])[
            np.arange(n_stores) % 4],
        "s_company_id": np.ones(n_stores, np.int32),
        "s_company_name": np.array(["Unknown"] * n_stores,
                                   dtype=object),
        "s_market_id": np.where(np.arange(n_stores) % 2 == 0, 8,
                                5).astype(np.int32),
        "s_street_number": np.array(
            [str(100 + i) for i in range(n_stores)], dtype=object),
        "s_street_name": np.array(
            ["Main", "Oak", "Park", "First", "Elm"], dtype=object)[
            np.arange(n_stores) % 5],
        "s_street_type": np.array(
            ["St", "Ave", "Blvd", "Rd", "Ln"], dtype=object)[
            np.arange(n_stores) % 5],
        "s_suite_number": np.array(
            [f"Suite {i * 10}" for i in range(n_stores)], dtype=object),
        # stores share the customer-address zip pool so zip-prefix
        # correlations (q8) have matches at small scale
        "s_zip": np.array(
            [f"{z:05d}" for z in
             rng.choice([85669, 86197, 88274, 83405, 86475, 85392,
                         85460, 80348, 81792, 10144, 60332, 47311],
                        n_stores)], dtype=object),
    })
    customer = pd.DataFrame({
        "c_customer_sk": np.arange(n_cust, dtype=np.int64),
        "c_customer_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_cust)], dtype=object),
        "c_first_name": np.array(
            [f"First{i % 97}" for i in range(n_cust)], dtype=object),
        "c_last_name": np.array(
            [f"Last{i % 89}" for i in range(n_cust)], dtype=object),
        "c_current_addr_sk": rng.integers(0, n_addr,
                                          n_cust).astype(np.int64),
        "c_current_cdemo_sk": rng.integers(0, 1000,
                                           n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(0, 60,
                                           n_cust).astype(np.int64),
        "c_birth_day": rng.integers(1, 29, n_cust).astype(np.int32),
        "c_birth_month": rng.integers(1, 13, n_cust).astype(np.int32),
        "c_birth_year": rng.integers(1924, 1993,
                                     n_cust).astype(np.int32),
        "c_birth_country": np.array(
            ["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"],
            dtype=object)[rng.integers(0, 5, n_cust)],
        "c_preferred_cust_flag": np.array(["N", "Y"], dtype=object)[
            rng.integers(0, 2, n_cust)],
        "c_salutation": np.array(
            ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"], dtype=object)[
            rng.integers(0, 5, n_cust)],
        "c_login": np.array(
            [f"login{i}" for i in range(n_cust)], dtype=object),
        "c_email_address": np.array(
            [f"c{i}@example.com" for i in range(n_cust)], dtype=object),
        "c_last_review_date": np.array(
            [str(2450000 + (i * 37) % 1500) for i in range(n_cust)],
            dtype=object),
        "c_first_sales_date_sk": rng.integers(
            0, n_dates, n_cust).astype(np.int64),
        "c_first_shipto_date_sk": rng.integers(
            0, n_dates, n_cust).astype(np.int64),
    })
    customer_address = pd.DataFrame({
        "ca_address_sk": np.arange(n_addr, dtype=np.int64),
        "ca_city": np.array(CITIES + ["Edgewood"], dtype=object)[
            rng.integers(0, len(CITIES) + 1, n_addr)],
        "ca_state": np.array(STATES, dtype=object)[
            (_ca_state_idx := rng.integers(0, len(STATES), n_addr))],
        "ca_country": np.array(["United States"] * n_addr, dtype=object),
        "ca_zip": np.array(
            [f"{z:05d}" for z in
             rng.choice([85669, 86197, 88274, 83405, 86475, 85392,
                         85460, 80348, 81792, 10144, 60332, 47311],
                        n_addr)], dtype=object),
        # county is a function of state (as in a real atlas), so
        # address<->store co-location joins (q54) have support
        "ca_county": np.array(COUNTIES, dtype=object)[
            _ca_state_idx % len(COUNTIES)],
        "ca_gmt_offset": np.array([-5.0, -6.0, -7.0, -8.0])[
            np.arange(n_addr) % 4],
        "ca_street_number": np.array(
            [str(100 + i % 900) for i in range(n_addr)], dtype=object),
        "ca_street_name": np.array(
            ["Main", "Oak", "Park", "First", "Elm"], dtype=object)[
            np.arange(n_addr) % 5],
        "ca_street_type": np.array(
            ["St", "Ave", "Blvd", "Rd", "Ln"], dtype=object)[
            np.arange(n_addr) % 5],
        "ca_suite_number": np.array(
            [f"Suite {(i * 10) % 500}" for i in range(n_addr)],
            dtype=object),
        "ca_location_type": np.array(
            ["apartment", "condo", "single family"], dtype=object)[
            np.arange(n_addr) % 3],
    })
    household_demographics = pd.DataFrame({
        "hd_demo_sk": np.arange(n_hd, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int32),
        "hd_vehicle_count": rng.integers(0, 5, n_hd).astype(np.int32),
        "hd_buy_potential": rng.choice(
            np.array(BUY_POTENTIAL, dtype=object), n_hd,
            p=[0.3, 0.15, 0.1, 0.1, 0.05, 0.3]),
        "hd_income_band_sk": rng.integers(0, 20, n_hd).astype(np.int64),
    })
    income_band = pd.DataFrame({
        "ib_income_band_sk": np.arange(20, dtype=np.int64),
        "ib_lower_bound": (np.arange(20) * 10_000).astype(np.int32),
        "ib_upper_bound": ((np.arange(20) + 1) * 10_000).astype(np.int32),
    })
    promotion = pd.DataFrame({
        "p_promo_sk": np.arange(n_promo, dtype=np.int64),
        "p_channel_email": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.12).astype(int)],
        "p_channel_event": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.12).astype(int)],
        "p_channel_dmail": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.5).astype(int)],
        "p_channel_tv": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.5).astype(int)],
    })
    n_times = 24 * 12  # 5-minute buckets
    n_cdemo = 1000
    n_wh = 5
    n = scale
    item_pop = _item_popularity(n_items)
    # a ticket (basket) belongs to exactly one customer, several items —
    # the invariant q68/q73's per-ticket aggregates group on
    tickets = rng.integers(0, max(n // 6, 1), n).astype(np.int64)
    ticket_cust = ((tickets * 7919) % n_cust).astype(np.int64)
    qty = rng.integers(1, 101, n).astype(np.int32)
    list_price = _money(rng, 1.0, 200.0, n)
    sales_price = _q4(list_price * rng.uniform(0.2, 1.0, n))
    store_sales = pd.DataFrame({
        "ss_sold_date_sk": _holiday_respike(
            rng, rng.integers(0, n_dates, n), n_dates
        ).astype(np.int64),
        "ss_sold_time_sk": rng.integers(0, n_times, n).astype(np.int64),
        "ss_item_sk": rng.choice(n_items, n,
                                 p=item_pop).astype(np.int64),
        "ss_customer_sk": ticket_cust,
        "ss_cdemo_sk": rng.integers(0, n_cdemo, n).astype(np.int64),
        "ss_hdemo_sk": rng.integers(0, n_hd, n).astype(np.int64),
        "ss_addr_sk": rng.integers(0, n_addr, n).astype(np.int64),
        "ss_store_sk": rng.integers(0, n_stores, n).astype(np.int64),
        "ss_promo_sk": rng.integers(0, n_promo, n).astype(np.int64),
        "ss_ticket_number": tickets,
        "ss_quantity": qty,
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_sales_price": _q4(sales_price * qty),
        "ss_ext_discount_amt": _money(rng, 0.0, 100.0, n),
        "ss_ext_list_price": _q4(list_price * qty),
        "ss_coupon_amt": np.where(rng.random(n) < 0.2,
                                  _money(rng, 0.0, 50.0, n), 0.0),
        "ss_net_profit": _money(rng, -500.0, 500.0, n),
        "ss_ext_wholesale_cost": _money(rng, 1.0, 100.0, n),
        "ss_net_paid": _q4(sales_price * qty),
        "ss_wholesale_cost": _money(rng, 1.0, 100.0, n),
    })

    t_hours = (np.arange(n_times) // 12).astype(np.int32)
    time_dim = pd.DataFrame({
        "t_time_sk": np.arange(n_times, dtype=np.int64),
        "t_hour": t_hours,
        "t_minute": ((np.arange(n_times) % 12) * 5).astype(np.int32),
        "t_meal_time": pd.array(
            np.select([(t_hours >= 6) & (t_hours <= 8),
                       (t_hours >= 11) & (t_hours <= 13),
                       (t_hours >= 17) & (t_hours <= 19)],
                      ["breakfast", "lunch", "dinner"],
                      default=None), dtype=object),
        "t_time": (np.arange(n_times) * 300).astype(np.int32),
    })
    customer_demographics = pd.DataFrame({
        "cd_demo_sk": np.arange(n_cdemo, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n_cdemo)],
        # biased toward the values the query predicates name, so
        # multi-way demographic chains (q10/q35/q85/q91) stay non-empty
        # at test scale
        "cd_marital_status": rng.choice(
            np.array(["M", "S", "D", "W", "U"], dtype=object), n_cdemo,
            p=[0.3, 0.2, 0.2, 0.2, 0.1]),
        "cd_education_status": rng.choice(
            np.array(["Primary", "Secondary", "College", "2 yr Degree",
                      "4 yr Degree", "Advanced Degree", "Unknown"],
                     dtype=object), n_cdemo,
            p=[0.05, 0.05, 0.2, 0.15, 0.15, 0.2, 0.2]),
        "cd_dep_count": rng.integers(0, 7, n_cdemo).astype(np.int32),
        "cd_purchase_estimate": (rng.integers(1, 20, n_cdemo) * 500
                                 ).astype(np.int32),
        "cd_credit_rating": np.array(
            ["Low Risk", "Good", "High Risk", "Unknown"],
            dtype=object)[rng.integers(0, 4, n_cdemo)],
        "cd_dep_employed_count": rng.integers(
            0, 7, n_cdemo).astype(np.int32),
        "cd_dep_college_count": rng.integers(
            0, 7, n_cdemo).astype(np.int32),
    })
    warehouse = pd.DataFrame({
        "w_warehouse_sk": np.arange(n_wh, dtype=np.int64),
        "w_warehouse_name": np.array(
            [f"Warehouse {i}" for i in range(n_wh)], dtype=object),
        "w_state": np.array(STATES, dtype=object)[
            np.arange(n_wh) % len(STATES)],
        "w_warehouse_sq_ft": rng.integers(
            50_000, 1_000_000, n_wh).astype(np.int32),
        "w_city": np.array(CITIES, dtype=object)[
            np.arange(n_wh) % len(CITIES)],
        "w_county": np.array(COUNTIES, dtype=object)[
            np.arange(n_wh) % len(COUNTIES)],
        "w_country": np.array(["United States"] * n_wh, dtype=object),
    })


    def _channel_sales(n_rows, order_div):
        orders = rng.integers(0, max(n_rows // order_div, 1),
                              n_rows).astype(np.int64)
        cust = ((orders * 6271) % n_cust).astype(np.int64)
        q = rng.integers(1, 101, n_rows).astype(np.int32)
        lp = _money(rng, 1.0, 250.0, n_rows)
        sp = _q4(lp * rng.uniform(0.2, 1.0, n_rows))
        sold = _holiday_respike(
            rng, rng.integers(0, n_dates, n_rows), n_dates
        ).astype(np.int64)
        return orders, cust, q, lp, sp, sold

    nc = max(n // 2, 1)
    c_orders, c_cust, c_qty, c_lp, c_sp, c_sold = _channel_sales(nc, 5)
    # half the catalog rows repeat a store (customer, item) pair so
    # cross-channel joins (q25/q29/q97 shapes) have real matches
    take = rng.random(nc) < 0.5
    src_idx = rng.integers(0, n, nc)
    cs_cust = np.where(take, ticket_cust[src_idx], c_cust)
    cs_item = np.where(
        take, store_sales["ss_item_sk"].to_numpy()[src_idx],
        rng.choice(n_items, nc, p=item_pop)).astype(np.int64)
    catalog_sales = pd.DataFrame({
        "cs_sold_date_sk": c_sold,
        "cs_sold_time_sk": rng.integers(0, n_times, nc).astype(np.int64),
        # shipping lag 1..120 days (q62/q99-style bucketing)
        "cs_ship_date_sk": np.minimum(
            c_sold + rng.integers(1, 121, nc), n_dates - 1
        ).astype(np.int64),
        "cs_bill_customer_sk": cs_cust,
        "cs_bill_cdemo_sk": rng.integers(0, n_cdemo, nc).astype(np.int64),
        "cs_item_sk": cs_item,
        "cs_order_number": c_orders,
        "cs_warehouse_sk": rng.integers(0, n_wh, nc).astype(np.int64),
        "cs_promo_sk": rng.integers(0, n_promo, nc).astype(np.int64),
        "cs_quantity": c_qty,
        "cs_list_price": c_lp,
        "cs_sales_price": c_sp,
        "cs_ext_sales_price": _q4(c_sp * c_qty),
        "cs_ext_discount_amt": _money(rng, 0.0, 100.0, nc),
        "cs_ext_list_price": _q4(c_lp * c_qty),
        "cs_ext_ship_cost": _money(rng, 0.0, 40.0, nc),
        "cs_net_profit": _money(rng, -500.0, 500.0, nc),
        "cs_net_paid": _q4(c_sp * c_qty),
        "cs_ship_addr_sk": rng.integers(0, n_addr, nc).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(0, n_addr, nc).astype(np.int64),
        "cs_ship_customer_sk": cs_cust,
        "cs_call_center_sk": rng.integers(0, 4, nc).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(0, 5, nc).astype(np.int64),
        "cs_coupon_amt": np.where(rng.random(nc) < 0.2,
                                  _money(rng, 0.0, 50.0, nc), 0.0),
        "cs_wholesale_cost": _money(rng, 1.0, 100.0, nc),
        "cs_catalog_page_sk": rng.integers(0, 20,
                                           nc).astype(np.int64),
        "cs_bill_hdemo_sk": rng.integers(0, n_hd, nc).astype(np.int64),
    })

    nw = max(n // 3, 1)
    w_orders, w_cust, w_qty, w_lp, w_sp, w_sold = _channel_sales(nw, 4)
    web_sales = pd.DataFrame({
        "ws_sold_date_sk": w_sold,
        "ws_sold_time_sk": rng.integers(0, n_times, nw).astype(np.int64),
        "ws_ship_date_sk": np.minimum(
            w_sold + rng.integers(1, 121, nw), n_dates - 1
        ).astype(np.int64),
        "ws_bill_customer_sk": w_cust,
        "ws_ship_customer_sk": w_cust,
        "ws_item_sk": rng.choice(n_items, nw,
                                 p=item_pop).astype(np.int64),
        "ws_order_number": w_orders,
        "ws_warehouse_sk": rng.integers(0, n_wh, nw).astype(np.int64),
        "ws_web_site_sk": rng.integers(0, 6, nw).astype(np.int64),
        "ws_promo_sk": rng.integers(0, n_promo, nw).astype(np.int64),
        "ws_quantity": w_qty,
        "ws_list_price": w_lp,
        "ws_sales_price": w_sp,
        "ws_ext_sales_price": _q4(w_sp * w_qty),
        "ws_ext_discount_amt": _money(rng, 0.0, 100.0, nw),
        "ws_ext_list_price": _q4(w_lp * w_qty),
        "ws_ext_ship_cost": _money(rng, 0.0, 40.0, nw),
        "ws_net_profit": _money(rng, -500.0, 500.0, nw),
        "ws_net_paid": _q4(w_sp * w_qty),
        "ws_wholesale_cost": _money(rng, 1.0, 100.0, nw),
        "ws_ship_addr_sk": rng.integers(0, n_addr, nw).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(0, n_addr, nw).astype(np.int64),
        "ws_ship_hdemo_sk": rng.integers(0, n_hd, nw).astype(np.int64),
        "ws_web_page_sk": rng.integers(0, 10, nw).astype(np.int64),
        "ws_ship_mode_sk": rng.integers(0, 5, nw).astype(np.int64),
    })

    # returns are samples of sales rows: join keys always match a sale
    ridx = rng.choice(n, size=max(n // 10, 1), replace=False)
    rq = np.minimum(rng.integers(1, 20, len(ridx)).astype(np.int32),
                    qty[ridx])
    store_returns = pd.DataFrame({
        "sr_returned_date_sk": np.minimum(
            store_sales["ss_sold_date_sk"].to_numpy()[ridx]
            + rng.integers(1, 60, len(ridx)), n_dates - 1
        ).astype(np.int64),
        "sr_item_sk": store_sales["ss_item_sk"].to_numpy()[ridx],
        "sr_customer_sk": store_sales["ss_customer_sk"].to_numpy()[ridx],
        "sr_ticket_number":
            store_sales["ss_ticket_number"].to_numpy()[ridx],
        "sr_store_sk": store_sales["ss_store_sk"].to_numpy()[ridx],
        "sr_return_quantity": rq,
        "sr_return_amt": _q4(
            store_sales["ss_sales_price"].to_numpy()[ridx] * rq),
        "sr_net_loss": _money(rng, 0.0, 200.0, len(ridx)),
        "sr_reason_sk": rng.integers(0, 10, len(ridx)).astype(np.int64),
        "sr_cdemo_sk": store_sales["ss_cdemo_sk"].to_numpy()[ridx],
    })

    call_center = pd.DataFrame({
        "cc_call_center_sk": np.arange(4, dtype=np.int64),
        "cc_call_center_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(4)], dtype=object),
        "cc_name": np.array(["NY Metro", "Mid Atlantic", "North Midwest",
                             "California"], dtype=object),
        "cc_county": np.array(COUNTIES, dtype=object)[
            rng.integers(0, len(COUNTIES), 4)],
        "cc_manager": np.array([f"Manager{i}" for i in range(4)],
                               dtype=object),
    })
    ship_mode = pd.DataFrame({
        "sm_ship_mode_sk": np.arange(5, dtype=np.int64),
        "sm_type": np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT",
                             "REGULAR", "LIBRARY"], dtype=object),
        "sm_carrier": np.array(["UPS", "FEDEX", "AIRBORNE", "USPS",
                                "DHL"], dtype=object),
    })
    web_site = pd.DataFrame({
        "web_site_sk": np.arange(6, dtype=np.int64),
        "web_site_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(6)], dtype=object),
        "web_name": np.array([f"site_{i}" for i in range(6)],
                             dtype=object),
        "web_company_name": np.array(
            ["pri", "able", "ese", "ought", "anti", "cally"],
            dtype=object),
    })
    catalog_page = pd.DataFrame({
        "cp_catalog_page_sk": np.arange(20, dtype=np.int64),
        "cp_catalog_page_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(20)], dtype=object),
    })
    web_page = pd.DataFrame({
        "wp_web_page_sk": np.arange(10, dtype=np.int64),
        "wp_char_count": rng.integers(100, 8000, 10).astype(np.int32),
    })
    reason = pd.DataFrame({
        "r_reason_sk": np.arange(10, dtype=np.int64),
        "r_reason_desc": np.array(
            [f"reason {i}" for i in range(10)], dtype=object),
    })
    cidx = rng.choice(nc, size=max(nc // 10, 1), replace=False)
    crq = np.minimum(rng.integers(1, 20, len(cidx)).astype(np.int32),
                     c_qty[cidx])
    catalog_returns = pd.DataFrame({
        "cr_returned_date_sk": np.minimum(
            c_sold[cidx] + rng.integers(1, 60, len(cidx)), n_dates - 1
        ).astype(np.int64),
        "cr_item_sk": catalog_sales["cs_item_sk"].to_numpy()[cidx],
        "cr_order_number": c_orders[cidx],
        "cr_returning_customer_sk": cs_cust[cidx],
        "cr_returning_addr_sk":
            catalog_sales["cs_bill_addr_sk"].to_numpy()[cidx],
        "cr_return_quantity": crq,
        "cr_return_amount": _q4(c_sp[cidx] * crq),
        "cr_return_amt_inc_tax": _q4(
            c_sp[cidx] * crq * 1.08),
        "cr_refunded_cash": _q4(
            c_sp[cidx] * crq * rng.uniform(0.5, 1.0, len(cidx))),
        "cr_reversed_charge": _money(rng, 0.0, 30.0, len(cidx)),
        "cr_store_credit": _money(rng, 0.0, 30.0, len(cidx)),
        "cr_call_center_sk": rng.integers(0, 4,
                                          len(cidx)).astype(np.int64),
        "cr_net_loss": _money(rng, 0.0, 200.0, len(cidx)),
        "cr_catalog_page_sk":
            catalog_sales["cs_catalog_page_sk"].to_numpy()[cidx],
    })
    widx = rng.choice(nw, size=max(nw // 6, 1), replace=False)
    wrq = np.minimum(rng.integers(1, 20, len(widx)).astype(np.int32),
                     w_qty[widx])
    web_returns = pd.DataFrame({
        "wr_returned_date_sk": np.minimum(
            w_sold[widx] + rng.integers(1, 60, len(widx)), n_dates - 1
        ).astype(np.int64),
        "wr_item_sk": web_sales["ws_item_sk"].to_numpy()[widx],
        "wr_order_number": w_orders[widx],
        "wr_returning_customer_sk": w_cust[widx],
        "wr_returning_addr_sk":
            web_sales["ws_bill_addr_sk"].to_numpy()[widx],
        "wr_refunded_cdemo_sk": (wr_cdemo := rng.integers(
            0, n_cdemo, len(widx)).astype(np.int64)),
        # the refunding customer usually IS the returning customer, so
        # matched-demographics predicates (q85) keep support
        "wr_returning_cdemo_sk": np.where(
            rng.random(len(widx)) < 0.9, wr_cdemo,
            rng.integers(0, n_cdemo, len(widx))).astype(np.int64),
        "wr_refunded_addr_sk":
            web_sales["ws_ship_addr_sk"].to_numpy()[widx],
        "wr_reason_sk": rng.integers(0, 10,
                                     len(widx)).astype(np.int64),
        "wr_fee": _money(rng, 0.0, 100.0, len(widx)),
        "wr_net_loss": _money(rng, 0.0, 200.0, len(widx)),
        "wr_web_page_sk":
            web_sales["ws_web_page_sk"].to_numpy()[widx],
        "wr_refunded_cash": _q4(
            w_sp[widx] * wrq * rng.uniform(0.5, 1.0, len(widx))),
        "wr_return_quantity": wrq,
        "wr_return_amt": _q4(w_sp[widx] * wrq),
    })

    # cluster ~30% of returns into three "returns spike" weeks (the
    # weeks of 2000-06-30 / 09-27 / 11-17, i.e. q83's selected weeks):
    # cross-channel per-item return intersections over short date
    # windows need shared mass, which independent uniform dates never
    # produce at test scale
    spike_days = np.concatenate([np.arange(7 * w, 7 * w + 7)
                                 for w in (130, 142, 150)])
    for frame, cname in ((store_returns, "sr_returned_date_sk"),
                         (catalog_returns, "cr_returned_date_sk"),
                         (web_returns, "wr_returned_date_sk")):
        m = rng.random(len(frame)) < 0.3
        frame.loc[m, cname] = rng.choice(spike_days, int(m.sum()))

    # inventory = weekly snapshots of the hot items across every
    # warehouse (the real table is a periodic full cross product, which
    # per-month dispersion stats like q39 require), plus a uniform
    # random tail for breadth
    snap_items = max(n_items // 20, 10)
    weeks = np.arange(0, n_dates, 7, dtype=np.int64)
    snap = np.stack(np.meshgrid(weeks,
                                np.arange(snap_items, dtype=np.int64),
                                np.arange(n_wh, dtype=np.int64),
                                indexing="ij"), -1).reshape(-1, 3)
    ni = max(n // 4, 1)
    inventory = pd.DataFrame({
        "inv_date_sk": np.concatenate(
            [snap[:, 0], rng.integers(0, n_dates, ni)]).astype(
            np.int64),
        "inv_item_sk": np.concatenate(
            [snap[:, 1], rng.integers(0, n_items, ni)]).astype(
            np.int64),
        "inv_warehouse_sk": np.concatenate(
            [snap[:, 2], rng.integers(0, n_wh, ni)]).astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, len(snap) + ni).astype(np.int32),
    })

    # ~2% missing fks in each channel's "null channel-id" column (the
    # q76 shape groups on them; returns tables were sampled above from
    # the pre-null values so their keys still always match a sale)
    for frame, cname in ((store_sales, "ss_store_sk"),
                         (store_sales, "ss_addr_sk"),
                         (web_sales, "ws_ship_customer_sk"),
                         (catalog_sales, "cs_ship_addr_sk")):
        vals = frame[cname].to_numpy()
        na = rng.random(len(vals)) < 0.02
        frame[cname] = pd.array(np.where(na, 0, vals), dtype="Int64")
        frame.loc[na, cname] = pd.NA

    return {"date_dim": date_dim, "item": item, "store": store,
            "income_band": income_band,
            "customer": customer, "customer_address": customer_address,
            "household_demographics": household_demographics,
            "promotion": promotion, "store_sales": store_sales,
            "time_dim": time_dim,
            "customer_demographics": customer_demographics,
            "warehouse": warehouse, "catalog_sales": catalog_sales,
            "web_sales": web_sales, "store_returns": store_returns,
            "catalog_returns": catalog_returns,
            "web_returns": web_returns, "inventory": inventory,
            "call_center": call_center, "ship_mode": ship_mode,
            "web_site": web_site, "web_page": web_page,
            "catalog_page": catalog_page,
            "reason": reason}


def sources(tables: dict[str, pd.DataFrame], num_partitions: int = 1):
    from spark_rapids_tpu.models.data_util import make_sources
    return make_sources(tables, SCHEMAS, num_partitions)
