"""TPC-DS table subset + synthetic data (reference
`integration_tests/.../tpcds/TpcdsLikeSpark.scala` table readers — the
full 24-table catalog; we carry the 8 tables the classic star-join query
set touches, generated in-memory).

Dates use the TPC-DS surrogate-key convention (d_date_sk joins, d_year /
d_moy predicates) — no calendar math needed in the queries themselves.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T

SCHEMAS = {
    "date_dim": T.Schema.of(
        ("d_date_sk", T.INT64), ("d_year", T.INT32),
        ("d_moy", T.INT32), ("d_dom", T.INT32),
        ("d_day_name", T.STRING), ("d_qoy", T.INT32)),
    "item": T.Schema.of(
        ("i_item_sk", T.INT64), ("i_item_id", T.STRING),
        ("i_brand_id", T.INT32), ("i_brand", T.STRING),
        ("i_category_id", T.INT32), ("i_category", T.STRING),
        ("i_manufact_id", T.INT32), ("i_manager_id", T.INT32),
        ("i_current_price", T.FLOAT64)),
    "store": T.Schema.of(
        ("s_store_sk", T.INT64), ("s_store_id", T.STRING),
        ("s_store_name", T.STRING), ("s_number_employees", T.INT32),
        ("s_city", T.STRING), ("s_state", T.STRING)),
    "customer": T.Schema.of(
        ("c_customer_sk", T.INT64), ("c_customer_id", T.STRING),
        ("c_first_name", T.STRING), ("c_last_name", T.STRING),
        ("c_current_addr_sk", T.INT64)),
    "customer_address": T.Schema.of(
        ("ca_address_sk", T.INT64), ("ca_city", T.STRING),
        ("ca_state", T.STRING), ("ca_country", T.STRING)),
    "household_demographics": T.Schema.of(
        ("hd_demo_sk", T.INT64), ("hd_dep_count", T.INT32),
        ("hd_vehicle_count", T.INT32), ("hd_buy_potential", T.STRING)),
    "promotion": T.Schema.of(
        ("p_promo_sk", T.INT64), ("p_channel_email", T.STRING),
        ("p_channel_event", T.STRING)),
    "store_sales": T.Schema.of(
        ("ss_sold_date_sk", T.INT64), ("ss_item_sk", T.INT64),
        ("ss_customer_sk", T.INT64), ("ss_cdemo_sk", T.INT64),
        ("ss_hdemo_sk", T.INT64), ("ss_addr_sk", T.INT64),
        ("ss_store_sk", T.INT64), ("ss_promo_sk", T.INT64),
        ("ss_ticket_number", T.INT64), ("ss_quantity", T.INT32),
        ("ss_list_price", T.FLOAT64), ("ss_sales_price", T.FLOAT64),
        ("ss_ext_sales_price", T.FLOAT64),
        ("ss_ext_discount_amt", T.FLOAT64),
        ("ss_ext_list_price", T.FLOAT64),
        ("ss_coupon_amt", T.FLOAT64), ("ss_net_profit", T.FLOAT64),
        ("ss_ext_wholesale_cost", T.FLOAT64)),
}

CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
              "Shoes", "Sports", "Women"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA"]
CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]


def _money(rng, lo, hi, n):
    return np.round(rng.uniform(lo, hi, n), 2)


def gen_tables(rng: np.random.Generator, scale: int = 10_000
               ) -> dict[str, pd.DataFrame]:
    """`scale` ~ store_sales rows; dimensions scale down dbgen-style."""
    n_dates = 365 * 5  # 1998-2002
    n_items = max(scale // 20, 50)
    n_stores = max(scale // 2000, 4)
    n_cust = max(scale // 10, 100)
    n_addr = n_cust
    n_hd = 60
    n_promo = max(scale // 500, 10)

    sk = np.arange(n_dates, dtype=np.int64)
    date_dim = pd.DataFrame({
        "d_date_sk": sk,
        "d_year": (1998 + sk // 365).astype(np.int32),
        "d_moy": ((sk % 365) // 31 + 1).clip(1, 12).astype(np.int32),
        "d_dom": ((sk % 31) + 1).astype(np.int32),
        "d_day_name": np.array(DAY_NAMES, dtype=object)[sk % 7],
        "d_qoy": (((sk % 365) // 92) + 1).clip(1, 4).astype(np.int32),
    })
    item = pd.DataFrame({
        "i_item_sk": np.arange(n_items, dtype=np.int64),
        "i_item_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_items)], dtype=object),
        "i_brand_id": rng.integers(1, 10, n_items).astype(np.int32),
        "i_brand": np.array(
            [f"brand#{rng.integers(1, 10)}" for _ in range(n_items)],
            dtype=object),
        "i_category_id": rng.integers(0, len(CATEGORIES),
                                      n_items).astype(np.int32),
        "i_category": np.array(CATEGORIES, dtype=object)[
            rng.integers(0, len(CATEGORIES), n_items)],
        "i_manufact_id": rng.integers(1, 100, n_items).astype(np.int32),
        "i_manager_id": rng.integers(1, 40, n_items).astype(np.int32),
        "i_current_price": _money(rng, 1.0, 100.0, n_items),
    })
    store = pd.DataFrame({
        "s_store_sk": np.arange(n_stores, dtype=np.int64),
        "s_store_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_stores)], dtype=object),
        "s_store_name": np.array(
            ["ese", "ought", "able", "pri", "bar", "anti"][:n_stores]
            * (n_stores // 6 + 1), dtype=object)[:n_stores],
        "s_number_employees": rng.integers(200, 301,
                                           n_stores).astype(np.int32),
        "s_city": np.array(CITIES, dtype=object)[
            rng.integers(0, len(CITIES), n_stores)],
        "s_state": np.array(STATES, dtype=object)[
            rng.integers(0, len(STATES), n_stores)],
    })
    customer = pd.DataFrame({
        "c_customer_sk": np.arange(n_cust, dtype=np.int64),
        "c_customer_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(n_cust)], dtype=object),
        "c_first_name": np.array(
            [f"First{i % 97}" for i in range(n_cust)], dtype=object),
        "c_last_name": np.array(
            [f"Last{i % 89}" for i in range(n_cust)], dtype=object),
        "c_current_addr_sk": rng.integers(0, n_addr,
                                          n_cust).astype(np.int64),
    })
    customer_address = pd.DataFrame({
        "ca_address_sk": np.arange(n_addr, dtype=np.int64),
        "ca_city": np.array(CITIES, dtype=object)[
            rng.integers(0, len(CITIES), n_addr)],
        "ca_state": np.array(STATES, dtype=object)[
            rng.integers(0, len(STATES), n_addr)],
        "ca_country": np.array(["United States"] * n_addr, dtype=object),
    })
    household_demographics = pd.DataFrame({
        "hd_demo_sk": np.arange(n_hd, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int32),
        "hd_vehicle_count": rng.integers(0, 5, n_hd).astype(np.int32),
        "hd_buy_potential": np.array(BUY_POTENTIAL, dtype=object)[
            rng.integers(0, len(BUY_POTENTIAL), n_hd)],
    })
    promotion = pd.DataFrame({
        "p_promo_sk": np.arange(n_promo, dtype=np.int64),
        "p_channel_email": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.12).astype(int)],
        "p_channel_event": np.array(["N", "Y"], dtype=object)[
            (rng.random(n_promo) < 0.12).astype(int)],
    })
    n = scale
    # a ticket (basket) belongs to exactly one customer, several items —
    # the invariant q68/q73's per-ticket aggregates group on
    tickets = rng.integers(0, max(n // 6, 1), n).astype(np.int64)
    ticket_cust = ((tickets * 7919) % n_cust).astype(np.int64)
    qty = rng.integers(1, 101, n).astype(np.int32)
    list_price = _money(rng, 1.0, 200.0, n)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, n), 2)
    store_sales = pd.DataFrame({
        "ss_sold_date_sk": rng.integers(0, n_dates, n).astype(np.int64),
        "ss_item_sk": rng.integers(0, n_items, n).astype(np.int64),
        "ss_customer_sk": ticket_cust,
        "ss_cdemo_sk": rng.integers(0, 1000, n).astype(np.int64),
        "ss_hdemo_sk": rng.integers(0, n_hd, n).astype(np.int64),
        "ss_addr_sk": rng.integers(0, n_addr, n).astype(np.int64),
        "ss_store_sk": rng.integers(0, n_stores, n).astype(np.int64),
        "ss_promo_sk": rng.integers(0, n_promo, n).astype(np.int64),
        "ss_ticket_number": tickets,
        "ss_quantity": qty,
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_sales_price": np.round(sales_price * qty, 2),
        "ss_ext_discount_amt": _money(rng, 0.0, 100.0, n),
        "ss_ext_list_price": np.round(list_price * qty, 2),
        "ss_coupon_amt": np.where(rng.random(n) < 0.2,
                                  _money(rng, 0.0, 50.0, n), 0.0),
        "ss_net_profit": _money(rng, -500.0, 500.0, n),
        "ss_ext_wholesale_cost": _money(rng, 1.0, 100.0, n),
    })
    return {"date_dim": date_dim, "item": item, "store": store,
            "customer": customer, "customer_address": customer_address,
            "household_demographics": household_demographics,
            "promotion": promotion, "store_sales": store_sales}


def sources(tables: dict[str, pd.DataFrame], num_partitions: int = 1):
    from spark_rapids_tpu.models.data_util import make_sources
    return make_sources(tables, SCHEMAS, num_partitions)
