"""Mortgage ETL workload (reference
`integration_tests/src/main/scala/.../mortgage/Mortgage.scala`: Fannie-Mae
performance + acquisition CSV ETL — parse, clean, join, aggregate into
delinquency features).

Shape preserved: two raw tables (perf: loan monthly records; acq: loan
originations), per-loan delinquency aggregation, join back to
originations, feature projection.  Data is generated in-memory in the
same value ranges.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.joins import JoinType
from spark_rapids_tpu.exec.sort import asc
from spark_rapids_tpu.exprs.aggregates import Count, Max, Min, Sum
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.conditional import CaseWhen
from spark_rapids_tpu.plan.nodes import (CpuAggregate, CpuFilter,
                                         CpuHashJoin, CpuProject, CpuSort)

PERF_SCHEMA = T.Schema.of(
    ("loan_id", T.INT64), ("monthly_reporting_period", T.INT32),
    ("current_actual_upb", T.FLOAT64), ("loan_age", T.FLOAT64),
    ("current_loan_delinquency_status", T.INT32),
    ("interest_rate", T.FLOAT64))

ACQ_SCHEMA = T.Schema.of(
    ("loan_id", T.INT64), ("orig_channel", T.STRING),
    ("seller_name", T.STRING), ("orig_interest_rate", T.FLOAT64),
    ("orig_upb", T.INT64), ("orig_loan_term", T.INT32),
    ("orig_ltv", T.FLOAT64), ("orig_cltv", T.FLOAT64),
    ("num_borrowers", T.FLOAT64), ("dti", T.FLOAT64),
    ("borrower_credit_score", T.FLOAT64))

CHANNELS = ["R", "C", "B"]
SELLERS = ["BANK OF AMERICA", "WELLS FARGO", "JPMORGAN", "CITI",
           "QUICKEN", "OTHER"]


def gen_tables(rng: np.random.Generator, loans: int = 1000,
               months: int = 24) -> dict[str, pd.DataFrame]:
    n_perf = loans * months
    loan_ids = np.repeat(np.arange(loans, dtype=np.int64), months)
    period = np.tile(np.arange(months, dtype=np.int32), loans)
    delinq = rng.choice([0, 0, 0, 0, 0, 1, 1, 2, 3, 6],
                        size=n_perf).astype(np.int32)
    perf = pd.DataFrame({
        "loan_id": loan_ids,
        "monthly_reporting_period": period,
        "current_actual_upb": np.round(
            rng.uniform(10_000, 800_000, n_perf), 2),
        "loan_age": period.astype(np.float64),
        "current_loan_delinquency_status": delinq,
        "interest_rate": np.round(rng.uniform(2.5, 7.5, n_perf), 3),
    })
    acq = pd.DataFrame({
        "loan_id": np.arange(loans, dtype=np.int64),
        "orig_channel": np.array(CHANNELS, dtype=object)[
            rng.integers(0, len(CHANNELS), loans)],
        "seller_name": np.array(SELLERS, dtype=object)[
            rng.integers(0, len(SELLERS), loans)],
        "orig_interest_rate": np.round(rng.uniform(2.5, 7.5, loans), 3),
        "orig_upb": rng.integers(10_000, 800_000, loans).astype(
            np.int64),
        "orig_loan_term": rng.choice([180, 240, 360],
                                     loans).astype(np.int32),
        "orig_ltv": np.round(rng.uniform(40, 97, loans), 1),
        "orig_cltv": np.round(rng.uniform(40, 99, loans), 1),
        "num_borrowers": rng.choice([1.0, 2.0], loans),
        "dti": np.round(rng.uniform(10, 50, loans), 1),
        "borrower_credit_score": rng.integers(
            550, 830, loans).astype(np.float64),
    })
    return {"perf": perf, "acq": acq}


def sources(tables, num_partitions: int = 1):
    from spark_rapids_tpu.models.data_util import make_sources
    return make_sources(tables, {"perf": PERF_SCHEMA,
                                 "acq": ACQ_SCHEMA}, num_partitions)


def etl_plan(t):
    """The mortgage feature pipeline as one plan tree (reference
    Mortgage.scala `createDelinquency` + final feature join)."""
    ever = CpuAggregate(
        [col("loan_id")],
        [Max(col("current_loan_delinquency_status")).alias("ever_delinq"),
         Min(col("current_actual_upb")).alias("min_upb"),
         Sum(CaseWhen(
             (((col("current_loan_delinquency_status") >= lit(1)),
               lit(1)),), lit(0))).alias("delinq_months"),
         Count(None).alias("reporting_months")],
        CpuProject([col("loan_id"),
                    col("current_loan_delinquency_status"),
                    col("current_actual_upb")], t["perf"]))
    j = CpuHashJoin(JoinType.INNER, [col("loan_id")], [col("loan_id_a")],
                    ever,
                    CpuProject(
                        [col("loan_id").alias("loan_id_a"),
                         col("orig_channel"), col("seller_name"),
                         col("orig_interest_rate"), col("orig_upb"),
                         col("orig_ltv"), col("dti"),
                         col("borrower_credit_score")], t["acq"]))
    features = CpuProject(
        [col("loan_id"), col("orig_channel"), col("seller_name"),
         col("orig_interest_rate"), col("orig_upb"),
         col("orig_ltv"), col("dti"), col("borrower_credit_score"),
         col("ever_delinq"), col("delinq_months"),
         col("reporting_months"), col("min_upb"),
         CaseWhen((((col("ever_delinq") >= lit(1)), lit(1)),),
                  lit(0)).alias("delinquency_12")], j)
    return CpuSort([asc(col("loan_id"))], features)


def summary_plan(t):
    """Post-ETL report: delinquency rate by channel and seller."""
    features = etl_plan(t)
    agg = CpuAggregate(
        [col("orig_channel"), col("seller_name")],
        [Count(None).alias("loans"),
         Sum(col("delinquency_12")).alias("delinquent")], features)
    return CpuSort([asc(col("orig_channel")), asc(col("seller_name"))],
                   agg)
