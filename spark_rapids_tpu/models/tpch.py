"""TPC-H workload definitions (reference
`integration_tests/src/main/scala/.../tpch/TpchLikeSpark.scala`).

Queries are built as physical plans over the engine; `build_q1_kernel`
additionally exposes Q1's compute as ONE pure jittable function — the
"flagship forward step" used by __graft_entry__ and bench.py.

Q1 (pricing summary report):
  select returnflag, linestatus, sum(qty), sum(extprice),
         sum(extprice*(1-disc)), sum(extprice*(1-disc)*(1+tax)),
         avg(qty), avg(extprice), avg(disc), count(*)
  from lineitem where shipdate <= date '1998-09-02'
  group by returnflag, linestatus
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import make_eval_context
from spark_rapids_tpu.exprs.base import col, lit
from spark_rapids_tpu.exprs.aggregates import (
    AggContext, Average, Count, CountStar, Sum)
from spark_rapids_tpu.ops.sort_encode import (
    multi_key_argsort, segment_boundaries)

LINEITEM_SCHEMA = T.Schema.of(
    ("l_returnflag", T.INT32),      # dictionary-encoded flag (A/N/R -> 0/1/2)
    ("l_linestatus", T.INT32),      # O/F -> 0/1
    ("l_quantity", T.FLOAT32),
    ("l_extendedprice", T.FLOAT32),
    ("l_discount", T.FLOAT32),
    ("l_tax", T.FLOAT32),
    ("l_shipdate", T.DATE32),
)

Q1_CUTOFF_DAYS = 10471  # 1998-09-02 as days since epoch


def gen_lineitem(rng: np.random.Generator, rows: int) -> ColumnarBatch:
    """Synthetic lineitem in TPC-H value ranges (dbgen-shaped, not dbgen
    bit-exact — the engine is being measured, not the generator)."""
    base = {
        "l_returnflag": rng.integers(0, 3, rows).astype(np.int32),
        "l_linestatus": rng.integers(0, 2, rows).astype(np.int32),
        "l_quantity": rng.integers(1, 51, rows).astype(np.float32),
        "l_extendedprice": np.round(
            rng.uniform(900.0, 105000.0, rows), 2).astype(np.float32),
        "l_discount": np.round(
            rng.uniform(0.0, 0.10, rows), 2).astype(np.float32),
        "l_tax": np.round(
            rng.uniform(0.0, 0.08, rows), 2).astype(np.float32),
        "l_shipdate": rng.integers(8400, 10600, rows).astype(np.int32),
    }
    return ColumnarBatch.from_numpy(base, LINEITEM_SCHEMA)


def q1_plan(source):
    """Q1 as a physical plan (exec pipeline)."""
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.basic import FilterExec, ProjectExec
    from spark_rapids_tpu.exec.sort import SortExec, asc
    filtered = FilterExec(
        col("l_shipdate") <= lit(Q1_CUTOFF_DAYS, T.DATE32), source)
    projected = ProjectExec([
        col("l_returnflag"), col("l_linestatus"), col("l_quantity"),
        col("l_extendedprice"), col("l_discount"),
        (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
         ).alias("disc_price"),
        (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
         * (lit(1.0) + col("l_tax"))).alias("charge"),
    ], filtered)
    agg = HashAggregateExec(
        [col("l_returnflag"), col("l_linestatus")],
        [Sum(col("l_quantity")).alias("sum_qty"),
         Sum(col("l_extendedprice")).alias("sum_base_price"),
         Sum(col("disc_price")).alias("sum_disc_price"),
         Sum(col("charge")).alias("sum_charge"),
         Average(col("l_quantity")).alias("avg_qty"),
         Average(col("l_extendedprice")).alias("avg_price"),
         Average(col("l_discount")).alias("avg_disc"),
         CountStar().alias("count_order")],
        projected)
    return SortExec([asc(col("l_returnflag")), asc(col("l_linestatus"))],
                    agg)


def build_q1_fused_kernel(capacity: int, batch_rows: int):
    """STACKED Q1 step: one dispatch aggregates capacity // batch_rows
    batches laid back to back (num_rows becomes a per-batch vector) —
    the device-side batch loop that amortizes per-dispatch runtime
    overhead.  Pallas single-HBM-pass kernel by default
    (spark.rapids.tpu.pallas.q1Fused.enabled, measured 3x XLA); falls
    back to vmapping the XLA step over the batch axis."""
    import jax
    from spark_rapids_tpu import config as C
    b = capacity // batch_rows
    pallas_ok = (b == 1) or (batch_rows % 1024 == 0)
    if C.get_active_conf()[C.PALLAS_Q1_FUSED_ENABLED] and pallas_ok:
        from spark_rapids_tpu.ops.pallas_kernels import (_on_tpu,
                                                         q1_fused_pallas)
        interp = not _on_tpu()

        def step(flag, status, qty, extprice, disc, tax, shipdate,
                 nums):
            return q1_fused_pallas(
                flag, status, qty, extprice, disc, tax, shipdate, nums,
                capacity=capacity, cutoff=Q1_CUTOFF_DAYS,
                batch_rows=batch_rows, interpret=interp)

        return step
    base = build_q1_kernel(batch_rows)

    @jax.jit
    def step(flag, status, qty, extprice, disc, tax, shipdate, nums):
        cols = [x.reshape(b, batch_rows)
                for x in (flag, status, qty, extprice, disc, tax,
                          shipdate)]
        outs = jax.vmap(base)(*cols, nums)
        # per-batch (8,) group rows -> combined (8, 6) table
        import jax.numpy as jnp
        return jnp.stack([outs[2 + j].sum(axis=0) for j in range(5)] +
                         [outs[7].sum(axis=0).astype(jnp.float64)],
                         axis=1)

    return step


def q1_reference_pandas(df):
    """Golden CPU implementation for parity checks."""
    f = df[df["l_shipdate"] <= Q1_CUTOFF_DAYS].copy()
    f["disc_price"] = f["l_extendedprice"] * (1 - f["l_discount"])
    f["charge"] = f["disc_price"] * (1 + f["l_tax"])
    out = f.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"])
    return out


def build_q1_kernel(capacity: int):
    """Q1 compute as ONE pure jittable function over column arrays:
       fn(qty, extprice, disc, tax, flag, status, shipdate, num_rows)
         -> (flag6, status6, sums..., counts)
    Output is a fixed 8-slot group table (3 flags x 2 statuses padded to
    8), fully static shapes — the whole query is a single fused XLA
    computation: the flagship single-chip forward step.

    With spark.rapids.tpu.pallas.q1.enabled the explicit Pallas kernel
    (ops/pallas_kernels.py) is returned instead — same contract."""
    from spark_rapids_tpu import config as C
    if C.get_active_conf()[C.PALLAS_Q1_ENABLED]:
        from spark_rapids_tpu.ops.pallas_kernels import (
            build_q1_kernel_pallas)
        return build_q1_kernel_pallas(capacity, Q1_CUTOFF_DAYS)
    cap = capacity

    def q1_step(flag, status, qty, extprice, disc, tax, shipdate,
                num_rows):
        row_mask = jnp.arange(cap) < num_rows
        keep = row_mask & (shipdate <= Q1_CUTOFF_DAYS)
        disc_price = extprice * (1.0 - disc)
        charge = disc_price * (1.0 + tax)
        # group id = flag * 2 + status, 6 groups (static!)
        gid = jnp.where(keep, flag * 2 + status, 7)
        # grouped reduction as one-hot matmuls on the MXU: scatter
        # (segment_sum) serializes on TPU, but (rows x 6)^T @ (rows x 8)
        # one-hot is a systolic-array pass — the elementwise prologue
        # fuses into the matmul's operand reads.  Chunked to 64K rows
        # with an f64 combine: a single f32 accumulation over millions of
        # rows loses ~1e-4 relative (HIGHEST only fixes operand
        # rounding, not the f32 accumulator).
        onehot = (gid[:, None] == jnp.arange(8)[None, :]).astype(
            jnp.float32)
        # jnp.where, not multiply-by-mask: NaN in a filtered-out row
        # must not poison the sums (NaN * 0 == NaN)
        vals = jnp.where(
            keep[:, None],
            jnp.stack([qty, extprice, disc_price, charge, disc,
                       jnp.ones_like(qty)], axis=1),
            jnp.float32(0))
        chunk = min(cap, 65536)
        table = jnp.einsum(
            "cbm,cbg->cmg", vals.reshape(-1, chunk, 6),
            onehot.reshape(-1, chunk, 8),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.float64).sum(axis=0)
        g = jnp.arange(8)
        cnt = table[5].astype(jnp.int32)
        return (g // 2, g % 2, table[0], table[1], table[2], table[3],
                table[4], cnt)

    return q1_step
