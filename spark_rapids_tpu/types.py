"""Type system for TPU columnar batches.

Mirrors the reference's supported type matrix (SURVEY.md §2.6; reference
`GpuOverrides.scala:397-409`): Boolean/Byte/Short/Int/Long/Float/Double/Date/
Timestamp/String.  Decimals/arrays/structs/maps are unsupported at this
snapshot, matching the reference v0 matrix.

TPU-first representation choices:
  - Dates are int32 days-since-epoch, timestamps int64 microseconds (UTC only,
    same guard as the reference).
  - Strings are fixed-width byte tensors (see columnar/strings.py): XLA needs
    static shapes, so variable-width data lives as uint8[capacity, char_cap]
    plus an int32 length column.  char_cap is bucketed like row capacity.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any

import jax.numpy as jnp
import numpy as np


class TypeId(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DATE32 = "date32"          # days since unix epoch, int32 storage
    TIMESTAMP_US = "timestamp"  # microseconds since epoch UTC, int64 storage
    STRING = "string"           # byte-tensor encoded


@dataclasses.dataclass(frozen=True)
class DataType:
    id: TypeId

    @property
    def is_string(self) -> bool:
        return self.id == TypeId.STRING

    @property
    def is_floating(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_integral(self) -> bool:
        return self.id in (TypeId.INT8, TypeId.INT16, TypeId.INT32,
                           TypeId.INT64, TypeId.DATE32, TypeId.TIMESTAMP_US)

    @property
    def is_numeric(self) -> bool:
        return self.is_floating or self.id in (
            TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64)

    @property
    def storage_dtype(self) -> np.dtype:
        """numpy/jax dtype used for the data buffer."""
        return _STORAGE[self.id]

    def __repr__(self) -> str:
        return self.id.value


BOOL = DataType(TypeId.BOOL)
INT8 = DataType(TypeId.INT8)
INT16 = DataType(TypeId.INT16)
INT32 = DataType(TypeId.INT32)
INT64 = DataType(TypeId.INT64)
FLOAT32 = DataType(TypeId.FLOAT32)
FLOAT64 = DataType(TypeId.FLOAT64)
DATE32 = DataType(TypeId.DATE32)
TIMESTAMP_US = DataType(TypeId.TIMESTAMP_US)
STRING = DataType(TypeId.STRING)

ALL_TYPES = (BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE32,
             TIMESTAMP_US, STRING)

_STORAGE = {
    TypeId.BOOL: np.dtype(np.bool_),
    TypeId.INT8: np.dtype(np.int8),
    TypeId.INT16: np.dtype(np.int16),
    TypeId.INT32: np.dtype(np.int32),
    TypeId.INT64: np.dtype(np.int64),
    TypeId.FLOAT32: np.dtype(np.float32),
    TypeId.FLOAT64: np.dtype(np.float64),
    TypeId.DATE32: np.dtype(np.int32),
    TypeId.TIMESTAMP_US: np.dtype(np.int64),
    TypeId.STRING: np.dtype(np.uint8),
}

_FROM_NP = {
    np.dtype(np.bool_): BOOL,
    np.dtype(np.int8): INT8,
    np.dtype(np.int16): INT16,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.float32): FLOAT32,
    np.dtype(np.float64): FLOAT64,
}


def from_numpy_dtype(dt: np.dtype) -> DataType:
    dt = np.dtype(dt)
    if dt.kind in ("U", "S", "O"):
        return STRING
    if dt.kind == "M":  # datetime64
        return TIMESTAMP_US
    if dt not in _FROM_NP:
        raise TypeError(f"unsupported numpy dtype {dt}")
    return _FROM_NP[dt]


def from_arrow(at: Any) -> DataType:
    """Map a pyarrow DataType to ours (scan schema negotiation)."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOL
    if pa.types.is_int8(at):
        return INT8
    if pa.types.is_int16(at):
        return INT16
    if pa.types.is_int32(at):
        return INT32
    if pa.types.is_int64(at):
        return INT64
    if pa.types.is_float32(at):
        return FLOAT32
    if pa.types.is_float64(at):
        return FLOAT64
    if pa.types.is_date32(at):
        return DATE32
    if pa.types.is_timestamp(at):
        return TIMESTAMP_US
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow(dt: DataType) -> Any:
    import pyarrow as pa
    return {
        TypeId.BOOL: pa.bool_(),
        TypeId.INT8: pa.int8(),
        TypeId.INT16: pa.int16(),
        TypeId.INT32: pa.int32(),
        TypeId.INT64: pa.int64(),
        TypeId.FLOAT32: pa.float32(),
        TypeId.FLOAT64: pa.float64(),
        TypeId.DATE32: pa.date32(),
        TypeId.TIMESTAMP_US: pa.timestamp("us", tz="UTC"),
        TypeId.STRING: pa.string(),
    }[dt.id]


def common_type(a: DataType, b: DataType) -> DataType:
    """Numeric promotion following Spark's binary arithmetic widening."""
    if a == b:
        return a
    order = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]
    if a in order and b in order:
        return order[max(order.index(a), order.index(b))]
    raise TypeError(f"no common type for {a}, {b}")


def result_jnp(dt: DataType):
    return jnp.dtype(dt.storage_dtype)


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def __repr__(self) -> str:
        return f"{self.name}:{self.dtype}{'' if self.nullable else '!'}"


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    @staticmethod
    def of(*pairs) -> "Schema":
        out = []
        for p in pairs:
            if isinstance(p, Field):
                out.append(p)
            else:
                name, dtype = p[0], p[1]
                nullable = p[2] if len(p) > 2 else True
                out.append(Field(name, dtype, nullable))
        return Schema(tuple(out))

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"
