"""PythonWorkerSemaphore: caps concurrent python UDF evaluations per
executor (reference `python/PythonWorkerSemaphore.scala:17-40`, conf
`spark.rapids.python.concurrentPythonWorkers`; 0 = unlimited)."""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional


class PythonWorkerSemaphore:
    _instance: Optional["PythonWorkerSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, max_workers: int):
        self.max_workers = max_workers
        self._sem = (threading.Semaphore(max_workers)
                     if max_workers > 0 else None)
        self.active = 0
        self._alock = threading.Lock()
        # per-thread hold depth: stacked python-UDF operators on one task
        # thread share a single worker slot instead of self-deadlocking
        self._tls = threading.local()

    @classmethod
    def initialize(cls, max_workers: int) -> "PythonWorkerSemaphore":
        with cls._lock:
            cls._instance = cls(max_workers)
            return cls._instance

    @classmethod
    def get(cls) -> "PythonWorkerSemaphore":
        with cls._lock:
            if cls._instance is None:
                from spark_rapids_tpu import config as C
                cls._instance = cls(
                    C.get_active_conf()[C.PYTHON_CONCURRENT_WORKERS])
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._lock:
            cls._instance = None

    @contextmanager
    def held(self):
        depth = getattr(self._tls, "depth", 0)
        outermost = depth == 0
        if outermost and self._sem is not None:
            # acquire before bumping the depth: a failed/interrupted
            # acquire must not leave this thread marked as holding.
            # Bounded poll + cancel check: a task parked behind
            # concurrentPythonWorkers must die with its query instead
            # of waiting out a slot forever (PR 4 wait discipline).
            from spark_rapids_tpu.utils import watchdog as W
            while not self._sem.acquire(timeout=0.1):
                W.check_cancelled()
        self._tls.depth = depth + 1
        if outermost:
            with self._alock:
                self.active += 1
        try:
            yield
        finally:
            self._tls.depth -= 1
            if outermost:
                with self._alock:
                    self.active -= 1
                if self._sem is not None:
                    self._sem.release()
