"""Pandas UDF execs (reference `GpuArrowEvalPythonExec.scala`,
`GpuMapInPandasExec.scala`).

`ArrowEvalPythonExec` evaluates vectorized (Series -> Series) UDFs: the
batch leaves HBM once, the UDF runs under the worker semaphore, and the
appended result columns re-upload under the task semaphore — the exact
device-boundary discipline of the reference (batches -> Arrow -> worker ->
batches).  `MapInPandasExec` maps whole DataFrames to DataFrames with a
declared output schema.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Sequence

import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, UnaryExecBase
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.plan.nodes import CpuNode, normalize_df
from spark_rapids_tpu.pyudf.semaphore import PythonWorkerSemaphore


def pandas_udf(return_type: T.DataType):
    """Vectorized UDF decorator: fn receives pandas Series (Spark's
    pandas_udf scalar flavor)."""

    def wrap(fn: Callable):
        fn.return_type = return_type
        fn.is_pandas_udf = True
        return fn
    return wrap


@dataclasses.dataclass
class PandasUdfSpec:
    name: str
    fn: Callable
    return_type: T.DataType
    args: tuple  # Expression args


def _eval_udfs(df: pd.DataFrame, udfs: Sequence[PandasUdfSpec],
               input_schema: T.Schema) -> pd.DataFrame:
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval, nullable_dtype
    out = df.copy()
    sem = PythonWorkerSemaphore.get()
    for u in udfs:
        args = [cpu_eval(a, df, input_schema) for a in u.args]
        with sem.held():
            res = u.fn(*args)
        if not isinstance(res, pd.Series):
            res = pd.Series(res, index=df.index)
        out[u.name] = res.astype(nullable_dtype(u.return_type))
    return out


def _output_schema(child_schema: T.Schema,
                   udfs: Sequence[PandasUdfSpec]) -> T.Schema:
    return T.Schema(tuple(child_schema.fields) + tuple(
        T.Field(u.name, u.return_type) for u in udfs))


class CpuArrowEvalPython(CpuNode):
    """Planner-facing node (Spark's ArrowEvalPythonExec analog): appends
    one column per UDF to the child output."""

    def __init__(self, udfs: Sequence[PandasUdfSpec], child: CpuNode):
        super().__init__(child)
        self.udfs = list(udfs)
        self._schema = _output_schema(child.output_schema(), self.udfs)

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"CpuArrowEvalPython({[u.name for u in self.udfs]})"

    def execute(self):
        cs = self.child.output_schema()

        def run(it):
            for df in it:
                yield normalize_df(_eval_udfs(df, self.udfs, cs),
                                   self._schema)
        return [run(it) for it in self.child.execute()]


class ArrowEvalPythonExec(UnaryExecBase):
    """Columnar exec: one HBM->host->HBM round trip per batch, worker
    semaphore around the UDF, task semaphore around the re-upload
    (reference GpuArrowEvalPythonExec.doExecuteColumnar :376)."""

    def __init__(self, udfs: Sequence[PandasUdfSpec], child: TpuExec):
        super().__init__(child)
        self.udfs = list(udfs)
        self._schema = _output_schema(child.output_schema(), self.udfs)

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"ArrowEvalPythonExec({[u.name for u in self.udfs]})"

    def process_partition(self, batches: Iterator[ColumnarBatch]
                          ) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan.transitions import (
            batch_from_df, df_from_batch)
        cs = self.child.output_schema()
        for batch in batches:
            df = df_from_batch(batch)
            with self.metrics.timed():
                out = _eval_udfs(df, self.udfs, cs)
            TpuSemaphore.get().acquire_if_necessary()
            nb = batch_from_df(normalize_df(out, self._schema),
                               self._schema)
            self.update_output_metrics(nb)
            yield nb


class CpuMapInPandas(CpuNode):
    """mapInPandas: fn maps an iterator of DataFrames to an iterator of
    DataFrames with a declared schema."""

    def __init__(self, fn: Callable, schema: T.Schema, child: CpuNode):
        super().__init__(child)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"CpuMapInPandas({getattr(self.fn, '__name__', 'fn')})"

    def execute(self):
        def run(it):
            sem = PythonWorkerSemaphore.get()
            gen = self.fn(iter(it))
            while True:
                with sem.held():
                    try:
                        out = next(gen)
                    except StopIteration:
                        break
                yield normalize_df(out, self._schema)
        return [run(it) for it in self.child.execute()]


class MapInPandasExec(UnaryExecBase):
    def __init__(self, node: CpuMapInPandas, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return f"MapInPandasExec({getattr(self.node.fn, '__name__', 'fn')})"

    def process_partition(self, batches: Iterator[ColumnarBatch]
                          ) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan.transitions import (
            batch_from_df, df_from_batch)
        schema = self.node.output_schema()

        def host_frames():
            for b in batches:
                yield df_from_batch(b)
        sem = PythonWorkerSemaphore.get()
        gen = self.node.fn(host_frames())
        # hold the worker slot only while python code runs (each next()),
        # not across downstream device work on the yielded batch
        while True:
            with sem.held():
                try:
                    out = next(gen)
                except StopIteration:
                    break
            out = normalize_df(out, schema)
            TpuSemaphore.get().acquire_if_necessary()
            nb = batch_from_df(out, schema)
            self.update_output_metrics(nb)
            yield nb
