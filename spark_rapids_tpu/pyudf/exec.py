"""Pandas UDF execs (reference `GpuArrowEvalPythonExec.scala`,
`GpuMapInPandasExec.scala`).

`ArrowEvalPythonExec` evaluates vectorized (Series -> Series) UDFs: the
batch leaves HBM once, the UDF runs under the worker semaphore, and the
appended result columns re-upload under the task semaphore — the exact
device-boundary discipline of the reference (batches -> Arrow -> worker ->
batches).  `MapInPandasExec` maps whole DataFrames to DataFrames with a
declared output schema.
"""
from __future__ import annotations

import dataclasses

import numpy as np
from typing import Callable, Iterator, Sequence

import pandas as pd

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, UnaryExecBase
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.plan.nodes import CpuNode, normalize_df
from spark_rapids_tpu.pyudf.semaphore import PythonWorkerSemaphore


def pandas_udf(return_type: T.DataType):
    """Vectorized UDF decorator: fn receives pandas Series (Spark's
    pandas_udf scalar flavor)."""

    def wrap(fn: Callable):
        fn.return_type = return_type
        fn.is_pandas_udf = True
        return fn
    return wrap


@dataclasses.dataclass
class PandasUdfSpec:
    name: str
    fn: Callable
    return_type: T.DataType
    args: tuple  # Expression args


def _eval_udfs(df: pd.DataFrame, udfs: Sequence[PandasUdfSpec],
               input_schema: T.Schema) -> pd.DataFrame:
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval, nullable_dtype
    sem = PythonWorkerSemaphore.get()
    if C.get_active_conf()[C.PYTHON_DAEMON_ENABLED]:
        return _eval_udfs_daemon(df, udfs, input_schema, sem)
    out = df.copy()
    for u in udfs:
        args = [cpu_eval(a, df, input_schema) for a in u.args]
        with sem.held():
            res = u.fn(*args)
        if not isinstance(res, pd.Series):
            res = pd.Series(res, index=df.index)
        out[u.name] = res.astype(nullable_dtype(u.return_type))
    return out


def _eval_udfs_daemon(df: pd.DataFrame, udfs: Sequence[PandasUdfSpec],
                      input_schema: T.Schema, sem) -> pd.DataFrame:
    """Evaluate all UDFs in one out-of-process worker round trip
    (pyudf/daemon.py): the worker computes only the result columns; the
    driver merges them (smaller pipe payloads than echoing the input)."""
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval, nullable_dtype
    from spark_rapids_tpu.plan.pruning import expr_refs
    from spark_rapids_tpu.pyudf.daemon import PythonWorkerPool
    specs = [(u.name, u.fn, tuple(u.args)) for u in udfs]

    def worker_side(frame: pd.DataFrame) -> pd.DataFrame:
        res = {}
        for name, fn, args in specs:
            vals = fn(*[cpu_eval(a, frame, input_schema) for a in args])
            if not isinstance(vals, pd.Series):
                vals = pd.Series(vals, index=frame.index)
            res[name] = vals
        return pd.DataFrame(res, index=frame.index)

    # ship only the columns the UDF args reference — the pipe payload,
    # not the batch width, should bound the round-trip cost
    needed = set()
    for u in udfs:
        needed |= expr_refs(list(u.args))
    cols = [c for c in df.columns if c in needed]
    if cols:
        shipped = df[cols]
    else:
        # all-literal args: a 0-column frame loses its row count over
        # Arrow IPC — ship a 1-byte row-count carrier instead
        shipped = pd.DataFrame(
            {"__rows__": np.zeros(len(df), np.int8)}, index=df.index)
    pool = PythonWorkerPool.get()
    with sem.held():
        res = pool.run_udf(worker_side, shipped)
    out = df.copy()
    for u in udfs:
        out[u.name] = pd.Series(res[u.name].values, index=df.index).astype(
            nullable_dtype(u.return_type))
    return out


def _output_schema(child_schema: T.Schema,
                   udfs: Sequence[PandasUdfSpec]) -> T.Schema:
    return T.Schema(tuple(child_schema.fields) + tuple(
        T.Field(u.name, u.return_type) for u in udfs))


class CpuArrowEvalPython(CpuNode):
    """Planner-facing node (Spark's ArrowEvalPythonExec analog): appends
    one column per UDF to the child output."""

    def __init__(self, udfs: Sequence[PandasUdfSpec], child: CpuNode):
        super().__init__(child)
        self.udfs = list(udfs)
        self._schema = _output_schema(child.output_schema(), self.udfs)

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"CpuArrowEvalPython({[u.name for u in self.udfs]})"

    def execute(self):
        cs = self.child.output_schema()

        def run(it):
            for df in it:
                yield normalize_df(_eval_udfs(df, self.udfs, cs),
                                   self._schema)
        return [run(it) for it in self.child.execute()]


class ArrowEvalPythonExec(UnaryExecBase):
    """Columnar exec: one HBM->host->HBM round trip per batch, worker
    semaphore around the UDF, task semaphore around the re-upload
    (reference GpuArrowEvalPythonExec.doExecuteColumnar :376)."""

    def __init__(self, udfs: Sequence[PandasUdfSpec], child: TpuExec):
        super().__init__(child)
        self.udfs = list(udfs)
        self._schema = _output_schema(child.output_schema(), self.udfs)

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"ArrowEvalPythonExec({[u.name for u in self.udfs]})"

    def process_partition(self, batches: Iterator[ColumnarBatch]
                          ) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan.transitions import (
            batch_from_df, df_from_batch)
        cs = self.child.output_schema()
        for batch in batches:
            df = df_from_batch(batch)
            with self.metrics.timed():
                out = _eval_udfs(df, self.udfs, cs)
            TpuSemaphore.get().acquire_if_necessary()
            nb = batch_from_df(normalize_df(out, self._schema),
                               self._schema)
            self.update_output_metrics(nb)
            yield nb


class CpuMapInPandas(CpuNode):
    """mapInPandas: fn maps an iterator of DataFrames to an iterator of
    DataFrames with a declared schema."""

    def __init__(self, fn: Callable, schema: T.Schema, child: CpuNode):
        super().__init__(child)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self) -> str:
        return f"CpuMapInPandas({getattr(self.fn, '__name__', 'fn')})"

    def execute(self):
        def run(it):
            sem = PythonWorkerSemaphore.get()
            gen = self.fn(iter(it))
            while True:
                with sem.held():
                    try:
                        out = next(gen)
                    except StopIteration:
                        break
                yield normalize_df(out, self._schema)
        return [run(it) for it in self.child.execute()]


class MapInPandasExec(UnaryExecBase):
    def __init__(self, node: CpuMapInPandas, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return f"MapInPandasExec({getattr(self.node.fn, '__name__', 'fn')})"

    def process_partition(self, batches: Iterator[ColumnarBatch]
                          ) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan.transitions import (
            batch_from_df, df_from_batch)
        schema = self.node.output_schema()

        def host_frames():
            for b in batches:
                yield df_from_batch(b)
        sem = PythonWorkerSemaphore.get()
        gen = self.node.fn(host_frames())
        # hold the worker slot only while python code runs (each next()),
        # not across downstream device work on the yielded batch
        while True:
            with sem.held():
                try:
                    out = next(gen)
                except StopIteration:
                    break
            out = normalize_df(out, schema)
            TpuSemaphore.get().acquire_if_necessary()
            nb = batch_from_df(out, schema)
            self.update_output_metrics(nb)
            yield nb


# ---------------------------------------------------------------------------
# Grouped variants (reference GpuFlatMapGroupsInPandasExec,
# GpuAggregateInPandasExec, GpuWindowInPandasExec,
# GpuFlatMapCoGroupsInPandasExec — all disabled by default,
# GpuOverrides.scala:1821-1845).  Grouping collapses to one partition and
# groups host-side, the same complete-mode simplification CpuAggregate
# uses; Spark plans the key exchange that makes this correct, and these
# operators are host round-trips by nature.

def _group_frames(df: pd.DataFrame, keys: Sequence[str]):
    """Deterministic (key-sorted) groups, null keys grouped together like
    Spark; yields (key_tuple, group_df)."""
    if not len(df):
        return
    grouped = df.groupby(list(keys), dropna=False, sort=True)
    for key, g in grouped:
        if not isinstance(key, tuple):
            key = (key,)
        yield key, g


def _flat_map_groups(df: pd.DataFrame, keys: Sequence[str], fn,
                     schema: T.Schema) -> pd.DataFrame:
    from spark_rapids_tpu.plan.nodes import empty_df
    sem = PythonWorkerSemaphore.get()
    outs = []
    for _, g in _group_frames(df, keys):
        with sem.held():
            res = fn(g.reset_index(drop=True))
        outs.append(res)
    if not outs:
        return empty_df(schema)
    return pd.concat(outs, ignore_index=True)


def _aggregate_in_pandas(df: pd.DataFrame, keys: Sequence[str],
                         udfs: Sequence[PandasUdfSpec],
                         input_schema: T.Schema,
                         out_schema: T.Schema) -> pd.DataFrame:
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval
    from spark_rapids_tpu.plan.nodes import empty_df
    sem = PythonWorkerSemaphore.get()
    rows = []
    for key, g in _group_frames(df, keys):
        g = g.reset_index(drop=True)
        row = dict(zip(keys, key))
        for u in udfs:
            args = [cpu_eval(a, g, input_schema) for a in u.args]
            with sem.held():
                row[u.name] = u.fn(*args)
        rows.append(row)
    if not rows:
        return empty_df(out_schema)
    return pd.DataFrame(rows)


def _window_in_pandas(df: pd.DataFrame, part_keys: Sequence[str],
                      udfs: Sequence[PandasUdfSpec],
                      input_schema: T.Schema) -> pd.DataFrame:
    """Unbounded-partition-frame window UDFs (the frame shape the
    reference's GpuWindowInPandas supports): each UDF reduces the
    partition to a scalar broadcast to every row of the partition."""
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval
    sem = PythonWorkerSemaphore.get()
    out = df.copy()
    from spark_rapids_tpu.plan.cpu_eval import nullable_dtype
    for u in udfs:
        out[u.name] = pd.Series([None] * len(df), index=df.index,
                                dtype=nullable_dtype(u.return_type))
    for _, g in _group_frames(df, part_keys):
        for u in udfs:
            args = [cpu_eval(a, g.reset_index(drop=True), input_schema)
                    for a in u.args]
            with sem.held():
                val = u.fn(*args)
            out.loc[g.index, u.name] = val
    return out


def _cogroup_apply(ldf: pd.DataFrame, rdf: pd.DataFrame,
                   lkeys: Sequence[str], rkeys: Sequence[str], fn,
                   schema: T.Schema) -> pd.DataFrame:
    """flatMapCoGroupsInPandas: fn(left_group, right_group) per distinct
    key across BOTH sides (missing side -> empty frame)."""
    from spark_rapids_tpu.plan.nodes import empty_df
    sem = PythonWorkerSemaphore.get()

    def _canon(key: tuple) -> tuple:
        # null keys must pair across sides: NaN != NaN and None vs pd.NA
        # would otherwise split one logical null group into two
        return tuple(None if pd.isna(v) else v for v in key)

    lgroups = {_canon(k): g.reset_index(drop=True)
               for k, g in _group_frames(ldf, lkeys)}
    rgroups = {_canon(k): g.reset_index(drop=True)
               for k, g in _group_frames(rdf, rkeys)}
    all_keys = sorted(set(lgroups) | set(rgroups),
                      key=lambda t: tuple((v is None, v) for v in t))
    outs = []
    for k in all_keys:
        lg = lgroups.get(k)
        rg = rgroups.get(k)
        if lg is None:
            lg = ldf.iloc[0:0].reset_index(drop=True)
        if rg is None:
            rg = rdf.iloc[0:0].reset_index(drop=True)
        with sem.held():
            outs.append(fn(lg, rg))
    if not outs:
        return empty_df(schema)
    return pd.concat(outs, ignore_index=True)


class CpuFlatMapGroupsInPandas(CpuNode):
    """groupby(keys).applyInPandas(fn, schema)."""

    def __init__(self, keys: Sequence[str], fn: Callable,
                 schema: T.Schema, child: CpuNode):
        super().__init__(child)
        self.keys = list(keys)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return f"CpuFlatMapGroupsInPandas(keys={self.keys})"

    def execute(self):
        out = _flat_map_groups(_gather_cpu(self.child), self.keys,
                               self.fn, self._schema)
        return _single_partition(out, self._schema)


class CpuAggregateInPandas(CpuNode):
    """groupby(keys).agg(pandas_udf): one output row per group."""

    def __init__(self, keys: Sequence[str],
                 udfs: Sequence[PandasUdfSpec], child: CpuNode):
        super().__init__(child)
        self.keys = list(keys)
        self.udfs = list(udfs)
        cs = child.output_schema()
        fields = [cs.field(k) for k in self.keys]
        fields += [T.Field(u.name, u.return_type) for u in self.udfs]
        self._schema = T.Schema(tuple(fields))

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return (f"CpuAggregateInPandas(keys={self.keys}, "
                f"udfs={[u.name for u in self.udfs]})")

    def execute(self):
        out = _aggregate_in_pandas(_gather_cpu(self.child), self.keys,
                                   self.udfs, self.child.output_schema(),
                                   self._schema)
        return _single_partition(out, self._schema)


class CpuWindowInPandas(CpuNode):
    """Window pandas UDFs over an unbounded partition frame: child
    columns + one column per UDF."""

    def __init__(self, part_keys: Sequence[str],
                 udfs: Sequence[PandasUdfSpec], child: CpuNode):
        super().__init__(child)
        self.part_keys = list(part_keys)
        self.udfs = list(udfs)
        self._schema = _output_schema(child.output_schema(), self.udfs)

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return f"CpuWindowInPandas(partitionBy={self.part_keys})"

    def execute(self):
        out = _window_in_pandas(_gather_cpu(self.child), self.part_keys,
                                self.udfs, self.child.output_schema())
        return _single_partition(out, self._schema)


class CpuFlatMapCoGroupsInPandas(CpuNode):
    """cogroup(left, right).applyInPandas(fn, schema)."""

    def __init__(self, left_keys: Sequence[str],
                 right_keys: Sequence[str], fn: Callable,
                 schema: T.Schema, left: CpuNode, right: CpuNode):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return (f"CpuFlatMapCoGroupsInPandas({self.left_keys} | "
                f"{self.right_keys})")

    def execute(self):
        out = _cogroup_apply(
            _gather_cpu(self.children[0]), _gather_cpu(self.children[1]),
            self.left_keys, self.right_keys, self.fn, self._schema)
        return _single_partition(out, self._schema)


def _empty_of(schema: T.Schema) -> pd.DataFrame:
    from spark_rapids_tpu.plan.nodes import empty_df
    return empty_df(schema)


def _gather_cpu(node: CpuNode) -> pd.DataFrame:
    """Concatenate every partition of a CPU child into one frame (the
    grouped execs collapse to a single partition, like CpuAggregate)."""
    parts = [df for it in node.execute() for df in it]
    if not parts:
        return _empty_of(node.output_schema())
    return pd.concat(parts, ignore_index=True)


def _single_partition(out: pd.DataFrame, schema: T.Schema) -> list:
    return [iter([normalize_df(out, schema)])]


class _GatherAllPythonExec(TpuExec):
    """Base for grouped python execs: collapses child partitions to one
    host frame (the key exchange is planned upstream), applies a host
    transform, re-uploads under the task semaphore."""

    def output_partition_count(self) -> int:
        return 1

    def execute_partitions(self):
        return [self.execute_columnar()]

    def _gather(self, child: TpuExec) -> pd.DataFrame:
        from spark_rapids_tpu.plan.transitions import df_from_batch
        frames = []
        for it in child.execute_partitions():
            for b in it:
                frames.append(df_from_batch(b))
        if not frames:
            return _empty_of(child.output_schema())
        return pd.concat(frames, ignore_index=True)

    def _emit(self, out: pd.DataFrame):
        from spark_rapids_tpu.plan.transitions import batch_from_df
        schema = self.output_schema()
        TpuSemaphore.get().acquire_if_necessary()
        nb = batch_from_df(normalize_df(out, schema), schema)
        self.update_output_metrics(nb)
        yield nb


class FlatMapGroupsInPandasExec(_GatherAllPythonExec):
    def __init__(self, node: CpuFlatMapGroupsInPandas, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return f"FlatMapGroupsInPandasExec(keys={self.node.keys})"

    def execute_columnar(self):
        df = self._gather(self.child)
        with self.metrics.timed():
            out = _flat_map_groups(df, self.node.keys, self.node.fn,
                                   self.output_schema())
        yield from self._emit(out)


class AggregateInPandasExec(_GatherAllPythonExec):
    def __init__(self, node: CpuAggregateInPandas, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return f"AggregateInPandasExec(keys={self.node.keys})"

    def execute_columnar(self):
        df = self._gather(self.child)
        with self.metrics.timed():
            out = _aggregate_in_pandas(
                df, self.node.keys, self.node.udfs,
                self.child.output_schema(), self.output_schema())
        yield from self._emit(out)


class WindowInPandasExec(_GatherAllPythonExec):
    def __init__(self, node: CpuWindowInPandas, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return f"WindowInPandasExec(partitionBy={self.node.part_keys})"

    def execute_columnar(self):
        df = self._gather(self.child)
        with self.metrics.timed():
            out = _window_in_pandas(df, self.node.part_keys,
                                    self.node.udfs,
                                    self.child.output_schema())
        yield from self._emit(out)


class FlatMapCoGroupsInPandasExec(_GatherAllPythonExec):
    def __init__(self, node: CpuFlatMapCoGroupsInPandas,
                 left: TpuExec, right: TpuExec):
        super().__init__(left, right)
        self.node = node

    def output_schema(self) -> T.Schema:
        return self.node.output_schema()

    def describe(self) -> str:
        return (f"FlatMapCoGroupsInPandasExec({self.node.left_keys} | "
                f"{self.node.right_keys})")

    def execute_columnar(self):
        ldf = self._gather(self.children[0])
        rdf = self._gather(self.children[1])
        with self.metrics.timed():
            out = _cogroup_apply(ldf, rdf, self.node.left_keys,
                                 self.node.right_keys, self.node.fn,
                                 self.output_schema())
        yield from self._emit(out)
