"""Out-of-process python UDF worker (reference `python/rapids/worker.py`:
the forked pyspark worker that calls `initialize_gpu_mem()` from env
vars before touching the device).

TPU adaptation of the memory init: a TPU chip is single-process — a UDF
worker that imported jax with the default platform would steal the chip
from the executor.  So `initialize_tpu_env()` pins the worker to the CPU
platform unless `RAPIDS_PYTHON_ON_TPU=true` (the analog of the
reference's `RAPIDS_PYTHON_ENABLED` gate), and bounds worker host memory
via `RAPIDS_PYTHON_MEM_LIMIT_BYTES` (rlimit — the role the RMM pool
size plays in `worker.py:34-50`).

Wire protocol over stdin/stdout (all little-endian):
    request:  u32 fn_len | cloudpickled fn | u32 ipc_len | Arrow IPC
              stream of the argument batch
    response: u8 status (0=ok, 1=error) | u32 len | payload
              ok: Arrow IPC stream of the result batch
              error: utf-8 traceback
    shutdown: u32 fn_len == 0
"""
from __future__ import annotations

import io
import os
import struct
import sys


def initialize_tpu_env() -> None:
    on_tpu = os.environ.get("RAPIDS_PYTHON_ON_TPU",
                            "false").lower() == "true"
    if not on_tpu:
        # keep the single-process TPU chip with the executor.  The env
        # var alone is not enough: TPU platform plugins can win default
        # platform selection during `import jax`, so pin via jax.config
        # too (same workaround as __graft_entry__.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    limit = int(os.environ.get("RAPIDS_PYTHON_MEM_LIMIT_BYTES", "0"))
    if limit > 0:
        try:
            import resource
            resource.setrlimit(resource.RLIMIT_AS, (limit, limit))
        except (ImportError, ValueError, OSError):
            pass  # best-effort, like the reference's optional pool init


def _read_exact(stream, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            raise EOFError("worker stdin closed")
        buf += chunk
    return buf


def _read_frame(stream) -> bytes:
    (n,) = struct.unpack("<I", _read_exact(stream, 4))
    return _read_exact(stream, n) if n else b""


def _write_response(stream, status: int, payload: bytes) -> None:
    stream.write(struct.pack("<BI", status, len(payload)))
    stream.write(payload)
    stream.flush()


def _df_to_ipc(df) -> bytes:
    import pyarrow as pa
    table = pa.Table.from_pandas(df, preserve_index=False)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _ipc_to_df(blob: bytes):
    import pyarrow as pa
    with pa.ipc.open_stream(pa.BufferReader(blob)) as r:
        return r.read_all().to_pandas()


def main() -> int:
    initialize_tpu_env()
    import cloudpickle
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    # anything the UDF prints must not corrupt the protocol stream
    sys.stdout = sys.stderr
    while True:
        try:
            fn_blob = _read_frame(stdin)
        except EOFError:
            return 0
        if not fn_blob:
            return 0
        # the second frame is PROTOCOL, not UDF work: EOF/truncation
        # mid-request means the stream is desynced — exit, don't report
        # a UDF error and keep looping (ADVICE r1).  KeyboardInterrupt/
        # SystemExit likewise terminate the worker instead of being
        # swallowed as a UDF failure.
        try:
            ipc = _read_frame(stdin)
        except EOFError:
            return 1
        try:
            fn = cloudpickle.loads(fn_blob)
            out = fn(_ipc_to_df(ipc))
            _write_response(stdout, 0, _df_to_ipc(out))
        except Exception:  # noqa: BLE001 — ship traceback to driver
            import traceback
            _write_response(stdout, 1,
                            traceback.format_exc().encode("utf-8"))


if __name__ == "__main__":
    sys.exit(main() or 0)
