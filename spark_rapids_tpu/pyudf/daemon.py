"""Python worker daemon pool (reference `python/rapids/daemon.py`: the
forked pyspark daemon that spawns memory-initialized workers; here a
pool of long-lived subprocesses speaking the Arrow-IPC pipe protocol of
`pyudf/worker.py`).

Enabled by `spark.rapids.python.daemon.enabled` — the in-process path
(pyudf/exec.py default) stays the fast local mode; the daemon pool gives
UDFs process isolation (a crashing or leaking UDF cannot take down the
executor) at one Arrow round-trip of cost, exactly the trade the
reference makes by running UDFs in pyspark workers.  Worker count is
capped by `spark.rapids.python.concurrentPythonWorkers` like the
reference's PythonWorkerSemaphore.
"""
from __future__ import annotations

import os
import struct
import subprocess
import sys
import threading
from queue import Empty, Queue
from typing import Callable, Optional

import pandas as pd


class WorkerCrash(RuntimeError):
    """Raised when a UDF worker process dies mid-request."""


class PythonUdfError(RuntimeError):
    """The UDF raised inside a healthy worker; carries the worker
    traceback (pyspark's PythonException analog — the original exception
    type does not survive the process boundary there either)."""


class _Worker:
    def __init__(self, env: dict):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.pyudf.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)

    def run(self, fn_blob: bytes, df: pd.DataFrame) -> pd.DataFrame:
        from spark_rapids_tpu.pyudf.worker import (
            _df_to_ipc, _ipc_to_df, _read_exact)
        ipc = _df_to_ipc(df)
        try:
            stdin = self.proc.stdin
            stdin.write(struct.pack("<I", len(fn_blob)))
            stdin.write(fn_blob)
            stdin.write(struct.pack("<I", len(ipc)))
            stdin.write(ipc)
            stdin.flush()
            stdout = self.proc.stdout
            status, n = struct.unpack("<BI", _read_exact(stdout, 5))
            payload = _read_exact(stdout, n)
        except (EOFError, OSError) as e:
            raise WorkerCrash(
                f"python worker died (exit {self.proc.poll()})") from e
        if status != 0:
            raise PythonUdfError(
                "python UDF worker error:\n" +
                payload.decode("utf-8", "replace"))
        return _ipc_to_df(payload)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def close(self) -> None:
        try:
            if self.alive():
                self.proc.stdin.write(struct.pack("<I", 0))
                self.proc.stdin.flush()
                self.proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            self.proc.kill()


class PythonWorkerPool:
    """Checkout/checkin pool of `_Worker`s, lazily grown to the cap."""

    _instance: Optional["PythonWorkerPool"] = None
    _lock = threading.Lock()

    def __init__(self, max_workers: int, env_extra: Optional[dict] = None):
        self.max_workers = max(1, max_workers)
        self._idle: "Queue[_Worker]" = Queue()
        self._slots = threading.Semaphore(self.max_workers)
        self._closed = False
        # guards _closed vs the idle queue: a _checkin racing close()
        # must not park a live worker in an already-drained queue
        self._state_lock = threading.Lock()
        self._settings = (max_workers, tuple(sorted(
            (env_extra or {}).items())))
        self._env = dict(os.environ)
        self._env.update(env_extra or {})
        # the worker must import this package regardless of launch cwd
        import spark_rapids_tpu
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(spark_rapids_tpu.__file__)))
        prev = self._env.get("PYTHONPATH", "")
        self._env["PYTHONPATH"] = (root + os.pathsep + prev) if prev \
            else root
        if self._env.get("RAPIDS_PYTHON_ON_TPU", "false") != "true":
            # a worker must not initialize the single-process TPU chip.
            # JAX_PLATFORMS=cpu alone is not enough when a TPU platform
            # plugin site-dir sits on PYTHONPATH (plugin registration can
            # win default-platform selection), so strip plugin discovery
            # from the worker env entirely.
            self._env["JAX_PLATFORMS"] = "cpu"
            self._env["PYTHONPATH"] = os.pathsep.join(
                p for p in self._env["PYTHONPATH"].split(os.pathsep)
                if "axon_site" not in p)
            self._env.pop("TPU_LIBRARY_PATH", None)

    @classmethod
    def get(cls) -> "PythonWorkerPool":
        from spark_rapids_tpu import config as C
        conf = C.get_active_conf()
        n = int(conf[C.PYTHON_CONCURRENT_WORKERS]) or \
            (os.cpu_count() or 4)
        env_extra = _worker_env_from_conf(conf)
        settings = (n, tuple(sorted(env_extra.items())))
        with cls._lock:
            if cls._instance is None or \
                    cls._instance._settings != settings:
                # conf changed since the pool was built (worker cap,
                # memory limit, onTpu): rebuild with the new settings
                if cls._instance is not None:
                    cls._instance.close()
                cls._instance = cls(n, env_extra)
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
                cls._instance = None

    def _checkout(self) -> _Worker:
        # slot semaphore bounds live workers; every checkout MUST be
        # paired with _checkin (which releases the slot) so failures can
        # never strand capacity
        if self._closed:
            raise RuntimeError("PythonWorkerPool is closed")
        # bounded poll + cancel check: a checkout parked behind a full
        # pool must die with its query (PR 4 wait discipline), and a
        # pool closed mid-wait must not strand the waiter
        from spark_rapids_tpu.utils import watchdog as W
        while not self._slots.acquire(timeout=0.1):
            W.check_cancelled()
            if self._closed:
                raise RuntimeError("PythonWorkerPool is closed")
        try:
            while True:
                try:
                    w = self._idle.get_nowait()
                except Empty:
                    return _Worker(self._env)
                if w.alive():
                    return w
                w.close()  # reap a dead idle worker, spawn a fresh one
        except BaseException:
            self._slots.release()
            raise

    def _checkin(self, w: _Worker, reusable: bool) -> None:
        try:
            with self._state_lock:
                keep = reusable and w.alive() and not self._closed
                if keep:
                    self._idle.put(w)
            if not keep:
                w.close()
        finally:
            self._slots.release()

    def run_udf(self, fn: Callable, df: pd.DataFrame) -> pd.DataFrame:
        import cloudpickle
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        fn_blob = cloudpickle.dumps(fn)  # before checkout: a pickling
        # failure must not touch pool state
        w = self._checkout()
        reusable = False
        try:
            # a worker that never answers is the pyudf hang mode: the
            # heartbeat names it, the injector fakes it, and a
            # cancelled run closes the worker (not reusable) so the
            # pool slot comes back clean
            with W.heartbeat(f"pyudf:worker-pid{w.proc.pid}",
                             kind="task"), \
                    P.span(f"pyudf:pid{w.proc.pid}", cat=P.CAT_UDF):
                W.maybe_hang("pyudf")
                out = w.run(fn_blob, df)
            reusable = True
            return out
        except PythonUdfError:
            # the UDF raised inside a healthy worker — keep the process
            reusable = True
            raise
        except WorkerCrash as e:
            P.event(P.EV_UDF_WORKER_CRASH, pid=w.proc.pid,
                    error=str(e)[:200])
            raise
        finally:
            self._checkin(w, reusable)

    def close(self) -> None:
        # checked-out workers are closed by their _checkin (which sees
        # _closed under the same lock); only the idle ones drain here
        with self._state_lock:
            self._closed = True
            drained = []
            while True:
                try:
                    drained.append(self._idle.get_nowait())
                except Empty:
                    break
        for w in drained:
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass


def _worker_env_from_conf(conf) -> dict:
    """Conf -> worker env (reference GpuPythonHelper passing RMM env vars
    to the daemon; PythonConfEntries)."""
    from spark_rapids_tpu import config as C
    env = {}
    env["RAPIDS_PYTHON_ON_TPU"] = str(bool(conf[C.PYTHON_ON_TPU])).lower()
    limit = int(conf[C.PYTHON_MEM_LIMIT] or 0)
    if limit:
        env["RAPIDS_PYTHON_MEM_LIMIT_BYTES"] = str(limit)
    return env
