"""Python/Pandas UDF execution (SURVEY.md §2.12).

Reference: `org/apache/spark/sql/rapids/execution/python/` —
`GpuArrowEvalPythonExec` ships batches to a python worker over Arrow IPC
and reads results back as batches, `GpuMapInPandas` maps whole frames,
`PythonWorkerSemaphore` caps concurrent workers
(`python/PythonWorkerSemaphore.scala:17-40`).

TPU shape: the engine is already host-driven Python, so the "worker" is
in-process — the Arrow IPC hop collapses to a zero-copy
`ColumnarBatch.to_arrow()` view.  The exec contract is identical: batches
leave HBM only at this operator, the UDF sees pandas objects, results are
re-uploaded under the task semaphore, and the worker semaphore still caps
concurrency (vectorized UDFs can be memory-hungry).  These execs are
disabled by default like the reference (GpuOverrides.scala:1821-1845).
"""
from spark_rapids_tpu.pyudf.exec import (  # noqa: F401
    AggregateInPandasExec, ArrowEvalPythonExec, CpuAggregateInPandas,
    CpuArrowEvalPython, CpuFlatMapCoGroupsInPandas,
    CpuFlatMapGroupsInPandas, CpuMapInPandas, CpuWindowInPandas,
    FlatMapCoGroupsInPandasExec, FlatMapGroupsInPandasExec,
    MapInPandasExec, WindowInPandasExec, pandas_udf)
from spark_rapids_tpu.pyudf.semaphore import (  # noqa: F401
    PythonWorkerSemaphore)
