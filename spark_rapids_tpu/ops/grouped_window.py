"""Windowed grouped-sum over key-SORTED rows — the high-cardinality
grouper (any group count, no dictionary range budget).

Reference parallel: the hash-groupby role cuDF plays for
`GpuHashAggregateExec` (`sql-plugin/.../aggregate.scala:312`) at high
cardinality.  TPU redesign: scatter-free.  With rows sorted by group
key, the group index `gid` is non-decreasing, so a block of R
consecutive rows spans at most R distinct groups and its one-hot
accumulation fits a 2R-wide window of 128-aligned group slabs:

  1. per block b (Pallas, grid over blocks): local table
     [M, 2W] = measures[M, R] @ onehot(gid - slab_base_b)[R, 2W]
     — the one-hot never materializes in HBM and the MXU does the
     accumulation (the plain one-hot matmul is O(rows x groups) and
     infeasible past ~32K groups; this is O(rows x 2R) regardless
     of G).
  2. merge (XLA): slab one-hot [S, B] @ locals[B, M*2W] — B is tiny
     (rows/R), then fold the 2W overlap into [G_pad, M].

No jnp.nonzero / masked_positions / per-measure segmented scans —
the per-group sums land already compact.  Accumulation is f32 (MXU);
callers gate exactness the dict lane's way (|v| certificate for
integers, variableFloatAgg for floats) and extract group keys as
11-bit f32 limb measures (exact by construction: one first-row hit
per group).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_rapids_tpu.ops.pallas_kernels import _LANES, _on_tpu, _x64_off

#: rows per block == slab width.  256 keeps the one-hot [R, 2R] at
#: 256x512 (two MXU tiles) and the locals array at cap/R x M x 2R f32.
WINDOW_ROWS = 256


def _window_block_kernel(g0_ref, gid_ref, *val_and_out, n_measures: int,
                         block_rows: int):
    """One [M, 2W] local table per block: measures @ one-hot(gid-g0)."""
    out_ref = val_and_out[n_measures]
    i = pl.program_id(0)
    w2 = 2 * block_rows
    gid = gid_ref[:]                       # [1, R] lane-major
    rel = gid - g0_ref[i]
    onehot = (jax.lax.broadcast_in_dim(rel, (w2, block_rows), (0, 1)) ==
              jax.lax.broadcasted_iota(jnp.int32, (w2, block_rows), 0)
              ).astype(jnp.float32)        # [2W, R]
    rows = [v[:] for v in val_and_out[:n_measures]]
    stacked = jnp.concatenate(rows, axis=0)  # [M, R]
    # HIGHEST precision: the default TPU matmul rounds f32 inputs to
    # bf16, which silently corrupts measure values (and the exactness
    # certificate's premise); the one-hot matmul is tiny, the 6-pass
    # f32 cost is noise.
    local = jax.lax.dot_general(
        stacked, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)  # [M, 2W]
    mp = out_ref.shape[1]
    out_ref[0] = jnp.pad(local, ((0, mp - n_measures), (0, 0)))


@functools.partial(jax.jit, static_argnames=("out_cap", "capacity",
                                             "interpret",
                                             "interpret_kernel"))
def window_group_sums(gid, vals, *, out_cap: int, capacity: int,
                      interpret: bool = False,
                      interpret_kernel: bool = False):
    """Per-group f32 sums of `vals` (tuple of [capacity] arrays, already
    zeroed on invalid rows) over non-decreasing group ids `gid` (int32,
    rows past the last group may repeat its id).  Returns
    [out_cap, n_measures] f32; groups at or past out_cap are dropped —
    callers pair this with a `num_groups > out_cap` deferred check.

    `interpret=True` (non-TPU backends) computes the same f32 result
    with plain segment sums — running the Mosaic block loop under the
    Pallas interpreter is minutes-per-call at engine widths."""
    n_measures = len(vals)
    if n_measures == 0:
        return jnp.zeros((out_cap, 0), jnp.float32)
    if interpret and not interpret_kernel:
        clamped = jnp.minimum(gid, out_cap)
        return jnp.stack(
            [jax.ops.segment_sum(v.astype(jnp.float32), clamped,
                                 num_segments=out_cap + 1)[:out_cap]
             for v in vals], axis=1)

    r = math.gcd(capacity, WINDOW_ROWS)
    w2 = 2 * r
    n_blocks = capacity // r
    m_pad = max(8, ((n_measures + 7) // 8) * 8)
    s_pad = -(-out_cap // r)                  # slabs of width R

    gid = gid.astype(jnp.int32)
    # slab base per block: 128-aligned... R-aligned floor of the block's
    # FIRST gid; the block's rows then live in [base, base + 2R) because
    # gid grows by at most 1 per row
    gid_first = gid[::r]
    g0 = (gid_first // r) * r
    ins = [gid.reshape(1, -1)] + [v.astype(jnp.float32).reshape(1, -1)
                                  for v in vals]
    block_in = pl.BlockSpec((1, r), lambda i: (0, i))
    with _x64_off():
        locals_ = pl.pallas_call(
            functools.partial(_window_block_kernel,
                              n_measures=n_measures, block_rows=r),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [block_in] * (1 + n_measures),
            out_specs=pl.BlockSpec((1, m_pad, w2), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((n_blocks, m_pad, w2),
                                           jnp.float32),
            compiler_params=None if interpret_kernel
            else pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
                vmem_limit_bytes=96 * 1024 * 1024),
            interpret=interpret_kernel,
        )(g0.astype(jnp.int32), *ins)

    # merge across blocks: slab one-hot [S, B] @ locals [B, M*2W].  B is
    # capacity/R (tiny), so this matmul is ~free on the MXU and replaces
    # a serialized scatter-add.
    slab = g0 // r                              # [B]
    onehot = (slab[None, :] == jnp.arange(s_pad, dtype=jnp.int32)[:, None]
              ).astype(jnp.float32)
    merged = jnp.einsum("sb,bmw->smw", onehot,
                        locals_.astype(jnp.float32),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
    # fold the 2W overlap: slab s's second half lands on slab s+1
    first, second = merged[:, :, :r], merged[:, :, r:]
    carry = jnp.concatenate(
        [jnp.zeros((1,) + second.shape[1:], second.dtype), second[:-1]],
        axis=0)
    table = first + carry                       # [S, M_pad, R]
    out = table.transpose(0, 2, 1).reshape(s_pad * r, m_pad)
    return out[:out_cap, :n_measures]


def use_window_grouper() -> bool:
    return _on_tpu()
