"""Pallas TPU kernels for the engine's hot ops.

Reference parallel: the role cuDF's fused groupby-aggregate kernels play
under `GpuHashAggregateExec` (`aggregate.scala:312`): a
scan→filter→project→group-reduce pipeline as one explicit pass over
HBM, the group table living in VMEM the whole time.

MEASURED RESULT (v5e, 16.8M rows, pipelined dispatch): the XLA one-hot
einsum kernel (models/tpch.build_q1_kernel) runs ~850 Mrows/s; this
Pallas VPU formulation runs ~150 Mrows/s.  The 8-group x 6-measure
masked reductions re-read each VMEM block 48 times at VPU rate, while
XLA's formulation puts the same 48 MACs/row on the MXU systolic array
and fuses the elementwise prologue into the matmul's operand reads.
This is the pallas_guide's own lesson — don't hand-schedule what the
compiler already fuses — so the XLA kernel stays the default and this
kernel is the conf-gated alternative
(`spark.rapids.tpu.pallas.q1.enabled`) and the template for ops where
XLA *doesn't* fuse (multi-pass layouts, future scatter-free radix
partitioning).

Kernels run in interpret mode off-TPU, so the CPU test suite exercises
the same code path the chip runs (`pl.pallas_call(..., interpret=True)`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 65536          # rows per grid step: (512, 128) f32 tiles
_LANES = 128


def _x64_off():
    """Context disabling x64 during kernel tracing.  jax 0.9 has no
    public context manager for this; prefer one if the installed version
    grows it, fall back to the private State object, and degrade to a
    no-op (interpret mode still works; mosaic compiles may not)."""
    try:
        from jax.experimental import enable_x64  # public, newer jax
        return enable_x64(False)
    except ImportError:
        pass
    try:
        from jax._src.config import enable_x64
        return enable_x64(False)
    except ImportError:
        import contextlib
        return contextlib.nullcontext()


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


def _q1_block_kernel(nrows_ref, flag_ref, status_ref, qty_ref, price_ref,
                     disc_ref, tax_ref, ship_ref, out_ref, *, cutoff: int):
    """One 65536-row block: filter + project + 8-group x 6-measure sums.

    Output block (1, 8, 128): [0, g, j] holds measure j's sum for group
    g (lanes 6..127 zero).  Scalars land via masked writes on an (8,128)
    iota grid — no scalar stores, mosaic-friendly."""
    i = pl.program_id(0)
    flag = flag_ref[:]
    status = status_ref[:]
    qty = qty_ref[:]
    price = price_ref[:]
    disc = disc_ref[:]
    tax = tax_ref[:]
    ship = ship_ref[:]
    nrows = nrows_ref[0]

    shape = flag.shape
    base = i * shape[0] * _LANES
    ridx = (base
            + jax.lax.broadcasted_iota(jnp.int32, shape, 0) * _LANES
            + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
    keep = (ridx < nrows) & (ship <= jnp.int32(cutoff))
    disc_price = price * (jnp.float32(1.0) - disc)
    charge = disc_price * (jnp.float32(1.0) + tax)
    gid = jnp.where(keep, flag * jnp.int32(2) + status, jnp.int32(7))
    measures = (qty, price, disc_price, charge, disc,
                jnp.ones_like(qty))

    gi = jax.lax.broadcasted_iota(jnp.int32, (8, _LANES), 0)
    ji = jax.lax.broadcasted_iota(jnp.int32, (8, _LANES), 1)
    acc = jnp.zeros((8, _LANES), jnp.float32)
    for g in range(8):
        in_g = keep & (gid == g)
        for j, v in enumerate(measures):
            # jnp.where, not multiply: NaN in a filtered row must not
            # poison the sum
            s = jnp.sum(jnp.where(in_g, v, jnp.float32(0)))
            acc = jnp.where((gi == g) & (ji == j), s, acc)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("capacity", "cutoff",
                                             "interpret"))
def q1_fused_pallas(flag, status, qty, price, disc, tax, ship,
                    num_rows, *, capacity: int, cutoff: int,
                    interpret: bool = False):
    """TPC-H Q1 scan→filter→project→group-reduce as one Pallas pass.

    Returns the (8, 6) float64 group table (per-block f32 partials are
    combined in f64 exactly like the XLA kernel, so millions of rows do
    not lose the accumulator's low bits)."""
    if capacity < _LANES:
        # tiny capacity buckets (32, 64) pad up to one full lane row;
        # the num_rows mask keeps the padding out of every sum
        pad = _LANES - capacity
        flag, status, ship = (jnp.pad(x, (0, pad))
                              for x in (flag, status, ship))
        qty, price, disc, tax = (jnp.pad(x, (0, pad))
                                 for x in (qty, price, disc, tax))
        capacity = _LANES
    block_rows = min(BLOCK_ROWS, capacity)
    assert capacity % block_rows == 0 and block_rows % _LANES == 0, \
        capacity
    sublanes = block_rows // _LANES
    n_blocks = capacity // block_rows

    def shape2d(x, dtype):
        return x.astype(dtype).reshape(n_blocks * sublanes, _LANES)

    ins = (shape2d(flag, jnp.int32), shape2d(status, jnp.int32),
           shape2d(qty, jnp.float32), shape2d(price, jnp.float32),
           shape2d(disc, jnp.float32), shape2d(tax, jnp.float32),
           shape2d(ship, jnp.int32))
    nrows = jnp.asarray(num_rows, jnp.int32).reshape(1)
    block_in = pl.BlockSpec((sublanes, _LANES), lambda i: (i, 0))
    # the engine enables x64 globally (Spark parity), but mosaic cannot
    # legalize the i64 index-map constants x64 promotion creates — trace
    # the kernel with x64 off (every dtype in it is explicit i32/f32)
    with _x64_off():
        partials = pl.pallas_call(
            functools.partial(_q1_block_kernel, cutoff=cutoff),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [block_in] * 7,
            out_specs=pl.BlockSpec((8, _LANES), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_blocks * 8, _LANES),
                                           jnp.float32),
            compiler_params=None if interpret else pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(nrows, *ins)
    # f64 cross-block combine (same numerics as the XLA kernel)
    return partials.reshape(n_blocks, 8, _LANES)[:, :, :6].astype(
        jnp.float64).sum(axis=0)


def build_q1_kernel_pallas(capacity: int, cutoff: int,
                           interpret: bool | None = None):
    """Drop-in alternative to models.tpch.build_q1_kernel with the same
    output contract, backed by the fused Pallas pass."""
    if interpret is None:
        interpret = not _on_tpu()

    def q1_step(flag, status, qty, extprice, disc, tax, shipdate,
                num_rows):
        table = q1_fused_pallas(
            flag, status, qty, extprice, disc, tax, shipdate, num_rows,
            capacity=capacity, cutoff=cutoff, interpret=interpret)
        table = table.T  # (6 measures, 8 groups) like the XLA kernel
        g = jnp.arange(8)
        cnt = table[5].astype(jnp.int32)
        return (g // 2, g % 2, table[0], table[1], table[2], table[3],
                table[4], cnt)

    return q1_step
