"""Pallas TPU kernels for the engine's hot ops.

Reference parallel: the role cuDF's fused groupby-aggregate kernels play
under `GpuHashAggregateExec` (`aggregate.scala:312`): a
scan→filter→project→group-reduce pipeline as one explicit pass over
HBM, the group table living in VMEM the whole time.

MEASURED RESULT (v5e via axon, 8 x 16.8M rows stacked in ONE dispatch,
round 2): this Pallas formulation runs ~2060 Mrows/s (58 GB/s effective,
65 ms/dispatch) vs the XLA one-hot einsum's 689 Mrows/s (195 ms) — a
3.0x win, so `spark.rapids.tpu.pallas.q1Fused.enabled` DEFAULTS ON and
this kernel is the engine's stacked-Q1 step.  Single-batch dispatches
stay on the XLA kernel (dispatch-overhead-bound: 9.6 ms XLA vs 13.0 ms
Pallas per 16.8M-row dispatch through the tunnel).  Why it wins: XLA must materialize
the [rows, 6] values and [rows, 8] one-hot einsum operands in HBM
(~19 GB of traffic for 3.8 GB of input, measured), while this kernel
keeps them in VMEM and touches each input byte once.  Round 1's version
lost (150 Mrows/s) because it did 48 CROSS-LANE reductions per block;
the fix is lane-wise partials in-kernel (sublane-axis sums only, at
full VPU width) with one deferred f64 cross-lane combine outside.
Platform note: a pure 7-column fused `.sum()` measures ~125 GB/s on
this tunnel-attached v5e — the practical bandwidth ceiling this kernel
is 48% of (nominal HBM is 819 GB/s).

Kernels run in interpret mode off-TPU, so the CPU test suite exercises
the same code path the chip runs (`pl.pallas_call(..., interpret=True)`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 1 << 18        # rows per grid step: 7 inputs x 1MB x 2
                            # (double-buffer) = 14MB, inside the
                            # 16MB scoped-vmem AOT limit; measured
                            # 2056 Mrows/s vs 2131 at 512K (OOMs)
_LANES = 128


def _x64_off():
    """Context disabling x64 during kernel tracing.  jax 0.9 has no
    public context manager for this; prefer one if the installed version
    grows it, fall back to the private State object, and degrade to a
    no-op (interpret mode still works; mosaic compiles may not)."""
    try:
        from jax.experimental import enable_x64  # public, newer jax
        return enable_x64(False)
    except ImportError:
        pass
    try:
        from jax._src.config import enable_x64
        return enable_x64(False)
    except ImportError:
        import contextlib
        return contextlib.nullcontext()


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


def _q1_block_kernel(nrows_ref, flag_ref, status_ref, qty_ref, price_ref,
                     disc_ref, tax_ref, ship_ref, out_ref, *,
                     cutoff: int, block_rows: int, batch_rows: int):
    """One block: filter + project + group x measure LANE-WISE sums.

    Output block (48, 128) — 8 group slots x 6 measures, 8-aligned for
    the sublane tiling; rows for groups 6-7 are zero padding.  Row
    g*6+j holds measure j's per-lane partial for group g.  Only the sublane axis is reduced in-kernel — the VPU
    does that at full lane width; the 128-lane cross reduction (and the
    f64 combine) happens once outside.  Round 1 reduced all the way to
    scalars per block (48 cross-lane reductions) and ran 5x slower than
    XLA; this formulation is the one that beats it.

    `batch_rows` supports stacked multi-batch dispatch: rows belong to
    batch ridx // batch_rows, each with its own num_rows in the SMEM
    vector (block_rows must divide batch_rows so a block never straddles
    batches)."""
    i = pl.program_id(0)
    flag = flag_ref[:]
    status = status_ref[:]
    qty = qty_ref[:]
    price = price_ref[:]
    disc = disc_ref[:]
    tax = tax_ref[:]
    ship = ship_ref[:]
    batch = (i * jnp.int32(block_rows)) // jnp.int32(batch_rows)
    nrows = nrows_ref[batch]
    local_base = (i * jnp.int32(block_rows)) % jnp.int32(batch_rows)

    shape = flag.shape
    ridx = (local_base
            + jax.lax.broadcasted_iota(jnp.int32, shape, 0) * _LANES
            + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
    keep = (ridx < nrows) & (ship <= jnp.int32(cutoff))
    disc_price = price * (jnp.float32(1.0) - disc)
    charge = disc_price * (jnp.float32(1.0) + tax)
    gid = jnp.where(keep, flag * jnp.int32(2) + status, jnp.int32(7))
    measures = (qty, price, disc_price, charge, disc, None)

    zeros = jnp.zeros((_LANES,), jnp.float32)
    for g in range(8):
        if g >= 6:
            # padding rows: blocks must be written whole (48 = 8-aligned)
            for j in range(6):
                out_ref[g * 6 + j, :] = zeros
            continue
        in_g = gid == g
        for j, v in enumerate(measures):
            # jnp.where, not multiply: NaN in a filtered row must not
            # poison the sum; counts reuse the mask itself
            vm = (in_g.astype(jnp.float32) if v is None
                  else jnp.where(in_g, v, jnp.float32(0)))
            out_ref[g * 6 + j, :] = jnp.sum(vm, axis=0)


@functools.partial(jax.jit, static_argnames=("capacity", "cutoff",
                                             "batch_rows", "interpret"))
def q1_fused_pallas(flag, status, qty, price, disc, tax, ship,
                    num_rows, *, capacity: int, cutoff: int,
                    batch_rows: int = 0, interpret: bool = False):
    """TPC-H Q1 scan→filter→project→group-reduce as one Pallas pass.

    `batch_rows` > 0 runs the STACKED multi-batch form: the columns hold
    B = capacity // batch_rows batches back to back and `num_rows` is a
    (B,) vector — one dispatch aggregates them all (the device-side
    batch loop that amortizes per-dispatch runtime overhead).

    Returns the (8, 6) float64 group table (per-block f32 lane partials
    are combined in f64, so millions of rows do not lose the
    accumulator's low bits)."""
    if capacity < _LANES:
        # tiny capacity buckets (32, 64) pad up to one full lane row;
        # the num_rows mask keeps the padding out of every sum
        pad = _LANES - capacity
        flag, status, ship = (jnp.pad(x, (0, pad))
                              for x in (flag, status, ship))
        qty, price, disc, tax = (jnp.pad(x, (0, pad))
                                 for x in (qty, price, disc, tax))
        capacity = _LANES
    if batch_rows <= 0:
        batch_rows = capacity
    block_rows = min(BLOCK_ROWS, batch_rows)
    assert capacity % batch_rows == 0 and \
        batch_rows % block_rows == 0 and block_rows % _LANES == 0, \
        (capacity, batch_rows)
    # mosaic block constraint: unless the block covers the whole array,
    # its sublane count must be a multiple of 8 (1024 rows); callers
    # (build_q1_fused_kernel) route smaller stacked batches to the XLA
    # fallback instead
    if capacity != block_rows:
        assert block_rows % (8 * _LANES) == 0, (
            f"stacked batch_rows={batch_rows} needs a multiple of 1024 "
            "rows per block for mosaic tiling")
    sublanes = block_rows // _LANES
    n_blocks = capacity // block_rows

    def shape2d(x, dtype):
        return x.astype(dtype).reshape(n_blocks * sublanes, _LANES)

    ins = (shape2d(flag, jnp.int32), shape2d(status, jnp.int32),
           shape2d(qty, jnp.float32), shape2d(price, jnp.float32),
           shape2d(disc, jnp.float32), shape2d(tax, jnp.float32),
           shape2d(ship, jnp.int32))
    nrows = jnp.asarray(num_rows, jnp.int32).reshape(-1)
    block_in = pl.BlockSpec((sublanes, _LANES), lambda i: (i, 0))
    # the engine enables x64 globally (Spark parity), but mosaic cannot
    # legalize the i64 index-map constants x64 promotion creates — trace
    # the kernel with x64 off (every dtype in it is explicit i32/f32)
    with _x64_off():
        partials = pl.pallas_call(
            functools.partial(_q1_block_kernel, cutoff=cutoff,
                              block_rows=block_rows,
                              batch_rows=batch_rows),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [block_in] * 7,
            out_specs=pl.BlockSpec((48, _LANES), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_blocks * 48, _LANES),
                                           jnp.float32),
            compiler_params=None if interpret else pltpu.CompilerParams(
                dimension_semantics=("parallel",),
                # 7 double-buffered 1MB input blocks + temporaries blow
                # the default 16MB scoped-vmem budget; v5e has 128MB
                vmem_limit_bytes=64 * 1024 * 1024),
            interpret=interpret,
        )(nrows, *ins)
    # f64 cross-block + cross-lane combine (same numerics as XLA kernel)
    return partials.reshape(n_blocks, 8, 6, _LANES).astype(
        jnp.float64).sum(axis=(0, 3))


def build_q1_kernel_pallas(capacity: int, cutoff: int,
                           interpret: bool | None = None):
    """Drop-in alternative to models.tpch.build_q1_kernel with the same
    output contract, backed by the fused Pallas pass."""
    if interpret is None:
        interpret = not _on_tpu()

    def q1_step(flag, status, qty, extprice, disc, tax, shipdate,
                num_rows):
        table = q1_fused_pallas(
            flag, status, qty, extprice, disc, tax, shipdate, num_rows,
            capacity=capacity, cutoff=cutoff, interpret=interpret)
        table = table.T  # (6 measures, 8 groups) like the XLA kernel
        g = jnp.arange(8)
        cnt = table[5].astype(jnp.int32)
        return (g // 2, g % 2, table[0], table[1], table[2], table[3],
                table[4], cnt)

    return q1_step


# ---------------------------------------------------------------------------
# Grouped sum/count for DICTIONARY-ENCODED keys (key ids in [0, n_groups)).
#
# The engine's general hash aggregate sorts rows by key (packed-word
# lexsort) because XLA:TPU scatter serializes — but sorting is the
# expensive part (bitonic, O(n log^2 n)).  When the key domain is a known
# dense dictionary (categoricals, already-dictionary-encoded columns, the
# BASELINE milestone-2 shape), grouping is a single HBM pass: per block,
# build the [rows, groups] one-hot in VMEM and matmul it against the
# measures on the MXU, accumulating the [groups, measures] table across
# sequential grid steps.  No sort, no scatter, input bytes touched once.
#
# MEASURED (v5e via axon, 4.2M rows x 2 f32 measures, 1024 groups):
# ~99 Mrows/s — ~230x the engine's sort-based aggregate path on the
# same shape (bench.py groupby_sf1: 0.43 Mrows/s) and ~4.6x single-
# thread pandas.  Sums carry f32-accumulator tolerance (~1e-3 relative
# over millions of rows) — the variableFloatAgg semantics Spark already
# gates float sums behind.  Planner integration (dictionary-encoding
# detection / stats-bounded key domains) is the round-3 follow-up;
# until then the kernel is the ops-level building block the bench
# exercises (metric groupby_dict_kernel).

_GROUP_BLOCK_ROWS = 1 << 13   # one-hot VMEM budget caps rows x groups


def _grouped_sum_kernel(nrows_ref, keys_ref, *val_and_out,
                        n_groups: int, n_measures: int, block_rows: int):
    """Blocks are LANE-MAJOR [1, block_rows]: the one-hot builds by
    broadcasting the key lane-vector across G sublanes (the native
    direction — sublane-flatten reshapes don't lower in mosaic), and one
    [G, R] x [M+1, R]^T matmul per block feeds the MXU."""
    vals = val_and_out[:n_measures]
    out_ref = val_and_out[n_measures]
    cnt_ref = val_and_out[n_measures + 1]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    keys = keys_ref[:]                      # [1, R]
    base = i * jnp.int32(block_rows)
    ridx = base + jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)
    valid = ridx < nrows_ref[0]
    k = jnp.where(valid, keys, jnp.int32(n_groups))
    kb = jax.lax.broadcast_in_dim(k, (n_groups, keys.shape[1]), (0, 1))
    onehot = (kb == jax.lax.broadcasted_iota(
        jnp.int32, (n_groups, keys.shape[1]), 0)).astype(jnp.float32)
    rows = [jnp.where(valid, v[:], jnp.float32(0)) for v in vals]
    rows.append(valid.astype(jnp.float32))
    stacked = jnp.concatenate(rows, axis=0)  # [M+1, R] lane-major
    table = jax.lax.dot_general(
        onehot, stacked, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # [G, M+1]
    gp, mp = out_ref.shape
    table = jnp.pad(table, ((0, gp - n_groups), (0, mp - n_measures - 1)))
    out_ref[:] = out_ref[:] + table
    # counts accumulate in INT32: a per-block count <= block_rows is
    # exact in f32, but cross-block f32 accumulation would silently
    # saturate past 2^24 rows per group
    cnt = table[:, n_measures].astype(jnp.int32)
    cnt_ref[:] = cnt_ref[:] + jnp.pad(
        cnt[:, None], ((0, 0), (0, cnt_ref.shape[1] - 1)))


@functools.partial(jax.jit, static_argnames=("n_groups", "capacity",
                                             "interpret",
                                             "interpret_kernel"))
def grouped_sum_pallas(keys, vals, num_rows, *, n_groups: int,
                       capacity: int, interpret: bool = False,
                       interpret_kernel: bool = False):
    """sums/counts per dictionary key id: keys int32 in [0, n_groups),
    vals a tuple of f32 arrays.  Returns ([n_groups, n_measures] f64
    sums, [n_groups] int32 counts).  Rows with out-of-range keys are
    COUNTED INVALID (masked) — callers guarantee the dictionary.

    `interpret=True` (any non-TPU backend) computes the same masked
    f32-accumulated result with plain segment sums instead of running
    the Mosaic kernel under the Pallas INTERPRETER — interpretation
    executes the block loop in Python and made the virtual-CPU test
    suite minutes slower per workload query once integral sums
    started qualifying for this lane.  `interpret_kernel=True` still
    runs the Mosaic kernel under the interpreter;
    `tests/test_pallas.py` compares it against this fallback so the
    two lanes cannot silently diverge."""
    import math
    assert capacity % _LANES == 0
    if interpret and not interpret_kernel:
        rows_ok = jnp.arange(capacity) < jnp.asarray(num_rows, jnp.int32)
        k = jnp.where(rows_ok, keys, n_groups)
        in_range = (k >= 0) & (k < n_groups)
        seg = jnp.where(in_range, k, n_groups)
        counts = jnp.bincount(seg, length=n_groups + 1)[:n_groups] \
            .astype(jnp.int32)
        sums = jnp.stack(
            [jax.ops.segment_sum(
                jnp.where(in_range, v.astype(jnp.float32), 0), seg,
                num_segments=n_groups + 1)[:n_groups]
             for v in vals], axis=1) if vals else \
            jnp.zeros((n_groups, 0), jnp.float32)
        return sums.astype(jnp.float64), counts
    n_measures = len(vals)
    g_budget_rows = (48 * 1024 * 1024 // (4 * max(n_groups, 1))
                     ) // _LANES * _LANES
    block_rows = max(_LANES, min(_GROUP_BLOCK_ROWS, capacity,
                                 max(g_budget_rows, _LANES)))
    # block must divide capacity WITHOUT abandoning the VMEM budget:
    # gcd keeps a 128-multiple divisor <= the budgeted size
    block_rows = max(_LANES, math.gcd(capacity, block_rows))
    n_blocks = capacity // block_rows
    g_pad = ((n_groups + 7) // 8) * 8
    m_pad = ((n_measures + 1 + _LANES - 1) // _LANES) * _LANES

    def lane_major(x, dtype):
        return x.astype(dtype).reshape(1, -1)

    ins = [lane_major(keys, jnp.int32)] + [lane_major(v, jnp.float32)
                                           for v in vals]
    nrows = jnp.asarray(num_rows, jnp.int32).reshape(1)
    block_in = pl.BlockSpec((1, block_rows), lambda i: (0, i))
    with _x64_off():
        table, cnt_tab = pl.pallas_call(
            functools.partial(_grouped_sum_kernel, n_groups=n_groups,
                              n_measures=n_measures,
                              block_rows=block_rows),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [block_in] * (1 + n_measures),
            out_specs=[pl.BlockSpec((g_pad, m_pad), lambda i: (0, 0)),
                       pl.BlockSpec((g_pad, _LANES), lambda i: (0, 0))],
            out_shape=[
                jax.ShapeDtypeStruct((g_pad, m_pad), jnp.float32),
                jax.ShapeDtypeStruct((g_pad, _LANES), jnp.int32)],
            compiler_params=None if (interpret or interpret_kernel)
            else pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
                vmem_limit_bytes=96 * 1024 * 1024),
            interpret=interpret or interpret_kernel,
        )(nrows, *ins)
    sums = table[:n_groups, :n_measures].astype(jnp.float64)
    counts = cnt_tab[:n_groups, 0]
    return sums, counts
