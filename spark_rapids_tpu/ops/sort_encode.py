"""Sortable key encoding + multi-key argsort (TPU groupby/sort substrate).

The reference leans on cuDF's `Table.orderBy` / groupby radix machinery;
on TPU the idiomatic equivalent is: encode every key column into one or
more totally-ordered integer arrays, then `jnp.lexsort` — XLA lowers this
to its sort HLO, which is efficient on VPU.

Encodings (all yield uint64/int16 keys whose integer order == SQL order):
  - signed ints/dates/timestamps: bias by the sign bit.
  - floats: IEEE754 total-order trick; NaN encodes above +inf which is
    exactly Spark's "NaN is largest" ordering, and -0.0 < 0.0.
  - bools: 0/1.
  - strings: one int16 key per byte position, +1 biased so "beyond end of
    string" (0) sorts before any real byte — prefix < longer string.
  - nulls: a separate 0/1 rank key ahead of the value keys.
  - invalid rows (padding beyond num_rows): forced to sort last via the
    most-significant key.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector

_SIGN64 = jnp.uint64(1 << 63)


def _encode_int(data) -> jnp.ndarray:
    """signed int/bool -> uint64 whose unsigned order matches value order."""
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    return data.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64


def _float_keys(data, ascending: bool) -> list[jnp.ndarray]:
    """Floats sort as [is_nan, value] key pairs instead of an IEEE bit
    encode: 64-bit bitcast_convert is unimplemented in the TPU X64-rewrite
    pass, and XLA's sort HLO orders plain floats natively.  NaN gets its
    own most-significant key (Spark: NaN is largest); NaN payloads don't
    affect SQL ordering so collapsing them to one flag is exact."""
    nan = jnp.isnan(data)
    val = jnp.where(nan, jnp.zeros_like(data), data)
    if ascending:
        return [nan.astype(jnp.uint8), val]
    return [(~nan).astype(jnp.uint8), -val]


def encode_key_bits(col: ColumnVector, ascending: bool = True,
                    nulls_first: bool = True
                    ) -> list[tuple[jnp.ndarray, int]]:
    """Sort keys for one column, each with its bit width so
    `packed_lexsort` can pack many keys into few uint64 sort words.
    A width of None marks an unpackable key (float64 values) that must be
    its own sort operand.

    Value bits are NORMALIZED under null (and NaN-payload) rows: the
    null-rank / nan-flag key already places those rows, and zeroing the
    garbage value bits makes encoded-word equality coincide with SQL
    group equality — which lets `sort_with_bounds` derive segment
    boundaries from the packed words with no extra per-key gathers."""
    keys: list = []
    null_rank = jnp.where(col.validity,
                          jnp.uint8(1 if nulls_first else 0),
                          jnp.uint8(0 if nulls_first else 1))
    keys.append((null_rank, 1))
    dt = col.dtype
    valid = col.validity

    def width_int(x, bits, bias):
        x = jnp.where(valid, x.astype(jnp.int64), 0)
        enc = (x + bias).astype(jnp.uint64)
        if not ascending:
            enc = jnp.uint64((1 << bits) - 1) - enc
        return (enc, bits)

    if dt.is_string:
        cc = col.char_cap
        pos = jnp.arange(cc)[None, :]
        b = jnp.where(valid[:, None] & (pos < col.lengths[:, None]),
                      col.data.astype(jnp.int16) + 1, 0)
        if not ascending:
            b = jnp.int16(256) - b
        for j in range(cc):
            keys.append((b[:, j].astype(jnp.uint64), 9))
    elif dt.id == T.TypeId.FLOAT32:
        nan = jnp.isnan(col.data) & valid
        keys.append(((nan if ascending else ~nan).astype(jnp.uint8), 1))
        val = jnp.where(valid & ~nan, col.data,
                        jnp.zeros_like(col.data))
        # -0.0 -> 0.0: SQL groups them together (murmur3 normalizes the
        # same way), and the IEEE bit encode would otherwise separate
        # them — both in sort order and in word-equality boundaries
        val = jnp.where(val == 0.0, jnp.zeros_like(val), val)
        bits = lax.bitcast_convert_type(val, jnp.uint32)
        sign = bits >> jnp.uint32(31)
        # IEEE total-order: negative floats reverse, positives offset
        enc = jnp.where(sign == 1, ~bits,
                        bits | jnp.uint32(0x80000000)).astype(jnp.uint64)
        if not ascending:
            enc = jnp.uint64((1 << 32) - 1) - enc
        keys.append((enc, 32))
    elif dt.is_floating:  # float64: 64-bit bitcast is unavailable on TPU
        nan = jnp.isnan(col.data) & valid
        keys.append(((nan if ascending else ~nan).astype(jnp.uint8), 1))
        val = jnp.where(valid & ~nan, col.data,
                        jnp.zeros_like(col.data))
        keys.append((val if ascending else -val, None))
    elif dt.id == T.TypeId.BOOL:
        enc = jnp.where(valid, col.data, False).astype(jnp.uint64)
        if not ascending:
            enc = jnp.uint64(1) - enc
        keys.append((enc, 1))
    elif dt.id == T.TypeId.INT8:
        keys.append(width_int(col.data, 8, 128))
    elif dt.id == T.TypeId.INT16:
        keys.append(width_int(col.data, 16, 1 << 15))
    elif dt.id in (T.TypeId.INT32, T.TypeId.DATE32):
        keys.append(_enc32(jnp.where(valid, col.data, 0)
                           .astype(jnp.int32), ascending))
    elif col.narrow is not None:
        # int64/timestamp whose values fit int32 (narrow shadow): a
        # 32-bit encode halves the packed sort-word width — 64-bit
        # compare-exchange is the dominant cost of bitonic sorts on
        # this chip
        keys.append(_enc32(jnp.where(valid, col.narrow, 0), ascending))
    else:  # int64 / timestamp
        x = jnp.where(valid, col.data.astype(jnp.int64), 0)
        enc = x.astype(jnp.uint64) ^ _SIGN64
        if not ascending:
            enc = ~enc
        keys.append((enc, 64))
    return keys


def _enc32(x_i32, ascending: bool):
    """int32 -> uint32 sort key in pure 32-bit ops (no int64 bias)."""
    enc = lax.bitcast_convert_type(x_i32, jnp.uint32) ^ jnp.uint32(1 << 31)
    if not ascending:
        enc = ~enc
    return (enc, 32)


#: at or below this many packed words, one variadic sort replaces the
#: per-word LSD chain (fewer networks, no re-gathers); above it the
#: chain keeps XLA:TPU variadic-sort compile time bounded
VARIADIC_MAX_WORDS = 3


def _pack_words_width(keys_msf: list, max_bits: int) -> list:
    """Greedily pack (array, bits) keys MSF->LSF into sort words of at
    most `max_bits`; returns [(array, used_bits-or-None), ...].  A key
    wider than max_bits still gets its own full-width word."""
    words: list = []          # (array, used_bits or None)
    acc, used = None, 0

    def flush():
        nonlocal acc, used
        if acc is not None:
            words.append((acc, used))
            acc, used = None, 0

    for arr, bits in keys_msf:
        if bits is None:
            flush()
            words.append((arr, None))
            continue
        if acc is not None and used + bits <= 32:
            # stay in 32-bit arithmetic while the word fits: 64-bit
            # shifts/ors are several times slower on this chip
            acc = ((acc.astype(jnp.uint32) << jnp.uint32(bits))
                   | arr.astype(jnp.uint32))
            used += bits
        elif acc is not None and used + bits <= max_bits:
            acc = ((acc.astype(jnp.uint64) << jnp.uint64(bits))
                   | arr.astype(jnp.uint64))
            used += bits
        else:
            flush()
            acc, used = arr, bits
    flush()
    return words


def _pack_words(keys_msf: list) -> list:
    """Pack keys into sort words, PREFERRING 32-bit words: a variadic
    sort over two u32 operands runs ~40% faster than over one u64 word
    on this chip (measured 85ms vs 118-142ms at 2M rows — 64-bit
    compare-exchange is the bitonic network's dominant cost).  The
    32-bit split only applies while the total word count stays within
    the variadic-network budget; past it, wide 64-bit words keep the
    word count (and the LSD chain length) down."""
    w32 = _pack_words_width(keys_msf, 32)
    if len(w32) <= VARIADIC_MAX_WORDS:
        return w32
    return _pack_words_width(keys_msf, 64)


def _narrowed(w, wbits):
    if wbits is not None:
        # sort at the narrowest width that holds the word
        return w.astype(jnp.uint32 if wbits <= 32 else jnp.uint64)
    return w


def _neq_prev(sorted_words, cap: int) -> jnp.ndarray:
    """True where any sorted word differs from its predecessor (the
    word-equality boundary primitive shared by both bounds
    derivations)."""
    acc = jnp.zeros(cap, bool)
    for s in sorted_words:
        acc = acc | (s != jnp.roll(s, 1))
    return acc


def _gather_sorted_words(words, perm):
    """Fallback when the sort didn't emit its sorted operands (LSD
    chain path): gather each packed word through the permutation."""
    return [jnp.take(_narrowed(w, b), perm) for w, b in words]


def _sort_words(words: list, cap: int) -> jnp.ndarray:
    """Stable argsort by packed words, most significant first."""
    return _sort_words_full(words, cap)[0]


def _sort_words_full(words: list, cap: int):
    """Stable argsort by packed words, most significant first.
    Returns (perm, sorted_words-or-None): the variadic network emits
    the SORTED key operands as a byproduct — callers that need
    word-equality boundaries use them directly instead of paying one
    random-access gather per word (~70ns/row on this chip)."""
    perm = jnp.arange(cap, dtype=jnp.int32)
    if len(words) <= VARIADIC_MAX_WORDS:
        # one variadic sort network beats the per-word chain ~2x at
        # multi-M rows (measured: 3 words 93ms vs 186ms at 4M) AND
        # skips the per-pass key re-gathers; kept to few operands
        # because XLA:TPU variadic-sort compile time grows steeply
        # with operand count
        ops = tuple(_narrowed(w, b) for w, b in words) + (perm,)
        out = lax.sort(ops, num_keys=len(words), is_stable=True)
        return out[-1], list(out[:-1])
    for w, wbits in reversed(words):
        kw = jnp.take(_narrowed(w, wbits), perm)
        _, perm = lax.sort((kw, perm), num_keys=1, is_stable=True)
    return perm, None


def packed_lexsort(keys_msf: list[tuple[jnp.ndarray, int]]) -> jnp.ndarray:
    """Stable multi-key argsort, most-significant key first.

    XLA:TPU sort compile time grows steeply with operand count and row
    count (a 10-operand variadic sort at 64K rows compiles for minutes),
    so keys are greedily packed MSF->LSF into uint64 words and the sort
    runs as one variadic network (few words) or a chain of 1-key stable
    sorts from the least significant word up (the LSD composition)."""
    cap = keys_msf[0][0].shape[0]
    return _sort_words(_pack_words(keys_msf), cap)


def sort_with_bounds(key_cols: list, row_mask: jnp.ndarray,
                     prefix: int = None):
    """Argsort by (column, ascending, nulls_first) keys AND derive
    segment boundaries from the PACKED SORT WORDS — encoded value bits
    are null/NaN-normalized, so word equality == SQL group equality and
    no per-key-column boundary gathers are needed (each costs ~30ms at
    2M rows on this chip; the words are gathered once for small counts).

    `prefix` (default: all keys) marks how many leading key columns
    form the GROUPING; packing never shares a word across the prefix
    border.  Returns (perm, sorted_valid, prefix_bounds, all_bounds);
    invalid rows sort last and never start a segment."""
    cap = row_mask.shape[0]
    if prefix is None:
        prefix = len(key_cols)
    lead = [((~row_mask).astype(jnp.uint8), 1)]
    for col, asc, nf in key_cols[:prefix]:
        lead.extend(encode_key_bits(col, asc, nf))
    rest: list = []
    for col, asc, nf in key_cols[prefix:]:
        rest.extend(encode_key_bits(col, asc, nf))
    # the 32-bit word preference (see _pack_words) must be decided over
    # the COMBINED word count — prefix and rest ride one sort network
    pwords = _pack_words_width(lead, 32)
    rwords = _pack_words_width(rest, 32)
    if len(pwords) + len(rwords) > VARIADIC_MAX_WORDS:
        pwords = _pack_words_width(lead, 64)
        rwords = _pack_words_width(rest, 64)
    perm, swords = _sort_words_full(pwords + rwords, cap)
    # invalid rows sort LAST (the lead word's MSB is the invalid flag),
    # so the sorted mask is a plain prefix — no gather needed
    sorted_valid = jnp.arange(cap) < row_mask.sum()

    if swords is None:
        swords = _gather_sorted_words(pwords + rwords, perm)
    first = jnp.arange(cap) == 0
    pneq = _neq_prev(swords[:len(pwords)], cap)
    prefix_bounds = sorted_valid & (pneq | first)
    if rwords:
        all_bounds = sorted_valid & \
            (pneq | _neq_prev(swords[len(pwords):], cap) | first)
    else:
        all_bounds = prefix_bounds
    return perm, sorted_valid, prefix_bounds, all_bounds


def _key_bit_widths(col) -> list:
    """Per-key bit widths `encode_key_bits` would emit for one column
    (None = unpackable float64 value word).  Kept adjacent to
    `encode_key_bits`' dtype dispatch — the two tables must agree for
    the routing estimate to match the real encode."""
    dt = col.dtype
    out = [1]  # null rank
    if dt.is_string:
        out += [9] * col.char_cap
    elif dt.id == T.TypeId.FLOAT32:
        out += [1, 32]
    elif dt.is_floating:
        out += [1, None]
    elif dt.id == T.TypeId.BOOL:
        out += [1]
    elif dt.id == T.TypeId.INT8:
        out += [8]
    elif dt.id == T.TypeId.INT16:
        out += [16]
    elif dt.id in (T.TypeId.INT32, T.TypeId.DATE32):
        out += [32]
    elif col.narrow is not None:
        out += [32]
    else:
        out += [64]
    return out


def estimate_packed_words(key_cols) -> int:
    """STATIC count of the packed sort words `sort_with_bounds` would
    need for (column, asc, nulls_first) keys — usable at kernel-build
    time to route wide key sets (string groupers explode into one
    9-bit key per char position) to the hash-grouping lane before
    paying the encode.  Simulates `_pack_words`' greedy rule exactly
    (keys never split across words; unpackable float64 flushes), so
    the estimate can't drift low and strand wide keys on the slow
    lane."""
    widths = [1]  # invalid-rows lead flag
    for col, _asc, _nf in key_cols:
        widths.extend(_key_bit_widths(col))
    words, used = 0, 0
    for bits in widths:
        if bits is None:           # unpackable: own word, flush first
            words += 1 if used else 0
            words += 1
            used = 0
        elif used and used + bits <= 64:
            used += bits
        else:
            words += 1 if used else 0
            used = bits
    return words + (1 if used else 0)


def _grouping_hash(cols, seed: int) -> jnp.ndarray:
    """Row hash for the hash-grouping lane.  NOT Spark's Murmur3Hash:
    Spark chains a null as the unchanged seed, which makes shifted
    null patterns — (NULL, x) vs (x, NULL) — collide DETERMINISTICALLY
    on every seed and would fire the collision deopt on ordinary
    nullable multi-key data.  Here a null mixes a per-column marker
    into the chain instead, so only genuine 64-bit accidents collide."""
    from spark_rapids_tpu.ops.murmur3 import hash_column, hash_int
    cap = cols[0].capacity
    h = jnp.full(cap, seed, jnp.uint32)
    for i, c in enumerate(cols):
        hc = hash_column(c, h)
        null_mark = jnp.full(cap, (0x9E3779B9 * (i + 1)) & 0xFFFFFFFF,
                             jnp.uint32)
        h = jnp.where(c.validity, hc, hash_int(null_mark, h))
    return h


def hash_sort_bounds(key_cols: list, row_mask: jnp.ndarray):
    """Equality-only grouping: sort rows by TWO murmur3 words instead
    of the full lexicographic key encode, then read exact segment
    boundaries off the ACTUAL key values of adjacent sorted rows
    (`segment_boundaries` — one vectorized compare per key column).

    Group-by needs grouping, not ordering, so this replaces the
    word-chain sort whose width scales with key content (a 15-column
    string grouper is ~100 packed words ⇒ a 100-pass sort chain whose
    XLA compile alone runs minutes and allocates GBs; TPC-DS q64).
    The murmur3 lane is 2 words for ANY key set.

    SQL-equal keys always hash equal (`ops/murmur3.hash_column`
    canonicalizes NaN / -0.0 and chains nulls as the unchanged seed),
    so a group can only fragment when two DIFFERENT key tuples collide
    on both 32-bit hashes.  That case is detected exactly — a key
    boundary with no hash change — and returned as a deferred flag the
    caller turns into a deopt check (reference analog: cuDF hash
    groupby under `aggregate.scala:312`, which also trades order for
    equality).

    Returns (perm, sorted_valid, bounds, collision_flag)."""
    cols = [c for c, _asc, _nf in key_cols]
    perm, sorted_valid, bounds, _all, collision = \
        hash_prefix_sort_bounds(cols, [], row_mask)
    return perm, sorted_valid, bounds, collision


class _WidthOnly:
    """Dtype/width stand-in for `estimate_packed_words` when a key is
    a computed expression (no backing column to inspect)."""
    __slots__ = ("dtype", "narrow", "char_cap")

    def __init__(self, dtype, narrow=None):
        self.dtype, self.narrow, self.char_cap = dtype, narrow, 0


#: past this many estimated packed sort words a GROUPING key set
#: routes through the 2-word murmur3 hash lane (see hash_sort_bounds)
HASH_GROUP_MIN_WORDS = 4


def wide_key_set(bound_exprs, batch, schema,
                 threshold: int = HASH_GROUP_MIN_WORDS) -> bool:
    """Shared lane routing for grouping sorts (aggregate group-by,
    window partition-by): True when the lexicographic encode of these
    bound key expressions would exceed `threshold` packed words."""
    pseudo = []
    for e in bound_exprs:
        ordinal = getattr(e, "ordinal", None)
        if ordinal is not None and batch is not None:
            pseudo.append((batch.columns[ordinal], True, True))
            continue
        dt = e.data_type(schema)
        if dt.is_string:
            return True  # computed string key: always wide
        pseudo.append((_WidthOnly(dt), True, True))
    return estimate_packed_words(pseudo) > threshold


def hash_prefix_sort_bounds(part_cols: list, order_keys: list,
                            row_mask: jnp.ndarray):
    """`sort_with_bounds` variant for window-style keys: the PARTITION
    prefix needs grouping only (partitions' relative order is
    unobservable), so it sorts as two murmur3 words regardless of key
    width, while the ORDER keys keep the exact lexicographic encode
    (their order IS the window semantics).  Partition boundaries come
    from the actual adjacent key values; a key boundary without a hash
    change is a genuine 64-bit collision, returned as a deferred deopt
    flag (same contract as hash_sort_bounds).

    Returns (perm, sorted_valid, prefix_bounds, all_bounds,
    collision_flag)."""
    cap = row_mask.shape[0]
    h1 = _grouping_hash(part_cols, 42)
    h2 = _grouping_hash(part_cols, 0x3C6EF372)
    w1 = ((~row_mask).astype(jnp.uint64) << jnp.uint64(32)) \
        | h1.astype(jnp.uint64)
    rest: list = []
    for col, asc, nf in order_keys:
        rest.extend(encode_key_bits(col, asc, nf))
    rwords = _pack_words(rest)
    perm, swords = _sort_words_full([(w1, 33), (h2, 32)] + rwords, cap)
    sorted_valid = jnp.arange(cap) < row_mask.sum()
    first = jnp.arange(cap) == 0
    prefix_bounds = segment_boundaries(part_cols, perm, row_mask)
    if swords is None:
        swords = _gather_sorted_words([(w1, 33), (h2, 32)] + rwords, perm)
    hash_change = _neq_prev(swords[:2], cap)
    collision = jnp.any(prefix_bounds & ~hash_change & ~first)
    if rwords:
        all_bounds = sorted_valid & \
            (prefix_bounds | _neq_prev(swords[2:], cap) | first)
    else:
        all_bounds = prefix_bounds
    return perm, sorted_valid, prefix_bounds, all_bounds, collision


def multi_key_argsort(key_cols: list[tuple[ColumnVector, bool, bool]],
                      row_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable argsort by multiple (column, ascending, nulls_first) keys;
    padded rows sort last.  Returns the permutation."""
    keys_msf: list = [((~row_mask).astype(jnp.uint8), 1)]
    for col, asc, nf in key_cols:
        keys_msf.extend(encode_key_bits(col, asc, nf))
    return packed_lexsort(keys_msf)


#: above this requested size the top_k lane hands over to a payload
#: sort: top_k cost grows with k (k=256K over 1M rows is close to a
#: full sort), while the 1-bit-key payload sort is flat in k
MASKED_POSITIONS_TOPK_MAX = 1 << 15


def masked_positions(mask: jnp.ndarray, size: int,
                     fill_value: int) -> jnp.ndarray:
    """First `size` indices where mask is set, ascending; `fill_value`
    past the set count.  `jnp.nonzero(size=...)` lowers to a serialized
    scatter-add on XLA:TPU (~107ms fused at 2M rows — it was the
    single largest op in the group-by kernel), so:
      - small size: 32-bit top_k over the masked iota (~62ms at 2M)
      - large size: ONE stable 1-bit-key sort carrying the iota as a
        payload operand (payload moves are ~free in the sort network;
        cost is flat in `size` where top_k grows with k)
      - size covering the array: nonzero fallback."""
    cap = mask.shape[0]
    if size * 2 > cap:
        return jnp.nonzero(mask, size=size, fill_value=fill_value)[0]
    iota = lax.iota(jnp.int32, cap)
    if size <= MASKED_POSITIONS_TOPK_MAX:
        keyv = jnp.where(mask, iota, jnp.iinfo(jnp.int32).max)
        neg, _ = lax.top_k(-keyv, size)
        pos = -neg
        return jnp.where(pos >= cap, fill_value, pos)
    _, sorted_iota = lax.sort([~mask, iota], num_keys=1, is_stable=True)
    count = mask.sum()
    head = sorted_iota[:size]
    return jnp.where(jnp.arange(size) < count, head, fill_value)


def segment_boundaries(key_cols: list[ColumnVector],
                       perm: jnp.ndarray,
                       row_mask: jnp.ndarray) -> jnp.ndarray:
    """After sorting by perm, True where a new group starts (valid rows
    only).  Equal keys = equal (value, null-flag) pairs; two nulls are
    grouped together (SQL GROUP BY semantics)."""
    cap = perm.shape[0]
    sorted_mask = jnp.take(row_mask, perm)
    diff = jnp.zeros(cap, bool)
    for col in key_cols:
        v = jnp.take(col.validity, perm)
        v_prev = jnp.roll(v, 1)
        if col.dtype.is_string:
            d = jnp.take(col.data, perm, axis=0)
            ln = jnp.take(col.lengths, perm)
            d_prev = jnp.roll(d, 1, axis=0)
            ln_prev = jnp.roll(ln, 1)
            pos = jnp.arange(col.char_cap)[None, :]
            in_a = pos < ln[:, None]
            in_b = pos < ln_prev[:, None]
            byte_neq = jnp.where(in_a | in_b,
                                 jnp.where(in_a & in_b,
                                           d != d_prev, True),
                                 False).any(axis=1)
            val_neq = byte_neq | (ln != ln_prev)
        else:
            d = jnp.take(col.data, perm)
            d_prev = jnp.roll(d, 1)
            if col.dtype.is_floating:
                # group NaNs together
                both_nan = jnp.isnan(d) & jnp.isnan(d_prev)
                val_neq = (d != d_prev) & ~both_nan
            else:
                val_neq = d != d_prev
        neq = (v != v_prev) | (v & v_prev & val_neq)
        diff = diff | neq
    first = jnp.arange(cap) == 0
    return sorted_mask & (diff | first)
