"""Sortable key encoding + multi-key argsort (TPU groupby/sort substrate).

The reference leans on cuDF's `Table.orderBy` / groupby radix machinery;
on TPU the idiomatic equivalent is: encode every key column into one or
more totally-ordered integer arrays, then `jnp.lexsort` — XLA lowers this
to its sort HLO, which is efficient on VPU.

Encodings (all yield uint64/int16 keys whose integer order == SQL order):
  - signed ints/dates/timestamps: bias by the sign bit.
  - floats: IEEE754 total-order trick; NaN encodes above +inf which is
    exactly Spark's "NaN is largest" ordering, and -0.0 < 0.0.
  - bools: 0/1.
  - strings: one int16 key per byte position, +1 biased so "beyond end of
    string" (0) sorts before any real byte — prefix < longer string.
  - nulls: a separate 0/1 rank key ahead of the value keys.
  - invalid rows (padding beyond num_rows): forced to sort last via the
    most-significant key.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector

_SIGN64 = jnp.uint64(1 << 63)


def _encode_int(data) -> jnp.ndarray:
    """signed int/bool -> uint64 whose unsigned order matches value order."""
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    return data.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64


def _float_keys(data, ascending: bool) -> list[jnp.ndarray]:
    """Floats sort as [is_nan, value] key pairs instead of an IEEE bit
    encode: 64-bit bitcast_convert is unimplemented in the TPU X64-rewrite
    pass, and XLA's sort HLO orders plain floats natively.  NaN gets its
    own most-significant key (Spark: NaN is largest); NaN payloads don't
    affect SQL ordering so collapsing them to one flag is exact."""
    nan = jnp.isnan(data)
    val = jnp.where(nan, jnp.zeros_like(data), data)
    if ascending:
        return [nan.astype(jnp.uint8), val]
    return [(~nan).astype(jnp.uint8), -val]


def encode_key_column(col: ColumnVector, ascending: bool = True,
                      nulls_first: bool = True) -> list[jnp.ndarray]:
    """Returns lexsort keys for this column in MOST-significant-first
    order: [null_rank, value_key...]."""
    keys: list[jnp.ndarray] = []
    null_rank = jnp.where(col.validity,
                          jnp.uint8(1 if nulls_first else 0),
                          jnp.uint8(0 if nulls_first else 1))
    keys.append(null_rank)
    if col.dtype.is_string:
        cc = col.char_cap
        pos = jnp.arange(cc)[None, :]
        b = jnp.where(pos < col.lengths[:, None],
                      col.data.astype(jnp.int16) + 1, 0)
        if not ascending:
            b = jnp.int16(256) - b
        for j in range(cc):
            keys.append(b[:, j])
    elif col.dtype.is_floating:
        keys.extend(_float_keys(col.data, ascending))
    else:
        k = _encode_int(col.data)
        if not ascending:
            k = ~k
        keys.append(k)
    return keys


def multi_key_argsort(key_cols: list[tuple[ColumnVector, bool, bool]],
                      row_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable argsort by multiple (column, ascending, nulls_first) keys;
    padded rows sort last.  Returns the permutation."""
    keys_msf: list[jnp.ndarray] = [(~row_mask).astype(jnp.uint8)]
    for col, asc, nf in key_cols:
        keys_msf.extend(encode_key_column(col, asc, nf))
    # lexsort: LAST key is primary -> feed least-significant first
    return jnp.lexsort(tuple(reversed(keys_msf)))


def segment_boundaries(key_cols: list[ColumnVector],
                       perm: jnp.ndarray,
                       row_mask: jnp.ndarray) -> jnp.ndarray:
    """After sorting by perm, True where a new group starts (valid rows
    only).  Equal keys = equal (value, null-flag) pairs; two nulls are
    grouped together (SQL GROUP BY semantics)."""
    cap = perm.shape[0]
    sorted_mask = jnp.take(row_mask, perm)
    diff = jnp.zeros(cap, bool)
    for col in key_cols:
        v = jnp.take(col.validity, perm)
        v_prev = jnp.roll(v, 1)
        if col.dtype.is_string:
            d = jnp.take(col.data, perm, axis=0)
            ln = jnp.take(col.lengths, perm)
            d_prev = jnp.roll(d, 1, axis=0)
            ln_prev = jnp.roll(ln, 1)
            pos = jnp.arange(col.char_cap)[None, :]
            in_a = pos < ln[:, None]
            in_b = pos < ln_prev[:, None]
            byte_neq = jnp.where(in_a | in_b,
                                 jnp.where(in_a & in_b,
                                           d != d_prev, True),
                                 False).any(axis=1)
            val_neq = byte_neq | (ln != ln_prev)
        else:
            d = jnp.take(col.data, perm)
            d_prev = jnp.roll(d, 1)
            if col.dtype.is_floating:
                # group NaNs together
                both_nan = jnp.isnan(d) & jnp.isnan(d_prev)
                val_neq = (d != d_prev) & ~both_nan
            else:
                val_neq = d != d_prev
        neq = (v != v_prev) | (v & v_prev & val_neq)
        diff = diff | neq
    first = jnp.arange(cap) == 0
    return sorted_mask & (diff | first)
