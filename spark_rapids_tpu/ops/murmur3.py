"""Spark-exact Murmur3 x86_32 row hashing, vectorized in JAX.

The reference uses cuDF's murmur3 partition hashing
(`GpuHashPartitioning.scala`), which matches Spark's
`org.apache.spark.sql.catalyst.expressions.Murmur3Hash` (seed 42):

  hash = 42
  for each column:  hash = hash_col(value, seed=hash)   # null: unchanged

  int/short/byte/bool/date -> hashInt(v)
  long/timestamp           -> hashLong(v)   (two 32-bit words)
  float  -> hashInt(floatToIntBits(f))   with -0.0 normalized to 0.0
  double -> hashLong(doubleToLongBits(d)) with -0.0 normalized
  string -> hashUnsafeBytes(utf8): 4-byte LE words, then per-byte tail
            (bytes are SIGNED in the tail), fmix with total length

All arithmetic is wrapping uint32.  Float bit patterns are recovered with
32-bit bitcasts only (64-bit bitcast_convert does not lower on TPU): a
double is split via frexp-based exact decomposition into hi/lo words.

Known divergence: XLA flushes f64 subnormals to zero (FTZ), so subnormal
doubles (|x| < 2.2e-308) hash as +/-0.0.  Spark/cuDF hash their exact bit
patterns.  No realistic SQL workload is affected.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector

C1 = jnp.uint32(0xCC9E2D51)
C2 = jnp.uint32(0x1B873593)
SPARK_SEED = 42


def _rotl(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix_k1(k1):
    k1 = k1 * C1
    k1 = _rotl(k1, 15)
    return k1 * C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> jnp.uint32(13))
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    return h1


def hash_int(v_u32, seed_u32):
    return _fmix(_mix_h1(seed_u32, _mix_k1(v_u32)), 4)


def hash_long(lo_u32, hi_u32, seed_u32):
    h1 = _mix_h1(seed_u32, _mix_k1(lo_u32))
    h1 = _mix_h1(h1, _mix_k1(hi_u32))
    return _fmix(h1, 8)


def _double_to_words(x):
    """doubleToLongBits as (lo, hi) uint32 without 64-bit bitcast.

    Exact IEEE754 reconstruction: frexp gives mantissa in [0.5, 1) and
    exponent; the 52-bit mantissa field is recovered with two exact f64
    multiplies (each fits 32 bits).  Specials (0, inf, nan, subnormal)
    handled explicitly; NaN canonicalized like Java's doubleToLongBits.

    KNOWN DIVERGENCE — accelerator-emulated f64 (ADVICE r4): on CPU
    this is Spark-exact for all normal values (verified 0/20009
    mismatches; subnormals flush to zero).  On the TPU backend f64
    arithmetic is float-float EMULATED and the decomposition inherits
    that precision: measured on-chip, 1e308 encodes as infinity's bit
    pattern and pi loses its 3 low mantissa bits.  Engine-internal
    partitioning stays self-consistent (every row hashes through the
    same path), but FLOAT64 keys must not mix CPU- and TPU-computed
    partition ids in one shuffle — identical f64 keys could route to
    different partitions.  Integral/string/f32 hashing is exact on
    both backends; only f64 carries this caveat."""
    x = x.astype(jnp.float64)
    # jnp.signbit lowers through a 64-bit bitcast XLA:TPU's x64
    # rewriter rejects; IEEE division distinguishes -0.0 instead
    neg = (x < 0) | ((x == 0) & (1.0 / x < 0))
    ax = jnp.abs(x)
    # frexp equivalent in pure f64 arithmetic: jnp.frexp lowers through
    # a 64-bit bitcast that XLA:TPU's x64 rewriter rejects.  Normalize
    # ax into [1, 2) by exact power-of-two multiplies selected with
    # comparisons (11 + 11 where-steps), accumulating the exponent.
    m = ax
    e = jnp.zeros(ax.shape, jnp.int32)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        big = m >= 2.0 ** k
        m = jnp.where(big, m * (2.0 ** -k), m)
        e = e + jnp.where(big, k, 0)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        small = m < 2.0 ** (1 - k)
        m = jnp.where(small, m * (2.0 ** k), m)
        e = e - jnp.where(small, k, 0)
    # ax = m * 2^e with m in [1, 2); shift to frexp's m in [0.5, 1)
    m = m * 0.5
    e = e + 1
    biased = (e + 1022).astype(jnp.int32)     # IEEE exponent field
    is_sub = biased <= 0                      # subnormal range
    # normal: mantissa field = (m*2 - 1) * 2^52, split hi 20 / lo 32
    frac = m * 2.0 - 1.0                      # [0, 1)
    hi20 = jnp.floor(frac * (1 << 20))
    rem = frac * (1 << 20) - hi20             # [0,1), 32 bits of precision
    lo32 = jnp.floor(rem * 4294967296.0)
    # subnormal: field = ax * 2^1074 exactly, in two exact steps
    sub_f = ax * (2.0 ** 537)
    sub_f = sub_f * (2.0 ** 537)
    sub_hi = jnp.floor(sub_f / 4294967296.0)
    sub_lo = sub_f - sub_hi * 4294967296.0
    is_zero = ax == 0.0
    is_inf = jnp.isinf(ax)
    is_nan = jnp.isnan(x)
    hi_field = jnp.where(is_sub, sub_hi, hi20 + biased.astype(
        jnp.float64) * (1 << 20))
    lo_field = jnp.where(is_sub, sub_lo, lo32)
    hi_u = hi_field.astype(jnp.uint32)
    lo_u = lo_field.astype(jnp.uint32)
    hi_u = jnp.where(is_zero, jnp.uint32(0), hi_u)
    lo_u = jnp.where(is_zero, jnp.uint32(0), lo_u)
    hi_u = jnp.where(is_inf, jnp.uint32(0x7FF00000), hi_u)
    lo_u = jnp.where(is_inf, jnp.uint32(0), lo_u)
    sign = jnp.where(neg & ~is_nan, jnp.uint32(0x80000000), jnp.uint32(0))
    hi_u = hi_u | sign
    # Java canonical NaN: 0x7FF8000000000000
    hi_u = jnp.where(is_nan, jnp.uint32(0x7FF80000), hi_u)
    lo_u = jnp.where(is_nan, jnp.uint32(0), lo_u)
    return lo_u, hi_u


def hash_column(col: ColumnVector, seed_u32: jnp.ndarray) -> jnp.ndarray:
    """Chain one column into the row hash; null rows keep the seed."""
    dt = col.dtype
    if dt.is_string:
        h = _hash_string(col, seed_u32)
    elif dt.id in (T.TypeId.BOOL,):
        h = hash_int(col.data.astype(jnp.uint32), seed_u32)
    elif dt.id in (T.TypeId.INT8, T.TypeId.INT16, T.TypeId.INT32,
                   T.TypeId.DATE32):
        h = hash_int(col.data.astype(jnp.int32).astype(jnp.uint32), seed_u32)
    elif dt.id in (T.TypeId.INT64, T.TypeId.TIMESTAMP_US):
        if col.narrow is not None:
            # values fit int32 (narrow shadow): lo is the i32 bits,
            # hi is the sign extension — pure 32-bit arithmetic,
            # ~4x faster than the 64-bit word split on this chip
            lo = col.narrow.astype(jnp.uint32)
            hi = (col.narrow >> 31).astype(jnp.uint32)
        else:
            v = col.data.astype(jnp.int64)
            lo = (v & 0xFFFFFFFF).astype(jnp.uint32)
            hi = ((v >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
        h = hash_long(lo, hi, seed_u32)
    elif dt.id == T.TypeId.FLOAT32:
        f = col.data
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)  # -0f -> 0f
        bits = lax.bitcast_convert_type(f.astype(jnp.float32), jnp.int32)
        # Java canonical NaN float: 0x7FC00000
        bits = jnp.where(jnp.isnan(f), jnp.int32(0x7FC00000), bits)
        h = hash_int(bits.astype(jnp.uint32), seed_u32)
    elif dt.id == T.TypeId.FLOAT64:
        d = col.data
        d = jnp.where(d == 0.0, 0.0, d)  # -0.0 -> 0.0
        lo, hi = _double_to_words(d)
        h = hash_long(lo, hi, seed_u32)
    else:
        raise TypeError(f"unhashable type {dt}")
    return jnp.where(col.validity, h, seed_u32)


def _hash_string(col: ColumnVector, seed_u32: jnp.ndarray) -> jnp.ndarray:
    cc = col.char_cap
    data = col.data.astype(jnp.uint32)        # [cap, cc]
    lens = col.lengths
    n_words = cc // 4
    h1 = seed_u32
    aligned = (lens // 4) * 4
    for w in range(n_words):
        base = w * 4
        word = (data[:, base]
                | (data[:, base + 1] << 8)
                | (data[:, base + 2] << 16)
                | (data[:, base + 3] << 24))
        in_bounds = base + 4 <= aligned
        h1 = jnp.where(in_bounds, _mix_h1(h1, _mix_k1(word)), h1)
    # tail: at most 3 bytes (len % 4), each mixed as a SIGNED byte —
    # gather them instead of scanning all cc positions
    for t in range(3):
        bpos = jnp.clip(aligned + t, 0, cc - 1)
        byte = jnp.take_along_axis(col.data, bpos[:, None], axis=1)[:, 0]
        sbyte = byte.astype(jnp.int8).astype(jnp.int32)
        in_tail = aligned + t < lens
        h1 = jnp.where(in_tail,
                       _mix_h1(h1, _mix_k1(sbyte.astype(jnp.uint32))), h1)
    return _fmix(h1, lens.astype(jnp.uint32))


def murmur3_row_hash(cols: list[ColumnVector],
                     seed: int = SPARK_SEED) -> jnp.ndarray:
    """Spark Murmur3Hash(columns...) as int32."""
    cap = cols[0].capacity
    h = jnp.full(cap, seed, jnp.uint32)
    for c in cols:
        h = hash_column(c, h)
    return h.astype(jnp.int32)


def partition_ids(cols: list[ColumnVector], num_partitions: int
                  ) -> jnp.ndarray:
    """Spark HashPartitioning: pmod(murmur3(keys), n)."""
    h = murmur3_row_hash(cols)
    m = lax.rem(h, jnp.int32(num_partitions))
    return jnp.where(m < 0, m + num_partitions, m)
