"""tpulint engine: file walking, suppressions, baseline, rule driving.

The linter is deliberately stdlib-only (`ast` + `re`): it must run in
the CI lint lane in well under a second with no environment beyond the
repo checkout, and it must never import the engine it polices — a
module with a side-effectful import (device probe, thread start) would
otherwise make the *linter* flaky.

Suppression grammar (reason mandatory, enforced by the `bad-suppress`
meta rule):

    some_call()  # tpulint: disable=host-sync -- host ndarray, no device value

A standalone comment line suppresses the line directly below it, so
79-column code does not have to grow a trailing comment:

    # tpulint: disable=unbounded-wait -- server parks awaiting requests
    frame = _recv_frame(conn)

The baseline file grandfathers pre-existing findings (keyed by rule +
path + a hash of the offending line's text, so pure line-number churn
does not invalidate it).  The repo policy is to FIX true positives in
the PR that finds them — the baseline exists for emergencies and
should stay empty.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Optional, Sequence

#: package directories whose batch loops are hot paths: a device->host
#: materialization here must be accounted (utils.checks.note_host_sync)
HOT_PATH_PACKAGES = ("exec", "ops", "shuffle", "exprs", "plan")

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\- *]+?)"
    r"(?:\s+--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int          # 1-based
    col: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    reason: str = ""
    baselined: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def fingerprint(self) -> str:
        """Line-number-independent identity for the baseline file."""
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.snippet.strip()}"
            .encode()).hexdigest()[:16]
        return h

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet.strip(),
                "fingerprint": self.fingerprint()}


@dataclasses.dataclass
class Suppression:
    line: int          # the line this suppression applies to
    rules: frozenset   # rule ids, or {"*"}
    reason: str
    comment_line: int  # where the comment physically lives

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


@dataclasses.dataclass
class FileContext:
    """Everything a rule pass needs about one source file."""
    path: str                      # absolute
    relpath: str                   # repo-relative, '/'-separated
    tree: ast.Module
    lines: list[str]
    conf_keys: frozenset           # registered spark.rapids.* keys

    @property
    def components(self) -> tuple:
        return tuple(self.relpath.split("/"))

    @property
    def is_hot_path(self) -> bool:
        return any(c in HOT_PATH_PACKAGES for c in self.components[:-1])

    def in_package(self, name: str) -> bool:
        return name in self.components[:-1]

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


@dataclasses.dataclass
class LintResult:
    findings: list        # active (not suppressed, not baselined)
    suppressed: list
    baselined: list
    bad_suppressions: list  # reason-less disables (active findings too)
    files_scanned: int
    rules: list

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


# ---------------------------------------------------------------------------
def parse_suppressions(lines: Sequence[str]) -> tuple[list, list]:
    """Scan raw source lines for tpulint disable comments.  Returns
    (suppressions, bad_suppress_lines): a comment without the mandatory
    ` -- reason` is NOT honored and is itself reported."""
    sups: list[Suppression] = []
    bad: list[int] = []
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if m is None:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(",")
                          if r.strip())
        reason = (m.group(2) or "").strip()
        if not reason:
            bad.append(i)
            continue
        # a standalone comment covers the next CODE line (continuation
        # comment lines may carry the rest of a long reason)
        target = i
        if raw.strip().startswith("#"):
            target = i + 1
            while (target <= len(lines)
                   and (not lines[target - 1].strip()
                        or lines[target - 1].lstrip().startswith("#"))):
                target += 1
        sups.append(Suppression(target, rules, reason, i))
    return sups, bad


def collect_conf_keys(config_path: str) -> frozenset:
    """Registered conf keys, read by PARSING config.py (never importing
    it): the first string argument of every `conf("spark....", ...)`
    call.  Returns an empty set when config.py is unreadable — rule 4a
    then reports nothing rather than everything."""
    try:
        with open(config_path) as f:
            tree = ast.parse(f.read(), filename=config_path)
    except (OSError, SyntaxError):
        return frozenset()
    keys = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "conf" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            keys.add(node.args[0].value)
    return frozenset(keys)


# ---------------------------------------------------------------------------
def _repo_root() -> str:
    # analysis/ -> spark_rapids_tpu/ -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def default_paths() -> list[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _iter_py_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for n in sorted(names):
                if n.endswith(".py"):
                    yield os.path.join(root, n)


def load_baseline(path: str) -> frozenset:
    """Set of grandfathered finding fingerprints (empty when the file
    is absent — absence means nothing is grandfathered)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return frozenset()
    return frozenset(e.get("fingerprint", "")
                     for e in data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    data = {"version": 1,
            "comment": "grandfathered tpulint findings; the repo "
                       "policy is to FIX violations, so this should "
                       "stay empty — see docs/dev-guide.md",
            "findings": sorted(
                (dict(f.as_dict(), line=f.line) for f in findings),
                key=lambda e: (e["path"], e["rule"], e["line"]))}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
def run_lint(paths: Optional[Sequence[str]] = None,
             disable: Sequence[str] = (),
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             root: Optional[str] = None) -> LintResult:
    """Run every enabled rule over `paths` (default: the
    spark_rapids_tpu package).  Suppressions and the baseline are
    applied here, so rules stay pure (AST in, raw findings out)."""
    from spark_rapids_tpu.analysis.rules import ALL_RULES
    root = root or _repo_root()
    paths = list(paths) if paths else default_paths()
    rules = [r for r in ALL_RULES if r.rule_id not in set(disable)]
    conf_keys = collect_conf_keys(
        os.path.join(root, "spark_rapids_tpu", "config.py"))
    baseline = (load_baseline(baseline_path)
                if baseline_path else frozenset())

    active: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    bad_sup: list[Finding] = []
    files = 0
    for path in _iter_py_files(paths):
        apath = os.path.abspath(path)
        rel = os.path.relpath(apath, root)
        if rel.startswith(".."):
            rel = os.path.basename(apath)
        rel = rel.replace(os.sep, "/")
        try:
            with open(apath) as f:
                src = f.read()
            tree = ast.parse(src, filename=apath)
        except (OSError, SyntaxError) as e:
            active.append(Finding("parse-error", rel, 1, 0,
                                  f"could not lint: {e}"))
            continue
        files += 1
        lines = src.splitlines()
        ctx = FileContext(apath, rel, tree, lines, conf_keys)
        sups, bad_lines = parse_suppressions(lines)
        if "bad-suppress" not in set(disable):
            for ln in bad_lines:
                bad_sup.append(Finding(
                    "bad-suppress", rel, ln, 0,
                    "tpulint suppression without a reason — write "
                    "'# tpulint: disable=<rule> -- <why>'",
                    snippet=ctx.snippet(ln)))
        by_line: dict[int, list[Suppression]] = {}
        for s in sups:
            by_line.setdefault(s.line, []).append(s)
        for rule in rules:
            for f in rule.check(ctx):
                f.snippet = f.snippet or ctx.snippet(f.line)
                cover = next((s for s in by_line.get(f.line, [])
                              if s.covers(f.rule)), None)
                if cover is not None:
                    f.suppressed = True
                    f.reason = cover.reason
                    suppressed.append(f)
                elif f.fingerprint() in baseline:
                    f.baselined = True
                    baselined.append(f)
                else:
                    active.append(f)
    active.extend(bad_sup)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(active, suppressed, baselined, bad_sup,
                      files, [r.rule_id for r in rules])
