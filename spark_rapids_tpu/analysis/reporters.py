"""tpulint output: text (file:line for humans/editors) and JSON (for
the CI lane and tooling), plus the one-line summary every run_suite.sh
lane ends with."""
from __future__ import annotations

import json

from spark_rapids_tpu.analysis.core import LintResult


def format_text(result: LintResult, verbose_suppressed: bool = False
                ) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
        if f.snippet.strip():
            lines.append(f"    {f.snippet.strip()}")
    if verbose_suppressed:
        for f in result.suppressed:
            lines.append(f"{f.location()}: [{f.rule}] suppressed "
                         f"({f.reason})")
    lines.append(summary_line(result))
    return "\n".join(lines)


def format_json(result: LintResult) -> str:
    return json.dumps({
        "rules": result.rules,
        "files": result.files_scanned,
        "findings": [f.as_dict() for f in result.findings],
        "suppressed": [dict(f.as_dict(), reason=f.reason)
                       for f in result.suppressed],
        "baselined": [f.as_dict() for f in result.baselined],
    }, indent=2)


def summary_line(result: LintResult) -> str:
    return ("tpulint summary: rules=%d files=%d findings=%d "
            "suppressed=%d baselined=%d" % (
                len(result.rules), result.files_scanned,
                len(result.findings), len(result.suppressed),
                len(result.baselined)))
